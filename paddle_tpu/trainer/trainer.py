"""The training driver.

Replaces the reference's whole driver column — ``Trainer::train ->
trainOnePass -> trainOneDataBatch -> TrainerInternal::trainOneBatch``
(``paddle/trainer/Trainer.cpp:261,492,402``, ``TrainerInternal.cpp:66``) and
the Python v2 loop (``python/paddle/v2/trainer.py:108-175``) — with one
jitted train step:

    (params, opt_state, batch, rng) -> (params, opt_state, metrics)

The reference pipelines parameter updates *during* backward via per-parameter
callbacks (``TrainerInternal.cpp:70-74``); under XLA the fused step gives the
same overlap automatically (grad+update compile into one program). Data
parallelism: pass a ``Mesh`` — the batch is sharded on the ``data`` axis and
XLA inserts the gradient all-reduce, the ICI equivalent of
``MultiGradientMachine``'s ring and the pserver's ``addGradient``.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P_spec

from paddle_tpu.config import dsl as _dsl
from paddle_tpu.config.model_config import ModelDef
from paddle_tpu.testing import chaos as _chaos
from paddle_tpu.core.argument import Argument
from paddle_tpu.core.network import Network
from paddle_tpu.data import prefetch as _prefetch
from paddle_tpu.utils.masks import assert_mask_f32
from paddle_tpu.optim.optimizers import Optimizer
from paddle_tpu.parallel import mesh as mesh_lib
from paddle_tpu.trainer import events as ev
from paddle_tpu.trainer.evaluators import Accumulator, classification_error

_CLASSIFICATION_COSTS = {"multi-class-cross-entropy"}

_END_OF_PASS = object()  # reader-exhausted sentinel for the timed next()


def _call_reader(reader, pass_id: int):
    """Invoke a per-pass reader. Readers that declare ``pass_aware = True``
    (``dist.master.master_reader``) receive the trainer's pass_id so a
    checkpoint-resumed run requests the correct pass from the master
    instead of getting an instant 'end' for already-finished ones."""
    if getattr(reader, "pass_aware", False):
        return reader(pass_id)
    return reader()


class Topology:
    """cost LayerOutput(s) -> executable Network (``python/paddle/v2/
    topology.py:44``). ``cost`` may be a list: multi-task configs train on
    the SUM of their cost layers, as the reference's ``Argument::sum``
    over all output args does."""

    def __init__(self, cost, extra_outputs: Optional[List] = None,
                 graph: Optional[ModelDef] = None):
        costs = list(cost) if isinstance(cost, (list, tuple)) else [cost]
        if graph is None:
            # prefer the graph the cost layer was built in (stays correct
            # after dsl.reset() begins another model)
            graph = getattr(costs[0], "graph", None) or _dsl.current_graph()
        names = [c.name if hasattr(c, "name") else c
                 for c in (costs + list(extra_outputs or []))]
        self.cost_names = names[:len(costs)]
        self.cost_name = names[0]
        graph.output_layer_names = names
        self.network = Network(graph, outputs=names)
        self.graph = graph


class SGD:
    """v2 ``trainer.SGD``: holds topology + parameters + optimizer and runs
    the training loop."""

    def __init__(self, cost, parameters: Optional[Dict[str, Any]] = None,
                 update_equation: Optimizer = None, *,
                 extra_layers: Optional[List] = None,
                 mesh=None, shard_rules: Optional[Dict[str, Any]] = None,
                 seed: int = 0, is_local: bool = True,
                 evaluators: Optional[List[dict]] = None,
                 prev_batch_state: bool = False,
                 compute_dtype: Optional[Any] = None,
                 recompile_warn: int = 8):
        if update_equation is None:
            raise ValueError("update_equation (an Optimizer) is required")
        self.topology = (cost if isinstance(cost, Topology)
                         else Topology(cost, extra_outputs=extra_layers))
        self.network = self.topology.network
        # config-declared evaluators (compat ctx().evaluators and/or the
        # DSL's graph.evaluators) wired to the metric registry — the
        # reference's gm->eval(evaluators) path (TrainerInternal.cpp:160)
        from paddle_tpu.trainer import metrics as _metrics_mod
        graph = self.topology.graph
        ev_cfgs = (list(evaluators or [])
                   + list(getattr(graph, "evaluators", None) or []))
        self._host_evals = _metrics_mod.build_from_configs(ev_cfgs)
        needed = {n for _, ins, _ in self._host_evals for n in ins
                  if n in graph.layers}
        missing = needed - set(self.network.shape_infos)
        if missing:
            # evaluator inputs off the loss path (e.g. a maxid decode
            # branch): extend the executed sub-graph to cover them
            self.network = Network(
                graph, outputs=list(graph.output_layer_names)
                + sorted(missing))
            self.topology.network = self.network
        self._eval_layers = sorted(needed)
        self.optimizer = update_equation
        self.mesh = mesh
        # ZeRO-1 sharded optimizer state (optim/zero1.py): disabled until
        # train(zero1=True) / enable_zero1(); the updater replaces the
        # optimizer in the jitted step, everything else is unchanged
        self._zero1 = None
        # full FSDP (optim/zero1.py:FsdpUpdater): disabled until
        # train(fsdp=True) / enable_fsdp(); while active, eligible
        # parameters live flat-packed (N, chunk) sharded 1/N over the
        # mesh's fsdp axis, the step gathers each one per layer on use,
        # and the shard-wise update keeps them sharded (--fsdp,
        # docs/spec_layout.md)
        self._fsdp = None
        # gather-overlap mode for the fsdp step (--fsdp_overlap):
        # True = double-buffer the next layer's all-gather behind the
        # current layer's compute (TPU traces only; the CPU spelling
        # stays sync so audit budgets pin one program), False = sync,
        # "force" = stage the chain on any backend (tests/bench)
        self._fsdp_overlap = True
        self._zero1_subsumed = False  # zero1 asked for while fsdp holds
        # slots at 1/N already; re-armed if fsdp is later disabled
        # pipeline parallelism (parallel/pipeline.py:PipelineTrainPlan):
        # disabled until train(pipeline=...) / enable_pipeline(); while
        # active, body parameters live stage-stacked [S, ...] sharded
        # one stage per pipe slot and the jitted step runs the GPipe
        # schedule (--parallel_nn, ParallelNeuralNetwork.h:23-62)
        self._pipe = None
        self._pipe_head_net = None
        self._pipe_microbatches = None
        self._flat_meta = None  # pre-stacking meta, restored on disable
        self.grad_accum_steps = 1
        self._recompile_warn = recompile_warn
        key = jax.random.PRNGKey(seed)
        self.meta = self.network.param_meta()
        if mesh is not None:
            # the canonical sharding plane (parallel/layout.py): user
            # rules + the sparse-table row-sharding default + the
            # config's per-layer device placement (--parallel_nn) fold
            # into ONE SpecLayout every derivation below queries —
            # init shardings, slot placement, ZeRO-1/FSDP eligibility,
            # and the pipeline's stage-stacked pins (installed via
            # layout.pin in enable_pipeline)
            from paddle_tpu.parallel.layout import SpecLayout
            self.layout = SpecLayout(mesh, self.network.param_specs,
                                     self.topology.graph, shard_rules)
            # alias, not a copy: pipeline pins flow through both names
            self._shard_rules = self.layout.rules
        else:
            self.layout = None
            self._shard_rules = None
        if parameters is not None:
            self.params = (self.layout.place_params(parameters)
                           if mesh is not None else parameters)
        else:
            # with a mesh, create parameters directly in their final
            # sharding (big tables never materialize on one device)
            shardings = (self.layout.param_shardings(
                self.network.param_specs) if mesh is not None else None)
            self.params = self.network.init_params(key, shardings=shardings)
        self.opt_state = self.optimizer.init(self.params, self.meta)
        # StaticPruningHook: masked weights are zero from step 0
        self.params = self.optimizer.prune_params(self.params,
                                                  self.opt_state)
        if mesh is not None:
            # slots/avg follow their owning parameter; scalars replicate
            self.opt_state = self.layout.place_opt_state(self.opt_state)
        # --prev_batch_state truncated BPTT (Trainer.cpp:396-418,
        # Flags.cpp:73): forward recurrent layers start each batch from the
        # previous batch's final state instead of zeros. Gradients are cut
        # at the batch boundary (stop_gradient), the reference's truncated
        # semantics. Reversed layers can't carry (they'd need the future).
        self.prev_batch_state = prev_batch_state
        self._carry_layers = [
            name for name, ld in self.topology.graph.layers.items()
            if ld.type in ("lstmemory", "gated_recurrent", "recurrent",
                           "recurrent_layer_group")
            and not (ld.attrs.get("reversed") or ld.attrs.get("reverse"))
            and name in self.network.order] if prev_batch_state else []
        self._carried = None  # {layer: state}, threaded across batches
        # mixed precision: master params/optimizer state stay float32,
        # forward+backward run in compute_dtype (bfloat16 feeds the MXU at
        # 2x the f32 rate; grads cast back to f32 before the update)
        self.compute_dtype = (jnp.dtype(compute_dtype)
                              if compute_dtype is not None else None)
        self._rng = jax.random.PRNGKey(seed + 1)
        # training-health plane (obs/health.py): None until train()
        # arms it (health= kwarg or --show_parameter_stats_period);
        # while armed, _rebuild_train_step pins TWO program variants —
        # stats-off (the hot step, + the sentry scalars when the sentry
        # is armed) and stats-on (the same step with the per-layer stat
        # reduction fused in), each behind its own RecompileGuard
        self._health_cfg = None
        self._health = None
        self._health_param_names = ()
        self._health_act_names = ()
        self._train_step_stats = None
        self.stats_recompile_guard = None
        self._stats_warm_pending = False
        self._rebuild_train_step()
        self._eval_step = self._build_eval_step()
        # (recompile-guard rationale: a ragged corpus with unbucketed
        # shapes silently retraces the step per batch; the guards make
        # that loud — data/prefetch.py:RecompileGuard)
        from paddle_tpu.utils.profiler import StepBreakdown
        # the eval forward thrashes the same way on unbucketed test
        # corpora (graftlint PT104): guard it like the train step
        self.eval_recompile_guard = _prefetch.RecompileGuard(
            self._eval_step, warn_after=recompile_warn, name="eval_step")
        self.breakdown = StepBreakdown()

    def _cast_compute(self, tree):
        if self.compute_dtype is None:
            return tree
        dt = self.compute_dtype
        from paddle_tpu.core.argument import Argument
        from paddle_tpu.data.feeder import ROW_MASK_KEY
        if isinstance(tree, dict) and ROW_MASK_KEY in tree:
            # the row-validity mask is f32 COUNT data like every mask
            # (bf16 saturates at 256 rows) — exempt it by key, the same
            # invariant the structural mask exemption below enforces
            rest = {k: v for k, v in tree.items() if k != ROW_MASK_KEY}
            out = self._cast_compute(rest)
            out[ROW_MASK_KEY] = tree[ROW_MASK_KEY]
            return out

        def cast(x):
            if hasattr(x, "dtype") and x.dtype == jnp.float32:
                return x.astype(dt)
            return x

        def go(x):
            if isinstance(x, Argument):
                # masks are COUNT/index data: summed for token counts and
                # per-row lengths, where bf16 saturates at 256 — they must
                # stay f32. Only values (and carried state) compute in dt.
                # The recursion treats nested Arguments inside state as
                # leaves too, so a mask carried anywhere in state (e.g. a
                # group's state["nested"] Argument, layers/group.py) is
                # exempted structurally — by type, not by key name.
                # The runtime side of graftlint PT102/PT203: a mask that
                # arrives below f32 fails AT TRACE TIME, here, not as a
                # silently saturated denominator steps later.
                assert_mask_f32(x.mask, "_cast_compute")
                return x.replace(
                    value=jax.tree_util.tree_map(cast, x.value),
                    state=jax.tree_util.tree_map(
                        go, x.state,
                        is_leaf=lambda s: isinstance(s, Argument)))
            return cast(x)

        return jax.tree_util.tree_map(
            go, tree, is_leaf=lambda x: isinstance(x, Argument))

    def _cast_f32(self, tree):
        if self.compute_dtype is None:
            return tree

        def cast(x):
            if hasattr(x, "dtype") and x.dtype == self.compute_dtype:
                return x.astype(jnp.float32)
            return x

        return jax.tree_util.tree_map(cast, tree)

    # ------------------------------------------------------------ builders
    @staticmethod
    def _row_mask(feed):
        """[B] f32 row-validity mask the bucketing feeder emits when it
        pads the batch dim (``data/feeder.py:ROW_MASK_KEY``); None for
        unpadded feeds. Read from the UNCAST feed — like every mask it
        is count data and must stay f32."""
        from paddle_tpu.data.feeder import ROW_MASK_KEY
        arg = feed.get(ROW_MASK_KEY) if feed is not None else None
        return arg.value if arg is not None else None

    def _total_cost(self, outputs, row_mask=None, accum_k=1,
                    total_live=None):
        """Sum of all cost layers' batch-mean — multi-task configs train
        on the sum (the reference's Argument::sum over outArgs). Reduces
        in f32 even under bf16 compute (batch sums need the mantissa).
        ``row_mask`` makes batch-bucket padding exact: dead rows are
        zeroed out of the sum AND out of the denominator, so the loss
        (and its gradient) equals the unpadded batch's.

        Under microbatch gradient accumulation the denominator must be the
        FULL batch's, not this microbatch's, so that summing the k partial
        losses (and their gradients) reproduces the single k×-batch step
        exactly: ``accum_k`` scales the unmasked per-layer denominator and
        ``total_live`` replaces the masked one with the whole batch's live
        row count."""
        total = 0.0
        for n in getattr(self.topology, "cost_names",
                         [self.topology.cost_name]):
            v = outputs[n].value.astype(jnp.float32)
            if row_mask is not None:
                denom = (total_live if total_live is not None
                         else jnp.sum(row_mask))
                rm = row_mask.reshape((-1,) + (1,) * (v.ndim - 1))
                total = total + jnp.sum(v * rm) / jnp.maximum(denom, 1.0)
            else:
                total = total + jnp.sum(v) / (v.shape[0] * accum_k)
        return total

    def _metrics(self, outputs, feed):
        cost_name = self.topology.cost_name
        cdef = self.topology.graph.layers[cost_name]
        row_mask = self._row_mask(feed)
        metrics = {"cost": self._total_cost(outputs, row_mask)}
        if cdef.type in _CLASSIFICATION_COSTS:
            out_l, lab_l = cdef.input_names()[0], cdef.input_names()[1]
            errs, cnt = classification_error(outputs[out_l], outputs[lab_l],
                                             row_mask=row_mask)
            metrics["classification_error"] = (errs, cnt)
        if self._eval_layers:
            # layer outputs the config-declared evaluators consume; fetched
            # to host once per batch (dict values are skipped by
            # _accumulate's tuple protocol)
            metrics["eval_outputs"] = {
                n: (outputs[n].value, outputs[n].mask)
                if not (isinstance(outputs[n].state, dict)
                        and "ids" in outputs[n].state)
                else (outputs[n].value, outputs[n].mask,
                      outputs[n].state["ids"],
                      outputs[n].state.get("ids_mask"))
                for n in self._eval_layers}
        return metrics

    # ------------------------------------------------- health telemetry
    #: param-table columns (the [P, 6] packed layout — ONE jit output
    #: for the whole table; P separate scalar outputs cost ~30us of
    #: dispatch EACH on the 1-core host, which alone blew the <=5%
    #: overhead budget before packing)
    _HEALTH_PARAM_COLS = ("avg_abs", "max_abs", "norm", "grad_norm",
                          "update_ratio", "touched_rows")

    def _act_stat_table(self, outputs):
        """Per-layer activation (avg_abs, max_abs, live-weight) over
        the executed graph's outputs, packed as ONE [L, 3] array — the
        in-step half
        of ``--show_layer_stat`` (same mask-aware math as the
        standalone ``layer_stats`` jit, fused into the train step
        instead of a second forward). Records the layer-name order on
        the trainer at trace time; returns None when no output is
        inexact."""
        names = [n for n, a in outputs.items()
                 if hasattr(a.value, "dtype")
                 and jnp.issubdtype(a.value.dtype, jnp.inexact)]
        self._health_act_names = tuple(names)
        if not names:
            return None

        def fenced(a):
            # the reductions must read the MATERIALIZED layer outputs:
            # unfenced, XLA duplicates producer computation into the
            # stat consumers (measured ~20 ms/step on the bench model
            # vs ~3 ms for the reductions themselves) — and the fence
            # doubles as the bitwise-neutrality guarantee the param
            # side gets from its own barrier
            value = jax.lax.optimization_barrier(a.value)
            mask = (jax.lax.optimization_barrier(a.mask)
                    if a.mask is not None else None)
            return a.replace(value=value, mask=mask)

        rows = [jnp.stack([jnp.asarray(s, jnp.float32)
                           for s in _arg_abs_stats(fenced(outputs[n]))])
                for n in names]
        return jnp.stack(rows)

    @staticmethod
    def _poison_grads(grads, poison):
        """Chaos ``step_stats`` corrupt trigger: NaN into element 0 of
        the first (sorted) gradient leaf when ``poison > 0``. With
        ``poison == 0`` the ``.at[0].set`` writes the element's own
        value back — a bitwise no-op — so ONE compiled program serves
        both the poisoned and the clean step and the fault stays
        deterministic in the plan seed."""
        if poison is None:
            return grads
        name = sorted(grads)[0]
        g = grads[name]
        flat = g.reshape((-1,))
        bad = jnp.asarray(jnp.nan, flat.dtype)
        flat = flat.at[0].set(jnp.where(poison > 0, bad, flat[0]))
        out = dict(grads)
        out[name] = flat.reshape(g.shape)
        return out

    def _health_metrics(self, loss, params, grads, new_params, new_opt,
                        num_passes, act_table, with_stats):
        """The in-step training-health reduction (obs/health.py owns
        the host side). Returns extra metrics entries:

        - ``sentry`` (when the sentry is armed): the per-step
          finiteness+threshold scalars — ``trip``, the global
          ``grad_absmax``, and a [P] per-parameter grad-absmax vector
          (fetched only on a trip, for the postmortem bundle).
        - ``health`` (stats-on variant only): a packed [P, 6]
          per-parameter table (columns ``_HEALTH_PARAM_COLS``) plus
          the [L, 3] activation table — packed because P+L separate
          scalar outputs cost more in dispatch than the reductions
          themselves on the 1-core host.
        - ``health_lr``: the step's effective base learning rate.

        Name order rides ``self._health_param_names`` /
        ``self._health_act_names``, recorded at trace time (static
        per program variant).

        Everything reduces from ``optimization_barrier``-fenced views
        of params/grads/new_params so XLA cannot fuse the stat
        reductions back into the update path's producers — the
        stats-on and stats-off programs must round the TRAINED values
        identically (the bitwise-neutrality matrix,
        tests/test_health_matrix.py, is the enforcement)."""
        cfg = self._health_cfg
        out: Dict[str, Any] = {}
        if cfg is None or not cfg.armed:
            return out
        p_b, g_b, np_b = jax.lax.optimization_barrier(
            (params, grads, new_params))
        names = sorted(p_b)
        self._health_param_names = tuple(names)
        loss_f = jnp.asarray(loss, jnp.float32)
        if cfg.sentry:
            per = jnp.stack([jnp.max(jnp.abs(g_b[n])).astype(jnp.float32)
                             for n in names]) if names \
                else jnp.zeros((0,), jnp.float32)
            gmax = (jnp.max(per) if names
                    else jnp.zeros((), jnp.float32))
            trip = ~jnp.isfinite(loss_f) | ~jnp.isfinite(gmax)
            if cfg.grad_threshold > 0:
                trip = trip | (gmax > cfg.grad_threshold)
            out["sentry"] = {"trip": trip, "grad_absmax": gmax,
                             "layer_grad_absmax": per}
        opt = self.optimizer
        ns = (new_opt.get("num_samples")
              if isinstance(new_opt, dict) else None)
        if ns is not None and hasattr(opt, "learning_rate"):
            from paddle_tpu.optim.schedules import learning_rate_at
            out["health_lr"] = learning_rate_at(
                getattr(opt, "learning_rate_schedule", "constant"),
                opt.learning_rate,
                getattr(opt, "learning_rate_decay_a", 0.0),
                getattr(opt, "learning_rate_decay_b", 0.0), ns,
                args=getattr(opt, "learning_rate_args", ""),
                num_passes=num_passes)
        if with_stats:
            def l2(x):
                return jnp.sqrt(jnp.sum(
                    jnp.square(x.astype(jnp.float32))))

            nan = jnp.asarray(jnp.nan, jnp.float32)
            rows = []
            for n in names:
                p = p_b[n]
                g = g_b.get(n)
                npv = np_b.get(n)
                pn = l2(p)
                row = [jnp.mean(jnp.abs(p)).astype(jnp.float32),
                       jnp.max(jnp.abs(p)).astype(jnp.float32), pn]
                row.append(l2(g) if g is not None else nan)
                row.append(l2(npv - p) / jnp.maximum(pn, 1e-12)
                           if npv is not None else nan)
                if g is not None and g.ndim >= 2 \
                        and self.optimizer._is_sparse(self.meta.get(n)):
                    # sparse tables: rows this batch touched (the
                    # reference's per-row update bookkeeping made
                    # observable); -1 marks the non-sparse rows the
                    # host drops
                    row.append(jnp.sum(jnp.any(
                        g != 0, axis=tuple(range(1, g.ndim))
                    ).astype(jnp.float32)))
                else:
                    row.append(jnp.asarray(-1.0, jnp.float32))
                rows.append(jnp.stack(row))
            out["health"] = {
                "param_table": (jnp.stack(rows) if rows
                                else jnp.zeros((0, 6), jnp.float32)),
                "act_table": (act_table if act_table is not None
                              else jnp.zeros((0, 3), jnp.float32)),
            }
        return out

    def _apply_skip_select(self, health, params, opt_state, new_params,
                           new_opt):
        """``skip_batch`` policy, in-graph: a tripped sentry discards
        the whole update — params, optimizer slots AND schedule
        counters revert to the step's inputs — so the post-skip
        trajectory is bitwise the run that never saw the batch (the
        host side rolls the RNG split back). Donation-safe: the
        selects read the donated inputs elementwise, which XLA
        resolves with copies only where aliasing actually needs
        them."""
        cfg = self._health_cfg
        sentry = health.get("sentry") if health else None
        if sentry is None or cfg.policy != "skip_batch":
            return new_params, new_opt
        # ONE cond over the whole state, not a per-leaf where: the
        # untripped (hot) branch must not pay an elementwise select
        # over every param + slot (~10 ms/step of pure memory traffic
        # on the 1-core CPU host — the difference between passing and
        # blowing the <=5% overhead budget). The moving-stat merge keys
        # of new_params are a superset-safe dict: revert those to the
        # step's input params too.
        old_params = {k: params[k] for k in new_params}
        return jax.lax.cond(
            sentry["trip"],
            lambda: (old_params, opt_state),
            lambda: (new_params, new_opt))

    def _accum_k_for(self, batch_size: int) -> int:
        """Effective accumulation factor for one batch shape. The FIRST
        batch shape must be divisible by ``grad_accum_steps`` — a k the
        run's dominant batch size can't honor is a config error, raised
        before any training happens (a silent gcd there would quietly run
        at full activation memory, the OOM the flag exists to avoid).
        Once a conforming shape has been seen, a LATER shape k doesn't
        divide (the dataset-tail partial batch) must not abort a nearly-
        finished pass: accumulation is a memory knob, not a math knob, so
        that batch scans gcd(k, B) fewer (larger) microbatches, with a
        warning."""
        import math
        if batch_size % self.grad_accum_steps == 0:
            self._accum_shape_seen = True
            return self.grad_accum_steps
        if not getattr(self, "_accum_shape_seen", False):
            raise ValueError(
                f"grad_accum_steps={self.grad_accum_steps} does not divide "
                f"the batch size ({batch_size} rows): pick a k that "
                "divides the reader's batch size (or bucket batches with "
                "DataFeeder batch_buckets)")
        k = math.gcd(self.grad_accum_steps, batch_size)
        from paddle_tpu.utils import logger
        logger.warning(
            "grad_accum_steps=%d does not divide this batch's %d rows (a "
            "final partial batch) — using %d microbatches for this shape; "
            "bucket batch sizes (DataFeeder batch_buckets) or drop the "
            "remainder batch to keep k uniform",
            self.grad_accum_steps, batch_size, k)
        return k

    def _split_microbatches(self, feed, k: int):
        """Reshape every feed leaf (B, ...) -> (k, B/k, ...) for the
        ``lax.scan`` over microbatches; on a mesh the microbatch dim keeps
        the batch sharding (dim 1 over the data axes) so each scan slice
        is exactly a smaller sharded batch."""
        n_data = (mesh_lib.data_parallel_degree(self.mesh)
                  if self.mesh is not None else 1)

        def split(x):
            if not hasattr(x, "shape") or x.ndim == 0 or x.shape[0] % k:
                raise ValueError(
                    f"grad_accum_steps={k} must divide the batch dim of "
                    f"every feed entry; got shape "
                    f"{getattr(x, 'shape', None)}")
            y = x.reshape((k, x.shape[0] // k) + x.shape[1:])
            if self.mesh is not None and (x.shape[0] // k) % n_data == 0:
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as P
                y = jax.lax.with_sharding_constraint(
                    y, NamedSharding(self.mesh,
                                     P(None, mesh_lib.batch_axes(self.mesh))))
            return y

        return jax.tree_util.tree_map(split, feed)

    def _build_pipe_step(self, with_stats=False):
        """The pipelined train step: body forward through the GPipe
        schedule (``PipelineTrainPlan.fwd`` — a shard_map'd scan whose
        ``jax.grad`` is the reverse-order backward pipeline), cost head
        replicated on the gathered body output, ONE optimizer update on
        the whole-batch gradient. Loss math is identical to the
        unpipelined step's (same denominators, same clip/decay point), so
        the step is gradient-exact on deterministic bodies — pinned by
        tests/test_pipeline_train.py. ``with_stats`` fuses the
        training-health stat reduction in (``_health_metrics``; the
        activation stats cover the head layers + gathered body output —
        the fetched surface of the pipelined graph)."""
        import math

        from paddle_tpu.core.argument import Argument
        plan = self._pipe
        head_net = self._pipe_head_net
        updater = self._fsdp or self._zero1 or self.optimizer
        fsdp = self._fsdp
        meta = self.meta
        cost_name = self.topology.cost_name
        body_names = list(plan.body_param_names())
        M_cfg = self._pipe_microbatches
        n_data = mesh_lib.data_parallel_degree(self.mesh)

        def step(params, opt_state, feed, rng, num_passes, carried=None,
                 poison=None):
            del carried  # rejected at enable time (no prev_batch_state)
            B = next(iter(feed.values())).value.shape[0]
            b_loc = B // n_data
            m_eff = math.gcd(M_cfg, b_loc)  # trace-time constant
            if m_eff != M_cfg:
                from paddle_tpu.utils import logger
                logger.warning(
                    "pipeline: %d microbatches do not divide the "
                    "per-device batch (%d rows) — using %d for this "
                    "shape (bubble fraction rises to %.3f)",
                    M_cfg, b_loc, m_eff,
                    (plan.S - 1) / (plan.S + m_eff - 1))
            fwd = plan.fwd(m_eff, train=True)

            def loss_fn(params, feed, rng):
                if fsdp is not None:
                    # gather-on-use: head parameters rebuild per layer
                    # from their fsdp shards (stage-stacked body keys
                    # are excluded from the plan by their P(pipe) pins)
                    params = fsdp.full_params(params)
                cast_params = self._cast_compute(params)
                cast_feed = self._cast_compute(feed)
                x = cast_feed[plan.body_in].value
                body = {k: cast_params[k] for k in body_names}
                y = fwd(body, x, rng)
                head_feed = dict(cast_feed)
                head_feed[plan.body_out] = Argument(value=y)
                outputs, updates = head_net.apply_with_state(
                    cast_params, head_feed, train=True, rng=rng,
                    mesh=self.mesh)
                return (self._total_cost(outputs, self._row_mask(feed)),
                        (outputs, updates))

            (loss, (outputs, updates)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, feed, rng)
            grads = self._poison_grads(grads, poison)
            updates = self._cast_f32(updates)
            row_mask = self._row_mask(feed)
            bsz = (jnp.sum(row_mask) if row_mask is not None
                   else outputs[cost_name].value.shape[0])
            new_params, new_opt = updater.update(
                grads, opt_state, params, meta, batch_size=bsz,
                num_passes=num_passes)
            new_params.update(updates)
            health = self._health_metrics(
                loss, params, grads, new_params, new_opt, num_passes,
                self._act_stat_table(outputs) if with_stats else None,
                with_stats)
            new_params, new_opt = self._apply_skip_select(
                health, params, opt_state, new_params, new_opt)
            metrics = self._metrics(outputs, feed)
            metrics.update(health)
            return new_params, new_opt, metrics

        return jax.jit(step, donate_argnums=(0, 1))

    def _build_train_step(self, with_stats=False):
        if self._pipe is not None:
            # the schedule's microbatching subsumes grad_accum_steps
            # (absorbed in enable_pipeline); accum/carry paths don't apply
            return self._build_pipe_step(with_stats=with_stats)
        network, optimizer, meta = self.network, self.optimizer, self.meta
        # the ZeRO-1/FSDP updaters are drop-ins for the optimizer's
        # update protocol (optim/zero1.py); everything upstream of the
        # update — forward, backward, metrics — is shared. Under FSDP
        # the loss_fn additionally rebuilds each planned parameter from
        # its shards (full_params: one all-gather per layer) before the
        # forward, and the gradients flow back INTO the packed layout.
        updater = self._fsdp or self._zero1 or self.optimizer
        fsdp = self._fsdp
        accum_k = self.grad_accum_steps
        cost_name = self.topology.cost_name
        carry_layers = self._carry_layers
        # gradient_printer evaluators need d(cost)/d(layer output) FOR THE
        # BATCH BEING STEPPED (the reference prints Argument.grad during
        # that batch's backward). Probes ride the SAME backward pass, so
        # the printed grads belong to the pre-update parameters — a lazy
        # recompute after the update would be one step stale (and
        # pre-update params can't be kept around: they're donated).
        grad_watch = sorted({
            n for e, ins, _ in self._host_evals
            if getattr(e, "wants_grad", False) for n in ins
            if n in self.network.shape_infos})

        def loss_fn(params, feed, rng, carried, probes=None):
            if fsdp is not None:
                params = fsdp.full_params(params)
            outputs, updates = network.apply_with_state(
                self._cast_compute(params), self._cast_compute(feed),
                train=True, rng=rng, carried=carried, probes=probes,
                mesh=self.mesh)
            return (self._total_cost(outputs, self._row_mask(feed)),
                    (outputs, updates))

        def step(params, opt_state, feed, rng, num_passes, carried=None,
                 poison=None):
            if carried is not None:
                # truncated BPTT: no gradient across the batch boundary
                carried = jax.lax.stop_gradient(carried)
            probe_grads = None
            if grad_watch:
                shapes = jax.eval_shape(
                    lambda p: loss_fn(p, feed, rng, carried)[1][0], params)
                probes = {n: jnp.zeros(shapes[n].value.shape,
                                       shapes[n].value.dtype)
                          for n in grad_watch}
                (loss, (outputs, updates)), (grads, probe_grads) = \
                    jax.value_and_grad(loss_fn, argnums=(0, 4),
                                       has_aux=True)(
                        params, feed, rng, carried, probes)
            else:
                (loss, (outputs, updates)), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params, feed, rng, carried)
            grads = self._poison_grads(grads, poison)
            # grads are already f32 (cotangents take the f32 params' dtype);
            # only the moving-stat updates computed in bf16 need casting
            updates = self._cast_f32(updates)
            row_mask = self._row_mask(feed)
            # LIVE rows drive the lr schedule's sample count, not the
            # padded shape (sum_gradients scaling likewise)
            bsz = (jnp.sum(row_mask) if row_mask is not None
                   else outputs[cost_name].value.shape[0])
            new_params, new_opt = updater.update(
                grads, opt_state, params, meta, batch_size=bsz,
                num_passes=num_passes)
            new_params.update(updates)  # moving statistics (batch_norm)
            health = self._health_metrics(
                loss, params, grads, new_params, new_opt, num_passes,
                self._act_stat_table(outputs) if with_stats else None,
                with_stats)
            new_params, new_opt = self._apply_skip_select(
                health, params, opt_state, new_params, new_opt)
            metrics = self._metrics(outputs, feed)
            metrics.update(health)
            if carry_layers:
                graph = self.topology.graph

                def final_state(n):
                    s = outputs[n].state
                    # a recurrent group's .state also holds extra outputs;
                    # only its final scan carry crosses the batch boundary
                    if graph.layers[n].type == "recurrent_layer_group":
                        return s["final"]
                    return s

                metrics["carried"] = jax.lax.stop_gradient(
                    {n: final_state(n) for n in carry_layers})
            if probe_grads is not None:
                metrics["probe_grads"] = {
                    n: g.astype(jnp.float32)
                    for n, g in probe_grads.items()}
            return new_params, new_opt, metrics

        def accum_step(params, opt_state, feed, rng, num_passes,
                       carried=None, poison=None):
            """Microbatch gradient accumulation: ``lax.scan`` over k
            equal slices of the batch, one forward+backward per slice (so
            only one microbatch's activations are ever live), gradients
            SUMMED with full-batch denominators baked into each partial
            loss — the sum is exactly the single k×-batch step's mean
            gradient. Clipping/decay/schedules then run ONCE, inside the
            optimizer, on that accumulated gradient."""
            del carried  # rejected in _configure_step (truncated-BPTT
            # state cannot cross microbatches of disjoint rows)
            row_mask_full = self._row_mask(feed)
            total_live = (jnp.sum(row_mask_full)
                          if row_mask_full is not None else None)
            full_bsz = next(iter(feed.values())).value.shape[0]
            # trace-time constant: a partial tail batch k doesn't divide
            # scans fewer microbatches instead of aborting the pass
            k_eff = self._accum_k_for(full_bsz)
            micro_feed = self._split_microbatches(feed, k_eff)
            rngs = jax.random.split(rng, k_eff)

            def loss_micro(params, mfeed, mrng):
                if fsdp is not None:
                    # per-microbatch gather: the scan body re-gathers,
                    # so only one microbatch's full params are live
                    params = fsdp.full_params(params)
                outputs, updates = network.apply_with_state(
                    self._cast_compute(params), self._cast_compute(mfeed),
                    train=True, rng=mrng, mesh=self.mesh)
                return (self._total_cost(outputs, self._row_mask(mfeed),
                                         accum_k=k_eff,
                                         total_live=total_live),
                        (outputs, updates))

            def micro(g_acc, xs):
                mfeed, mrng = xs
                (loss, (outputs, updates)), grads = jax.value_and_grad(
                    loss_micro, has_aux=True)(params, mfeed, mrng)
                g_acc = jax.tree_util.tree_map(jnp.add, g_acc, grads)
                acts = (self._act_stat_table(outputs)
                        if with_stats else None)
                return g_acc, (loss, self._cast_f32(updates),
                               self._metrics(outputs, mfeed),
                               acts if acts is not None
                               else jnp.zeros((0, 3), jnp.float32))

            g_zero = jax.tree_util.tree_map(jnp.zeros_like, params)
            grads, (losses, updates_k, metrics_k, acts_k) = jax.lax.scan(
                micro, g_zero, (micro_feed, rngs))
            grads = self._poison_grads(grads, poison)
            # moving statistics (batch_norm): mean over microbatches —
            # for equal-size unmasked microbatches this IS the k×-batch
            # update (the EMA is affine in the batch mean)
            updates = jax.tree_util.tree_map(
                lambda x: jnp.mean(x, axis=0), updates_k)
            # partial losses already carry full-batch denominators: the
            # sum is the k×-batch cost
            metrics = {"cost": jnp.sum(losses)}
            for key, val in metrics_k.items():
                if key == "cost":
                    continue
                if isinstance(val, tuple):
                    # (sum, count) accumulator pairs: sum the k partials
                    metrics[key] = tuple(jnp.sum(x, axis=0) for x in val)
                elif key == "eval_outputs":
                    # per-row fetches: merge (k, b, ...) back to (B, ...)
                    # — bucket-padded dead rows sat at the end of the
                    # batch and end up at the end again, so the host-side
                    # live-prefix slice stays exact
                    metrics[key] = jax.tree_util.tree_map(
                        lambda x: x.reshape((-1,) + x.shape[2:]), val)
            bsz = total_live if total_live is not None else full_bsz
            new_params, new_opt = updater.update(
                grads, opt_state, params, meta, batch_size=bsz,
                num_passes=num_passes)
            new_params.update(updates)
            act_table = None
            if with_stats and acts_k.shape[1] > 0:
                # (k, L, 3)-stacked per-microbatch tables -> the
                # whole-batch view: max over microbatches is exact,
                # and the avg reweights each micro's masked mean by
                # its live-element count — the whole-batch masked mean
                # even when padded rows land unevenly across
                # microbatches (a plain mean-of-means would bias it)
                w = acts_k[:, :, 2]
                w_tot = jnp.maximum(jnp.sum(w, axis=0), 1.0)
                act_table = jnp.stack(
                    [jnp.sum(acts_k[:, :, 0] * w, axis=0) / w_tot,
                     jnp.max(acts_k[:, :, 1], axis=0), w_tot], axis=1)
            health = self._health_metrics(
                metrics["cost"], params, grads, new_params, new_opt,
                num_passes, act_table, with_stats)
            new_params, new_opt = self._apply_skip_select(
                health, params, opt_state, new_params, new_opt)
            metrics.update(health)
            return new_params, new_opt, metrics

        return jax.jit(accum_step if accum_k > 1 else step,
                       donate_argnums=(0, 1))

    def _build_eval_step(self):
        network = self.network

        def step(params, feed):
            # under the pipeline the params arrive stage-stacked; the
            # eval forward runs the plain (unpipelined) graph on the flat
            # view — jnp slicing, free at trace time
            outputs = network.apply(
                self._cast_compute(self._flat_params_view(params)),
                self._cast_compute(feed), train=False,
                mesh=self.mesh)
            return self._metrics(outputs, feed)

        return jax.jit(step)

    # ---------------------------------------------------------------- loop
    def enable_zero1(self):
        """Switch to the ZeRO-1 sharded optimizer update
        (``optim/zero1.py``): optimizer slots reshard to each device's 1/N
        partition of the data axis, the jitted step updates shard-wise and
        all-gathers the parameters. Bit-exact vs the replicated path; a
        no-op (with a warning) when there is no data-parallel axis to
        partition over. Parameters and the ``swig_api`` surface are
        untouched — only optimizer state changes layout."""
        if self._zero1 is not None:
            return
        from paddle_tpu.utils import logger
        if self._fsdp is not None:
            # subsumption, not composition-by-negotiation: the FSDP
            # updater already holds every planned slot at 1/N over the
            # fsdp axis — remember the request so disabling fsdp later
            # re-arms the plain zero1 layout instead of silently
            # dropping it
            logger.info(
                "zero1 requested with FSDP active — already subsumed "
                "(the fsdp updater partitions optimizer slots 1/N over "
                "the fsdp axis alongside the parameters)")
            self._zero1_subsumed = True
            return
        if self.mesh is None or mesh_lib.data_parallel_degree(self.mesh) <= 1:
            logger.warning(
                "zero1 requested but the mesh has no data-parallel axis "
                "to partition optimizer state over (mesh=%s) — keeping "
                "the replicated update", self.mesh)
            return
        from paddle_tpu.optim.zero1 import Zero1Updater
        self._zero1 = Zero1Updater(self.optimizer, self.mesh, self.params,
                                   self.meta, rules=self._shard_rules)
        self.opt_state = self._zero1.convert_state(self.opt_state)
        self._rebuild_train_step()

    def disable_zero1(self):
        """Back to the replicated update: gather the sharded slots to
        their full shapes, restore the rule-driven placement
        (``SpecLayout.place_opt_state``), drop the updater, rebuild the
        step. The inverse of :meth:`enable_zero1`, so A/B comparisons
        on one SGD instance measure what they claim to."""
        self._zero1_subsumed = False
        if self._zero1 is None:
            return
        self.opt_state = self._zero1.gather_opt_state(self.opt_state)
        self._zero1 = None
        if self.mesh is not None:
            self.opt_state = self.layout.place_opt_state(self.opt_state)
        self._rebuild_train_step()

    # ---------------------------------------------------------------- fsdp
    def enable_fsdp(self, overlap=None) -> bool:
        """Switch to full FSDP (``--fsdp``,
        ``optim/zero1.py:FsdpUpdater``): eligible parameters AND their
        optimizer slots reshard to flat-packed 1/N partitions of the
        mesh's ``fsdp`` axis, the jitted step gathers each parameter
        per layer on use, and the shard-wise update keeps everything
        sharded — a model ~N× one device's memory trains on an N-way
        fsdp axis. Eligibility comes from the canonical layout
        (``SpecLayout.fsdp_eligible``), so model-sharded tables and
        pipeline stage-stacked keys keep their own placement and the
        modes compose. ``overlap`` (``--fsdp_overlap``) picks the
        gather spelling: True (default) double-buffers the next
        parameter's all-gather behind the current layer's compute in
        the SpecLayout prefetch order, False keeps every gather
        synchronous, "force" stages the chain on any backend; None
        keeps the trainer's current mode. Returns True when FSDP is
        active; meshes without an fsdp axis (and models with model
        averaging) WARN and stand down — training continues with the
        replicated layout."""
        if overlap is not None:
            self._fsdp_overlap = overlap
        if self._fsdp is not None:
            if overlap is not None and \
                    self._fsdp.overlap_mode != self._fsdp_overlap:
                # same plan, different gather spelling: rebuild the
                # updater (cheap, no device ops) and re-jit
                self.disable_fsdp(_rearm_subsumed=False)
            else:
                return True
        from paddle_tpu.utils import logger
        if self.mesh is None or \
                dict(self.mesh.shape).get(mesh_lib.FSDP_AXIS, 1) <= 1:
            logger.warning(
                "fsdp requested but the mesh has no %r axis to "
                "partition parameters over (mesh=%s) — keeping the "
                "replicated parameter layout; build one with "
                "create_mesh(n_fsdp=N)", mesh_lib.FSDP_AXIS,
                dict(self.mesh.shape) if self.mesh is not None else None)
            return False
        if "avg" in self.opt_state:
            logger.warning(
                "fsdp requested but model averaging ('avg' optimizer "
                "state) is consumed whole at eval/save time and is not "
                "packed — keeping the replicated parameter layout")
            return False
        # zero1 composes by subsumption: unwind its batch-axis slot
        # layout first; the fsdp updater repartitions the same slots
        # over the fsdp axis next to their parameters
        if self._zero1 is not None:
            self.disable_zero1()
            self._zero1_subsumed = True
        from paddle_tpu.optim.zero1 import FsdpUpdater
        upd = FsdpUpdater(self.optimizer, self.mesh, self.params,
                          self.meta, rules=self._shard_rules,
                          overlap=self._fsdp_overlap, graph=self.network)
        self.params = upd.pack_params(self.params)
        self.opt_state = upd.convert_state(self.opt_state)
        self._fsdp = upd
        self.breakdown.set_fsdp(len(upd.plan), bool(upd.overlap_mode))
        logger.info(
            "fsdp enabled: %d parameters packed 1/%d over the %r axis "
            "(gather-on-use per layer, overlap=%s; slots follow)",
            len(upd.plan), upd.n, mesh_lib.FSDP_AXIS, upd.overlap_mode)
        self._rebuild_train_step()
        return True

    def disable_fsdp(self, _rearm_subsumed: bool = True):
        """Back to the replicated parameter layout: unpack every planned
        parameter and slot to full shapes, restore the rule-driven
        placement, drop the updater — and re-arm plain ZeRO-1 when it
        was subsumed by :meth:`enable_fsdp`. The inverse of
        ``enable_fsdp``, so A/B runs and checkpoint crossings measure
        what they claim to. ``_rearm_subsumed=False`` is the pipeline
        toggle's private spelling: fsdp re-enables right after the
        restack and re-subsumes directly, so the intermediate ZeRO-1
        repack/gather round trips of the whole slot state would be
        pure churn."""
        if self._fsdp is None:
            return
        self.opt_state = self._fsdp.gather_opt_state(self.opt_state)
        self.params = self._fsdp.unpack_params(self.params)
        resub, self._zero1_subsumed = self._zero1_subsumed, False
        self._fsdp = None
        self.breakdown.set_fsdp(0, False)
        if self.mesh is not None:
            self.params = self.layout.place_params(self.params)
            self.opt_state = self.layout.place_opt_state(self.opt_state)
        if resub and _rearm_subsumed:
            self.enable_zero1()
        self._rebuild_train_step()

    def _rebuild_train_step(self):
        self._train_step = self._build_train_step()
        self.recompile_guard = _prefetch.RecompileGuard(
            self._train_step, warn_after=self._recompile_warn)
        cfg = self._health_cfg
        if cfg is not None and cfg.period > 0:
            # the stats-on program variant: the SAME step with the
            # per-layer stat reduction fused in, pinned + guarded like
            # the hot variant; the loop warms it on the first batch so
            # no compile lands mid-run (warmed once, then zero growth)
            self._train_step_stats = self._build_train_step(
                with_stats=True)
            self.stats_recompile_guard = _prefetch.RecompileGuard(
                self._train_step_stats, warn_after=self._recompile_warn,
                name="train_step_stats")
            self._stats_warm_pending = True
        else:
            self._train_step_stats = None
            self.stats_recompile_guard = None
            self._stats_warm_pending = False

    # ------------------------------------------------------------ pipeline
    def enable_pipeline(self, microbatches: Optional[int] = None) -> bool:
        """Switch to the pipelined train step (``--parallel_nn``): stages
        derive from the config's per-layer ``device`` attrs
        (``parallel/pipeline.py:split_pipeline_graph``), body parameters
        and optimizer slots restructure to stage-stacked arrays sharded
        one stage per ``pipe`` mesh slot, and the jitted step runs the
        GPipe microbatch schedule with the cost head replicated on the
        body output. Gradient-exact vs the unpipelined step (full-batch
        denominators, clipping/decay once on the whole-batch gradient).

        Returns True when pipelining is active. Any config/mesh shape the
        schedule cannot honor WARNS and stands down (returns False,
        training continues unpipelined) — the reference's --parallel_nn
        likewise degrades to single-device execution when the config pins
        nothing."""
        from paddle_tpu.parallel.pipeline import PipelineTrainPlan
        from paddle_tpu.utils import logger
        if self._pipe is not None:
            if microbatches and microbatches != self._pipe_microbatches:
                self._pipe_microbatches = int(microbatches)
                self.breakdown.set_pipeline(self._pipe.S,
                                            self._pipe_microbatches)
                self._rebuild_train_step()
            return True

        def stand_down(msg, *args):
            logger.warning(
                "pipeline requested but " + msg +
                " — keeping the unpipelined step", *args)
            return False

        if self.mesh is None \
                or mesh_lib.PIPE_AXIS not in self.mesh.axis_names:
            return stand_down(
                "the mesh has no %r axis (mesh=%s); build one with "
                "create_mesh(n_pipe=<n_stages>)", mesh_lib.PIPE_AXIS,
                dict(self.mesh.shape) if self.mesh is not None else None)
        if self._carry_layers:
            return stand_down(
                "prev_batch_state carries recurrent state across batches; "
                "the pipeline scan cannot thread it")
        if "avg" in self.opt_state:
            return stand_down(
                "model averaging ('avg' optimizer state) is consumed "
                "whole at eval/save time and is not stage-stacked")
        if any(getattr(e, "wants_grad", False)
               for e, _, _ in self._host_evals):
            return stand_down(
                "gradient_printer evaluators probe layer-output gradients "
                "inside the body; probes do not thread through the "
                "pipeline scan")
        try:
            plan = PipelineTrainPlan(
                self.topology.graph, self.network, self.params, self.meta,
                self.mesh, mesh_lib.PIPE_AXIS,
                n_microbatches=microbatches)
        except ValueError as e:
            return stand_down("the config cannot pipeline: %s", e)
        head_set = set(plan.head)
        missing_cost = [c for c in self.topology.cost_names
                        if c not in head_set]
        if missing_cost:
            return stand_down(
                "cost layers %s carry device attrs (staged); the loss is "
                "not part of the repeated block — leave cost layers "
                "unpinned", missing_cost)
        off_head = [n for n in self._eval_layers
                    if n not in head_set and n != plan.body_out]
        if off_head:
            return stand_down(
                "evaluator inputs %s live inside the pipeline body; only "
                "the body output and head layers are fetched", off_head)
        ruled = [n for n in plan.body_pnames
                 if mesh_lib.rule_for(n, self._shard_rules) != P_spec()]
        if ruled:
            return stand_down(
                "body parameters %s carry shard rules; a stage owns its "
                "parameters whole (shard the head instead)", ruled[:3])
        sparse = [n for n in plan.body_pnames
                  if self.optimizer._is_sparse(self.meta.get(n))]
        if sparse:
            return stand_down(
                "body parameters %s take the sparse lazy update (per-row "
                "t_rows bookkeeping is not stage-stackable)", sparse[:3])

        # ZeRO-1/FSDP must wrap the STACKED layout: unwind them first,
        # re-arm after (their plans exclude the stacked keys via the
        # pipe pins the layout carries, and keep partitioning the
        # replicated head over their own axes). A SUBSUMED zero1 is
        # remembered, not re-armed: fsdp re-enables right after the
        # restack and subsumes it again — re-arming in between would
        # repack+gather the whole slot state twice for nothing.
        refsdp = self._fsdp is not None
        resub = refsdp and self._zero1_subsumed
        if refsdp:
            self.disable_fsdp(_rearm_subsumed=False)
        rezero = self._zero1 is not None
        if rezero:
            self.disable_zero1()
        needed = list(dict.fromkeys(
            list(self.topology.cost_names) + list(self._eval_layers)))
        self._pipe_head_net = plan.build_head_net(needed)
        self.params = plan.stack_params(self.params)
        self.opt_state = plan.stack_opt_state(self.opt_state)
        self._flat_meta = self.meta
        self.meta = plan.stacked_meta(self.meta)
        # the stage-stacked pins enter the CANONICAL layout, so every
        # downstream derivation (slot placement, ZeRO-1/FSDP
        # eligibility, PT505 hygiene) sees them through one table
        self.layout.pin(plan.shard_rules())
        self._pipe = plan
        if microbatches:
            self._pipe_microbatches = int(microbatches)
        elif self.grad_accum_steps > 1:
            # the pipeline's microbatching IS the gradient accumulation
            # (full-batch denominators, one clip/decay): absorb the knob
            logger.info(
                "pipeline: grad_accum_steps=%d absorbed as the microbatch "
                "count (the schedule accumulates per-microbatch gradients "
                "with full-batch denominators)", self.grad_accum_steps)
            self._pipe_microbatches = self.grad_accum_steps
        else:
            self._pipe_microbatches = plan.M  # plan default: M = S
        self.breakdown.set_pipeline(plan.S, self._pipe_microbatches)
        logger.info(
            "pipeline enabled: %d stages over the %r axis, %d "
            "microbatches, %s layout (bubble fraction %.3f)",
            plan.S, mesh_lib.PIPE_AXIS, self._pipe_microbatches,
            "stage-stacked" if plan.identical else
            "heterogeneous (replicated params)",
            (plan.S - 1) / (plan.S + self._pipe_microbatches - 1))
        if rezero:
            self.enable_zero1()
        if refsdp:
            self.enable_fsdp()
            self._zero1_subsumed = self._zero1_subsumed or resub
        self._rebuild_train_step()
        return True

    def disable_pipeline(self):
        """Back to the unpipelined step: unstack body parameters and
        slots to their flat per-stage names, restore rule-driven
        placement and the flat meta. The inverse of
        :meth:`enable_pipeline`, so resume and A/B runs cross pipeline
        on/off freely."""
        if self._pipe is None:
            return
        refsdp = self._fsdp is not None
        resub = refsdp and self._zero1_subsumed
        if refsdp:
            self.disable_fsdp(_rearm_subsumed=False)
        rezero = self._zero1 is not None
        if rezero:
            self.disable_zero1()
        plan = self._pipe
        self.layout.unpin(plan.shard_rules())
        self.params = plan.unstack_params(self.params)
        self.opt_state = plan.unstack_opt_state(self.opt_state)
        self.meta = self._flat_meta or self.meta
        self._flat_meta = None
        if self.mesh is not None:
            self.params = self.layout.place_params(self.params)
            self.opt_state = self.layout.place_opt_state(self.opt_state)
        self._pipe = None
        self._pipe_head_net = None
        self.breakdown.set_pipeline(0, 0)
        if rezero:
            self.enable_zero1()
        if refsdp:
            self.enable_fsdp()
            self._zero1_subsumed = self._zero1_subsumed or resub
        self._rebuild_train_step()

    def _flat_params_view(self, params=None):
        """Full flat view of the live params — fsdp-packed leaves
        gathered back to their parameter shapes and stage-stacked
        arrays unstacked to flat per-stage names. jnp ops, so it works
        both eagerly and under a trace; identity when neither mode is
        on. Eval, forward, merge, checkgrad and serving all read the
        model through this one view."""
        params = self.params if params is None else params
        if self._fsdp is not None:
            params = self._fsdp.unpack_params(params)
        if self._pipe is not None:
            params = self._pipe.unstack_params(params)
        return params

    def _configure_step(self, zero1: Optional[bool],
                        grad_accum_steps: Optional[int],
                        pipeline=None, fsdp: Optional[bool] = None,
                        fsdp_overlap=None):
        # pipeline first: zero1/fsdp must build their plans over the
        # final (possibly stage-stacked) parameter layout
        if pipeline is not None:
            if pipeline is False or pipeline == 0:
                # 0 (a CLI-derived int flag) means OFF, same as False —
                # not "enable with the default microbatch count"
                self.disable_pipeline()
            else:
                mb = None
                if isinstance(pipeline, dict):
                    mb = pipeline.get("microbatches")
                elif pipeline is not True and isinstance(pipeline, int):
                    mb = pipeline
                self.enable_pipeline(microbatches=mb)
        if grad_accum_steps is None:   # like zero1=None: keep current —
            # a later train() without the kwarg must not silently drop
            # accumulation (and 8x the activation memory)
            grad_accum_steps = self.grad_accum_steps
        if grad_accum_steps < 1:
            raise ValueError(f"grad_accum_steps must be >= 1, got "
                             f"{grad_accum_steps}")
        if grad_accum_steps > 1:
            if self._carry_layers:
                raise ValueError(
                    "grad_accum_steps > 1 is incompatible with "
                    "prev_batch_state: truncated-BPTT state cannot carry "
                    "across microbatches of disjoint rows")
            if any(getattr(e, "wants_grad", False)
                   for e, _, _ in self._host_evals):
                raise ValueError(
                    "grad_accum_steps > 1 is incompatible with "
                    "gradient_printer evaluators (per-batch output "
                    "gradients are not accumulated across microbatches)")
            bn = [n for n, ld in self.topology.graph.layers.items()
                  if ld.type in ("batch_norm", "cudnn_batch_norm",
                                 "batch_normalization")]
            if bn:
                from paddle_tpu.utils import logger
                logger.warning(
                    "grad_accum_steps > 1 with batch-stat layers %s: each "
                    "microbatch normalizes by ITS OWN batch statistics "
                    "(1/k of the rows), so the step is NOT exactly the "
                    "k×-batch step — the usual accumulation caveat, loud "
                    "here because the exactness claim holds only for "
                    "batch-stat-free models (moving averages are still "
                    "averaged across microbatches)", bn)
        if fsdp is True or (fsdp is None and fsdp_overlap is not None
                            and self._fsdp is not None):
            # fsdp on (or already on with a new overlap mode requested)
            self.enable_fsdp(overlap=fsdp_overlap)
        elif fsdp is False:
            self.disable_fsdp()    # None = keep the current mode
        elif fsdp_overlap is not None:
            self._fsdp_overlap = fsdp_overlap  # sticky for a later enable
        if zero1 is True:
            self.enable_zero1()
        elif zero1 is False:
            self.disable_zero1()   # None = keep the current mode
        if grad_accum_steps != self.grad_accum_steps:
            self.grad_accum_steps = grad_accum_steps
            self._rebuild_train_step()

    def _configure_health(self, health, show_parameter_stats_period=0):
        """Arm/disarm the training-health plane. Tri-state like zero1:
        ``None`` keeps the current mode, ``False`` disarms, a
        ``HealthConfig``/dict arms. A bare
        ``show_parameter_stats_period > 0`` arms the in-step telemetry
        on that period (the dedupe: the periodic parameter dump reads
        the fused reduction instead of running a second program), and
        fills the period of an explicit config that left it 0. A config
        change rebuilds the step variants; the monitor (and its
        counters/snapshot) survives unchanged configs across train()
        calls."""
        import dataclasses as _dc

        from paddle_tpu.obs.health import HealthConfig, HealthMonitor
        from paddle_tpu.utils import logger
        cfg = self._health_cfg
        if health is False:
            cfg = None
        elif health is not None:
            cfg = HealthConfig.coerce(health)
        if show_parameter_stats_period:
            if cfg is None:
                cfg = HealthConfig(
                    period=int(show_parameter_stats_period))
            elif cfg.period == 0:
                cfg = _dc.replace(
                    cfg, period=int(show_parameter_stats_period))
            elif cfg.period != int(show_parameter_stats_period):
                # the dump reads the telemetry's period-N snapshot:
                # with misaligned cadences a dump line can be up to
                # N-1 batches stale — loud, not silent
                logger.warning(
                    "show_parameter_stats_period=%d but the health "
                    "telemetry period is %d: the periodic parameter "
                    "dump reads the in-step snapshot, which refreshes "
                    "every %d batches — align the periods (or drop "
                    "the explicit health period) for current-step "
                    "dumps", show_parameter_stats_period, cfg.period,
                    cfg.period)

        def graph_sig(c):
            # the compiled-program-affecting subset: the sentry
            # scalars + threshold + skip-select policy, and WHETHER a
            # stats variant exists. Host-only fields (log_path,
            # log_clipping, service, the period VALUE) must not cost
            # a recompile of warmed variants.
            if c is None:
                return None
            return (c.sentry, c.grad_threshold, c.policy, c.period > 0)

        rebuild = graph_sig(cfg) != graph_sig(self._health_cfg)
        self._health_cfg = cfg
        if cfg is None:
            self._health = None
        elif self._health is None:
            self._health = HealthMonitor(cfg)
        else:
            # keep the monitor (counters, snapshots, timeline tail)
            # across config tweaks — one training session, one story;
            # open_timeline() picks up a changed log_path next train()
            self._health.cfg = cfg
        if rebuild:
            self._rebuild_train_step()

    def _health_step(self, hm, sentry_host, health_raw, health_lr, cost,
                     pass_id, batch_id, reader, prev_rng) -> bool:
        """Host side of one armed step: fetch the sentry scalars,
        convert the stats-on snapshot, apply the sentry policy, append
        the timeline record. Returns True when the batch was skipped
        (``skip_batch`` trip: the in-graph select already discarded the
        update; here the RNG split rolls back and the caller skips
        accumulation/carry, so the trajectory is bitwise the run that
        never saw the batch)."""
        bd = self.breakdown
        cfg = self._health_cfg
        param_snap = act_snap = None
        if health_raw is not None:
            # two packed tables -> the reader-facing dicts (name order
            # was recorded at trace time)
            table, act = jax.device_get((health_raw["param_table"],
                                         health_raw["act_table"]))
            param_snap = {}
            for i, n in enumerate(self._health_param_names):
                vals = table[i]
                d = {"avg_abs": float(vals[0]),
                     "max_abs": float(vals[1]),
                     "norm": float(vals[2]),
                     "grad_norm": float(vals[3]),
                     "update_ratio": float(vals[4]),
                     "size": int(self.params[n].size)}
                if vals[5] >= 0:
                    d["touched_rows"] = float(vals[5])
                param_snap[n] = d
            act_snap = {n: {"avg_abs": float(act[i, 0]),
                            "max_abs": float(act[i, 1])}
                        for i, n in enumerate(self._health_act_names)}
        grad_absmax = None
        tripped = False
        if sentry_host is not None:
            trip, gmax = jax.device_get((sentry_host["trip"],
                                         sentry_host["grad_absmax"]))
            tripped = bool(trip)
            grad_absmax = float(gmax)
        skipped = False
        if tripped:
            per_vec = jax.device_get(sentry_host["layer_grad_absmax"])
            per = {n: float(per_vec[i])
                   for i, n in enumerate(self._health_param_names)}
            policy = hm.on_divergence(
                pass_id=pass_id, batch_id=batch_id, loss=cost,
                grad_absmax=grad_absmax, layer_grad_absmax=per,
                rng=np.asarray(jax.device_get(prev_rng)).tolist(),
                ledger=getattr(reader, "ledger_state", None),
                param_stats=param_snap, act_stats=act_snap)
            skipped = policy == "skip_batch"
            if skipped:
                # the clean run never split a key for this batch
                self._rng = prev_rng
        hm.on_step(pass_id=pass_id, batch_id=batch_id, loss=cost,
                   lr=(float(health_lr) if health_lr is not None
                       else None),
                   grad_absmax=grad_absmax,
                   data_wait_ms=bd.last.get("data_wait", 0.0) * 1e3,
                   compute_ms=bd.last.get("compute", 0.0) * 1e3,
                   param_stats=param_snap, act_stats=act_snap,
                   skipped=skipped)
        if tripped and cfg.policy == "halt":
            from paddle_tpu.obs.health import DivergenceError
            raise DivergenceError(
                f"divergence sentry tripped at pass={pass_id} "
                f"batch={batch_id}: loss={cost!r} "
                f"max|grad|={grad_absmax!r} (postmortem: "
                f"{hm.last_postmortem})")
        return skipped

    def _opt_state_for_save(self):
        """Checkpoint view of the optimizer state: with ZeRO-1 active the
        sharded slots are gathered back to their parameters' full shapes,
        and with the pipeline active the stage-stacked slot dicts unstack
        to their flat per-stage names — the file format (keys AND array
        shapes) never depends on the update path, so resume crosses
        sharded<->replicated and pipelined<->unpipelined in any
        combination."""
        state = self.opt_state
        if self._fsdp is not None:
            state = self._fsdp.gather_opt_state(state)
        if self._zero1 is not None:
            state = self._zero1.gather_opt_state(state)
        if self._pipe is not None:
            state = self._pipe.unstack_opt_state(state)
        return state

    def _params_for_save(self):
        """Checkpoint view of the parameters: fsdp-packed leaves gather
        to full shapes and stage-stacked body params unstack to the
        flat per-stage names (``_blk3.w0`` etc.) — the on-disk format
        (keys AND shapes) never depends on the run's layout, so resume
        crosses fsdp/pipeline on/off in any combination."""
        return self._flat_params_view()

    def _trainer_state_for_save(self):
        """The exact-resume state inventory beyond params/opt_state: the
        step RNG key (split once per batch — a resumed run must continue
        the same key stream) and the truncated-BPTT carried state (the
        previous batch's final recurrent state, mid-pass only). The LR
        schedule's step/sample counters live inside opt_state and ride
        the normal save; the reader's position rides the ``ledger``.
        See docs/fault_tolerance.md for the full inventory."""
        state = {"rng": np.asarray(jax.device_get(self._rng))}
        if self._carried is not None:
            state["carried"] = self._carried
        return state

    def train(self, reader, *, feeder=None, num_passes: int = 1,
              event_handler: Optional[Callable] = None,
              log_period: int = 0, checkpointer=None,
              dot_period: int = 0, show_parameter_stats_period: int = 0,
              show_layer_stat: bool = False,
              async_load_data: bool = False, prefetch_depth: int = 2,
              show_step_breakdown: bool = False,
              zero1: Optional[bool] = None,
              grad_accum_steps: Optional[int] = None,
              pipeline=None, auto_resume: bool = True,
              health=None, fsdp: Optional[bool] = None,
              fsdp_overlap=None):
        """reader yields minibatches (lists of sample tuples); feeder
        converts them to Arguments (or pass feed dicts directly).
        ``log_period``>0 logs a TrainerStats-style line and dumps+resets the
        timer registry every N batches (``TrainerInternal.cpp:160-170``,
        ``Trainer.cpp:443-451``); ``dot_period``>0 prints a progress dot
        every N batches (``--dot_period``, ``Flags.cpp``);
        ``show_parameter_stats_period``>0 logs the parameter health dump
        every N batches (``showParameterStats``,
        ``TrainerInternal.cpp:81-88``); ``show_layer_stat`` logs every
        layer output's mean/abs-max at each log_period
        (``--show_layer_stat``, ``Flags.cpp:71``). ``checkpointer``
        (dist.Checkpointer) restores the newest intact checkpoint before
        training (``auto_resume=False`` makes it save-only, the
        ``--no-auto_resume`` CLI spelling) — resuming at the pass after
        the saved one, the ``--start_pass`` semantics of
        ``Trainer.cpp:229-250`` — and saves on its cadence at batch and
        pass boundaries. Resume is EXACT: checkpoints carry the step
        RNG key, carried BPTT state, LR-schedule counters (inside
        opt_state) and the data position — a plain deterministic reader
        is fast-forwarded to the checkpoint's batch (prepared batches
        discarded, not trained), a pass-aware master reader restores
        its task ledger through ``resume_lease`` and has its finishes
        committed only after each checkpoint is durable — so a
        killed-and-resumed run is bitwise the uninterrupted one
        (tests/test_exact_resume_matrix.py, docs/fault_tolerance.md).
        A pass-aware reader's ``sync_pass`` also reconciles the start
        pass with the master's authoritative pass, so a resumed trainer
        neither replays nor starves on passes the cluster already
        resolved.

        ``async_load_data`` (the reference's ``--use_async_load_data``,
        ``DataProvider.h:249``) runs decode → pad/bucket → shard →
        device_put in a background thread with ``prefetch_depth`` batches
        in flight (``data/prefetch.py``), overlapping host data work with
        device compute. A reader already wrapped by ``prefetch_reader``
        (``is_prefetched``) yields ready feeds and is consumed as such.
        ``show_step_breakdown`` logs the per-step host-time split
        {data_wait, h2d, compute, callback} at each log_period and pass
        end (``utils/profiler.py:StepBreakdown``; always accumulated —
        the flag only controls logging) plus the per-device
        parameter/optimizer-slot byte accounting
        (``utils/profiler.py:memory_stats``).

        ``zero1`` (the ``--use_zero1`` flag) partitions optimizer state
        over the mesh's data axis — each device holds 1/N of every slot,
        updates its shard, and all-gathers the parameters (ZeRO-1; the
        reference pserver's sharded update, ``ParameterServer2.cpp:362``).
        Tri-state: ``True`` enables, ``False`` disables (resharding the
        slots back), ``None`` (default) keeps the current mode.
        ``fsdp`` (the ``--fsdp`` flag) goes further: eligible
        PARAMETERS (not just slots) live flat-packed 1/N over the
        mesh's dedicated ``fsdp`` axis with one all-gather per layer on
        use and gradients reduce-scattered back into the packed layout
        (``optim/zero1.py:FsdpUpdater``; ``docs/spec_layout.md``), so a
        model ~N× one device's memory trains on the mesh. Same
        tri-state; composes with ``pipeline`` (stage-stacked body keys
        keep their pipe placement, the head shards over fsdp),
        seq-parallel, and ``zero1`` (subsumed: slots already ride the
        fsdp partition). Meshes without an fsdp axis
        (``create_mesh(n_fsdp=N)``) warn and stand down. Checkpoints
        stay format-compatible (gather-on-save, reshard-on-load), so
        resume crosses fsdp on/off in both directions.
        ``fsdp_overlap`` (the ``--fsdp_overlap`` flag) picks the fsdp
        gather spelling: ``True`` (the default mode) double-buffers
        each next parameter's all-gather behind the current layer's
        compute — and, by transposition, each backward reduce-scatter
        behind the previous layer's backward — in the SpecLayout
        prefetch order (``optim/zero1.py:FsdpUpdater.full_params``);
        ``False`` keeps every gather synchronous; ``"force"`` stages
        the overlap chain on any backend (tests/bench — normally the
        chain is TPU-only so CPU audit compiles pin one program);
        ``None`` keeps the current mode. Bitwise-identical training
        trajectory either way (the chain is an
        ``optimization_barrier``, identity on values;
        ``tests/test_fsdp_overlap_matrix.py``).
        ``grad_accum_steps`` (``--grad_accum_steps``) splits each batch
        into k microbatches scanned inside the jitted step, applying the
        optimizer (and clipping/decay) once on the accumulated gradient —
        effective batch size decouples from per-device activation
        memory. Like ``zero1``, sticky: ``None`` (default) keeps the
        previously configured value.

        ``health`` arms the training-health plane
        (``obs/health.py:HealthConfig`` or a kwargs dict; tri-state
        like ``zero1``: ``None`` keeps, ``False`` disarms). While the
        telemetry period is armed — explicitly, or implicitly by
        ``show_parameter_stats_period`` — per-layer param/grad/update/
        activation stats fold INTO the compiled step every Nth batch
        (no second forward: the periodic dumps and
        ``parameter_stats()``/``layer_stats()`` read the in-step
        values), each step appends to the JSONL event timeline when
        ``log_path`` is set, and the divergence sentry (finiteness +
        ``grad_threshold`` on loss/grads, the reference's
        ``--error_clipping_threshold``) applies its policy on a trip:
        ``halt`` | ``skip_batch`` (discard the batch's update in-graph
        and roll the RNG split back — bitwise the run that never saw
        the batch) | ``dump``; every trip writes a postmortem bundle
        and a ``train.divergence`` flight event
        (docs/observability.md, pillar 4).

        ``pipeline`` (the reference-spelled ``--parallel_nn`` flag,
        ``Flags.cpp:23`` / ``ParallelNeuralNetwork.h:23-62``) runs the
        config's device-attr-staged body through the GPipe microbatch
        schedule on the mesh's ``pipe`` axis (``enable_pipeline``).
        ``True`` enables with the default microbatch count (S, or the
        configured grad_accum_steps), an int or ``{"microbatches": k}``
        sets it, ``False`` disables (unstacking the body back to flat
        parameters), ``None`` keeps the current mode. Configs or meshes
        the schedule cannot honor warn and stand down cleanly."""
        from paddle_tpu.utils import global_stat, logger, timer
        self._configure_step(zero1, grad_accum_steps, pipeline, fsdp,
                             fsdp_overlap)
        self._configure_health(health, show_parameter_stats_period)
        hm = self._health
        if hm is not None:
            hm.open_timeline()
        if async_load_data and getattr(reader, "pass_aware", False):
            # the prefetch worker would advance the master reader's task
            # ledger (finishes, in-flight offset) ahead of training by
            # the queue depth; a mid-pass checkpoint would then record
            # prefetched-but-untrained records as consumed and resume
            # would skip them — breaking exact resume AND at-least-once.
            logger.warning(
                "async_load_data: pass-aware master readers are consumed "
                "synchronously (the task ledger must track TRAINED "
                "position, not prefetch position) — ignoring the flag "
                "for this reader")
            async_load_data = False
        start_pass = 0
        resume_base = 0       # batch_id numbering continues here
        resume_skip = 0       # prepared batches to discard, not train
        resume_carried = None
        if checkpointer is not None:
            # commit the master's task ledger only once the checkpoint
            # holding that work is DURABLE (the writer calls on_save
            # after fsync+rename — possibly from its background thread)
            commit = getattr(reader, "commit_ledger", None)
            # couple when the slot is free OR holds a previous train()
            # call's coupling (same Checkpointer reused across runs: the
            # stale closure would commit to the old run's — likely
            # closed — master client and this reader would never couple);
            # a user-provided callback is never clobbered
            if commit is not None and (
                    getattr(checkpointer, "on_save", None) is None or
                    getattr(checkpointer.on_save, "_reader_coupled",
                            False)):
                def _commit_on_save(meta):
                    commit(meta.get("ledger"))
                _commit_on_save._reader_coupled = True
                checkpointer.on_save = _commit_on_save
                # the reader must NOT also commit at its pass end: the
                # durable-save callback owns commits now
                reader.checkpoint_coupled = True
                # the master's durability-gated pass roll waits on this
                # trainer's parked finishes; if the background writer
                # died no on_save will ever commit them, and each poll
                # of the wait renews our liveness so even the lease
                # timeout cannot free the work. Let the wait loop see
                # the writer's error instead of spinning forever.
                if hasattr(reader, "health_check") and \
                        hasattr(checkpointer, "poll_error"):
                    reader.health_check = checkpointer.poll_error
        else:
            commit = None
        # what this process can prove it trained of the pass it is
        # about to (re)start: nothing, until a mid-pass checkpoint
        # says otherwise. A pass-aware reader sends this to the
        # master (resume_lease) so work a previous life finished
        # beyond the restored checkpoint is requeued, its stale
        # lease voided, and dispatch order restored — without it a
        # crashed-then-restarted trainer starves on (or replays out
        # of order) its own requeued tasks.
        ledger = {"pass": 0, "done": [], "inflight": None, "offset": 0}
        restored_from_disk = False
        if checkpointer is not None and auto_resume:
            restored = checkpointer.restore()
            if restored is not None:
                restored_from_disk = True
                r_params, r_opt, meta = restored
                self.load_state(r_params, r_opt)
                tstate = meta.get("trainer_state") or {}
                if "rng" in tstate:
                    # continue the uninterrupted run's key stream, not a
                    # fresh seed's (dropout etc. stay bitwise on track)
                    self._rng = jnp.asarray(np.asarray(tstate["rng"]))
                pid = int(meta.get("pass_id", -1))
                if meta.get("end_of_pass", meta.get("batch_id", 0) == 0):
                    start_pass = pid + 1
                    led = meta.get("ledger")
                    if led:
                        # the completed pass's ledger: its commit may
                        # have been lost between the fsync and the
                        # commit RPC — the reader re-marks that work
                        # done on the master (no-op if the pass
                        # already rolled)
                        ledger = led
                    else:
                        ledger["pass"] = start_pass
                else:
                    # mid-pass (batch-cadence) checkpoint: resume INSIDE
                    # that pass at the exact batch. A pass-aware master
                    # reader restores its task ledger (resume_lease
                    # re-marks consumed tasks done and requeues this
                    # trainer's post-checkpoint work — the old "remaining
                    # tasks only" caveat is gone); a plain deterministic
                    # reader is fast-forwarded past the already-trained
                    # batches instead.
                    start_pass = pid
                    resume_base = int(meta.get("batch_id", 0))
                    if getattr(reader, "pass_aware", False):
                        ledger = meta.get("ledger") or dict(
                            ledger, **{"pass": start_pass})
                    else:
                        resume_skip = resume_base
                        logger.warning(
                            "mid-pass resume fast-forwards %d batches of "
                            "a plain reader: this assumes the reader "
                            "replays the SAME batch order as the "
                            "interrupted run — one that shuffles "
                            "differently per process silently drops "
                            "untrained records. Seed the shuffle, use a "
                            "master reader (task-ledger resume), or save "
                            "only at pass boundaries", resume_base)
                    carried = tstate.get("carried")
                    if carried is not None:
                        resume_carried = jax.tree_util.tree_map(
                            jnp.asarray, carried)
        if getattr(reader, "pass_aware", False) and \
                hasattr(reader, "restore_ledger") and \
                (restored_from_disk or
                 not getattr(reader, "_ledger_reconciled", False)):
            # armed on a FRESH start too (not just an actual restore —
            # and regardless of auto_resume or a checkpointer at all):
            # a previous life under the same trainer id that died
            # before its first durable checkpoint leaves finishes
            # parked on the master — invisible to this process, yet
            # its own polling renews the liveness that would otherwise
            # expire them. Gated behind auto_resume, a
            # --no-auto_resume restart with a stable trainer id would
            # livelock the durability-gated pass roll on exactly that
            # parked work. The empty-ledger reconcile requeues the
            # lost work (it was trained into parameters that no longer
            # exist) and no-ops on a genuine first boot; it re-sorts
            # only its own requeued slice, so queue state other
            # trainers depend on keeps its order. ONCE per reader: a
            # later train() on the same reader is a continuation, not
            # a previous life — an empty re-reconcile would requeue
            # (and silently retrain) everything this very process
            # already finished in the current pass. Only an actual
            # disk restore re-arms, with the restored ledger.
            reader.restore_ledger(ledger)
            reader._ledger_reconciled = True
        if getattr(reader, "sync_pass", None):
            # the master's pass counter is authoritative: a resumed
            # trainer whose cluster moved on must neither replay passes
            # that are fully resolved nor starve through them one empty
            # reader call at a time
            synced = int(reader.sync_pass(start_pass))
            if synced != start_pass:
                logger.info(
                    "resume: master is at pass %d (checkpoint suggested "
                    "%d) — following the master", synced, start_pass)
                start_pass = synced
                resume_base = resume_skip = 0
                resume_carried = None
        event_handler = event_handler or (lambda e: None)
        acc = Accumulator()
        bd = self.breakdown
        bd.reset()
        # a prefetch_reader-wrapped reader already yields prepared,
        # device-placed feeds; async_load_data wraps a plain reader here
        pre_prepared = bool(getattr(reader, "is_prefetched", False))
        if pre_prepared and feeder is not None:
            raise ValueError(
                "feeder would be silently ignored: this reader is already "
                "prefetched — pass the feeder to prefetch_reader(...) "
                "instead")
        loop_ok = False
        unwind_exc = None
        try:
            for pass_id in range(start_pass, num_passes):
                event_handler(ev.BeginPass(pass_id))
                acc.reset()
                self._start_host_evaluators()
                # reference resets RNN state per pass; a mid-pass resume
                # reinstates the checkpointed carry instead
                resuming = pass_id == start_pass and resume_base > 0
                self._carried = resume_carried if resuming else None
                window_cost, window_n = 0.0, 0
                dots_pending = False
                pipe = None
                if async_load_data and not pre_prepared:
                    from paddle_tpu.data.prefetch import PrefetchPipeline
                    pipe = PrefetchPipeline(
                        lambda: _call_reader(reader, pass_id), feeder=feeder,
                        mesh=self.mesh, depth=prefetch_depth)
                    stream = iter(pipe)
                else:
                    stream = iter(_call_reader(reader, pass_id))
                batch_id = -1
                if resuming:
                    # exact-resume replay: discard the already-trained prefix
                    # (plain readers; a ledger-restored master reader yields
                    # only untrained records, so resume_skip is 0) and keep
                    # the uninterrupted run's batch numbering so checkpoint
                    # cadence and logs stay aligned
                    for _ in range(resume_skip):
                        if next(stream, _END_OF_PASS) is _END_OF_PASS:
                            break
                    batch_id = resume_base - 1
                try:
                    while True:
                        t_step = time.perf_counter()
                        # blocked-on-data time: the sync reader's own cost, or
                        # the prefetch queue wait (near zero once it keeps up)
                        with bd.measure("data_wait"):
                            data = next(stream, _END_OF_PASS)
                        if data is _END_OF_PASS:
                            break
                        batch_id += 1
                        event_handler(ev.BeginIteration(pass_id, batch_id))
                        if pipe is not None or pre_prepared:
                            feed = data  # decoded + sharded by the worker thread
                        else:
                            with bd.measure("h2d"), timer("prepareBatchData"):
                                feed = feeder(data) if feeder is not None else data
                                if self.mesh is not None:
                                    feed = mesh_lib.shard_batch(feed, self.mesh)
                        prev_rng = self._rng  # skip_batch rolls back here
                        self._rng, step_rng = jax.random.split(self._rng)
                        if self._carried is not None:
                            # a batch-size change (e.g. smaller final batch) makes
                            # the carried state unusable: reset, like the
                            # reference's resetState on shape change
                            b_feed = next(iter(feed.values())).value.shape[0]
                            b_carry = jax.tree_util.tree_leaves(
                                self._carried)[0].shape[0]
                            if b_carry != b_feed:
                                self._carried = None
                        stats_on = self._train_step_stats is not None and (
                            (batch_id + 1) % self._health_cfg.period == 0
                            or self._stats_warm_pending)
                        self._stats_warm_pending = False
                        poison = None
                        if hm is not None and self._health_cfg.sentry:
                            fired = ()
                            if _chaos._ACTIVE is not None:
                                # the health plane's own chaos site: a
                                # `corrupt` fault here poisons one
                                # gradient leaf IN-GRAPH (the traced
                                # `poison` scalar), the divergence-
                                # sentry drill
                                fired = _chaos._ACTIVE.hit(
                                    "step_stats", pass_id=pass_id,
                                    batch_id=batch_id) or ()
                            poison = jnp.float32(
                                1.0 if "corrupt" in fired else 0.0)
                        with bd.measure("compute"), timer("trainBatch"):
                            step_fn = (self._train_step_stats if stats_on
                                       else self._train_step)
                            if hm is not None:
                                self.params, self.opt_state, metrics = \
                                    step_fn(self.params, self.opt_state,
                                            feed, step_rng,
                                            jnp.int32(pass_id),
                                            self._carried, poison)
                            else:
                                self.params, self.opt_state, metrics = \
                                    step_fn(self.params, self.opt_state,
                                            feed, step_rng,
                                            jnp.int32(pass_id),
                                            self._carried)
                            # a real host fetch: on remote devices
                            # block_until_ready returns before execution finishes
                            cost = float(metrics["cost"])
                        (self.stats_recompile_guard if stats_on
                         else self.recompile_guard).check()
                        t_cb = time.perf_counter()
                        sentry_host = metrics.pop("sentry", None)
                        health_raw = metrics.pop("health", None)
                        health_lr = metrics.pop("health_lr", None)
                        skipped = False
                        if hm is not None:
                            skipped = self._health_step(
                                hm, sentry_host, health_raw, health_lr,
                                cost, pass_id, batch_id, reader,
                                prev_rng)
                        if self._carry_layers:
                            carried_new = metrics.pop("carried")
                            if not skipped:
                                self._carried = carried_new
                        if skipped:
                            # the clean run never saw this batch:
                            # nothing accumulates, the log window and
                            # host evaluators stay untouched
                            evals = acc.result()
                        else:
                            evals = self._accumulate(acc, metrics)
                            self._feed_host_evaluators(metrics, feed=feed,
                                                       rng=step_rng)
                            window_cost += cost
                            window_n += 1
                        if dot_period and (batch_id + 1) % dot_period == 0:
                            print(".", end="", flush=True)
                            dots_pending = True
                        stats_due = show_parameter_stats_period and \
                            (batch_id + 1) % show_parameter_stats_period == 0
                        log_due = log_period and (batch_id + 1) % log_period == 0
                        if dots_pending and (stats_due or log_due):
                            print(flush=True)  # newline before the periodic lines
                            dots_pending = False
                        if stats_due:
                            for pname, st in self.parameter_stats().items():
                                logger.info(
                                    "Param %s: %s", pname,
                                    " ".join(f"{k}={v:.5g}"
                                             for k, v in st.items()))
                        if log_due:
                            # Cost is windowed (reset each log_period); AvgEval is
                            # cumulative since pass start, like the reference's
                            # "Eval:" vs "CurrentEval:" split (TrainerInternal.cpp).
                            logger.info(
                                "Pass=%d Batch=%d Cost=%.5f AvgEval: %s", pass_id,
                                batch_id + 1,
                                window_cost / max(window_n, 1),
                                " ".join(f"{k}={v:.5g}" for k, v in
                                         {**evals, **self.host_eval_values(
                                             include_printers=False)}.items()))
                            if show_step_breakdown:
                                from paddle_tpu.utils.profiler import \
                                    memory_status
                                logger.info("%s", bd.status())
                                logger.info("%s", memory_status(
                                    self.params, self.opt_state,
                                    gather_peak=self._gather_peak()))
                            logger.info("\n%s", global_stat.status(reset=True))
                            window_cost, window_n = 0.0, 0
                            if show_layer_stat:
                                for lname, st in self.layer_stats(feed).items():
                                    logger.info(
                                        "Layer %s: avg_abs=%.5g max_abs=%.5g",
                                        lname, st["avg_abs"], st["max_abs"])
                        event_handler(ev.EndIteration(pass_id, batch_id, cost, evals))
                        if _chaos._ACTIVE is not None:
                            # a kill here dies BEFORE this batch could
                            # checkpoint → resume replays it
                            _chaos._ACTIVE.hit("step", pass_id=pass_id,
                                               batch_id=batch_id)
                        if checkpointer is not None:
                            # the callables defer the (device-op) ZeRO-1 slot
                            # gather / pipeline unstack to saves actually due
                            checkpointer.maybe_save(
                                self._params_for_save,
                                self._opt_state_for_save,
                                pass_id=pass_id, batch_id=batch_id + 1,
                                trainer_state=self._trainer_state_for_save,
                                ledger=getattr(reader, "ledger_state", None))
                        if _chaos._ACTIVE is not None:
                            # a kill here dies AFTER the cadence ran → resume
                            # restores the generation just written
                            _chaos._ACTIVE.hit("step_done", pass_id=pass_id,
                                               batch_id=batch_id)
                        bd.add("callback", time.perf_counter() - t_cb)
                        # true wall denominator: work outside the four
                        # brackets (BeginIteration handlers, rng split) shows
                        # as a shortfall from 1.0 instead of inflating steps/s
                        bd.step_done(time.perf_counter() - t_step)
                finally:
                    # the worker must not outlive this pass — a raising
                    # event handler / step / checkpointer (or Ctrl-C)
                    # would otherwise leak a thread holding `depth`
                    # device batches until GC (and a traceback pinning the
                    # frame defeats GC entirely)
                    if pipe is not None:
                        pipe.close()
                    close = getattr(stream, "close", None)
                    if close is not None:
                        close()  # a prefetch_reader stream: its generator's
                        # finally closes the pipeline it owns; harmless on
                        # plain generators
                if dots_pending:
                    print(flush=True)  # close the dot line at pass end
                # apply deferred sparse-row updates so the pass ends with
                # current tables (reference catchUpWith before eval/save);
                # routed through the active updater so a zero1 state always
                # goes through the delegate that understands its layout
                self.params, self.opt_state = (
                    self._fsdp or self._zero1 or self.optimizer).catch_up(
                    self.params, self.opt_state, self.meta,
                    num_passes=pass_id)
                if show_step_breakdown:
                    from paddle_tpu.utils.profiler import memory_status
                    logger.info("%s", bd.status())
                    logger.info("%s", memory_status(
                        self.params, self.opt_state,
                        gather_peak=self._gather_peak()))
                event_handler(ev.EndPass(
                    pass_id, {**acc.result(), **self.host_eval_values()}))
                if checkpointer is not None:
                    # the pass-boundary save carries the COMPLETED pass's
                    # ledger: if the crash lands in the durable-but-
                    # uncommitted window (fsync done, commit RPC lost)
                    # the restarted trainer re-marks that work done via
                    # resume_lease — without it the finishes sit parked
                    # under a liveness the restarted process itself keeps
                    # renewing (stable trainer id), holding the
                    # durability-gated roll of a pass its restored
                    # parameters fully contain
                    saved = checkpointer.maybe_save(
                        self._params_for_save, self._opt_state_for_save,
                        pass_id=pass_id, end_of_pass=True,
                        trainer_state=self._trainer_state_for_save,
                        ledger=getattr(reader, "ledger_state", None))
                    if not saved and commit is not None and \
                            getattr(reader, "checkpoint_coupled", False):
                        # no checkpoint was due this pass, so no on_save
                        # will ever commit its finishes — commit now or the
                        # master's durability-gated pass roll waits forever.
                        # Recovery for this pass falls back to the older
                        # generation (plain at-least-once, the cadence the
                        # user chose with saving_period>1).
                        commit(None)
            loop_ok = True
        except BaseException as e:
            unwind_exc = e
            raise
        finally:
            if self._health is not None:
                # drain the event timeline's background writer so the
                # run's JSONL artifact is complete even when the loop
                # unwinds; the monitor (counters, stat snapshots)
                # stays armed for the next train()/reader calls
                self._health.close()
            flush_exc = None
            if checkpointer is not None:
                try:
                    if hasattr(checkpointer, "flush"):
                        # drain background writes even when the loop
                        # unwinds (chaos kill, NaN anomaly,
                        # KeyboardInterrupt): every generation
                        # maybe_save() queued must become durable — a
                        # sync run would have had them on disk already.
                        # When ALREADY unwinding, a writer error must
                        # not replace the exception that actually
                        # killed the run (finally semantics would also
                        # downgrade a chaos-kill BaseException to a
                        # plain RuntimeError). The flag, not
                        # sys.exc_info(), decides: train() called
                        # inside a caller's except block has ambient
                        # exc_info even on a clean run, and a clean run
                        # must NOT swallow the error.
                        try:
                            checkpointer.flush()
                        except Exception as flush_err:
                            if loop_ok:
                                # a clean run's flush error IS the
                                # surfaced failure — but it must not
                                # skip the lease release below: this
                                # process (and its heartbeat) lives
                                # on, so nothing else can ever free
                                # the parked finishes whose commit the
                                # dead writer just lost. Park the
                                # error, release, then re-raise.
                                flush_exc = flush_err
                            else:
                                logger.error(
                                    "checkpoint flush failed while the "
                                    "training loop was unwinding: %r",
                                    flush_err)
                finally:
                    # even when a clean-run flush() raised (the
                    # surfacing path for a dead background writer)
                    if getattr(getattr(checkpointer, "on_save", None),
                               "_reader_coupled", False):
                        # unwire this run's coupling so the
                        # Checkpointer can be reused with a fresh
                        # reader/client — and the READER too: left
                        # True, a reader reused in a later train()
                        # without (re)coupling would never self-commit
                        # at pass end and the master's durability-gated
                        # pass roll would wait forever; the stale
                        # health_check would poll the OLD run's writer
                        # and never surface the hang
                        checkpointer.on_save = None
                        reader.checkpoint_coupled = False
                        if hasattr(reader, "health_check"):
                            reader.health_check = None
            if (isinstance(unwind_exc, Exception) or
                    flush_exc is not None) and \
                    getattr(reader, "release_lease", None) is not None:
                # the loop unwound on a plain Exception (user callback,
                # NaN anomaly) — or a clean run's final flush() raised
                # (dead background writer) — but the process and the
                # master client's heartbeat thread live on: liveness
                # expiry can never free this trainer's in-flight lease
                # or parked uncommitted finishes, so the master's
                # durability-gated pass roll would wait on them
                # forever. Release them explicitly. Runs AFTER the
                # flush above, so generations made durable there have
                # already committed their finishes via on_save — only
                # genuinely uncommittable work requeues. BaseException
                # unwinds (chaos kill, KeyboardInterrupt)
                # emulate/precede process death and must NOT release:
                # the heartbeat dies with the process and the
                # expiry/resume_lease path owns recovery.
                try:
                    reader.release_lease()
                except Exception as release_err:
                    logger.warning(
                        "release_lease failed while the training loop "
                        "was unwinding: %r", release_err)
            if flush_exc is not None:
                raise flush_exc

    def step_breakdown(self) -> Dict[str, float]:
        """Summary of the last train() call's per-step host-time split
        (plus the prefetch worker's queue-wait total): the bench's
        ``input_pipeline_steps_per_sec`` / ``data_wait_frac`` source.
        Under fsdp it carries the ``fsdp_exposed_*`` collective
        accounting (``utils/profiler.py:fsdp_overlap_stats``)."""
        return self.breakdown.summary()

    def _gather_peak(self):
        """FSDP transient gathered-buffer peak for memory reports
        (None when fsdp is off): two layers live under the overlap
        double-buffer, one under the sync spelling."""
        return (self._fsdp.gather_peak_bytes()
                if self._fsdp is not None else None)

    def load_state(self, params: Dict[str, Any], opt_flat=None):
        """Install restored parameters (+ optionally a flattened optimizer
        state as produced by checkpoint.load_params): values are cast and
        re-placed with each current array's sharding, so resuming under a
        mesh keeps tables sharded. Checkpoints always arrive in the flat
        per-stage format (``_params_for_save``); a pipelined run restacks
        them into its stage-stacked layout here — resume crosses pipeline
        on/off in both directions."""
        if self._pipe is not None:
            params, opt_flat = self._pipe.restack_checkpoint(params,
                                                             opt_flat)
        if self._fsdp is not None:
            # checkpoints always store full-shape parameters
            # (_params_for_save gathers): repack the planned ones into
            # this run's (N, chunk) fsdp partition on the host so the
            # placement below sees matching shapes
            params = self._fsdp.pack_params_host(params)

        def place(new, old):
            arr = jnp.asarray(new, dtype=old.dtype)
            if self.mesh is not None and hasattr(old, "sharding"):
                return jax.device_put(arr, old.sharding)
            return arr

        missing = sorted(set(self.params) - set(params))
        unknown = sorted(set(params) - set(self.params))
        if missing or unknown:
            raise ValueError(
                "restored checkpoint does not match the model's parameters"
                + (f"; missing: {missing}" if missing else "")
                + (f"; unknown: {unknown}" if unknown else ""))
        self.params = {k: place(v, self.params[k]) for k, v in params.items()}

        if opt_flat:
            def restore(tree, prefix=""):
                if isinstance(tree, dict):
                    return {k: restore(v, f"{prefix}{k}/")
                            for k, v in tree.items()}
                key = prefix.rstrip("/")
                if key not in opt_flat:
                    return tree
                new = opt_flat[key]
                upd = self._fsdp or self._zero1
                if upd is not None:
                    # checkpoints always store full-shape slots
                    # (_opt_state_for_save gathers): reshard a planned
                    # slot into this run's (N, chunk) partition
                    new = upd.pack_for_load(key, new, tree)
                return place(new, tree)

            self.opt_state = restore(self.opt_state)

    def test(self, reader, *, feeder=None) -> ev.TestResult:
        acc = Accumulator()
        self._start_host_evaluators()
        total_cost, batches = 0.0, 0
        for data in reader():
            feed = feeder(data) if feeder is not None else data
            if self.mesh is not None:
                feed = mesh_lib.shard_batch(feed, self.mesh)
            metrics = self._eval_step(self.params, feed)
            self.eval_recompile_guard.check()
            total_cost += float(metrics["cost"])
            batches += 1
            self._accumulate(acc, metrics)
            self._feed_host_evaluators(metrics, feed=feed)
        return ev.TestResult(0, total_cost / max(batches, 1),
                             {**acc.result(), **self.host_eval_values()})

    def _accumulate(self, acc: Accumulator, metrics) -> Dict[str, float]:
        for k, v in metrics.items():
            if isinstance(v, tuple):
                acc.add(k, *(jax.device_get(x) for x in v))
        return acc.result()

    # -------------------------------------------- config-driven evaluators
    def _start_host_evaluators(self):
        for e, _, _ in self._host_evals:
            e.start()

    def _feed_host_evaluators(self, metrics, feed=None, rng=None):
        """Per-batch accumulation into the config-declared evaluators.
        Inputs bind by the roles the DSL recorded — [outputs..., label?,
        weight?, query_id?] — so e.g. pnpair's query_id lands on its
        keyword, not on ``weight``. gradient_printer evaluators
        additionally receive d(cost)/d(layer output), computed via zero
        probes at the watched layers (the reference prints
        ``Argument.grad``, Evaluator.cpp:1046)."""
        outs = metrics.get("eval_outputs")
        if not outs or not self._host_evals:
            return
        host = jax.device_get(outs)
        row_mask = self._row_mask(feed) if feed is not None else None
        if row_mask is not None:
            # batch-bucket padding appends dead rows at the END of the
            # batch (feeder.py): slice every fetched array to the live
            # prefix so host evaluators never see padding — exact for
            # sequence AND non-sequence metrics alike
            n_live = int(np.asarray(jax.device_get(row_mask)).sum())
            host = {k: tuple(v[:n_live] if v is not None else None
                             for v in tup) for k, tup in host.items()}
        probe_grads = metrics.get("probe_grads")
        if probe_grads is not None:
            # d(cost)/d(layer output) computed in the SAME backward as the
            # batch's step (pre-update params, reference semantics)
            pg = jax.device_get(probe_grads)
            if row_mask is not None:
                pg = {k: v[:n_live] for k, v in pg.items()}
            for e, ins, _ in self._host_evals:
                if getattr(e, "wants_grad", False) and ins and ins[0] in pg:
                    e.last = pg[ins[0]]
        for e, ins, roles in self._host_evals:
            if not ins or ins[0] not in host:
                continue
            vals = [host[n][0] if n in host else None for n in ins]
            n_out = roles.get("n_outputs", 1)
            rest = vals[n_out:]
            kwargs = {"mask": host[ins[0]][1]}
            if getattr(e, "wants_ids", False) and len(host[ins[0]]) > 2:
                # the layer exposes a decoded-ids view alongside its
                # value (crf_decoding with label: value = error
                # indicator, ids = the path — ChunkEvaluator reads ids,
                # Evaluator.cpp / CRFDecodingLayer.cpp semantics)
                vals[0] = host[ins[0]][2]
                kwargs["mask"] = host[ins[0]][3]
            if getattr(e, "wants_grad", False):
                kwargs["grad"] = None  # supplied at print time
            if roles.get("has_label") and rest:
                kwargs["label"] = rest.pop(0)
            if roles.get("has_weight") and rest:
                kwargs["weight"] = rest.pop(0)
            if roles.get("has_query") and rest:
                kwargs["query_id"] = rest.pop(0)
            e.eval_batch(vals[0], **kwargs)

    def host_eval_values(self, include_printers: bool = True
                         ) -> Dict[str, float]:
        return {e.name: e.value() for e, _, _ in self._host_evals
                if include_printers or not e.prints_on_value}

    def parameter_stats(self) -> Dict[str, Dict[str, float]]:
        """Parameter health dump — per-parameter mean |v| and max |v|
        (``showParameterStats``, ``TrainerInternal.cpp:186+``). With the
        in-step telemetry armed (``train(health=...)`` /
        ``show_parameter_stats_period``) this READS the last fused
        reduction's snapshot — no extra program runs, and the table
        additionally carries norm/grad_norm/update_ratio (and sparse
        touched_rows). The standalone jit below remains only for the
        stats-off cold path (a dump requested before any armed step)."""
        hm = self._health
        if hm is not None and hm.param_stats is not None:
            # a COPY: the monitor's dict is also queued for timeline
            # serialization — a caller reformatting the returned rows
            # must not corrupt the JSONL record behind it
            return {n: dict(d) for n, d in hm.param_stats.items()}
        raw = jax.device_get(_param_stats_jit(self.params))
        _param_stats_guard.check()
        return {n: {"avg_abs": float(a), "max_abs": float(m),
                    "size": int(self.params[n].size)}
                for n, (a, m) in raw.items()}

    def layer_stats(self, feed) -> Dict[str, Dict[str, float]]:
        """Per-layer output stats on one batch (``--show_layer_stat``,
        ``Flags.cpp:71``). With the in-step telemetry armed this READS
        the last stats-on step's activation snapshot (the fused
        reduction already saw the executed forward — no second
        forward); the jitted standalone forward below remains only for
        the stats-off cold path (compiled once, cached)."""
        hm = self._health
        if hm is not None and hm.act_stats is not None:
            # same copy rationale as parameter_stats above
            return {n: dict(d) for n, d in hm.act_stats.items()}
        if not hasattr(self, "_layer_stat_fn"):
            # the EXECUTED subgraph only (self.network): off-path layers
            # have no parameters in self.params and possibly no feeds.
            # Same compute dtype as training so the stats reflect the
            # numerics the step actually sees (bf16 range problems are
            # exactly what this flag exists to surface).
            net = self.network

            @jax.jit
            def stat_fn(params, feed):
                outs = net.apply(self._cast_compute(params),
                                 self._cast_compute(feed), train=False)
                return {n: _arg_abs_stats(a)[:2]
                        for n, a in outs.items()
                        if hasattr(a.value, "dtype")
                        and jnp.issubdtype(a.value.dtype, jnp.inexact)}

            self._layer_stat_fn = stat_fn
            self._layer_stat_guard = _prefetch.RecompileGuard(
                stat_fn, warn_after=8, name="layer_stats")
        raw = jax.device_get(self._layer_stat_fn(self._flat_params_view(),
                                                 feed))
        self._layer_stat_guard.check()
        return {n: {"avg_abs": float(a), "max_abs": float(m)}
                for n, (a, m) in raw.items()}

    # ------------------------------------------------------------ forward
    def forward(self, feed, output_names: Optional[List[str]] = None):
        outputs = self.network.apply(self._flat_params_view(), feed,
                                     train=False, mesh=self.mesh)
        if output_names is None:
            return outputs
        return {n: outputs[n] for n in output_names}


def _arg_abs_stats(a):
    """(avg |out|, max |out|) of one layer output Argument — mask-aware
    (padded positions excluded from both). Shared by the standalone
    ``layer_stats`` jit and the in-step telemetry's fused activation
    reduction (``SGD._act_stat_table``), so both paths report the same
    numbers. Reduces the contiguous trailing feature axes FIRST (a
    vectorizable row reduce, ~2x the throughput of XLA:CPU's
    whole-tensor reduce on the big [B, T, H] sequences) and applies
    the mask to the [B, T] partials — masked positions contribute 0
    to the sum and are max'd against 0 exactly as the elementwise
    form did (|out| >= 0).

    Returns ``(avg_abs, max_abs, weight)`` — the weight is the live
    element count the avg divided by, so a consumer combining PARTIAL
    batches (the grad-accum microbatch scan) can reweight the avgs
    into the exact whole-batch masked mean instead of a biased mean
    of means."""
    v = jnp.abs(a.value)
    if a.mask is not None and v.ndim >= 2 \
            and a.mask.shape == v.shape[:a.mask.ndim]:
        feat_axes = tuple(range(a.mask.ndim, v.ndim))
        s = jnp.sum(v, axis=feat_axes) if feat_axes else v
        mx = jnp.max(v, axis=feat_axes) if feat_axes else v
        n = jnp.maximum(jnp.sum(a.mask), 1.0) * (
            v.size / max(1, a.mask.size))
        return (jnp.sum(s * a.mask) / n, jnp.max(mx * a.mask), n)
    return (jnp.mean(v), jnp.max(v),
            jnp.asarray(float(v.size), jnp.float32))


@jax.jit
def _param_stats_jit(params):
    return {n: (jnp.mean(jnp.abs(v)), jnp.max(jnp.abs(v)))
            for n, v in params.items()}


# module-level jit = one cache across every SGD instance in the
# process; the guard makes per-topology cache growth loud (each
# distinct param-dict structure is one legitimate variant)
_param_stats_guard = _prefetch.RecompileGuard(
    _param_stats_jit, warn_after=32, name="param_stats")
