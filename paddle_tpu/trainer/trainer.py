"""The training driver.

Replaces the reference's whole driver column — ``Trainer::train ->
trainOnePass -> trainOneDataBatch -> TrainerInternal::trainOneBatch``
(``paddle/trainer/Trainer.cpp:261,492,402``, ``TrainerInternal.cpp:66``) and
the Python v2 loop (``python/paddle/v2/trainer.py:108-175``) — with one
jitted train step:

    (params, opt_state, batch, rng) -> (params, opt_state, metrics)

The reference pipelines parameter updates *during* backward via per-parameter
callbacks (``TrainerInternal.cpp:70-74``); under XLA the fused step gives the
same overlap automatically (grad+update compile into one program). Data
parallelism: pass a ``Mesh`` — the batch is sharded on the ``data`` axis and
XLA inserts the gradient all-reduce, the ICI equivalent of
``MultiGradientMachine``'s ring and the pserver's ``addGradient``.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Union

import jax
import jax.numpy as jnp

from paddle_tpu.config import dsl as _dsl
from paddle_tpu.config.model_config import ModelDef
from paddle_tpu.core.argument import Argument
from paddle_tpu.core.network import Network
from paddle_tpu.optim.optimizers import Optimizer
from paddle_tpu.parallel import mesh as mesh_lib
from paddle_tpu.trainer import events as ev
from paddle_tpu.trainer.evaluators import Accumulator, classification_error

_CLASSIFICATION_COSTS = {"multi-class-cross-entropy"}


def _call_reader(reader, pass_id: int):
    """Invoke a per-pass reader. Readers that declare ``pass_aware = True``
    (``dist.master.master_reader``) receive the trainer's pass_id so a
    checkpoint-resumed run requests the correct pass from the master
    instead of getting an instant 'end' for already-finished ones."""
    if getattr(reader, "pass_aware", False):
        return reader(pass_id)
    return reader()


class Topology:
    """cost LayerOutput -> executable Network (``python/paddle/v2/
    topology.py:44``)."""

    def __init__(self, cost, extra_outputs: Optional[List] = None,
                 graph: Optional[ModelDef] = None):
        if graph is None:
            # prefer the graph the cost layer was built in (stays correct
            # after dsl.reset() begins another model)
            graph = getattr(cost, "graph", None) or _dsl.current_graph()
        names = [c.name if hasattr(c, "name") else c
                 for c in ([cost] + list(extra_outputs or []))]
        self.cost_name = names[0]
        graph.output_layer_names = names
        self.network = Network(graph, outputs=names)
        self.graph = graph


class SGD:
    """v2 ``trainer.SGD``: holds topology + parameters + optimizer and runs
    the training loop."""

    def __init__(self, cost, parameters: Optional[Dict[str, Any]] = None,
                 update_equation: Optimizer = None, *,
                 extra_layers: Optional[List] = None,
                 mesh=None, shard_rules: Optional[Dict[str, Any]] = None,
                 seed: int = 0, is_local: bool = True):
        if update_equation is None:
            raise ValueError("update_equation (an Optimizer) is required")
        self.topology = (cost if isinstance(cost, Topology)
                         else Topology(cost, extra_outputs=extra_layers))
        self.network = self.topology.network
        self.optimizer = update_equation
        self.mesh = mesh
        key = jax.random.PRNGKey(seed)
        self.meta = self.network.param_meta()
        if parameters is not None:
            self.params = (mesh_lib.shard_params(parameters, mesh, shard_rules)
                           if mesh is not None else parameters)
        else:
            # with a mesh, create parameters directly in their final
            # sharding (big tables never materialize on one device)
            shardings = (mesh_lib.param_shardings(
                self.network.param_specs, mesh, shard_rules)
                if mesh is not None else None)
            self.params = self.network.init_params(key, shardings=shardings)
        self.opt_state = self.optimizer.init(self.params, self.meta)
        if mesh is not None:
            # slots/avg follow their owning parameter; scalars replicate
            self.opt_state = mesh_lib.shard_opt_state(
                self.opt_state, mesh, shard_rules)
        self._rng = jax.random.PRNGKey(seed + 1)
        self._train_step = self._build_train_step()
        self._eval_step = self._build_eval_step()

    # ------------------------------------------------------------ builders
    def _metrics(self, outputs, feed):
        cost_name = self.topology.cost_name
        cdef = self.topology.graph.layers[cost_name]
        cost_val = outputs[cost_name].value
        bsz = cost_val.shape[0]
        metrics = {"cost": jnp.sum(cost_val) / bsz}
        if cdef.type in _CLASSIFICATION_COSTS:
            out_l, lab_l = cdef.input_names()[0], cdef.input_names()[1]
            errs, cnt = classification_error(outputs[out_l], outputs[lab_l])
            metrics["classification_error"] = (errs, cnt)
        return metrics

    def _build_train_step(self):
        network, optimizer, meta = self.network, self.optimizer, self.meta
        cost_name = self.topology.cost_name

        def loss_fn(params, feed, rng):
            outputs, updates = network.apply_with_state(
                params, feed, train=True, rng=rng)
            cost_val = outputs[cost_name].value
            loss = jnp.sum(cost_val) / cost_val.shape[0]
            return loss, (outputs, updates)

        def step(params, opt_state, feed, rng, num_passes):
            (_, (outputs, updates)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, feed, rng)
            bsz = outputs[cost_name].value.shape[0]
            new_params, new_opt = optimizer.update(
                grads, opt_state, params, meta, batch_size=bsz,
                num_passes=num_passes)
            new_params.update(updates)  # moving statistics (batch_norm)
            return new_params, new_opt, self._metrics(outputs, feed)

        return jax.jit(step, donate_argnums=(0, 1))

    def _build_eval_step(self):
        network = self.network

        def step(params, feed):
            outputs = network.apply(params, feed, train=False)
            return self._metrics(outputs, feed)

        return jax.jit(step)

    # ---------------------------------------------------------------- loop
    def train(self, reader, *, feeder=None, num_passes: int = 1,
              event_handler: Optional[Callable] = None,
              log_period: int = 0, checkpointer=None):
        """reader yields minibatches (lists of sample tuples); feeder
        converts them to Arguments (or pass feed dicts directly).
        ``log_period``>0 logs a TrainerStats-style line and dumps+resets the
        timer registry every N batches (``TrainerInternal.cpp:160-170``,
        ``Trainer.cpp:443-451``). ``checkpointer`` (dist.Checkpointer)
        restores the newest intact checkpoint before training — resuming
        at the pass after the saved one, the ``--start_pass`` semantics of
        ``Trainer.cpp:229-250`` — and saves on its cadence at batch and
        pass boundaries."""
        from paddle_tpu.utils import global_stat, logger, timer
        start_pass = 0
        if checkpointer is not None:
            restored = checkpointer.restore()
            if restored is not None:
                r_params, r_opt, meta = restored
                self.load_state(r_params, r_opt)
                pid = int(meta.get("pass_id", -1))
                if meta.get("end_of_pass", meta.get("batch_id", 0) == 0):
                    start_pass = pid + 1
                else:
                    # mid-pass (batch-cadence) checkpoint: restart that
                    # pass from its beginning so no batch goes untrained
                    # (early batches re-train — at-least-once, like the
                    # master's task requeue). With a pass-aware master
                    # reader only the pass's *unfinished* tasks replay —
                    # see the caveat on dist.master.master_reader.
                    start_pass = pid
        event_handler = event_handler or (lambda e: None)
        acc = Accumulator()
        for pass_id in range(start_pass, num_passes):
            event_handler(ev.BeginPass(pass_id))
            acc.reset()
            window_cost, window_n = 0.0, 0
            for batch_id, data in enumerate(_call_reader(reader, pass_id)):
                event_handler(ev.BeginIteration(pass_id, batch_id))
                with timer("prepareBatchData"):
                    feed = feeder(data) if feeder is not None else data
                    if self.mesh is not None:
                        feed = mesh_lib.shard_batch(feed, self.mesh)
                self._rng, step_rng = jax.random.split(self._rng)
                with timer("trainBatch"):
                    self.params, self.opt_state, metrics = self._train_step(
                        self.params, self.opt_state, feed, step_rng,
                        jnp.int32(pass_id))
                    cost = float(metrics["cost"])
                evals = self._accumulate(acc, metrics)
                window_cost += cost
                window_n += 1
                if log_period and (batch_id + 1) % log_period == 0:
                    # Cost is windowed (reset each log_period); AvgEval is
                    # cumulative since pass start, like the reference's
                    # "Eval:" vs "CurrentEval:" split (TrainerInternal.cpp).
                    logger.info(
                        "Pass=%d Batch=%d Cost=%.5f AvgEval: %s", pass_id,
                        batch_id + 1, window_cost / window_n,
                        " ".join(f"{k}={v:.5g}" for k, v in evals.items()))
                    logger.info("\n%s", global_stat.status(reset=True))
                    window_cost, window_n = 0.0, 0
                event_handler(ev.EndIteration(pass_id, batch_id, cost, evals))
                if checkpointer is not None:
                    checkpointer.maybe_save(self.params, self.opt_state,
                                            pass_id=pass_id,
                                            batch_id=batch_id + 1)
            event_handler(ev.EndPass(pass_id, acc.result()))
            if checkpointer is not None:
                checkpointer.maybe_save(self.params, self.opt_state,
                                        pass_id=pass_id, end_of_pass=True)

    def load_state(self, params: Dict[str, Any], opt_flat=None):
        """Install restored parameters (+ optionally a flattened optimizer
        state as produced by checkpoint.load_params): values are cast and
        re-placed with each current array's sharding, so resuming under a
        mesh keeps tables sharded."""

        def place(new, old):
            arr = jnp.asarray(new, dtype=old.dtype)
            if self.mesh is not None and hasattr(old, "sharding"):
                return jax.device_put(arr, old.sharding)
            return arr

        missing = sorted(set(self.params) - set(params))
        unknown = sorted(set(params) - set(self.params))
        if missing or unknown:
            raise ValueError(
                "restored checkpoint does not match the model's parameters"
                + (f"; missing: {missing}" if missing else "")
                + (f"; unknown: {unknown}" if unknown else ""))
        self.params = {k: place(v, self.params[k]) for k, v in params.items()}

        if opt_flat:
            def restore(tree, prefix=""):
                if isinstance(tree, dict):
                    return {k: restore(v, f"{prefix}{k}/")
                            for k, v in tree.items()}
                key = prefix.rstrip("/")
                return place(opt_flat[key], tree) if key in opt_flat else tree

            self.opt_state = restore(self.opt_state)

    def test(self, reader, *, feeder=None) -> ev.TestResult:
        acc = Accumulator()
        total_cost, batches = 0.0, 0
        for data in reader():
            feed = feeder(data) if feeder is not None else data
            if self.mesh is not None:
                feed = mesh_lib.shard_batch(feed, self.mesh)
            metrics = self._eval_step(self.params, feed)
            total_cost += float(metrics["cost"])
            batches += 1
            self._accumulate(acc, metrics)
        return ev.TestResult(0, total_cost / max(batches, 1), acc.result())

    def _accumulate(self, acc: Accumulator, metrics) -> Dict[str, float]:
        for k, v in metrics.items():
            if isinstance(v, tuple):
                acc.add(k, *(jax.device_get(x) for x in v))
        return acc.result()

    def parameter_stats(self) -> Dict[str, Dict[str, float]]:
        """Parameter health dump — per-parameter mean |v| and max |v|
        (``showParameterStats``, ``TrainerInternal.cpp:186+``). One jitted
        program for the whole table (per-parameter eager reductions would
        trigger dozens of tiny compilations)."""
        raw = jax.device_get(_param_stats_jit(self.params))
        return {n: {"avg_abs": float(a), "max_abs": float(m),
                    "size": int(self.params[n].size)}
                for n, (a, m) in raw.items()}

    # ------------------------------------------------------------ forward
    def forward(self, feed, output_names: Optional[List[str]] = None):
        outputs = self.network.apply(self.params, feed, train=False)
        if output_names is None:
            return outputs
        return {n: outputs[n] for n in output_names}


@jax.jit
def _param_stats_jit(params):
    return {n: (jnp.mean(jnp.abs(v)), jnp.max(jnp.abs(v)))
            for n, v in params.items()}
