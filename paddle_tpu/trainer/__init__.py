from paddle_tpu.trainer import events  # noqa: F401
from paddle_tpu.trainer.trainer import SGD, Topology  # noqa: F401
from paddle_tpu.trainer.checkpoint import load_params, save_params  # noqa: F401
from paddle_tpu.trainer.evaluators import classification_error  # noqa: F401
from paddle_tpu.trainer.metrics import (create_evaluator,  # noqa: F401
                                        register_evaluator)
