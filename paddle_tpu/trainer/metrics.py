"""Metric evaluators: the full ``gserver/evaluators`` family.

Mirrors ``paddle/gserver/evaluators/Evaluator.{h,cpp}`` (+
``ChunkEvaluator.cpp``, ``CTCErrorEvaluator.cpp``): classification error,
AUC (``AucEvaluator``, Evaluator.h:252), precision/recall, positive-negative
pair (pnpair), chunk F1 (NER), CTC sequence error, sum/column-sum, and the
printer evaluators. Each evaluator follows the reference's
``start/eval(batch)/finish`` accumulation protocol, but split TPU-style:
``batch_stats`` is a cheap device-side reduction (jit-friendly) where
possible, and accumulation/finalization runs host-side on small arrays.

Registered by name the way ``REGISTER_EVALUATOR`` does (Evaluator.h:28-42).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

_EVALUATORS: Dict[str, type] = {}


def register_evaluator(name: str):
    def deco(cls):
        _EVALUATORS[name] = cls
        cls.type_name = name
        return cls
    return deco


def create_evaluator(type_name: str, **kwargs) -> "EvaluatorBase":
    """By-type construction (``Evaluator::create``); kwargs may include
    ``name=`` for the instance's reported name."""
    if type_name not in _EVALUATORS:
        raise KeyError(
            f"unknown evaluator {type_name!r}; have {sorted(_EVALUATORS)}")
    return _EVALUATORS[type_name](**kwargs)


class EvaluatorBase:
    """start/eval/finish protocol (``Evaluator.h``). Subclasses implement
    ``eval_batch(output, label, weight=None, mask=None)`` with numpy arrays
    (already fetched from device) and ``value()``."""

    type_name = "?"
    # printer evaluators set this: value() has print side effects, so the
    # trainer reads them once per pass (EndPass), not every log period
    prints_on_value = False

    def __init__(self, name: Optional[str] = None):
        self.name = name or self.type_name
        self.start()

    def start(self):
        raise NotImplementedError

    def eval_batch(self, output, label=None, weight=None, mask=None):
        raise NotImplementedError

    def value(self) -> float:
        raise NotImplementedError

    def finish(self) -> float:
        return self.value()


def _align_label(label, out_T):
    """Trim/pad a feeder-padded label sequence to the output's padded
    length (positions align semantically; masks carry truth)."""
    label = np.asarray(label)
    if label.ndim >= 2 and label.shape[1] != out_T:
        if label.shape[1] > out_T:
            return label[:, :out_T]
        pad = [(0, 0), (0, out_T - label.shape[1])] + \
            [(0, 0)] * (label.ndim - 2)
        return np.pad(label, pad)
    return label


@register_evaluator("classification_error")
class ClassificationErrorEvaluator(EvaluatorBase):
    """``ClassificationErrorEvaluator`` — fraction argmax(output) != label;
    honors sample weights and sequence masks."""

    def __init__(self, name=None, top_k: int = 1):
        self.top_k = top_k
        super().__init__(name)

    def start(self):
        self.wrong = 0.0
        self.count = 0.0

    def eval_batch(self, output, label=None, weight=None, mask=None):
        output = np.asarray(output)
        label = np.asarray(label)
        if output.ndim >= 3:
            label = _align_label(label, output.shape[1])
        if self.top_k == 1:
            hit = np.argmax(output, axis=-1) == label
        else:
            topk = np.argsort(-output, axis=-1)[..., :self.top_k]
            hit = (topk == label[..., None]).any(axis=-1)
        wrong = (~hit).astype(np.float64)
        w = np.ones_like(wrong) if weight is None else np.asarray(weight)
        if mask is not None:
            w = w * np.asarray(mask)
        self.wrong += float((wrong * w).sum())
        self.count += float(w.sum())

    def value(self):
        return self.wrong / max(self.count, 1.0)


@register_evaluator("seq_classification_error")
class SeqClassificationErrorEvaluator(ClassificationErrorEvaluator):
    """``SequenceClassificationErrorEvaluator`` (``Evaluator.cpp:172``):
    sequence-level error — if ANY frame of a sequence is wrong, the whole
    sequence counts as one error; the denominator is the number of
    sequences."""

    def eval_batch(self, output, label=None, weight=None, mask=None):
        output = np.asarray(output)
        label = np.asarray(label)
        if self.top_k == 1:
            hit = np.argmax(output, axis=-1) == label
        else:
            topk = np.argsort(-output, axis=-1)[..., :self.top_k]
            hit = (topk == label[..., None]).any(axis=-1)
        wrong = (~hit).astype(np.float64)
        if mask is not None:
            wrong = wrong * np.asarray(mask)
        # [B, T] frame errors -> per-sequence any()
        seq_wrong = (wrong.reshape(wrong.shape[0], -1).sum(axis=-1) > 0)
        self.wrong += float(seq_wrong.sum())
        self.count += float(wrong.shape[0])


@register_evaluator("rankauc")
class RankAucEvaluator(EvaluatorBase):
    """``RankAucEvaluator`` (``Evaluator.cpp:503``): per-sequence ranking
    AUC over (score, click, pageview) triples; value is the mean
    per-sequence AUC. The tie-handling trapezoid walk mirrors
    ``calcRankAuc`` exactly."""

    def start(self):
        self.total = 0.0
        self.n_seq = 0.0

    @staticmethod
    def _calc(score, click, pv):
        order = np.argsort(-score, kind="stable")
        auc = click_sum = old_click_sum = 0.0
        no_click = no_click_sum = 0.0
        last = float(score[order[0]]) + 1.0
        for i in order:
            s = float(score[i])
            if last != s:
                auc += (click_sum + old_click_sum) * no_click / 2.0
                old_click_sum = click_sum
                no_click = 0.0
                last = s
            no_click += float(pv[i]) - float(click[i])
            no_click_sum += no_click
            click_sum += float(click[i])
        auc += (click_sum + old_click_sum) * no_click / 2.0
        denom = click_sum * no_click_sum
        return 0.0 if denom == 0.0 else auc / denom

    def eval_batch(self, output, label=None, weight=None, mask=None):
        # inputs: output scores, click (label), optional pv (weight)
        score = np.asarray(output)
        if score.ndim == 3:
            score = score[..., 0]
        click = np.asarray(label).reshape(score.shape)
        pv = (np.ones_like(score) if weight is None
              else np.asarray(weight).reshape(score.shape))
        if score.ndim == 1:
            score, click, pv = score[None], click[None], pv[None]
        for b in range(score.shape[0]):
            n = int(np.asarray(mask)[b].sum()) if mask is not None \
                else score.shape[1]
            if n <= 0:
                continue
            self.total += self._calc(score[b, :n], click[b, :n], pv[b, :n])
            self.n_seq += 1.0

    def value(self):
        return self.total / max(self.n_seq, 1.0)


@register_evaluator("auc")
class AucEvaluator(EvaluatorBase):
    """``AucEvaluator`` (Evaluator.h:252): bucketed ROC-AUC. The reference
    histograms P(positive) into fixed bins (statPos_/statNeg_) and
    integrates by trapezoid; identical scheme here with ``num_bins``."""

    def __init__(self, name=None, num_bins: int = 4096, column: int = -1):
        self.num_bins = num_bins
        self.column = column
        super().__init__(name)

    def start(self):
        self.stat_pos = np.zeros(self.num_bins, np.float64)
        self.stat_neg = np.zeros(self.num_bins, np.float64)

    def eval_batch(self, output, label=None, weight=None, mask=None):
        output = np.asarray(output)
        if output.ndim > 1:
            col = self.column if self.column >= 0 else output.shape[-1] - 1
            score = output[..., col]
        else:
            score = output
        score = score.reshape(-1)
        label = np.asarray(label).reshape(-1)
        w = (np.ones_like(score, np.float64) if weight is None
             else np.asarray(weight, np.float64).reshape(-1))
        if mask is not None:
            keep = np.asarray(mask).reshape(-1) > 0
            score, label, w = score[keep], label[keep], w[keep]
        idx = np.clip((score * self.num_bins).astype(np.int64),
                      0, self.num_bins - 1)
        np.add.at(self.stat_pos, idx[label > 0], w[label > 0])
        np.add.at(self.stat_neg, idx[label <= 0], w[label <= 0])

    def value(self):
        # walk bins from high score to low, trapezoid over (FP, TP) curve —
        # same calcAuc as the reference.
        tot_pos = self.stat_pos.sum()
        tot_neg = self.stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.5
        tp = np.cumsum(self.stat_pos[::-1])
        fp = np.cumsum(self.stat_neg[::-1])
        tpr = np.concatenate([[0.0], tp / tot_pos])
        fpr = np.concatenate([[0.0], fp / tot_neg])
        trapz = getattr(np, "trapezoid", np.trapz)
        return float(trapz(tpr, fpr))


@register_evaluator("precision_recall")
class PrecisionRecallEvaluator(EvaluatorBase):
    """``PrecisionRecallEvaluator``: per-class TP/FP/FN with macro-averaged
    precision/recall/F1; ``positive_label`` selects single-class mode as in
    the reference config."""

    def __init__(self, name=None, positive_label: int = -1):
        self.positive_label = positive_label
        super().__init__(name)

    def start(self):
        self.tp: Dict[int, float] = {}
        self.fp: Dict[int, float] = {}
        self.fn: Dict[int, float] = {}

    def eval_batch(self, output, label=None, weight=None, mask=None):
        pred = np.argmax(np.asarray(output), axis=-1).reshape(-1)
        label = np.asarray(label).reshape(-1)
        w = (np.ones_like(pred, np.float64) if weight is None
             else np.asarray(weight, np.float64).reshape(-1))
        if mask is not None:
            keep = np.asarray(mask).reshape(-1) > 0
            pred, label, w = pred[keep], label[keep], w[keep]
        for c in np.unique(np.concatenate([pred, label])):
            c = int(c)
            self.tp[c] = self.tp.get(c, 0.0) + float(
                w[(pred == c) & (label == c)].sum())
            self.fp[c] = self.fp.get(c, 0.0) + float(
                w[(pred == c) & (label != c)].sum())
            self.fn[c] = self.fn.get(c, 0.0) + float(
                w[(pred != c) & (label == c)].sum())

    def _prf(self, c):
        tp, fp, fn = self.tp.get(c, 0), self.fp.get(c, 0), self.fn.get(c, 0)
        p = tp / max(tp + fp, 1e-12)
        r = tp / max(tp + fn, 1e-12)
        f = 2 * p * r / max(p + r, 1e-12)
        return p, r, f

    def value(self):
        if self.positive_label >= 0:
            return self._prf(self.positive_label)[2]
        classes = sorted(set(self.tp) | set(self.fp) | set(self.fn))
        if not classes:
            return 0.0
        return float(np.mean([self._prf(c)[2] for c in classes]))

    def detail(self):
        classes = sorted(set(self.tp) | set(self.fp) | set(self.fn))
        return {c: dict(zip(("precision", "recall", "f1"), self._prf(c)))
                for c in classes}


@register_evaluator("pnpair")
class PnpairEvaluator(EvaluatorBase):
    """``PnpairEvaluator``: for ranking — over all pairs within a query
    group, count pairs ordered correctly (pos scored above neg) vs
    incorrectly; value = correct/incorrect ratio."""

    def start(self):
        self.records: List = []

    def eval_batch(self, output, label=None, weight=None, mask=None,
                   query_id=None):
        score = np.asarray(output)
        if score.ndim > 1:
            score = score[..., -1]
        score = score.reshape(-1)
        label = np.asarray(label).reshape(-1)
        qid = (np.zeros_like(label) if query_id is None
               else np.asarray(query_id).reshape(-1))
        w = (np.ones_like(score, np.float64) if weight is None
             else np.asarray(weight, np.float64).reshape(-1))
        for s, l, q, ww in zip(score, label, qid, w):
            self.records.append((int(q), float(s), float(l), float(ww)))

    def value(self):
        pos, neg, tie = 0.0, 0.0, 0.0
        from collections import defaultdict
        groups = defaultdict(list)
        for q, s, l, w in self.records:
            groups[q].append((s, l, w))
        for items in groups.values():
            for i in range(len(items)):
                for j in range(i + 1, len(items)):
                    (s1, l1, w1), (s2, l2, w2) = items[i], items[j]
                    if l1 == l2:
                        continue
                    w = (w1 + w2) / 2
                    hi, lo = (s1, s2) if l1 > l2 else (s2, s1)
                    if hi > lo:
                        pos += w
                    elif hi < lo:
                        neg += w
                    else:
                        tie += w
        return (pos + 0.5 * tie) / max(neg + 0.5 * tie, 1e-12)


@register_evaluator("chunk")
class ChunkEvaluator(EvaluatorBase):
    """``ChunkEvaluator.cpp``: F1 over chunks decoded from tag sequences.

    Encoding matches the reference: with ``tag_num`` tags per scheme
    (IOB: B,I / IOE: I,E / IOBES: B,I,E,S / plain: single tag), a label is
    ``chunk_type * tag_num + tag`` and the "other" (outside) label is
    ``num_chunk_types * tag_num``.
    """

    SCHEMES = {"plain": 1, "IOB": 2, "IOE": 2, "IOBES": 4}
    # reads the layer's decoded-ids view when it carries one (the
    # reference evaluator consumes output_.ids, ChunkEvaluator.cpp)
    wants_ids = True

    def __init__(self, name=None, chunk_scheme: str = "IOB",
                 num_chunk_types: int = 1, excluded_chunk_types=()):
        if chunk_scheme not in self.SCHEMES:
            raise ValueError(f"bad chunk_scheme {chunk_scheme}")
        self.scheme = chunk_scheme
        self.tag_num = self.SCHEMES[chunk_scheme]
        self.num_chunk_types = num_chunk_types
        self.excluded = set(excluded_chunk_types)
        super().__init__(name)

    def start(self):
        self.num_label = 0.0
        self.num_output = 0.0
        self.num_correct = 0.0

    def _decode(self, t: int):
        """label id -> (tag, chunk_type) or None for the outside label."""
        other = self.num_chunk_types * self.tag_num
        if t < 0 or t >= other:
            return None
        ctype, tag = divmod(int(t), self.tag_num)
        return tag, ctype

    def _is_start(self, prev, cur):
        """Does ``cur`` begin a new chunk given the previous position?
        (isChunkBegin in ChunkEvaluator.cpp)."""
        if cur is None:
            return False
        tag, ctype = cur
        if self.scheme == "plain":
            return True
        if prev is None or prev[1] != ctype:
            return True
        if self.scheme == "IOB":
            return tag == 0                       # B
        if self.scheme == "IOE":
            return prev[0] == 1                   # previous was E
        # IOBES: B=0, I=1, E=2, S=3
        return tag in (0, 3) or prev[0] in (2, 3)

    def _is_end(self, cur, nxt):
        """Does ``cur`` end its chunk given the next position?
        (isChunkEnd)."""
        if cur is None:
            return False
        tag, ctype = cur
        if self.scheme == "plain":
            return True
        if nxt is None or nxt[1] != ctype:
            return True
        if self.scheme == "IOB":
            return nxt[0] == 0                    # next is B
        if self.scheme == "IOE":
            return tag == 1                       # E
        return tag in (2, 3) or nxt[0] in (0, 3)  # IOBES

    def _segments(self, tags: Sequence[int]):
        """Decode (begin, end, type) chunks; mirrors getSegments in
        ChunkEvaluator.cpp."""
        decoded = [self._decode(t) for t in tags]
        out = []
        start = None
        for i, cur in enumerate(decoded):
            prev = decoded[i - 1] if i > 0 else None
            nxt = decoded[i + 1] if i + 1 < len(decoded) else None
            if self._is_start(prev, cur):
                start = i
            if cur is not None and start is None:
                start = i  # tolerate malformed prediction (I without B)
            if self._is_end(cur, nxt) and start is not None:
                out.append((start, i, cur[1]))
                start = None
            if cur is None:
                start = None
        return [(b, e, c) for (b, e, c) in out if c not in self.excluded]

    def eval_batch(self, output, label=None, weight=None, mask=None):
        """output: predicted tag ids [B, T] (or list of lists); label same."""
        pred = np.asarray(output)
        lab = np.asarray(label)
        if pred.ndim == 3 and pred.shape[-1] == 1:  # [B, T, 1] decode output
            pred = pred[..., 0]
        if lab.ndim == 3 and lab.shape[-1] == 1:
            lab = lab[..., 0]
        if pred.ndim == 1:
            pred, lab = pred[None], lab[None]
            mask = None if mask is None else np.asarray(mask)[None]
        for b in range(pred.shape[0]):
            if mask is not None:
                n = int(np.asarray(mask)[b].sum())
            else:
                n = pred.shape[1]
            p_chunks = set(self._segments(pred[b, :n].tolist()))
            l_chunks = set(self._segments(lab[b, :n].tolist()))
            self.num_output += len(p_chunks)
            self.num_label += len(l_chunks)
            self.num_correct += len(p_chunks & l_chunks)

    def value(self):
        p = self.num_correct / max(self.num_output, 1e-12)
        r = self.num_correct / max(self.num_label, 1e-12)
        return 2 * p * r / max(p + r, 1e-12)


def edit_distance(a: Sequence[int], b: Sequence[int]) -> int:
    """Levenshtein distance (the core of ``CTCErrorEvaluator.cpp``)."""
    la, lb = len(a), len(b)
    prev = np.arange(lb + 1)
    for i in range(1, la + 1):
        cur = np.empty(lb + 1, np.int64)
        cur[0] = i
        for j in range(1, lb + 1):
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1,
                         prev[j - 1] + (a[i - 1] != b[j - 1]))
        prev = cur
    return int(prev[lb])


def ctc_best_path(log_probs: np.ndarray, blank: int) -> List[int]:
    """Greedy best-path decoding: argmax per frame, collapse repeats,
    drop blanks."""
    path = np.argmax(log_probs, axis=-1)
    out: List[int] = []
    prev = -1
    for t in path:
        t = int(t)
        if t != prev and t != blank:
            out.append(t)
        prev = t
    return out


@register_evaluator("ctc_edit_distance")
class CTCErrorEvaluator(EvaluatorBase):
    """``CTCErrorEvaluator.cpp``: normalized edit distance between the
    best-path-decoded CTC output and the label sequence."""

    def __init__(self, name=None, blank: Optional[int] = None):
        self.blank = blank
        super().__init__(name)

    def start(self):
        self.total_dist = 0.0
        self.total_len = 0.0
        self.seqs = 0

    def eval_batch(self, output, label=None, weight=None, mask=None,
                   label_mask=None):
        """output: [B, T, C] frame scores; label: [B, L] int ids."""
        out = np.asarray(output)
        lab = np.asarray(label)
        if out.ndim == 2:
            out, lab = out[None], lab[None]
        blank = self.blank if self.blank is not None else out.shape[-1] - 1
        for b in range(out.shape[0]):
            T = (int(np.asarray(mask)[b].sum()) if mask is not None
                 else out.shape[1])
            L = (int(np.asarray(label_mask)[b].sum())
                 if label_mask is not None else lab.shape[1])
            hyp = ctc_best_path(out[b, :T], blank)
            ref = [int(x) for x in lab[b, :L]]
            self.total_dist += edit_distance(hyp, ref)
            self.total_len += max(len(ref), 1)
            self.seqs += 1

    def value(self):
        return self.total_dist / max(self.total_len, 1e-12)


@register_evaluator("sum")
class SumEvaluator(EvaluatorBase):
    def start(self):
        self.total = 0.0
        self.count = 0.0

    def eval_batch(self, output, label=None, weight=None, mask=None):
        out = np.asarray(output, np.float64)
        if mask is not None:
            out = out * np.asarray(mask)[..., None]
        if weight is not None:
            # per-sample weight [B] aligned against out [B, ...]
            w = np.asarray(weight, np.float64).reshape(
                (-1,) + (1,) * (out.ndim - 1))
            out = out * w
        self.total += float(out.sum())
        self.count += (float(np.asarray(mask).sum()) if mask is not None
                       else out.shape[0])

    def value(self):
        return self.total / max(self.count, 1.0)


@register_evaluator("column_sum")
class ColumnSumEvaluator(EvaluatorBase):
    def __init__(self, name=None, column: int = 0):
        self.column = column
        super().__init__(name)

    def start(self):
        self.total = 0.0
        self.count = 0.0

    def eval_batch(self, output, label=None, weight=None, mask=None):
        out = np.asarray(output, np.float64)
        col = out[..., self.column].reshape(-1)
        w = (np.ones_like(col) if weight is None
             else np.asarray(weight, np.float64).reshape(-1))
        if mask is not None:
            w = w * np.asarray(mask).reshape(-1)
        self.total += float((col * w).sum())
        self.count += float(w.sum())

    def value(self):
        return self.total / max(self.count, 1.0)


def _matrix_str(m) -> str:
    """Row-per-line space-separated rendering (``Matrix::print``)."""
    m = np.asarray(m, np.float64)
    m = m.reshape(m.shape[0], -1) if m.ndim > 1 else m.reshape(1, -1)
    return "\n".join(" ".join(f"{v:g}" for v in row) for row in m) + "\n"


@register_evaluator("value_printer")
class ValuePrinter(EvaluatorBase):
    """``ValuePrinter`` (``Evaluator.cpp:1008``): prints each watched
    layer's output. Format follows ``Argument::printValueString``:
    ``layer=<name> value:\\n<matrix>`` (+ sequence pos when masked)."""

    prints_on_value = True

    def start(self):
        self.last = None
        self.last_mask = None

    def eval_batch(self, output, label=None, weight=None, mask=None):
        self.last = np.asarray(output)
        self.last_mask = None if mask is None else np.asarray(mask)

    def value(self):
        v, m = self.last, self.last_mask
        pos_str = ""
        if m is not None and v is not None and v.ndim >= 2:
            # pack padded [B, T, ...] to the reference's flat
            # [total_frames, D] layout so the printed matrix and the
            # sequence pos vector describe the same rows
            lens = m.sum(axis=-1).astype(int)
            rows = [v[b, :lens[b]].reshape(lens[b], -1)
                    for b in range(v.shape[0])]
            v = (np.concatenate(rows, axis=0) if rows
                 else v.reshape(0, v.shape[-1]))
            pos = np.concatenate([[0], np.cumsum(lens)])
            pos_str = ("layer=" + self.name + " sequence pos:\n"
                       + " ".join(str(int(p)) for p in pos) + "\n")
        print("layer=" + self.name + " value:\n"
              + _matrix_str(v) + pos_str, end="")
        return 0.0


@register_evaluator("gradient_printer")
class GradientPrinter(EvaluatorBase):
    """``GradientPrinter`` (``Evaluator.cpp:1046``): prints
    d(cost)/d(layer output) — ``Argument.grad`` in the reference. The
    trainer computes it via a zero probe added at the watched layer
    (Network.apply_with_state(probes=...)) and passes it as ``grad``."""

    prints_on_value = True
    wants_grad = True

    def start(self):
        self.last = None

    def eval_batch(self, output, label=None, weight=None, mask=None,
                   grad=None):
        if grad is not None:
            self.last = np.asarray(grad)

    def value(self):
        if self.last is None:
            print(f"layer={self.name} grad: (not computed)")
        else:
            print("layer=" + self.name + " grad matrix:\n"
                  + _matrix_str(self.last), end="")
        return 0.0


@register_evaluator("max_id_printer")
class MaxIdPrinter(EvaluatorBase):
    """``MaxIdPrinter`` (``Evaluator.cpp:1088``, registered as
    ``max_id_printer``): per row, the top ``num_results`` ids with their
    values, ``id : value, `` repeated. The repo's pre-r4 name
    ``maxid_printer`` stays as an alias."""

    prints_on_value = True

    def __init__(self, name=None, num_results: int = 1):
        self.num_results = max(int(num_results or 1), 1)
        super().__init__(name)

    def start(self):
        self.ids = None
        self.values = None

    def eval_batch(self, output, label=None, weight=None, mask=None):
        out = np.asarray(output)
        out = out.reshape(-1, out.shape[-1])
        k = min(self.num_results, out.shape[-1])
        idx = np.argsort(-out, axis=-1)[:, :k]
        self.ids = idx
        self.values = np.take_along_axis(out, idx, axis=-1)

    def value(self):
        if self.ids is None:
            return 0.0
        lines = []
        for row_i, row_v in zip(self.ids, self.values):
            lines.append("".join(f"{int(i)} : {float(v):g}, "
                                 for i, v in zip(row_i, row_v)))
        print("layer=" + self.name + " row max id vector:\n"
              + "\n".join(lines) + "\n", end="")
        return 0.0


@register_evaluator("max_frame_printer")
class MaxFramePrinter(EvaluatorBase):
    """``MaxFramePrinter`` (``Evaluator.cpp:1142``): for a width-1
    sequence output, prints each sequence's top ``num_results`` frames as
    ``time_index : value, `` plus ``total N frames``."""

    prints_on_value = True

    def __init__(self, name=None, num_results: int = 1):
        self.num_results = max(int(num_results or 1), 1)
        super().__init__(name)

    def start(self):
        self.lines: List[str] = []

    def eval_batch(self, output, label=None, weight=None, mask=None):
        out = np.asarray(output)
        if out.ndim == 3:
            out = out[..., 0]
        if out.ndim == 1:
            out = out[None]
        for b in range(out.shape[0]):
            n = int(np.asarray(mask)[b].sum()) if mask is not None \
                else out.shape[1]
            if n <= 0:
                continue
            seq = out[b, :n]
            k = min(self.num_results, n)
            idx = np.argsort(-seq, kind="stable")[:k]
            self.lines.append(
                "".join(f"{int(i)} : {float(seq[i]):g}, " for i in idx)
                + f"total {n} frames")

    def value(self):
        print("layer=" + self.name + " sequence max frames:\n"
              + "\n".join(self.lines) + "\n", end="")
        return 0.0


@register_evaluator("classification_error_printer")
class ClassificationErrorPrinter(EvaluatorBase):
    """``ClassificationErrorPrinter`` (``Evaluator.cpp:1346``): prints the
    per-sample 0/1 error matrix (``calcError``) and, for sequences, the
    start-position vector."""

    prints_on_value = True

    def start(self):
        self.err = None
        self.last_mask = None

    def eval_batch(self, output, label=None, weight=None, mask=None):
        out = np.asarray(output)
        lab = np.asarray(label)
        err = (np.argmax(out, axis=-1) != lab).astype(np.float64)
        if mask is not None:
            err = err * np.asarray(mask)
        self.err = err
        self.last_mask = None if mask is None else np.asarray(mask)

    def value(self):
        if self.err is None:
            return 0.0
        out = ("Printer=" + self.name + " Classification Error:\n"
               + _matrix_str(self.err.reshape(-1, 1)))
        if self.last_mask is not None:
            lens = self.last_mask.sum(axis=-1).astype(int)
            pos = np.concatenate([[0], np.cumsum(lens)])
            out += ("Printer=" + self.name + " sequence pos vector:\n"
                    + " ".join(str(int(p)) for p in pos) + "\n")
        print(out, end="")
        return 0.0


@register_evaluator("seq_text_printer")
class SeqTextPrinter(EvaluatorBase):
    prints_on_value = True
    """``utils/SeqTextPrinter`` analogue: map id sequences through a dict
    file and print."""

    def __init__(self, name=None, dict_file: Optional[str] = None,
                 id_input=None):
        self.vocab = None
        if dict_file:
            with open(dict_file) as f:
                self.vocab = [line.rstrip("\n") for line in f]
        super().__init__(name)

    def start(self):
        self.lines: List[str] = []

    def eval_batch(self, output, label=None, weight=None, mask=None):
        ids = np.asarray(output)
        if ids.ndim == 1:
            ids = ids[None]
        for b in range(ids.shape[0]):
            n = int(np.asarray(mask)[b].sum()) if mask is not None \
                else ids.shape[1]
            toks = [self.vocab[int(i)] if self.vocab else str(int(i))
                    for i in ids[b, :n]]
            self.lines.append(" ".join(toks))

    def value(self):
        print("\n".join(self.lines))
        return 0.0


@register_evaluator("detection_map")
class DetectionMAPEvaluator(EvaluatorBase):
    """``DetectionMAPEvaluator.cpp``: mean average precision over detection
    outputs. output rows (per image): [keep_top_k, 7] =
    (label, score, xmin, ymin, xmax, ymax, valid) — the detection_output
    layer's format; label rows: [M, 6] = (label, xmin, ymin, xmax, ymax,
    difficult), with label < 0 marking padding rows.
    ap_type: "11point" (default) or "integral"."""

    def __init__(self, name=None, overlap_threshold: float = 0.5,
                 background_id: int = 0, evaluate_difficult: bool = False,
                 ap_type: str = "11point"):
        self.overlap_threshold = overlap_threshold
        self.background_id = background_id
        self.evaluate_difficult = evaluate_difficult
        self.ap_type = ap_type
        super().__init__(name)

    def start(self):
        # per class: list of (score, is_tp) + ground-truth count
        self.dets: Dict[int, List] = {}
        self.n_gt: Dict[int, int] = {}

    @staticmethod
    def _iou(a, b):
        ix = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
        iy = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
        inter = ix * iy
        ua = ((a[2] - a[0]) * (a[3] - a[1])
              + (b[2] - b[0]) * (b[3] - b[1]) - inter)
        return inter / ua if ua > 0 else 0.0

    def eval_batch(self, output, label=None, weight=None, mask=None):
        out = np.asarray(output)
        gt = np.asarray(label)
        if out.ndim == 2:
            out, gt = out[None], gt[None]
        if out.shape[-1] != 7:
            out = out.reshape(out.shape[0], -1, 7)
        if gt.shape[-1] != 6:
            gt = gt.reshape(gt.shape[0], -1, 6)
        for b in range(out.shape[0]):
            gts = [g for g in gt[b] if g[0] >= 0]
            for g in gts:
                c = int(g[0])
                if self.evaluate_difficult or not g[5]:
                    self.n_gt[c] = self.n_gt.get(c, 0) + 1
            matched = [False] * len(gts)
            dets = [d for d in out[b] if d[6] > 0 and d[0] != self.background_id]
            dets.sort(key=lambda d: -d[1])
            for d in dets:
                c = int(d[0])
                best, best_i = 0.0, -1
                for i, g in enumerate(gts):
                    if int(g[0]) != c:
                        continue
                    o = self._iou(d[2:6], g[1:5])
                    if o > best:
                        best, best_i = o, i
                tp = False
                if best >= self.overlap_threshold and best_i >= 0:
                    g = gts[best_i]
                    if not self.evaluate_difficult and g[5]:
                        continue  # difficult match: ignore the detection
                    if not matched[best_i]:
                        matched[best_i] = True
                        tp = True
                self.dets.setdefault(c, []).append((float(d[1]), tp))

    def _ap(self, recs, precs):
        if self.ap_type == "integral":
            ap, prev_r = 0.0, 0.0
            for r, p in zip(recs, precs):
                ap += p * (r - prev_r)
                prev_r = r
            return ap
        ap = 0.0
        for t in np.arange(0.0, 1.01, 0.1):
            ps = [p for r, p in zip(recs, precs) if r >= t]
            ap += (max(ps) if ps else 0.0) / 11.0
        return ap

    def value(self):
        aps = []
        for c, n_gt in self.n_gt.items():
            dets = sorted(self.dets.get(c, []), key=lambda d: -d[0])
            tp_cum = fp_cum = 0
            recs, precs = [], []
            for score, tp in dets:
                tp_cum += tp
                fp_cum += not tp
                recs.append(tp_cum / max(n_gt, 1))
                precs.append(tp_cum / max(tp_cum + fp_cum, 1))
            aps.append(self._ap(recs, precs) if dets else 0.0)
        return float(np.mean(aps)) if aps else 0.0


# the canonical registration is max_id_printer (the reference's string,
# Evaluator.cpp:1088); keep the repo's pre-r4 spelling working
_EVALUATORS["maxid_printer"] = MaxIdPrinter

# ---------------------------------------------------------- config wiring
# reference EvaluatorConfig.type -> registry name
_TYPE_ALIASES = {
    "last-column-auc": "auc",
    "last-column-sum": "column_sum",
}


def build_from_configs(configs: Sequence[dict]):
    """EvaluatorConfig-shaped dicts (compat ctx().evaluators / ModelDef
    .evaluators) -> [(evaluator, input_layer_names, roles)]. ``roles``
    (the ``_roles`` key the DSLs record) says how many leading inputs are
    outputs and whether label/weight/query follow, so the trainer binds
    ``eval_batch`` kwargs correctly. Unknown types are skipped with a
    warning — a config must not fail to train because a printer evaluator
    is missing."""
    import inspect
    from paddle_tpu.utils import logger
    built = []
    for cfg in configs or []:
        tname = _TYPE_ALIASES.get(cfg.get("type"), cfg.get("type"))
        cls = _EVALUATORS.get(tname)
        if cls is None:
            logger.warning("evaluator type %r not supported; skipping",
                           cfg.get("type"))
            continue
        accepted = set(inspect.signature(cls.__init__).parameters)
        kwargs = {k: v for k, v in cfg.items()
                  if k in accepted and k not in ("input_layers", "type")}
        roles = cfg.get("_roles") or {"n_outputs": 1,
                                      "has_label":
                                      len(cfg.get("input_layers", [])) > 1,
                                      "has_weight": False}
        built.append((cls(**kwargs), list(cfg.get("input_layers", [])),
                      roles))
    return built
