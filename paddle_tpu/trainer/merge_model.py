"""Deploy-time model merging (`paddle/trainer/MergeModel.cpp`).

Fuses the model graph (the ModelDef that plays ModelConfig's role) and
trained parameters into ONE integrity-checked file for deployment — the
artifact the C inference API loads (`paddle/capi`), and what
`python/paddle/utils/merge_model.py` produced for v2 users.

Format: ``b"PTM1" + md5(payload)[16 bytes] + pickle(payload)`` where
payload = {"graph": ModelDef, "params": {name: np.ndarray},
"outputs": [names]}. Two OPTIONAL sections ride ``--quantize`` merges
(``paddle_tpu/quant.py``): ``"quant"`` (storage dtype + per-tensor
scales + named stand-downs) and ``"golden"`` (the warmup accuracy
gate's request set with fp32 reference outputs). Both are strictly
additive — an unquantized merge writes byte-identical payloads to the
old format, and :func:`load_merged` ignores unknown keys, so an old
reader of an unquantized file sees no change and a quantized artifact
fed to an old reader still loads (as its raw storage-dtype params).

SECURITY: the MD5 gives *integrity* (torn-file detection), not
*authenticity* — the payload is a pickle, so ``load_merged`` (and the C
API's ``ptc_load``) must only be given model files from trusted sources,
exactly like any pickle-based checkpoint format.
"""

from __future__ import annotations

import hashlib
import pickle
from typing import Dict, List, Optional

import numpy as np

_MAGIC = b"PTM1"


def merge_model(path: str, graph, params: Dict[str, np.ndarray],
                outputs: Optional[List[str]] = None,
                quant: Optional[Dict] = None,
                golden: Optional[Dict] = None):
    import jax
    data = {
        "graph": graph,
        "params": {k: np.asarray(jax.device_get(v))
                   for k, v in params.items()},
        "outputs": list(outputs or graph.output_layer_names or []),
    }
    # optional sections only when present: the unquantized payload must
    # stay byte-identical to the pre-quant format (digest stability)
    if quant is not None:
        data["quant"] = quant
    if golden is not None:
        data["golden"] = golden
    payload = pickle.dumps(data, protocol=pickle.HIGHEST_PROTOCOL)
    with open(path, "wb") as f:
        f.write(_MAGIC + hashlib.md5(payload).digest() + payload)


def merged_digest(path: str) -> str:
    """The PTM1 payload MD5 (hex) without unpickling the payload — the
    model-version key the serving AOT warmup cache and rolling reload
    use (``serving/aot_cache.py``)."""
    with open(path, "rb") as f:
        head = f.read(20)
    if head[:4] != _MAGIC:
        raise IOError(f"{path}: not a merged model (bad magic)")
    return head[4:20].hex()


def load_merged(path: str):
    """-> (graph, params, output_names); raises on corruption.
    Only load files from trusted sources (pickle payload — see module
    docstring)."""
    graph, params, outputs, _extras = load_merged_ex(path)
    return graph, params, outputs


def load_merged_ex(path: str):
    """-> (graph, params, output_names, extras) where ``extras`` holds
    the optional sections a quantized merge adds (``"quant"``,
    ``"golden"`` — empty dict for a plain fp32 artifact). The serving
    predictor loads through here; :func:`load_merged` stays the
    old-reader surface."""
    with open(path, "rb") as f:
        raw = f.read()
    if raw[:4] != _MAGIC:
        raise IOError(f"{path}: not a merged model (bad magic)")
    digest, payload = raw[4:20], raw[20:]
    if hashlib.md5(payload).digest() != digest:
        raise IOError(f"{path}: merged model failed MD5 integrity check")
    data = pickle.loads(payload)
    extras = {k: data[k] for k in ("quant", "golden") if k in data}
    return data["graph"], data["params"], data["outputs"], extras
