"""``py_paddle.swig_paddle`` surface (the L7a SWIG training API).

The reference exposes its C++ stack to Python through SWIG
(``paddle/api/PaddleAPI.h:103-700``, ``Paddle.i``): Matrix/IVector/
Arguments value types with numpy bridges, ``GradientMachine`` driven by
``forward``/``forwardBackward``, the ``ParameterUpdater`` batch protocol
(startPass/startBatch/update/finishBatch/apply/restore/catchUpWith/
finishPass, ``PaddleAPI.h:576-644``), ``Trainer.create`` +
``trainOneDataBatch``, and per-batch evaluators. Raw-API programs
(``v1_api_demo/mnist/api_train.py``, ``v1_api_demo/gan/gan_trainer.py``)
are written directly against this surface.

Here the engine is native Python, so this module is a thin object layer
with the same names and calling conventions over the Network/optimizer
machinery — no binding generator, numpy in, numpy out. Slot order
follows the proto's ``input_layer_names``/``output_layer_names``, which
is how the reference's DataProviderConverter lines up arguments.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

# ---------------------------------------------------------------- enums
# (utils/GlobalConstants.h / PaddleAPI.h enum values)
PASS_TRAIN = 0
PASS_TEST = 1
PASS_GC = 2

CREATE_MODE_NORMAL = 0
CREATE_MODE_SGD_SPARSE_CPU_TRAINING = 3
CREATE_MODE_TESTING = 4

PARAMETER_VALUE = 0
PARAMETER_GRADIENT = 1
PARAMETER_MOMENTUM = 2


def initPaddle(*args):
    """``swig_paddle.initPaddle(...)``: gflags-style process init. Flags
    are forwarded to ``paddle.init`` semantics (mesh/logging); unknown
    flags are accepted and ignored like gflags does for modules that
    aren't linked in."""
    kwargs = {}
    for a in args:
        a = str(a).lstrip("-")
        k, _, v = a.partition("=")
        kwargs[k] = v
    from paddle_tpu.v2 import init as _init
    known = {}
    for k in ("use_gpu", "trainer_count", "seed", "log_period", "dot_period",
              "save_dir"):
        if k in kwargs:
            known[k] = kwargs[k]
    try:
        _init(**known)
    except TypeError:
        _init()


class RangeError(IndexError):
    """Out-of-range element access (the SWIG-thrown ``RangeError``)."""


class UnsupportError(RuntimeError):
    """Operation unsupported for this value kind (reference name)."""


# sparse enums (Matrix.h)
SPARSE_NON_VALUE = 0
SPARSE_VALUE = 1
SPARSE_CSR = 0
SPARSE_CSC = 1


def isUsingGpu():
    return False  # device residency is XLA's, not a per-object flag


def isGpuVersion():
    return False


# ---------------------------------------------------------- value types
class Matrix:
    """Dense or sparse 2-D float matrix (``PaddleAPI.h:103`` role).
    Sparse support covers the test surface (CSR row/col/value views);
    the engine consumes dense numpy either way."""

    def __init__(self, arr):
        self._a = np.atleast_2d(np.asarray(arr, np.float32))
        self._sparse = None  # (value_type, format, rows, cols, vals)

    @staticmethod
    def createDenseFromNumpy(arr, copy=True):
        return Matrix(np.array(arr, np.float32, copy=copy))

    @staticmethod
    def createDense(data, height, width):
        return Matrix(np.asarray(data, np.float32).reshape(height, width))

    @staticmethod
    def createZero(height, width):
        return Matrix(np.zeros((height, width), np.float32))

    @staticmethod
    def createSparse(height, width, nnz, non_value=True, trans=False,
                     use_gpu=False):
        m = Matrix(np.zeros((height, width), np.float32))
        m._sparse = {
            "value_type": SPARSE_NON_VALUE if non_value else SPARSE_VALUE,
            "format": SPARSE_CSR, "rows": [0] * (height + 1), "cols": [],
            "vals": []}
        return m

    def isSparse(self):
        return self._sparse is not None

    def getSparseValueType(self):
        if not self.isSparse():
            raise UnsupportError("dense matrix")
        return self._sparse["value_type"]

    def getSparseFormat(self):
        if not self.isSparse():
            raise UnsupportError("dense matrix")
        return self._sparse["format"]

    def sparseCopyFrom(self, rows, cols, values=()):
        s = self._sparse
        if s is None:
            raise UnsupportError("dense matrix")
        s["rows"], s["cols"] = list(rows), list(cols)
        s["vals"] = list(values)
        self._a = np.zeros_like(self._a)
        for r in range(len(s["rows"]) - 1):
            for k in range(s["rows"][r], s["rows"][r + 1]):
                c = s["cols"][k]
                self._a[r, c] = s["vals"][k] if s["vals"] else 1.0

    def getSparseRowCols(self, row):
        s = self._sparse
        return s["cols"][s["rows"][row]:s["rows"][row + 1]]

    def getSparseRowColsVal(self, row):
        s = self._sparse
        lo, hi = s["rows"][row], s["rows"][row + 1]
        return list(zip(s["cols"][lo:hi], s["vals"][lo:hi]))

    def get(self, x, y):
        # reference api/Matrix.cpp:116: x is the COLUMN, y the ROW
        # (element x + y * width)
        if not (0 <= x < self.getWidth() and 0 <= y < self.getHeight()):
            raise RangeError(f"({x}, {y}) out of {self._a.shape}")
        return float(self._a[y, x])

    def set(self, x, y, value):
        if not (0 <= x < self.getWidth() and 0 <= y < self.getHeight()):
            raise RangeError(f"({x}, {y}) out of {self._a.shape}")
        self._a[y, x] = value

    def copyToNumpyMat(self):
        return np.array(self._a)

    def toNumpyMatInplace(self):
        return self._a  # the backing array: mutations are visible

    def copyFromNumpyMat(self, arr):
        self._a = np.atleast_2d(np.asarray(arr, np.float32))

    def isGpu(self):
        return False

    def getHeight(self):
        return self._a.shape[0]

    def getWidth(self):
        return self._a.shape[1]

    def getData(self):
        return self._a.reshape(-1).tolist()


class IVector:
    """Int vector (ids / labels)."""

    def __init__(self, arr):
        self._a = np.asarray(arr, np.int32).reshape(-1)

    @staticmethod
    def createVectorFromNumpy(arr, copy=True):
        return IVector(np.array(arr, np.int32, copy=copy))

    @staticmethod
    def createCpuVectorFromNumpy(arr, copy=True):
        return IVector(np.array(arr, np.int32, copy=copy))

    @staticmethod
    def create(data, use_gpu=False):
        return IVector(np.asarray(list(data), np.int32))

    @staticmethod
    def createZero(size, use_gpu=False):
        return IVector(np.zeros(size, np.int32))

    def copyToNumpyArray(self):
        return np.array(self._a)

    def toNumpyArrayInplace(self):
        return self._a

    def isGpu(self):
        return False

    def getSize(self):
        return int(self._a.shape[0])

    def __len__(self):
        return self.getSize()

    def __getitem__(self, i):
        if not 0 <= i < self.getSize():
            raise RangeError(str(i))
        return int(self._a[i])

    def __setitem__(self, i, v):
        if not 0 <= i < self.getSize():
            raise RangeError(str(i))
        self._a[i] = v

    def getData(self):
        return self._a.tolist()


class Vector:
    """Float vector (parameter buffers use this shape)."""

    def __init__(self, arr):
        self._a = np.asarray(arr, np.float32).reshape(-1)

    @staticmethod
    def createVectorFromNumpy(arr, copy=True):
        return Vector(np.array(arr, np.float32, copy=copy))

    @staticmethod
    def create(data, use_gpu=False):
        return Vector(np.asarray(list(data), np.float32))

    @staticmethod
    def createZero(size, use_gpu=False):
        return Vector(np.zeros(size, np.float32))

    def copyToNumpyArray(self):
        return np.array(self._a)

    def toNumpyArrayInplace(self):
        return self._a

    def isGpu(self):
        return False

    def getSize(self):
        return int(self._a.shape[0])

    def __len__(self):
        return self.getSize()


class Arguments:
    """Slot-indexed network inputs/outputs (``api/Arguments.cpp`` role).
    Slot i of inputs lines up with ``input_layer_names[i]``; outputs with
    ``output_layer_names[i]`` — the DataProviderConverter contract."""

    def __init__(self, n: int):
        self._slots: List[Dict[str, Any]] = [dict() for _ in range(n)]

    @staticmethod
    def createArguments(n: int) -> "Arguments":
        return Arguments(n)

    def resize(self, n: int):
        self._slots = [dict() for _ in range(n)]

    def size(self) -> int:
        return len(self._slots)

    def getSlotNum(self) -> int:
        return len(self._slots)

    def _slot(self, i) -> Dict[str, Any]:
        while i >= len(self._slots):
            self._slots.append(dict())
        return self._slots[i]

    def setSlotValue(self, i, m: Matrix):
        self._slot(i)["value"] = m

    def setSlotIds(self, i, ids: IVector):
        self._slot(i)["ids"] = ids

    def getSlotValue(self, i) -> Matrix:
        return self._slots[i]["value"]

    def getSlotIds(self, i) -> IVector:
        return self._slots[i]["ids"]

    def setSlotSequenceStartPositions(self, i, starts: "IVector"):
        """Offset vector marking sequence boundaries within the flat slot
        (``Argument::sequenceStartPositions``); the engine converts to
        its padded+masked layout at feed time."""
        self._slot(i)["seq_starts"] = starts

    def getSlotSequenceStartPositions(self, i) -> "IVector":
        return self._slots[i]["seq_starts"]

    def setSlotFrameHeight(self, i, h):
        self._slot(i)["frame_height"] = h

    def setSlotFrameWidth(self, i, w):
        self._slot(i)["frame_width"] = w

    def getSlotFrameHeight(self, i=0):
        return self._slots[i].get("frame_height", 0)

    def getSlotFrameWidth(self, i=0):
        return self._slots[i].get("frame_width", 0)

    def sum(self) -> float:
        total = 0.0
        for slot in self._slots:
            if "value" in slot:
                total += float(slot["value"]._a.sum())
        return total


# ------------------------------------------------------------ parameters
class _ParameterBuffer:
    """A typed view of one parameter's buffer, flat like the reference's
    ``Vector`` handles (shape restored on write-back)."""

    def __init__(self, machine: "GradientMachine", name: str, kind: int):
        self._m, self._name, self._kind = machine, name, kind

    def _array(self):
        if self._kind == PARAMETER_VALUE:
            return np.asarray(jax.device_get(self._m._params[self._name]))
        if self._kind == PARAMETER_GRADIENT:
            g = self._m._grads.get(self._name)
            return (np.asarray(jax.device_get(g)) if g is not None
                    else np.zeros(self._shape(), np.float32))
        slots = self._m._opt_state["slots"].get(self._name, {}) \
            if self._m._opt_state else {}
        mom = slots.get("mom")
        return (np.asarray(jax.device_get(mom)) if mom is not None
                else np.zeros(self._shape(), np.float32))

    def _shape(self):
        return tuple(self._m._params[self._name].shape)  # no transfer

    def getSize(self) -> int:
        return int(np.prod(self._shape()))

    def copyToNumpyArray(self):
        return self._array().reshape(-1).copy()

    def copyFromNumpyArray(self, arr):
        if self._kind != PARAMETER_VALUE:
            raise ValueError("only PARAMETER_VALUE buffers are writable "
                             "through the api surface")
        shape = self._shape()
        self._m._params[self._name] = jnp.asarray(
            np.asarray(arr, np.float32).reshape(shape))


class ParameterConfigView:
    """``Parameter::getConfig()`` — a handle whose ``toProto()`` yields
    the ``ParameterConfig`` message (name/size/dims)."""

    def __init__(self, name: str, shape):
        self._name, self._shape = name, tuple(shape)

    def toProto(self):
        from paddle_tpu.proto import ParameterConfig_pb2
        pc = ParameterConfig_pb2.ParameterConfig()
        pc.name = self._name
        pc.size = int(np.prod(self._shape))
        dims = self._shape if len(self._shape) > 1 else (1, self._shape[0])
        pc.dims.extend(int(d) for d in dims)
        return pc

    @property
    def name(self):
        return self._name

    @property
    def shape(self):
        return self._shape


class _BoundVector(Vector):
    """A Vector view bound to a machine parameter buffer: writes commit
    back (the SWIG buffers alias C++ memory; here the commit is
    explicit)."""

    def __init__(self, arr, writeback=None):
        super().__init__(arr)
        self._writeback = writeback

    def commit(self):
        if self._writeback is not None:
            self._writeback(self._a)


class Parameter:
    def __init__(self, machine: "GradientMachine", name: str, pid: int = 0):
        self._m, self._name, self._pid = machine, name, pid

    def getName(self) -> str:
        return self._name

    def getID(self) -> int:
        return self._pid

    def getSize(self) -> int:
        return int(np.prod(self._m._params[self._name].shape))

    def getBuf(self, kind=PARAMETER_VALUE) -> _ParameterBuffer:
        return _ParameterBuffer(self._m, self._name, kind)

    def getConfig(self) -> ParameterConfigView:
        return ParameterConfigView(
            self._name, tuple(self._m._params[self._name].shape))

    def getBufs(self):
        """(value, gradient, slot...) Vector views; the value view
        commits back into the machine (``Parameter::getBufs`` feeding
        ``ParameterOptimizer::update``)."""
        m, name = self._m, self._name
        value = np.asarray(jax.device_get(m._params[name]))
        shape = value.shape

        def write_value(arr):
            m._params[name] = jnp.asarray(
                np.asarray(arr, np.float32).reshape(shape))

        bufs = [_BoundVector(value.reshape(-1).copy(), write_value)]
        g = m._grads.get(name)
        bufs.append(Vector(np.asarray(jax.device_get(g)).reshape(-1)
                           if g is not None
                           else np.zeros(value.size, np.float32)))
        slots = (m._opt_state or {}).get("slots", {}).get(name, {})
        for s in sorted(slots):
            bufs.append(Vector(
                np.asarray(jax.device_get(slots[s])).reshape(-1)))
        return bufs

    def save(self, path: str) -> bool:
        """Write the reference's binary parameter format
        (``Parameter::save``)."""
        from paddle_tpu.compat.param_format import save_v1_param
        try:
            save_v1_param(path, np.asarray(
                jax.device_get(self._m._params[self._name])))
            return True
        except OSError:
            return False

    def load(self, path: str) -> bool:
        from paddle_tpu.compat.param_format import load_v1_param
        try:
            arr = load_v1_param(path)
        except (OSError, ValueError):
            return False
        self.getBuf(PARAMETER_VALUE).copyFromNumpyArray(arr.reshape(-1))
        return True


# ------------------------------------------------------------- evaluator
class Evaluator:
    """Per-batch metric accumulator (``Evaluator`` via
    ``GradientMachine::makeEvaluator``). Accumulates between start() and
    finish(); prints the reference's ``name=value`` form."""

    def __init__(self, machine: "GradientMachine"):
        self._m = machine
        self._err = 0.0
        self._cnt = 0.0

    def start(self):
        self._err, self._cnt = 0.0, 0.0

    def finish(self):
        pass

    def accumulate(self, err: float, cnt: float):
        self._err += err
        self._cnt += cnt

    def getError(self) -> float:
        return self._err / max(self._cnt, 1.0)

    def __str__(self):
        if self._cnt == 0:
            return " classification_error_evaluator=nan "
        return f" classification_error_evaluator={self.getError():.6g} "


# ------------------------------------------------------- gradient machine
class GradientMachine:
    """``GradientMachine::create`` over a ``ModelConfig`` proto
    (``PaddleAPI.h:700`` region; createFromConfigProto at
    ``api/GradientMachine.cpp``). Imports the proto through
    ``compat.proto_import`` — the same path that executes wire-format
    configs — and drives the jitted Network."""

    def __init__(self, graph, seed: int = 0):
        from paddle_tpu.core.network import Network
        self._graph = graph
        outs = list(graph.output_layer_names) or list(graph.layers)
        self._network = Network(graph, outputs=outs)
        self._params = self._network.init_params(jax.random.PRNGKey(seed))
        self._meta = self._network.param_meta()
        # a generating config references the target-word embedding only
        # by PARAMETER NAME (GeneratedInput.embedding_name) — no layer
        # owns it, so the Network table misses it. Register it here so
        # init/loadParameters/save all cover the load-then-generate flow.
        from paddle_tpu.core.registry import ParamSpec
        for ldef in graph.layers.values():
            if ldef.type != "beam_search_group":
                continue
            gen = ldef.attrs.get("gen") or {}
            emb = gen.get("embedding_name")
            if emb and emb not in self._params:
                shape = (int(gen["size"]), int(gen["embedding_size"]))
                self._meta[emb] = ParamSpec(shape=shape)
                self._params[emb] = jax.random.normal(
                    jax.random.fold_in(jax.random.PRNGKey(seed), 99),
                    shape) / jnp.sqrt(shape[0])
        self._grads: Dict[str, jnp.ndarray] = {}
        self._opt_state: Optional[Dict[str, Any]] = None
        self._last_outputs: Optional[Dict[str, Any]] = None
        self._last_feed: Optional[Dict[str, Any]] = None
        self._rng = jax.random.PRNGKey(seed + 17)
        self._fwd = jax.jit(
            lambda p, f, r: self._network.apply(p, f, train=True, rng=r))
        self._fwd_test = jax.jit(
            lambda p, f: self._network.apply(p, f, train=False))
        from paddle_tpu.data.prefetch import RecompileGuard
        self._jit_guards = [
            RecompileGuard(self._fwd, warn_after=16, name="swig_fwd"),
            RecompileGuard(self._fwd_test, warn_after=16,
                           name="swig_fwd_test"),
        ]

        def loss_fn(p, f, r):
            # apply_with_state: batch-norm moving statistics update during
            # training exactly as in the SGD trainer's step
            outputs, updates = self._network.apply_with_state(
                p, f, train=True, rng=r)
            total = 0.0
            for n in self._cost_layers():
                v = outputs[n].value.astype(jnp.float32)
                total = total + jnp.sum(v) / v.shape[0]
            return total, (outputs, updates)

        self._grad_fn = jax.jit(jax.value_and_grad(loss_fn, has_aux=True))
        self._jit_guards.append(RecompileGuard(
            self._grad_fn, warn_after=16, name="swig_grad"))

    # -- construction ---------------------------------------------------
    @staticmethod
    def createFromConfigProto(model_config, mode=CREATE_MODE_NORMAL,
                              enable_types=None):
        from paddle_tpu.compat.proto_import import model_from_proto
        if hasattr(model_config, "layers") and not hasattr(
                model_config, "SerializeToString"):
            graph = model_config  # already a ModelDef
        else:
            graph = model_from_proto(model_config)
        return GradientMachine(graph)

    # testGradientMachine.py spelling
    createByModelConfig = createFromConfigProto

    def _cost_layers(self) -> List[str]:
        from paddle_tpu.compat.config_parser import COST_TYPES
        names = [n for n in self._graph.output_layer_names
                 if self._graph.layers[n].type in COST_TYPES]
        if not names:
            names = [n for n, l in self._graph.layers.items()
                     if l.type in COST_TYPES]
        return names

    # -- feed/slot mapping ----------------------------------------------
    def _input_names(self) -> List[str]:
        names = list(self._graph.input_layer_names)
        if not names:
            names = [n for n, l in self._graph.layers.items()
                     if l.type == "data"]
        return names

    def _feed_from(self, args: Arguments) -> Dict[str, Any]:
        from paddle_tpu.core.argument import Argument, from_ragged
        names = self._input_names()
        feed = {}
        for i, name in enumerate(names[: args.size()]):
            slot = args._slots[i]
            starts = slot.get("seq_starts")
            if starts is not None:
                # flat (totalTokens, ...) + offsets -> padded + masked
                # (the engine's native layout; Argument.h:84 semantics)
                offs = list(starts._a)
                flat = (slot["ids"]._a if "ids" in slot
                        else slot["value"]._a)
                dtype = np.int32 if "ids" in slot else np.float32
                rows = [flat[offs[j]:offs[j + 1]]
                        for j in range(len(offs) - 1)]
                feed[name] = from_ragged(rows, dtype=dtype)
            elif "ids" in slot:
                feed[name] = Argument(value=jnp.asarray(
                    slot["ids"]._a, jnp.int32))
            elif "value" in slot:
                feed[name] = Argument(value=jnp.asarray(
                    slot["value"]._a, jnp.float32))
        return feed

    def _fill_out(self, outputs, outArgs: Arguments):
        names = [n for n in self._graph.output_layer_names] or \
            list(outputs)
        outArgs.resize(len(names))
        for i, n in enumerate(names):
            v = np.asarray(jax.device_get(outputs[n].value))
            if v.ndim == 1:
                v = v[:, None]
            outArgs.setSlotValue(i, Matrix(v))

    # -- the SWIG protocol ----------------------------------------------
    def start(self):
        pass

    def finish(self):
        pass

    def getParameters(self) -> List[Parameter]:
        return [Parameter(self, n, i)
                for i, n in enumerate(self._params)]

    def getParameter(self, name: str) -> Parameter:
        if name not in self._params:
            raise KeyError(name)
        return Parameter(self, name, list(self._params).index(name))

    def getParameterSize(self) -> int:
        return len(self._params)

    def randParameters(self):
        self._params = self._network.init_params(
            jax.random.PRNGKey(int(np.random.randint(0, 2**31 - 1))))

    def forward(self, inArgs: Arguments, outArgs: Arguments, passType):
        feed = self._feed_from(inArgs)
        if passType == PASS_TRAIN:
            self._rng, r = jax.random.split(self._rng)
            self._last_rng = r  # backward() must see the SAME dropout
            outputs = self._fwd(self._params, feed, r)
        else:
            outputs = self._fwd_test(self._params, feed)
        self._last_outputs, self._last_feed = outputs, feed
        for g in self._jit_guards:
            g.check()
        self._fill_out(outputs, outArgs)

    def forwardBackward(self, inArgs: Arguments, outArgs: Arguments,
                        passType):
        feed = self._feed_from(inArgs)
        self._rng, r = jax.random.split(self._rng)
        (cost, (outputs, updates)), grads = self._grad_fn(
            self._params, feed, r)
        self._grads = grads
        self._state_updates = dict(updates)
        self._last_outputs, self._last_feed = outputs, feed
        # the scalar the loss_fn actually optimized (batch-mean over every
        # cost layer) — callers read this instead of sniffing output slots
        self._last_cost = float(jax.device_get(cost))
        for g in self._jit_guards:
            g.check()
        self._fill_out(outputs, outArgs)

    def backward(self, callback=None):
        """Backward over the LAST forward's batch, then the per-parameter
        update callback — the pipelined-update-during-backward protocol
        (``TrainerInternal.cpp:70-74``; here gradients arrive all at once
        from ``jax.grad``, so the callback runs per parameter after).
        Reuses the last PASS_TRAIN forward's rng so gradients belong to
        the same dropout realization the caller observed."""
        if self._last_feed is None:
            raise RuntimeError("backward() needs a prior forward()")
        r = getattr(self, "_last_rng", None)
        if r is None:
            self._rng, r = jax.random.split(self._rng)
        (_, (outputs, updates)), grads = self._grad_fn(
            self._params, self._last_feed, r)
        self._grads = grads
        self._state_updates = dict(updates)
        if callback is not None:
            for p in self.getParameters():
                callback(p)

    def makeEvaluator(self) -> Evaluator:
        return Evaluator(self)

    def eval(self, evaluator: Evaluator):
        """Accumulate classification error of the last forward into the
        evaluator (``Evaluator.cpp:35`` ClassificationErrorEvaluator)."""
        from paddle_tpu.trainer.evaluators import classification_error
        if self._last_outputs is None:
            return
        for n in self._cost_layers():
            cdef = self._graph.layers[n]
            if cdef.type != "multi-class-cross-entropy":
                continue
            out_l, lab_l = cdef.input_names()[0], cdef.input_names()[1]
            outs = self._last_outputs
            lab = outs.get(lab_l) or self._last_feed.get(lab_l)
            if lab is None:
                continue
            err, cnt = classification_error(outs[out_l], lab)
            evaluator.accumulate(float(err), float(cnt))

    def loadParameters(self, path: str, strict: bool = True):
        """``GradientMachine::loadParameters`` (``PaddleAPI.h:790``):
        accepts an engine ``.npz`` checkpoint or a reference v1 model
        directory (one Parameter::save file per parameter).

        ``strict`` (default on, the reference's behavior — its
        ``Parameter::load`` CHECK-fails on a missing file) raises when
        any model parameter is absent from the checkpoint; pass
        ``strict=False`` for intentional partial loads (the old
        warn-and-keep-random-init behavior, ADVICE r05 #4)."""
        import os
        if os.path.isdir(path):
            from paddle_tpu.compat.param_format import load_v1_model_dir
            raw = load_v1_model_dir(path)
            loaded = {}
            for name, spec in self._meta.items():
                if name not in raw:
                    continue
                want = 1
                for d in spec.shape:
                    want *= int(d)
                if raw[name].size != want:
                    raise ValueError(
                        f"loadParameters: {name!r} has {raw[name].size} "
                        f"values, the model needs {want} (shape "
                        f"{spec.shape})")
                loaded[name] = jnp.asarray(raw[name].reshape(spec.shape))
        else:
            from paddle_tpu.trainer.checkpoint import load_params
            params, _ = load_params(path)
            loaded = {}
            for name, v in params.items():
                if name not in self._params:
                    continue
                # params outside the Network's table (e.g. a generation
                # embedding installed post-hoc) aren't in _meta; their
                # current array's .shape is the contract (no host copy)
                want = tuple(int(d) for d in self._meta[name].shape) \
                    if name in self._meta else tuple(
                        self._params[name].shape)
                if tuple(v.shape) != want:
                    raise ValueError(
                        f"loadParameters: {name!r} has shape {v.shape}, "
                        f"the model needs {want}")
                loaded[name] = jnp.asarray(v)
        missing = sorted(set(self._params) - set(loaded))
        if missing and strict:
            # raise BEFORE mutating: a partially-loaded machine silently
            # training/generating from garbage is the failure mode
            raise ValueError(
                f"loadParameters: {len(missing)} model parameters absent "
                f"from {path}: {missing[:8]}"
                + ("..." if len(missing) > 8 else "")
                + " (pass strict=False for an intentional partial load)")
        # every shape validated above — only now mutate, so a mismatch
        # never leaves the machine half-loaded
        self._params.update(loaded)
        if missing:
            from paddle_tpu.utils import logger
            logger.warning("loadParameters: %d parameters missing in %s "
                           "(kept initialized): %s", len(missing), path,
                           missing[:5])

    def asSequenceGenerator(self, dict=(), begin_id=None, end_id=None,
                            max_length=100, beam_size=-1
                            ) -> "SequenceGenerator":
        """``GradientMachine::asSequenceGenerator`` (``PaddleAPI.h:809``):
        the raw-API generation surface over the engine's jitted beam
        search. ``begin_id``/``end_id`` default to the config's
        generator bos/eos (``None`` here where the C++ default of ``0``
        cannot be told apart from an explicit 0)."""
        return SequenceGenerator(self, dict=dict, begin_id=begin_id,
                                 end_id=end_id, max_length=max_length,
                                 beam_size=beam_size)


# ----------------------------------------------------- sequence generator
class ISequenceResults:
    """N-best results from one generation call (``PaddleAPI.h:1003-1022``).
    Concrete results are ``_PathSequenceResults``; this base mirrors the
    reference's abstract interface."""

    def getSize(self) -> int:
        raise NotImplementedError

    def getSentence(self, id, split=False) -> str:
        raise NotImplementedError

    def getSequence(self, id) -> List[int]:
        raise NotImplementedError

    def getScore(self, id) -> float:
        raise NotImplementedError


class _PathSequenceResults(ISequenceResults):
    """``PathSequenceResults`` (``api/SequenceGenerator.cpp:158-200``):
    paths sorted best-first, scores are cumulative log-probabilities."""

    def __init__(self, paths, dict_words):
        self._paths = paths  # [(ids: List[int], logprob: float)]
        self._dict = list(dict_words)

    def getSize(self) -> int:
        return len(self._paths)

    def _check(self, id):
        if not 0 <= id < len(self._paths):
            raise RangeError(str(id))

    def getSentence(self, id, split=False) -> str:
        self._check(id)
        ids = self._paths[id][0]
        if ids and (not self._dict or max(ids) >= len(self._dict)):
            raise UnsupportError(
                f"getSentence needs a word dict covering id "
                f"{max(ids)} (have {len(self._dict)} words) — call "
                "setDict() / pass dict= to asSequenceGenerator")
        words = [self._dict[i] for i in ids]
        return (" " if split else "").join(words)

    def getSequence(self, id) -> List[int]:
        self._check(id)
        return list(self._paths[id][0])

    def getScore(self, id) -> float:
        self._check(id)
        return float(self._paths[id][1])


class SequenceGenerator:
    """``SequenceGenerator`` (``PaddleAPI.h:1024-1046``, impl
    ``api/SequenceGenerator.cpp``): obtained via
    ``GradientMachine.asSequenceGenerator``. Where the reference re-runs
    the machine per candidate path with host-side state save/restore
    (``findNBest``, ``SequenceGenerator.cpp:42-113``), this drives the
    engine's single jitted ``lax.scan`` beam search
    (``core/generation.py``) — same N-best contract, sorted by score."""

    def __init__(self, machine: GradientMachine, dict=(), begin_id=None,
                 end_id=None, max_length=100, beam_size=-1):
        self._machine = machine
        self._dict = list(dict)
        self._bos = begin_id
        self._eos = end_id
        self._max_length = int(max_length)
        self._beam_size = int(beam_size)
        self._hooks = {}  # registerBeamSearchControlCallbacks
        self._built = None  # (engine generator, encoder Network)

    # -- setters (PaddleAPI.h:1040-1044) --------------------------------
    def setDict(self, dict):
        self._dict = list(dict)

    def setBos(self, bos):
        self._bos = int(bos)
        self._built = None  # bos/eos are trace-time constants

    def setEos(self, eos):
        self._eos = int(eos)
        self._built = None

    def setMaxLength(self, maxlength):
        self._max_length = int(maxlength)

    def setBeamSize(self, beamSize):
        self._beam_size = int(beamSize)

    # -- beam-control callbacks (RecurrentGradientMachine.h:92-145) -----
    def registerBeamSearchControlCallbacks(self, candidate_adjust=None,
                                           drop_callback=None,
                                           norm_or_drop=None,
                                           stop_beam_search=None):
        """``RecurrentGradientMachine::registerBeamSearchControlCallbacks``
        surfaced on the generator handle. Registered hooks MERGE with
        the config's pinned ones (``dsl.beam_search``): a hook passed
        here wins for its slot; a slot left ``None`` keeps the
        config-pinned hook (to disable a pinned hook, rebuild the config
        without it). Signatures in
        ``core/generation.py:SequenceGenerator.generate``."""
        self._hooks = {"candidate_adjust": candidate_adjust,
                       "drop_callback": drop_callback,
                       "norm_or_drop": norm_or_drop,
                       "stop_beam_search": stop_beam_search}

    def removeBeamSearchControlCallbacks(self):
        """``removeBeamSearchControlCallbacks``: back to the config's
        pinned hooks (or none)."""
        self._hooks = {}

    # -------------------------------------------------------------------
    def _build(self):
        if self._built is not None:
            return self._built
        from paddle_tpu.core.generation import \
            SequenceGenerator as EngineGenerator
        from paddle_tpu.core.network import Network
        graph = self._machine._graph
        gen_name = next((n for n, l in graph.layers.items()
                         if l.type == "beam_search_group"), None)
        if gen_name is None:
            raise UnsupportError(
                "asSequenceGenerator needs a generating config (a "
                "beam_search group); this model has none")
        engine = EngineGenerator(graph, gen_name)
        if self._bos is not None or self._eos is not None:
            gen = dict(engine.gen)
            if self._bos is not None:
                gen["bos_id"] = self._bos
            if self._eos is not None:
                gen["eos_id"] = self._eos
            engine.gen = gen
        encoder = Network(graph, outputs=engine.static_input_layers())
        self._built = (engine, encoder)
        return self._built

    def generateSequence(self, inArgs: Arguments) -> ISequenceResults:
        """N-best generation for the input sequence(s), sorted by score
        (``SequenceGenerator::generateSequence``). Results are
        batch-major: with B input sequences and beam K, path ``b*K + k``
        is sequence b's k-th best."""
        engine, encoder = self._build()
        m = self._machine
        emb_name = engine.gen["embedding_name"]
        if emb_name not in m._params:
            raise KeyError(
                f"generation embedding {emb_name!r} is not in the "
                "machine's parameters — loadParameters() a trained "
                "model first")
        feed = m._feed_from(inArgs)
        outer = encoder.apply(m._params, feed, train=False)
        tokens, scores, lengths = engine.generate(
            m._params, outer,
            beam_size=self._beam_size if self._beam_size > 0 else None,
            max_length=self._max_length,
            **{k: v for k, v in self._hooks.items() if v is not None})
        tokens = np.asarray(tokens)
        scores = np.asarray(scores)
        lengths = np.asarray(lengths)
        paths = []
        for b in range(tokens.shape[0]):
            for k in range(tokens.shape[1]):
                ids = tokens[b, k, : int(lengths[b, k])].tolist()
                paths.append((ids, float(scores[b, k])))
        return _PathSequenceResults(paths, self._dict)


# ------------------------------------------------------ parameter updater
class ParameterUpdater:
    """The local updater protocol (``PaddleAPI.h:576-644``,
    ``TrainerInternal.cpp:66-131`` batch lifecycle) over a paddle_tpu
    optimizer: startPass → N×(startBatch → [update per param] →
    finishBatch) → [apply/restore for model-average test] → finishPass."""

    def __init__(self, optimizer):
        if hasattr(optimizer, "make_optimizer"):
            optimizer = optimizer.make_optimizer()  # OptimizationConfig
        self._opt = optimizer
        self._m: Optional[GradientMachine] = None
        self._bsz = 1
        self._pass = 0
        self._backup: Optional[Dict[str, jnp.ndarray]] = None

    @staticmethod
    def createLocalUpdater(optimizer):
        return ParameterUpdater(optimizer)

    def init(self, machine: GradientMachine):
        self._m = machine
        machine._opt_state = self._opt.init(machine._params, machine._meta)

    def startPass(self):
        pass

    def startBatch(self, batch_size: int) -> int:
        self._bsz = batch_size
        return PASS_TRAIN

    def update(self, parameter: Parameter):
        # per-parameter pipelined update in the reference; here the whole
        # dict steps once in finishBatch (same observable result)
        pass

    def finishBatch(self, cost: float = 0.0):
        m = self._m
        if m._grads:
            m._params, m._opt_state = self._opt.update(
                m._grads, m._opt_state, m._params, m._meta,
                batch_size=self._bsz, num_passes=self._pass)
            m._grads = {}
        if getattr(m, "_state_updates", None):
            m._params.update(m._state_updates)  # batch-norm moving stats
            m._state_updates = {}

    def apply(self):
        """Swap in the model-averaged parameters (AverageOptimizer's
        test-time apply); no-op without an average window."""
        m = self._m
        if m._opt_state and "avg" in m._opt_state and self._backup is None:
            self._backup = dict(m._params)
            m._params = self._opt.averaged_params(m._opt_state, m._params)

    def restore(self):
        if self._backup is not None:
            self._m._params = self._backup
            self._backup = None

    def catchUpWith(self):
        # dense parameters are always current here; the sparse lazy-row
        # catch-up lives inside the optimizer's sparse path
        pass

    def finishPass(self):
        self._pass += 1


# ----------------------------------------------- config + raw optimizers
class OptimizationConfig:
    """``swig_paddle.OptimizationConfig``: a handle ParameterOptimizer
    consumes. Wraps an engine-optimizer factory (from a parsed config's
    settings, or mapped from a raw ``OptimizationConfig`` proto)."""

    def __init__(self, factory):
        self._factory = factory

    @staticmethod
    def createFromProtoString(blob: bytes) -> "OptimizationConfig":
        """``OptimizationConfig::createFromProtoString``
        (``PaddleAPI.h:533``): deserialize the wire-format proto and
        route through ``createFromProto``."""
        from paddle_tpu.proto import OptimizationConfig as _OptProto
        proto = _OptProto()
        proto.ParseFromString(bytes(blob))
        return OptimizationConfig.createFromProto(proto)

    @staticmethod
    def createFromProto(proto, parameters=None):
        """Map a wire-format ``OptimizationConfig`` onto an
        engine-optimizer factory. ``parameters`` (the sibling
        ``model_config.parameters``, when the caller has the full
        ``TrainerConfig``) recovers the momentum coefficient — it rides
        the wire per-parameter (``ParameterConfig.momentum``, the
        reference's default_momentum path), not on OptimizationConfig."""
        from paddle_tpu.compat.trainer_config_helpers.optimizers import (
            build_optimizer)
        settings = {
            "learning_rate": proto.learning_rate,
            "learning_method": None,
            "batch_size": getattr(proto, "batch_size", 1),
            "learning_rate_schedule": proto.learning_rate_schedule or None,
            "learning_rate_decay_a": proto.learning_rate_decay_a,
            "learning_rate_decay_b": proto.learning_rate_decay_b,
            "learning_rate_args": proto.learning_rate_args,
        }
        method = proto.learning_method or "momentum"
        # map the proto's method string through the helper classes so the
        # per-method hyper-params (momentum/ada_epsilon/...) ride along
        from paddle_tpu.compat.trainer_config_helpers import optimizers as o
        cls = {
            "momentum": lambda: o.MomentumOptimizer(
                max((p.momentum for p in parameters), default=0.0)
                if parameters is not None else 0.0),
            "adagrad": lambda: o.AdaGradOptimizer(),
            "adadelta": lambda: o.AdaDeltaOptimizer(),
            "rmsprop": lambda: o.RMSPropOptimizer(),
            "decayed_adagrad": lambda: o.DecayedAdaGradOptimizer(),
            "adam": lambda: o.AdamOptimizer(),
            "adamax": lambda: o.AdamaxOptimizer(),
        }.get(method)
        if cls is not None:
            settings["learning_method"] = cls()
        return OptimizationConfig(lambda: build_optimizer(settings))

    def make_optimizer(self):
        return self._factory()


class _ProtoParsedConfig:
    """Wire-format stand-in for ``config_parser.ParsedConfig``: the two
    members ``TrainerConfig`` hands out (``model_config`` proto +
    ``optimizer`` factory), reconstituted from a deserialized
    ``TrainerConfig`` message instead of re-run python source."""

    def __init__(self, proto):
        self.trainer_config = proto
        self.model_config = proto.model_config

    def optimizer(self):
        return OptimizationConfig.createFromProto(
            self.trainer_config.opt_config,
            parameters=self.trainer_config.model_config.parameters,
        ).make_optimizer()


class TrainerConfig:
    """``swig_paddle.TrainerConfig``: parse a config file and hand out
    its model/optimization pieces (``TrainerConfigHelper`` role)."""

    def __init__(self, parsed):
        self._parsed = parsed

    @staticmethod
    def createFromTrainerConfigFile(path, config_args: str = ""):
        from paddle_tpu.compat.config_parser import parse_config
        return TrainerConfig(parse_config(path, config_args))

    @staticmethod
    def createFromProtoString(blob: bytes) -> "TrainerConfig":
        """``TrainerConfig::createFromProtoString`` (``PaddleAPI.h:631``):
        a serialized ``TrainerConfig`` needs no python source to re-run —
        the wire-format importer (``compat/proto_import.py``) rebuilds a
        runnable graph from its expanded ``model_config``, and the
        ``opt_config`` maps through ``OptimizationConfig.createFromProto``
        (the same path ``GradientMachine.createFromConfigProto`` uses)."""
        from paddle_tpu.proto import TrainerConfig as _TCProto
        proto = _TCProto()
        proto.ParseFromString(bytes(blob))
        return TrainerConfig(_ProtoParsedConfig(proto))

    def getModelConfig(self):
        return self._parsed.model_config

    def getOptimizationConfig(self) -> OptimizationConfig:
        parsed = self._parsed
        return OptimizationConfig(parsed.optimizer)


class ParameterOptimizer:
    """Per-parameter optimizer handles (``paddle/optimizer``'s C-ABI role
    consumed through SWIG, ``testTrain.py`` / ``testGradientMachine.py``
    protocol): create per parameter, startPass/startBatch, then
    ``update([value, grad, ...], param_config)`` applies one step to the
    value buffer (committed back to its machine), finishBatch/finishPass."""

    def __init__(self, optimizer):
        self._opt = optimizer
        self._state: Dict[str, Any] = {}
        self._bsz = 1
        self._pass = 0

    @staticmethod
    def create(opt_config: OptimizationConfig) -> "ParameterOptimizer":
        return ParameterOptimizer(opt_config.make_optimizer())

    def getParameterTypes(self):
        return self._opt.enable_types()

    def init(self, num_rows: int, param_config=None):
        pass  # state allocates lazily per parameter on first update

    def startPass(self):
        pass

    def startBatch(self, batch_size: int):
        self._bsz = batch_size

    def update(self, vecs, param_config):
        name = getattr(param_config, "name", None) or param_config.toProto().name
        shape = getattr(param_config, "shape", None)
        value, grad = vecs[0], vecs[1]
        arr = value._a.reshape(shape) if shape else value._a
        g = grad._a.reshape(arr.shape)
        params = {name: jnp.asarray(arr)}
        grads = {name: jnp.asarray(g)}
        if name not in self._state:
            self._state[name] = self._opt.init(params, None)
        new_params, self._state[name] = self._opt.update(
            grads, self._state[name], params, None,
            batch_size=self._bsz, num_passes=self._pass)
        value._a[:] = np.asarray(
            jax.device_get(new_params[name])).reshape(-1)
        if hasattr(value, "commit"):
            value.commit()

    def needSpecialTraversal(self, param_config) -> bool:
        return False

    def finishBatch(self):
        pass

    def finishPass(self):
        self._pass += 1


# ---------------------------------------------------------------- trainer
class Trainer:
    """``api.Trainer.create(config, machine)`` + the train-by-batch calls
    the GAN demo drives (``Trainer.cpp:402`` trainOneDataBatch)."""

    def __init__(self, machine: GradientMachine, updater: ParameterUpdater):
        self._machine = machine
        self._updater = updater
        self._outArgs = Arguments.createArguments(0)

    @staticmethod
    def create(config, machine: GradientMachine) -> "Trainer":
        # accepted spellings: a ParsedConfig (parse_config return), this
        # module's TrainerConfig/OptimizationConfig handles, or a bare
        # engine Optimizer
        if isinstance(config, TrainerConfig):
            opt = config.getOptimizationConfig().make_optimizer()
        elif isinstance(config, OptimizationConfig):
            opt = config.make_optimizer()
        elif hasattr(config, "optimizer"):
            opt = config.optimizer()
        else:
            opt = config
        updater = ParameterUpdater(opt)
        updater.init(machine)
        return Trainer(machine, updater)

    def startTrain(self):
        self._machine.start()

    def finishTrain(self):
        self._machine.finish()

    def startTrainPass(self):
        self._updater.startPass()

    def finishTrainPass(self):
        self._updater.finishPass()

    def trainOneDataBatch(self, batch_size: int, args: Arguments) -> float:
        pt = self._updater.startBatch(batch_size)
        self._machine.forwardBackward(args, self._outArgs, pt)
        for p in self._machine.getParameters():
            self._updater.update(p)
        # the machine records the scalar its loss_fn optimized (batch-mean
        # over all cost layers) — a config may declare a non-cost output
        # in slot 0, so never sniff output slots for the cost
        # (Trainer.cpp:402 likewise reads the machine's cost)
        cost = self._machine._last_cost
        self._updater.finishBatch(cost)
        return cost

    def startTestPeriod(self):
        self._updater.apply()  # model-averaged params for testing

    def testOneDataBatch(self, batch_size: int, args: Arguments):
        self._machine.forward(args, self._outArgs, PASS_TEST)

    def finishTestPeriod(self):
        self._updater.restore()

    def getForwardOutput(self):
        """The last batch's outputs as [{'value': ndarray}, ...]
        (``Trainer::getForwardOutput`` through the SWIG typemap)."""
        return [{"value": self._outArgs.getSlotValue(i).copyToNumpyMat()}
                for i in range(self._outArgs.getSlotNum())]
