"""``paddle.trainer_config_helpers.activations`` surface.

Activation objects whose ``.name`` is the proto ``active_type`` string
(`trainer_config_helpers/activations.py`; applied by the engine's
activation table, paddle_tpu/layers/activations.py).
"""

__all__ = [
    "TanhActivation", "SigmoidActivation", "SoftmaxActivation",
    "IdentityActivation", "LinearActivation", "SequenceSoftmaxActivation",
    "ExpActivation", "ReluActivation", "BReluActivation",
    "SoftReluActivation", "STanhActivation", "AbsActivation",
    "SquareActivation", "BaseActivation", "LogActivation",
    "SqrtActivation", "ReciprocalActivation",
]


class BaseActivation:
    name = ""
    support_hppl = True

    def __init__(self):
        pass

    def __repr__(self):
        return self.name or "linear"


def _make(cls_name, act_name):
    cls = type(cls_name, (BaseActivation,), {"name": act_name})
    return cls


TanhActivation = _make("TanhActivation", "tanh")
SigmoidActivation = _make("SigmoidActivation", "sigmoid")
SoftmaxActivation = _make("SoftmaxActivation", "softmax")
SequenceSoftmaxActivation = _make("SequenceSoftmaxActivation",
                                  "sequence_softmax")
IdentityActivation = _make("IdentityActivation", "")
LinearActivation = IdentityActivation
ReluActivation = _make("ReluActivation", "relu")
BReluActivation = _make("BReluActivation", "brelu")
SoftReluActivation = _make("SoftReluActivation", "softrelu")
STanhActivation = _make("STanhActivation", "stanh")
AbsActivation = _make("AbsActivation", "abs")
SquareActivation = _make("SquareActivation", "square")
ExpActivation = _make("ExpActivation", "exponential")
LogActivation = _make("LogActivation", "log")
SqrtActivation = _make("SqrtActivation", "sqrt")
ReciprocalActivation = _make("ReciprocalActivation", "reciprocal")
