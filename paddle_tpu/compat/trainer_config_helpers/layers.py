"""``paddle.trainer_config_helpers.layers`` surface.

The 100+ v1 layer helpers (`trainer_config_helpers/layers.py`, 6212 LoC)
re-implemented over the native graph DSL: each helper validates its
arguments, applies the reference's defaults/naming conventions
(``__fc_layer_0__`` etc.), and appends a ``LayerDef`` whose ``type`` is
the same ``LayerConfig.type`` string the reference registers — so the
engine's registry (paddle_tpu/core/registry.py) executes it and the proto
exporter (paddle_tpu/compat/proto_export.py) can emit the contract
``ModelConfig``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Union

from paddle_tpu.compat.config_parser import ctx, ensure_ctx
from paddle_tpu.compat.trainer_config_helpers.activations import (
    BaseActivation, IdentityActivation, LinearActivation, ReluActivation,
    SigmoidActivation, TanhActivation)
from paddle_tpu.compat.trainer_config_helpers.attrs import (
    ExtraLayerAttribute, ParameterAttribute)
from paddle_tpu.compat.trainer_config_helpers.poolings import (
    AvgPooling, BasePoolingType, MaxPooling)
from paddle_tpu.config import dsl
from paddle_tpu.config.dsl import GeneratedInput, LayerOutput  # noqa: F401


def StaticInput(input, is_seq=False, size=None):
    """Reference StaticInput accepts (input, is_seq, size). The native
    group always passes the WHOLE Argument — including its sequence
    structure/mask — to every step, so is_seq is honored implicitly;
    size is informational (the layer carries it)."""
    return dsl.StaticInput(_one(input))
from paddle_tpu.config.model_config import Input, LayerDef, ParamAttr

__all__ = [
    'full_matrix_projection', 'AggregateLevel', 'ExpandLevel',
    'identity_projection', 'dotmul_projection', 'dotmul_operator',
    'repeat_layer', 'seq_reshape_layer', 'table_projection', 'mixed_layer',
    'data_layer', 'embedding_layer', 'fc_layer', 'grumemory',
    'pooling_layer', 'lstmemory', 'last_seq', 'first_seq', 'cos_sim',
    'hsigmoid', 'conv_projection', 'mse_cost', 'regression_cost',
    'classification_cost', 'LayerOutput', 'img_conv_layer',
    'img_pool_layer', 'batch_norm_layer', 'img_cmrnorm_layer',
    'addto_layer', 'concat_layer', 'seq_concat_layer', 'lstm_step_layer',
    'recurrent_group', 'memory', 'StaticInput', 'expand_layer',
    'scaling_layer', 'scaling_projection', 'power_layer',
    'interpolation_layer', 'bilinear_interp_layer', 'trans_layer',
    'rotate_layer', 'sum_to_one_norm_layer', 'row_l2_norm_layer',
    'get_output_layer', 'LayerType', 'context_projection', 'beam_search',
    'maxid_layer', 'GeneratedInput', 'SubsequenceInput', 'gru_step_layer',
    'gru_step_naive_layer', 'recurrent_layer', 'BaseGeneratedInput',
    'conv_operator', 'conv_shift_layer', 'tensor_layer',
    'selective_fc_layer', 'sampling_id_layer', 'slope_intercept_layer',
    'trans_full_matrix_projection', 'linear_comb_layer',
    'convex_comb_layer', 'ctc_layer', 'warp_ctc_layer', 'crf_layer',
    'crf_decoding_layer', 'nce_layer', 'cross_entropy_with_selfnorm',
    'cross_entropy', 'multi_binary_label_cross_entropy', 'sum_cost',
    'rank_cost', 'lambda_cost', 'huber_cost', 'block_expand_layer',
    'maxout_layer', 'out_prod_layer', 'printer_layer', 'print_layer',
    'priorbox_layer', 'cross_channel_norm_layer', 'multibox_loss_layer',
    'detection_output_layer', 'spp_layer', 'pad_layer', 'eos_layer',
    'smooth_l1_cost', 'layer_support', 'multiplex_layer', 'row_conv_layer',
    'dropout_layer', 'prelu_layer', 'gated_unit_layer', 'crop_layer',
    'sub_nested_seq_layer', 'clip_layer', 'slice_projection',
    'kmax_sequence_score_layer',
]


class LayerType:
    """The proto ``LayerConfig.type`` vocabulary."""

    DATA = 'data'
    MIXED_LAYER = 'mixed'
    LSTMEMORY = 'lstmemory'
    GRUMEMORY = 'gated_recurrent'
    SEQUENCE_LAST_INSTANCE = 'seqlastins'
    SEQUENCE_FIRST_INSTANCE = 'seqlastins'
    SEQUENCE_RESHAPE = 'seqreshape'
    POOLING_MAX = 'max'
    POOLING_AVG = 'average'
    FC_LAYER = 'fc'
    COST = 'cost'
    COSINE_SIM_VEC = 'cos_vm'
    COSINE_SIM = 'cos'
    HSIGMOID = 'hsigmoid'
    CONV_LAYER = 'conv'
    CONVTRANS_LAYER = 'convt'
    EXCONV_LAYER = 'exconv'
    EXCONVTRANS_LAYER = 'exconvt'
    CUDNNCONV_LAYER = 'cudnn_conv'
    POOL_LAYER = 'pool'
    BATCH_NORM_LAYER = 'batch_norm'
    NORM_LAYER = 'norm'
    ADDTO_LAYER = 'addto'
    CONCAT_LAYER = 'concat'
    SEQUENCE_CONCAT_LAYER = 'seqconcat'


class AggregateLevel:
    TO_NO_SEQUENCE = 'non-seq'
    TO_SEQUENCE = 'seq'
    # legacy aliases
    EACH_TIMESTEP = 'non-seq'
    EACH_SEQUENCE = 'seq'


class ExpandLevel:
    FROM_NO_SEQUENCE = 'non-seq'
    FROM_SEQUENCE = 'seq'
    FROM_TIMESTEP = 'non-seq'


def layer_support(*attrs):
    """Decorator marker in the reference; a no-op passthrough here."""

    def deco(fn):
        return fn

    return deco


# ------------------------------------------------------------------ helpers
def _name(name: Optional[str], prefix: str) -> str:
    return name if name is not None else ensure_ctx().auto_name(prefix)


def _act(act, default: type = TanhActivation) -> str:
    if act is None:
        act = default()
    if isinstance(act, BaseActivation):
        return act.name
    if isinstance(act, str):
        return act
    raise TypeError(f"bad activation {act!r}")


def _pattr(attr) -> Optional[ParamAttr]:
    if attr is None:
        # default_initial_std() etc. set parse-wide defaults that apply
        # wherever a layer gives no explicit attribute (single source:
        # ConfigContext.default_param_attr)
        return ensure_ctx().default_param_attr()
    if isinstance(attr, ParameterAttribute):
        return attr.to_param_attr()
    if isinstance(attr, ParamAttr):
        return attr
    if isinstance(attr, dict):
        return ParamAttr(**attr)
    raise TypeError(f"bad param attr {attr!r}")


def _battr(bias_attr, default: bool = True):
    """Reference bias semantics: None -> default; False/0 -> no bias;
    True -> default bias; ParameterAttribute -> custom bias."""
    if bias_attr is None:
        return default
    if isinstance(bias_attr, ParameterAttribute):
        return bias_attr.to_param_attr()
    return bool(bias_attr)


def _one(x) -> LayerOutput:
    if isinstance(x, (list, tuple)):
        if len(x) != 1:
            raise ValueError("this layer takes exactly one input")
        x = x[0]
    if isinstance(x, MixedLayerType):
        x = x._finalize()
    if not isinstance(x, LayerOutput):
        raise TypeError(f"input must be a LayerOutput, got {type(x)}")
    return x


def _many(x) -> List[LayerOutput]:
    xs = [x] if isinstance(x, (LayerOutput, MixedLayerType)) else list(x)
    xs = [i._finalize() if isinstance(i, MixedLayerType) else i for i in xs]
    for i in xs:
        if not isinstance(i, LayerOutput):
            raise TypeError(f"input must be LayerOutput, got {type(i)}")
    return xs


def _layer(name, type_, inputs, *, size=None, act="", bias=False,
           drop_rate=0.0, attrs=None, layer_attr=None) -> LayerOutput:
    extra = ExtraLayerAttribute.to_kwargs(layer_attr)
    drop = extra.pop("drop_rate", drop_rate)
    at = dict(attrs or {})
    if "error_clipping_threshold" in extra:
        at["error_clipping_threshold"] = extra.pop(
            "error_clipping_threshold")
    at.update(extra)
    ldef = LayerDef(name=name, type=type_, inputs=inputs, size=size,
                    act=act or "linear", bias=bias, drop_rate=drop or 0.0,
                    attrs=at)
    return dsl._add(ldef)


# ------------------------------------------------------------- projections
@dataclasses.dataclass
class Projection:
    """A projection bound to one input (reference Projection configs;
    consumed by mixed_layer)."""

    input: LayerOutput
    spec: Dict[str, Any]
    size: int                      # output size (0 = same as mixed size)
    param_attr: Optional[ParamAttr] = None
    # operators take several inputs
    extra_inputs: List[LayerOutput] = dataclasses.field(
        default_factory=list)
    is_operator: bool = False

    # `proj + proj` shorthand builds an anonymous mixed layer
    def __add__(self, other):
        if isinstance(other, Projection):
            return mixed_layer(input=[self, other])
        raise TypeError("can only add projections")


def full_matrix_projection(input, size=0, param_attr=None):
    return Projection(_one(input), {"type": "full_matrix"}, size,
                      _pattr(param_attr))


def trans_full_matrix_projection(input, size=0, param_attr=None):
    return Projection(_one(input), {"type": "trans_full_matrix"}, size,
                      _pattr(param_attr))


def table_projection(input, size=0, param_attr=None):
    src = _one(input)
    spec = {"type": "table", "vocab_size": src.size}
    g = getattr(src, "graph", None)
    producer = g.layers.get(src.name) if g is not None else None
    if producer is not None and producer.type != "data":
        # the reference's own golden projections.py feeds a table
        # projection a dense float layer (TableProjection.cpp would
        # CHECK-fail at run time); flag the executable interpretation
        # (argmax-id) EXPLICITLY so ids-fed tables stay strict
        spec["dense_argmax_ids"] = True
    return Projection(src, spec, size, _pattr(param_attr))


def identity_projection(input, offset=None, size=None):
    src = _one(input)
    if offset is None:
        return Projection(src, {"type": "identity"}, src.size)
    if size is None:
        size = src.size - offset
    return Projection(src, {"type": "identity_offset", "offset": offset},
                      size)


def slice_projection(input, slices):
    src = _one(input)
    total = 0
    for s, e in slices:
        if not 0 <= s < e <= src.size:
            raise ValueError(f"bad slice [{s}, {e}) for size {src.size}")
        total += e - s
    return Projection(src, {"type": "slice", "slices": list(slices)}, total)


def scaling_projection(input, param_attr=None):
    src = _one(input)
    return Projection(src, {"type": "scaling"}, src.size, _pattr(param_attr))


def dotmul_projection(input, param_attr=None):
    src = _one(input)
    return Projection(src, {"type": "dot_mul"}, src.size, _pattr(param_attr))


def dotmul_operator(a=None, b=None, scale=1, **kwargs):
    a = kwargs.get("x", a)
    b = kwargs.get("y", b)
    a, b = _one(a), _one(b)
    return Projection(a, {"type": "dot_mul_op", "scale": scale}, a.size,
                      extra_inputs=[b], is_operator=True)


_PADDING_NOT_SET = object()


def context_projection(input, context_len, context_start=None,
                       padding_attr=_PADDING_NOT_SET):
    """Sliding window concat over the sequence axis
    (`function/ContextProjection*`). Decorator quirk reproduced from the
    reference (`@wrap_bias_attr_default(['padding_attr'])`,
    default_decorators.py:146-151): padding_attr omitted / None / True
    becomes a default zero-init ParameterAttribute, so the padding rows
    are TRAINABLE by default; only an explicit ``padding_attr=False``
    keeps them static zeros."""
    src = _one(input)
    start = -(context_len // 2) if context_start is None else context_start
    if padding_attr is _PADDING_NOT_SET or padding_attr is None \
            or padding_attr is True:
        padding_attr = ParameterAttribute(initial_std=0.0, initial_mean=0.0)
    trainable = isinstance(padding_attr, ParameterAttribute)
    spec = {"type": "context", "context_start": start,
            "context_length": context_len,
            "trainable_padding": trainable}
    return Projection(src, spec, src.size * context_len,
                      _pattr(padding_attr) if trainable else None)


def _resolved_channels(src, num_channels):
    """Channel count for conv init defaults — the reference resolves
    num_channels from the input layer before computing init_w
    (layers.py:2418-2445); flat inputs derive a square side."""
    if num_channels:
        return num_channels
    from paddle_tpu.config.dsl import _shape_of
    from paddle_tpu.layers.conv import derive_geom
    try:
        return derive_geom(_shape_of(src.name), None)[0]
    except (KeyError, ValueError):
        return 1


def _conv_proj_out_size(src, channels, filter_size, stride, padding,
                        num_filters, trans=False, filter_size_y=None,
                        stride_y=None, padding_y=None):
    """Output size of a conv projection/operator. Geometry comes from the
    engine's single source of truth (layers/conv.py): channels default to
    the producing layer's (the reference infers img.num_filters,
    `trainer_config_helpers/layers.py:4201`), flat inputs derive a square
    side. y params default to their x twins."""
    from paddle_tpu.config.dsl import _shape_of
    from paddle_tpu.layers.conv import _conv_geom, derive_geom
    info = _shape_of(src.name)
    c, in_h, in_w = derive_geom(info, channels)
    fsy = filter_size if filter_size_y is None else filter_size_y
    sty = stride if stride_y is None else stride_y
    pady = padding if padding_y is None else padding_y

    def _out(sz, f, s, p):
        return (sz - 1) * s + f - 2 * p if trans else _conv_geom(sz, f, p, s)

    return num_filters * _out(in_h, fsy, sty, pady) * _out(
        in_w, filter_size, stride, padding)


def conv_operator(img, filter, filter_size, num_filters, num_channels=None,
                  stride=1, padding=0, filter_size_y=None, stride_y=None,
                  padding_y=None, trans=False):
    img, flt = _one(img), _one(filter)
    spec = {"type": "convt_op" if trans else "conv_op",
            "filter_size": filter_size, "num_filters": num_filters,
            "num_channels": num_channels, "stride": stride,
            "padding": padding, "filter_size_y": filter_size_y,
            "stride_y": stride_y, "padding_y": padding_y}
    size = _conv_proj_out_size(img, num_channels, filter_size, stride,
                               padding, num_filters, trans,
                               filter_size_y, stride_y, padding_y)
    return Projection(img, spec, size, extra_inputs=[flt], is_operator=True)


def conv_projection(input, filter_size, num_filters, num_channels=None,
                    stride=1, padding=0, filter_size_y=None, stride_y=None,
                    padding_y=None, groups=1, param_attr=None, trans=False):
    src = _one(input)
    spec = {"type": "convt" if trans else "conv",
            "filter_size": filter_size, "num_filters": num_filters,
            "num_channels": num_channels, "stride": stride,
            "padding": padding, "groups": groups,
            "filter_size_y": filter_size_y, "stride_y": stride_y,
            "padding_y": padding_y}
    size = _conv_proj_out_size(src, num_channels, filter_size, stride,
                               padding, num_filters, trans,
                               filter_size_y, stride_y, padding_y)
    if param_attr is None:
        # reference default (layers.py:4310): He-style std from the
        # filter fan-in (channels resolved from the input when omitted),
        # truncated like Python 2's str(float)
        init_w = (2.0 / (filter_size ** 2
                         * _resolved_channels(src, num_channels))) ** 0.5
        param_attr = ParameterAttribute(initial_mean=0.0,
                                        initial_std=float(f"{init_w:.12g}"))
    return Projection(src, spec, size, _pattr(param_attr))


class MixedLayerType:
    """The ``with mixed_layer(...) as m: m += projection`` protocol."""

    def __init__(self, name, size, act, bias_attr, layer_attr):
        self.name = name
        self.size = size
        self.act = act
        self.bias_attr = bias_attr
        self.layer_attr = layer_attr
        self.projections: List[Projection] = []
        self.finalized: Optional[LayerOutput] = None

    def __iadd__(self, proj):
        if self.finalized is not None:
            raise ValueError("mixed_layer already finalized")
        if not isinstance(proj, Projection):
            raise TypeError("can only add projections/operators")
        self.projections.append(proj)
        return self

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *a):
        if exc_type is None:
            self._finalize()
        return False

    def _finalize(self) -> LayerOutput:
        if self.finalized is not None:
            return self.finalized
        if not self.projections:
            raise ValueError("mixed_layer has no projections")
        size = self.size
        if not size:
            sizes = [p.size for p in self.projections if p.size]
            size = sizes[0] if sizes else None
        inputs, projs, operators = [], [], []
        deferred = []  # (operator dict, extra inputs) appended at the end
        for p in self.projections:
            if p.is_operator:
                # reference MixedLayer (config_parser.py:2895-2905): the
                # operator's first arg sits inline at the operator's add
                # position; the remaining args append AFTER all inputs
                idxs = [len(inputs)]
                inputs.append(Input(p.input.name))
                projs.append({"type": "identity_op_arg"})
                op = {**p.spec, "input_indices": idxs}
                operators.append(op)
                deferred.append((op, p.extra_inputs))
            else:
                inputs.append(Input(p.input.name,
                                    param_attr=p.param_attr))
                projs.append(dict(p.spec))
        for op, extras in deferred:
            for ex in extras:
                op["input_indices"].append(len(inputs))
                inputs.append(Input(ex.name))
                projs.append({"type": "identity_op_arg"})
        self.finalized = _layer(
            self.name, "mixed", inputs, size=size, act=self.act,
            bias=self.bias_attr,
            attrs={"projections": projs, "operators": operators},
            layer_attr=self.layer_attr)
        return self.finalized

    # LayerOutput duck-typing for immediate-mode use
    @property
    def _lo(self):
        return self._finalize()

    def __getattr__(self, item):
        if item in ("name", "size") and "finalized" in self.__dict__:
            lo = self._finalize()
            return getattr(lo, item)
        raise AttributeError(item)


def mixed_layer(size=0, input=None, name=None, act=None, bias_attr=False,
                layer_attr=None):
    name = _name(name, "mixed")
    m = MixedLayerType(name, size, _act(act, LinearActivation),
                      _battr(bias_attr, False), layer_attr)
    if input is not None:
        for p in (input if isinstance(input, (list, tuple)) else [input]):
            m += p
        return m._finalize()
    return m


# ------------------------------------------------------------ basic layers
def data_layer(name, size, height=None, width=None, layer_attr=None):
    return dsl.data(name=name, size=size, height=height, width=width)


def embedding_layer(input, size, name=None, param_attr=None,
                    layer_attr=None):
    src = _one(input)
    pa = _pattr(param_attr)
    return _layer(_name(name, "embedding"), "embedding",
                  [Input(src.name, param_attr=pa)], size=size,
                  attrs={"vocab_size": src.size}, layer_attr=layer_attr)


def fc_layer(input, size, act=None, name=None, param_attr=None,
             bias_attr=None, layer_attr=None):
    ins = _many(input)
    if isinstance(param_attr, (list, tuple)):
        pas = [_pattr(a) for a in param_attr]
    else:
        pas = [_pattr(param_attr) for _ in ins]
    return _layer(
        _name(name, "fc_layer"), "fc",
        [Input(i.name, param_attr=a) for i, a in zip(ins, pas)],
        size=size, act=_act(act), bias=_battr(bias_attr),
        layer_attr=layer_attr)


def printer_layer(input, format=None, name=None):
    ins = _many(input)
    if format is None:
        # config_parser.py:1690: default format lists each input
        format = "\n".join(f"layer={i.name} %s" for i in ins)
    return _layer(_name(name, "print"), "print",
                  [Input(i.name) for i in ins],
                  attrs={"format": format, "user_arg": format})


print_layer = printer_layer


def trans_layer(input, name=None, layer_attr=None):
    return _layer(_name(name, "trans_layer"), "trans",
                  [Input(_one(input).name)], layer_attr=layer_attr)


def rotate_layer(input, height, width, name=None, layer_attr=None):
    return _layer(_name(name, "rotate_layer"), "rotate",
                  [Input(_one(input).name)],
                  attrs={"height": height, "width": width},
                  layer_attr=layer_attr)


def repeat_layer(input, num_repeats, as_row_vector=True, act=None,
                 name=None, layer_attr=None):
    src = _one(input)
    return _layer(_name(name, "repeat_layer"), "featmap_expand",
                  [Input(src.name)], size=src.size * num_repeats,
                  act=_act(act, IdentityActivation),
                  attrs={"num_filters": num_repeats,
                         "user_arg": None if as_row_vector else "as_col_vec"},
                  layer_attr=layer_attr)


def seq_reshape_layer(input, reshape_size, act=None, name=None,
                      layer_attr=None, bias_attr=None):
    return _layer(_name(name, "seqreshape"), "seqreshape",
                  [Input(_one(input).name)], size=reshape_size,
                  act=_act(act, IdentityActivation),
                  bias=_battr(bias_attr, False), layer_attr=layer_attr)


def interpolation_layer(input, weight, name=None, layer_attr=None):
    a, b = _many(input)
    w = _one(weight)
    return _layer(_name(name, "interpolation_layer"), "interpolation",
                  [Input(w.name), Input(a.name), Input(b.name)],
                  layer_attr=layer_attr)


def bilinear_interp_layer(input, out_size_x=None, out_size_y=None,
                          name=None, layer_attr=None):
    return _layer(_name(name, "bilinear_interp_layer"), "bilinear_interp",
                  [Input(_one(input).name)],
                  attrs={"out_size_x": out_size_x,
                         "out_size_y": out_size_y},
                  layer_attr=layer_attr)


def power_layer(input, weight, name=None, layer_attr=None):
    return _layer(_name(name, "power_layer"), "power",
                  [Input(_one(weight).name), Input(_one(input).name)],
                  layer_attr=layer_attr)


def scaling_layer(input, weight, name=None, layer_attr=None):
    return _layer(_name(name, "scaling_layer"), "scaling",
                  [Input(_one(weight).name), Input(_one(input).name)],
                  layer_attr=layer_attr)


def sum_to_one_norm_layer(input, name=None, layer_attr=None):
    return _layer(_name(name, "sum_to_one_norm_layer"), "sum_to_one_norm",
                  [Input(_one(input).name)], layer_attr=layer_attr)


def row_l2_norm_layer(input, name=None, layer_attr=None):
    return _layer(_name(name, "row_l2_norm_layer"), "row_l2_norm",
                  [Input(_one(input).name)], layer_attr=layer_attr)


def cos_sim(a, b, scale=1, size=1, name=None, layer_attr=None):
    if size == 1:
        return _layer(_name(name, "cos_sim"), "cos",
                      [Input(_one(a).name), Input(_one(b).name)],
                      attrs={"cos_scale": scale}, layer_attr=layer_attr)
    return _layer(_name(name, "cos_sim"), "cos_vm",
                  [Input(_one(a).name), Input(_one(b).name)], size=size,
                  attrs={"cos_scale": scale}, layer_attr=layer_attr)


def out_prod_layer(input1, input2, name=None, layer_attr=None):
    return _layer(_name(name, "out_prod_layer"), "out_prod",
                  [Input(_one(input1).name), Input(_one(input2).name)],
                  layer_attr=layer_attr)


# ------------------------------------------------------------ aggregation
def pooling_layer(input, pooling_type=None, name=None, bias_attr=None,
                  agg_level=AggregateLevel.TO_NO_SEQUENCE, stride=-1,
                  layer_attr=None):
    src = _one(input)
    pt = pooling_type if pooling_type is not None else MaxPooling()
    attrs = {"trans_type": agg_level, "seq_pool_stride": stride}
    if isinstance(pt, AvgPooling):
        ltype = "average"
        attrs["average_strategy"] = pt.strategy
    elif isinstance(pt, (MaxPooling, BasePoolingType)):
        ltype = "max"
        if getattr(pt, "output_max_index", None):
            attrs["output_max_index"] = True
    else:
        raise TypeError(f"bad pooling type {pt!r}")
    return _layer(_name(name, "seq_pooling"), ltype, [Input(src.name)],
                  bias=_battr(bias_attr, False), attrs=attrs,
                  layer_attr=layer_attr)


def last_seq(input, agg_level=AggregateLevel.TO_NO_SEQUENCE, name=None,
             stride=-1, layer_attr=None):
    return _layer(_name(name, "last_seq"), "seqlastins",
                  [Input(_one(input).name)],
                  attrs={"trans_type": agg_level,
                         "seq_pool_stride": stride},
                  layer_attr=layer_attr)


def first_seq(input, agg_level=AggregateLevel.TO_NO_SEQUENCE, name=None,
              stride=-1, layer_attr=None):
    return _layer(_name(name, "first_seq"), "seqlastins",
                  [Input(_one(input).name)],
                  attrs={"trans_type": agg_level, "select_first": True,
                         "seq_pool_stride": stride},
                  layer_attr=layer_attr)


def expand_layer(input, expand_as, name=None, bias_attr=False,
                 expand_level=ExpandLevel.FROM_NO_SEQUENCE,
                 layer_attr=None):
    return _layer(_name(name, "expand_layer"), "expand",
                  [Input(_one(input).name), Input(_one(expand_as).name)],
                  bias=_battr(bias_attr, False),
                  attrs={"trans_type": expand_level}, layer_attr=layer_attr)


def concat_layer(input, act=None, name=None, layer_attr=None,
                 bias_attr=None):
    items = input if isinstance(input, (list, tuple)) else [input]
    if any(isinstance(p, Projection) for p in items):
        # the reference's ConcatenateLayer2: projection inputs, outputs
        # concatenated per-projection (config_parser `concat2`)
        inputs, projs, total = [], [], 0
        for p in items:
            if not isinstance(p, Projection):
                p = identity_projection(_one(p))
            psize = int(p.size or p.input.size)
            inputs.append(Input(p.input.name, param_attr=p.param_attr))
            projs.append(dict(p.spec, size=psize))
            total += psize
        return _layer(_name(name, "concat"), "concat2", inputs,
                      size=total, act=_act(act, IdentityActivation),
                      bias=_battr(bias_attr, False),
                      attrs={"projections": projs}, layer_attr=layer_attr)
    ins = _many(items)
    return _layer(_name(name, "concat"), "concat",
                  [Input(i.name) for i in ins],
                  act=_act(act, IdentityActivation), layer_attr=layer_attr)


def seq_concat_layer(a, b, act=None, name=None, layer_attr=None,
                     bias_attr=None):
    return _layer(_name(name, "seqconcat"), "seqconcat",
                  [Input(_one(a).name), Input(_one(b).name)],
                  act=_act(act, IdentityActivation),
                  bias=_battr(bias_attr, False), layer_attr=layer_attr)


def addto_layer(input, act=None, name=None, bias_attr=None,
                layer_attr=None):
    ins = _many(input)
    return _layer(_name(name, "addto"), "addto",
                  [Input(i.name) for i in ins],
                  act=_act(act, IdentityActivation),
                  bias=_battr(bias_attr, False), layer_attr=layer_attr)


def dropout_layer(input, dropout_rate, name=None):
    """addto with dropout, exactly the reference composition."""
    return _layer(_name(name, "dropout"), "addto",
                  [Input(_one(input).name)], drop_rate=dropout_rate)


# ------------------------------------------------------------- recurrence
def lstmemory(input, name=None, size=None, reverse=False, act=None,
              gate_act=None, state_act=None, bias_attr=None,
              param_attr=None, layer_attr=None):
    src = _one(input)
    if size is not None and src.size != size * 4:
        raise ValueError("lstmemory input must be 4x its size "
                         "(project with fc_layer first)")
    return _layer(
        _name(name, "lstmemory"), "lstmemory",
        [Input(src.name, param_attr=_pattr(param_attr))],
        act="", bias=_battr(bias_attr),
        attrs={"reversed": reverse,
               "active_type": _act(act, TanhActivation),
               "active_gate_type": _act(gate_act, SigmoidActivation),
               "active_state_type": _act(state_act, TanhActivation)},
        layer_attr=layer_attr)


def grumemory(input, size=None, name=None, reverse=False, act=None,
              gate_act=None, bias_attr=None, param_attr=None,
              layer_attr=None):
    src = _one(input)
    if size is not None and src.size != size * 3:
        raise ValueError("grumemory input must be 3x its size")
    return _layer(
        _name(name, "gru"), "gated_recurrent",
        [Input(src.name, param_attr=_pattr(param_attr))],
        act="", bias=_battr(bias_attr),
        attrs={"reversed": reverse,
               "active_type": _act(act, TanhActivation),
               "active_gate_type": _act(gate_act, SigmoidActivation)},
        layer_attr=layer_attr)


def recurrent_layer(input, act=None, bias_attr=None, param_attr=None,
                    name=None, reverse=False, layer_attr=None):
    return _layer(
        _name(name, "recurrent_layer"), "recurrent",
        [Input(_one(input).name, param_attr=_pattr(param_attr))],
        act="", bias=_battr(bias_attr),
        attrs={"reversed": reverse,
               "active_type": _act(act, TanhActivation)},
        layer_attr=layer_attr)


def memory(name, size, memory_name=None, is_seq=False, boot_layer=None,
           boot_bias=None, boot_bias_active_type=None,
           boot_with_const_id=None):
    boot_const = 0.0
    if boot_with_const_id is not None:
        boot_const = float(boot_with_const_id)
    # reference @wrap_name_default("memory", "memory_name") consumes the
    # global memory counter on EVERY call; the auto name is only used as
    # the agent name when the memory is anonymous (layers.py:3230-3241)
    auto = ctx().auto_name("memory")
    if memory_name is None:
        memory_name = auto
    agent = None if name is not None else memory_name
    return dsl.memory(name=name, size=size, boot_layer=boot_layer,
                      boot_with_const_value=boot_const, agent_name=agent)


def recurrent_group(step, input, reverse=False, name=None,
                    targetInlink=None):
    if isinstance(input, MixedLayerType):
        input = input._finalize()
    elif isinstance(input, (list, tuple)):
        input = [i._finalize() if isinstance(i, MixedLayerType) else i
                 for i in input]
    if targetInlink is not None:
        targetInlink = _one(targetInlink)
    return dsl.recurrent_group(step, input, reverse=reverse, name=name,
                               target_inlink=targetInlink)


def SubsequenceInput(input):
    """Two-level sequence input of a recurrent_group: the outer group
    steps over sub-sequences (nested frames,
    ``RecurrentGradientMachine.cpp:294-346``)."""
    return dsl.SubsequenceInput(_one(input))


class BaseGeneratedInput:
    pass


def lstm_step_layer(input, state, size=None, act=None, name=None,
                    gate_act=None, state_act=None, bias_attr=None,
                    layer_attr=None):
    inp, st = _one(input), _one(state)
    size = size or st.size
    return _layer(
        _name(name, "lstm_step"), "lstm_step",
        [Input(inp.name), Input(st.name)], size=size,
        act="", bias=_battr(bias_attr),
        attrs={"active_type": _act(act, TanhActivation),
               "active_gate_type": _act(gate_act, SigmoidActivation),
               "active_state_type": _act(state_act, TanhActivation)},
        layer_attr=layer_attr)


def gru_step_layer(input, output_mem, size=None, act=None, name=None,
                   gate_act=None, bias_attr=None, param_attr=None,
                   layer_attr=None):
    inp, mem = _one(input), _one(output_mem)
    size = size or inp.size // 3
    return _layer(
        _name(name, "gru_step"), "gru_step",
        [Input(inp.name, param_attr=_pattr(param_attr)),
         Input(mem.name)], size=size,
        act="", bias=_battr(bias_attr),
        attrs={"active_type": _act(act, TanhActivation),
               "active_gate_type": _act(gate_act, SigmoidActivation)},
        layer_attr=layer_attr)


def gru_step_naive_layer(input, output_mem, size=None, name=None, act=None,
                         gate_act=None, bias_attr=None, param_attr=None,
                         layer_attr=None):
    return gru_step_layer(input, output_mem, size=size, name=name, act=act,
                          gate_act=gate_act, bias_attr=bias_attr,
                          param_attr=param_attr, layer_attr=layer_attr)


def get_output_layer(input, arg_name, name=None, layer_attr=None):
    src = _one(input)
    return _layer(_name(name, "get_output_layer"), "get_output",
                  [Input(src.name, extra={"input_layer_argument": arg_name})],
                  attrs={"arg_name": arg_name}, layer_attr=layer_attr)


def maxid_layer(input, name=None, layer_attr=None):
    return _layer(_name(name, "maxid_layer"), "maxid",
                  [Input(_one(input).name)], layer_attr=layer_attr)


def eos_layer(input, eos_id, name=None, layer_attr=None):
    return _layer(_name(name, "eos_layer"), "eos_id",
                  [Input(_one(input).name)], attrs={"eos_id": eos_id},
                  layer_attr=layer_attr)


def kmax_sequence_score_layer(input, name=None, beam_size=1):
    return _layer(_name(name, "kmax_sequence_score_layer"), "kmax_seq_score",
                  [Input(_one(input).name)], attrs={"beam_size": beam_size})


def beam_search(step, input, bos_id, eos_id, beam_size,
                max_length=500, name=None, num_results_per_sample=None):
    out = dsl.beam_search(step, input, bos_id=bos_id, eos_id=eos_id,
                          beam_size=beam_size, max_length=max_length,
                          name=name)
    # the reference names the prediction output "__beam_search_predict__"
    # regardless of the group name; configs reference it in Outputs()
    graph = dsl.current_graph()
    if "__beam_search_predict__" not in graph.layers:
        _layer("__beam_search_predict__", "agent", [Input(out.name)])
    return out


# ---------------------------------------------------------------- vision
def img_conv_layer(input, filter_size, num_filters, name=None,
                   num_channels=None, act=None, groups=1, stride=1,
                   padding=0, dilation=1, bias_attr=None, param_attr=None,
                   shared_biases=True, layer_attr=None, filter_size_y=None,
                   stride_y=None, padding_y=None, dilation_y=None,
                   trans=False, layer_type=None):
    src = _one(input)

    def _pair(v):
        return v if not isinstance(v, (list, tuple)) else v[0]

    ltype = layer_type or ("exconvt" if trans else "exconv")
    extra = {"filter_size": _pair(filter_size), "stride": _pair(stride),
             "padding": _pair(padding), "groups": groups}
    if num_channels:
        extra["channels"] = num_channels
    if param_attr is None:
        # reference default (layers.py:2445): He-style std from the
        # filter fan-in (channels resolved from the input when omitted),
        # truncated like Python 2's str(float)
        init_w = (2.0 / (_pair(filter_size) ** 2
                         * _resolved_channels(src, num_channels))) ** 0.5
        param_attr = ParameterAttribute(initial_mean=0.0,
                                        initial_std=float(f"{init_w:.12g}"))
    return _layer(
        _name(name, "conv"), ltype,
        [Input(src.name, param_attr=_pattr(param_attr), extra=extra)],
        act=_act(act, ReluActivation), bias=_battr(bias_attr),
        attrs={"num_filters": num_filters, "shared_biases": shared_biases},
        layer_attr=layer_attr)


def img_pool_layer(input, pool_size, name=None, num_channels=None,
                   pool_type=None, stride=1, padding=0, layer_attr=None,
                   pool_size_y=None, stride_y=None, padding_y=None,
                   ceil_mode=True, exclude_mode=None):
    src = _one(input)
    pt = pool_type if pool_type is not None else MaxPooling()
    # name-based: CudnnMaxPooling etc. are plain BasePoolingType, not
    # MaxPooling subclasses
    pt_name = ("max-projection" if "max" in getattr(pt, "name", "max")
               else "avg-projection")
    extra = {"filter_size": pool_size, "stride": stride, "padding": padding,
             "pool_type": pt_name, "ceil_mode": ceil_mode}
    if pool_size_y:
        extra["size_y"] = pool_size_y
    if stride_y:
        extra["stride_y"] = stride_y
    if num_channels:
        extra["channels"] = num_channels
    return _layer(_name(name, "pool"), "pool",
                  [Input(src.name, extra=extra)], layer_attr=layer_attr)


def spp_layer(input, name=None, num_channels=None, pool_type=None,
              pyramid_height=None, layer_attr=None):
    src = _one(input)
    pt = "max-projection" if pool_type is None or isinstance(
        pool_type, MaxPooling) else "avg-projection"
    return _layer(_name(name, "spp"), "spp",
                  [Input(src.name)],
                  attrs={"pyramid_height": pyramid_height,
                         "pool_type": pt, "channels": num_channels},
                  layer_attr=layer_attr)


def img_cmrnorm_layer(input, size, scale=0.0128, power=0.75, name=None,
                      num_channels=None, layer_attr=None):
    return _layer(_name(name, "crmnorm"), "norm",
                  [Input(_one(input).name,
                         extra={"size": size, "scale": scale, "pow": power,
                                "channels": num_channels})],
                  layer_attr=layer_attr)


def batch_norm_layer(input, act=None, name=None, img3D=False,
                     num_channels=None, bias_attr=None, param_attr=None,
                     layer_attr=None, batch_norm_type=None,
                     moving_average_fraction=0.9, use_global_stats=None,
                     mean_var_names=None):
    src = _one(input)
    return _layer(
        _name(name, "batch_norm"), "batch_norm",
        [Input(src.name, param_attr=_pattr(param_attr))],
        act=_act(act, IdentityActivation), bias=_battr(bias_attr),
        attrs={"use_global_stats": use_global_stats,
               "moving_average_fraction": moving_average_fraction,
               "channels": num_channels},
        layer_attr=layer_attr)


def maxout_layer(input, groups, num_channels=None, name=None,
                 layer_attr=None):
    return _layer(_name(name, "maxout_layer"), "maxout",
                  [Input(_one(input).name)],
                  attrs={"groups": groups, "channels": num_channels},
                  layer_attr=layer_attr)


def block_expand_layer(input, block_x=0, block_y=0, stride_x=0, stride_y=0,
                       padding_x=0, padding_y=0, num_channels=None,
                       name=None, layer_attr=None):
    return _layer(_name(name, "block_expand_layer"), "blockexpand",
                  [Input(_one(input).name)],
                  attrs={"block_x": block_x, "block_y": block_y,
                         "stride_x": stride_x, "stride_y": stride_y,
                         "padding_x": padding_x, "padding_y": padding_y,
                         "channels": num_channels},
                  layer_attr=layer_attr)


def pad_layer(input, pad_c=None, pad_h=None, pad_w=None, name=None,
              layer_attr=None):
    # the pad amounts live in LayerDef.attrs (where layers/misc.PadLayer
    # reads them), not in Input.extra
    return _layer(_name(name, "pad"), "pad", [Input(_one(input).name)],
                  attrs={"pad_c": list(pad_c or [0, 0]),
                         "pad_h": list(pad_h or [0, 0]),
                         "pad_w": list(pad_w or [0, 0])},
                  layer_attr=layer_attr)


def crop_layer(input, offset, axis=2, shape=None, name=None,
               layer_attr=None):
    ins = _many(input)
    return _layer(_name(name, "crop_layer"), "crop",
                  [Input(i.name) for i in ins],
                  attrs={"axis": axis, "offset": offset, "shape": shape},
                  layer_attr=layer_attr)


def bilinear_interp(input, **kw):
    return bilinear_interp_layer(input, **kw)


def rotate(input, **kw):
    return rotate_layer(input, **kw)


def cross_channel_norm_layer(input, name=None, param_attr=None):
    return _layer(_name(name, "cross_channel_norm"), "cross_channel_norm",
                  [Input(_one(input).name, param_attr=_pattr(param_attr))])


def prelu_layer(input, name=None, partial_sum=1, param_attr=None,
                layer_attr=None):
    return _layer(_name(name, "prelu_layer"), "prelu",
                  [Input(_one(input).name, param_attr=_pattr(param_attr))],
                  attrs={"partial_sum": partial_sum}, layer_attr=layer_attr)


def gated_unit_layer(input, size, act=None, name=None, gate_attr=None,
                     gate_param_attr=None, gate_bias_attr=True,
                     inproj_attr=None, inproj_param_attr=None,
                     inproj_bias_attr=True, layer_attr=None):
    """input_proj ⊙ sigmoid(gate): the reference composes fc+fc+mixed."""
    name = _name(name, "gated_unit_layer")
    src = _one(input)
    proj = fc_layer(src, size, act=act or LinearActivation(),
                    name=f"{name}_input_proj",
                    param_attr=inproj_param_attr,
                    bias_attr=inproj_bias_attr, layer_attr=inproj_attr)
    gate = fc_layer(src, size, act=SigmoidActivation(),
                    name=f"{name}_gate", param_attr=gate_param_attr,
                    bias_attr=gate_bias_attr, layer_attr=gate_attr)
    return mixed_layer(size=size, name=f"{name}_gated_act",
                       input=dotmul_operator(proj, gate),
                       layer_attr=layer_attr)


# ------------------------------------------------------------- structured
def hsigmoid(input, label, num_classes=None, name=None, bias_attr=None,
             param_attr=None, layer_attr=None):
    ins = _many(input)
    lab = _one(label)
    num_classes = num_classes or lab.size
    if isinstance(param_attr, (list, tuple)):
        pas = [_pattr(a) for a in param_attr]
    else:
        pas = [_pattr(param_attr) for _ in ins]
    return _layer(
        _name(name, "hsigmoid"), "hsigmoid",
        [Input(i.name, param_attr=a) for i, a in zip(ins, pas)]
        + [Input(lab.name)],
        bias=_battr(bias_attr),
        attrs={"num_classes": num_classes}, layer_attr=layer_attr)


def tensor_layer(a, b, size, act=None, name=None, param_attr=None,
                 bias_attr=None, layer_attr=None):
    return _layer(
        _name(name, "tensor_layer"), "tensor",
        [Input(_one(a).name, param_attr=_pattr(param_attr)),
         Input(_one(b).name)],
        size=size, act=_act(act, LinearActivation),
        bias=_battr(bias_attr), layer_attr=layer_attr)


def selective_fc_layer(input, size, select=None, act=None, name=None,
                       pass_generation=False, has_selected_colums=True,
                       mul_ratio=0.02, param_attr=None, bias_attr=None,
                       layer_attr=None):
    ins = _many(input)
    if isinstance(param_attr, (list, tuple)):
        pas = [_pattr(a) for a in param_attr]
    else:
        pas = [_pattr(param_attr) for _ in ins]
    inputs = [Input(i.name, param_attr=a) for i, a in zip(ins, pas)]
    if select is not None:
        inputs.append(Input(_one(select).name))
    return _layer(
        _name(name, "selective_fc_layer"), "selective_fc", inputs, size=size,
        act=_act(act), bias=_battr(bias_attr),
        attrs={"selective_fc_pass_generation": pass_generation,
               "has_selected_colums": has_selected_colums,
               "selective_fc_full_mul_ratio": mul_ratio},
        layer_attr=layer_attr)


def sampling_id_layer(input, name=None, layer_attr=None):
    return _layer(_name(name, "sampling_id_layer"), "sampling_id",
                  [Input(_one(input).name)], layer_attr=layer_attr)


def slope_intercept_layer(input, name=None, slope=1.0, intercept=0.0,
                          layer_attr=None):
    return _layer(_name(name, "slope_intercept_layer"), "slope_intercept",
                  [Input(_one(input).name)],
                  attrs={"slope": slope, "intercept": intercept},
                  layer_attr=layer_attr)


def linear_comb_layer(weights, vectors, size=None, name=None,
                      layer_attr=None):
    w, v = _one(weights), _one(vectors)
    if size is None:
        size = v.size // w.size
    return _layer(_name(name, "linear_comb_layer"), "convex_comb",
                  [Input(w.name), Input(v.name)], size=size,
                  layer_attr=layer_attr)


convex_comb_layer = linear_comb_layer


def conv_shift_layer(a, b, name=None, layer_attr=None):
    return _layer(_name(name, "conv_shift_layer"), "conv_shift",
                  [Input(_one(a).name), Input(_one(b).name)],
                  layer_attr=layer_attr)


def multiplex_layer(input, name=None, layer_attr=None):
    ins = _many(input)
    return _layer(_name(name, "multiplex_layer"), "multiplex",
                  [Input(i.name) for i in ins], layer_attr=layer_attr)


def row_conv_layer(input, context_len, act=None, name=None,
                   param_attr=None, layer_attr=None):
    return _layer(
        _name(name, "row_conv_layer"), "row_conv",
        [Input(_one(input).name, param_attr=_pattr(param_attr))],
        act=_act(act, LinearActivation),
        attrs={"context_length": context_len}, layer_attr=layer_attr)


def sub_nested_seq_layer(input, selected_indices, name=None):
    return _layer(_name(name, "sub_nested_seq_layer"), "sub_nested_seq",
                  [Input(_one(input).name),
                   Input(_one(selected_indices).name)])


def clip_layer(input, min, max, name=None):
    return _layer(_name(name, "clip"), "clip",
                  [Input(_one(input).name)],
                  attrs={"min": min, "max": max})


# ---------------------------------------------------------------- detection
def priorbox_layer(input, image, aspect_ratio, variance, min_size,
                   max_size=[], name=None):
    return dsl.priorbox_layer(_one(input), _one(image), min_size=min_size,
                              max_size=max_size, aspect_ratio=aspect_ratio,
                              variance=variance, name=_name(name,
                                                            "priorbox"))


def multibox_loss_layer(input_loc, input_conf, priorbox, label, num_classes,
                        overlap_threshold=0.5, neg_pos_ratio=3.0,
                        neg_overlap=0.5, background_id=0, name=None):
    loc = _many(input_loc)
    conf = _many(input_conf)
    return dsl.multibox_loss_layer(
        _one(priorbox), _one(label), conf[0], loc[0],
        num_classes=num_classes, overlap_threshold=overlap_threshold,
        neg_pos_ratio=neg_pos_ratio, neg_overlap=neg_overlap,
        background_id=background_id,
        name=_name(name, "multibox_loss"))


def detection_output_layer(input_loc, input_conf, priorbox, num_classes,
                           nms_threshold=0.45, nms_top_k=400,
                           keep_top_k=200, confidence_threshold=0.01,
                           background_id=0, name=None):
    loc = _many(input_loc)
    conf = _many(input_conf)
    return dsl.detection_output_layer(
        _one(priorbox), conf[0], loc[0], num_classes=num_classes,
        nms_threshold=nms_threshold, nms_top_k=nms_top_k,
        keep_top_k=keep_top_k, confidence_threshold=confidence_threshold,
        background_id=background_id,
        name=_name(name, "detection_output"))


# -------------------------------------------------------------------- costs
def _cost(name, prefix, type_, inputs, coeff=1.0, attrs=None,
          layer_attr=None):
    at = {"coeff": coeff}
    at.update(attrs or {})
    return _layer(_name(name, prefix), type_,
                  [Input(i.name) for i in inputs], attrs=at,
                  layer_attr=layer_attr)


def classification_cost(input, label, weight=None, name=None,
                        evaluator=None, top_k=None, layer_attr=None,
                        coeff=1.0):
    inp, lab = _one(input), _one(label)
    w = _one(weight) if weight is not None else None
    ins = [inp, lab] + ([w] if w is not None else [])
    out = _cost(name, "cost", "multi-class-cross-entropy", ins,
                coeff=coeff, layer_attr=layer_attr)
    # the reference attaches a classification_error evaluator by default
    # (`layers.py:4086,4122-4134`); it lands in ctx().evaluators and the
    # exported ModelConfig.evaluators. Opt out with evaluator=[] (None
    # means "the default", matching the reference's signature semantics).
    from paddle_tpu.compat.trainer_config_helpers.evaluators import (
        classification_error_evaluator)
    if evaluator is None:
        # default evaluator understands top_k; forward it
        classification_error_evaluator(
            name="classification_error_evaluator", input=inp, label=lab,
            weight=w, top_k=top_k)
    else:
        evs = evaluator if isinstance(evaluator, (list, tuple)) \
            else [evaluator]
        for e in evs:
            if e is None:
                continue
            # exactly the reference's __add_evaluator__ call shape
            # (name/input/label/weight only); reports alongside the
            # trainer's built-in cost-derived metric, as the reference's
            # per-batch evaluator does
            e(name=getattr(e, "__name__", "evaluator"), input=inp,
              label=lab, weight=w)
    return out


def cross_entropy(input, label, name=None, coeff=1.0, weight=None,
                  layer_attr=None):
    ins = [_one(input), _one(label)]
    if weight is not None:
        ins.append(_one(weight))
    return _cost(name, "cross_entropy", "multi-class-cross-entropy", ins,
                 coeff=coeff, layer_attr=layer_attr)


def cross_entropy_with_selfnorm(input, label, name=None, coeff=1.0,
                                softmax_selfnorm_alpha=0.1,
                                layer_attr=None):
    return _cost(name, "cross_entropy_with_selfnorm",
                 "multi_class_cross_entropy_with_selfnorm",
                 [_one(input), _one(label)], coeff=coeff,
                 attrs={"softmax_selfnorm_alpha": softmax_selfnorm_alpha},
                 layer_attr=layer_attr)


def mse_cost(input, label, weight=None, name=None, coeff=1.0,
             layer_attr=None):
    ins = [_one(input), _one(label)]
    if weight is not None:
        ins.append(_one(weight))
    return _cost(name, "mse_cost", "square_error", ins, coeff=coeff,
                 layer_attr=layer_attr)


regression_cost = mse_cost


def multi_binary_label_cross_entropy(input, label, name=None, coeff=1.0,
                                     layer_attr=None):
    return _cost(name, "multi_binary_label_cross_entropy",
                 "multi_binary_label_cross_entropy",
                 [_one(input), _one(label)], coeff=coeff,
                 layer_attr=layer_attr)


def sum_cost(input, name=None, layer_attr=None):
    return _cost(name, "sum_cost", "sum_cost", [_one(input)],
                 layer_attr=layer_attr)


def rank_cost(left, right, label, weight=None, name=None, coeff=1.0,
              layer_attr=None):
    ins = [_one(left), _one(right), _one(label)]
    if weight is not None:
        ins.append(_one(weight))
    return _cost(name, "rank_cost", "rank-cost", ins, coeff=coeff,
                 layer_attr=layer_attr)


def lambda_cost(input, score, name=None, NDCG_num=5, max_sort_size=-1,
                layer_attr=None):
    return _cost(name, "lambda_cost", "lambda_cost",
                 [_one(input), _one(score)],
                 attrs={"NDCG_num": NDCG_num,
                        "max_sort_size": max_sort_size},
                 layer_attr=layer_attr)


def huber_cost(input, label, name=None, coeff=1.0, layer_attr=None):
    return _cost(name, "huber_cost", "huber", [_one(input), _one(label)],
                 coeff=coeff, layer_attr=layer_attr)


def smooth_l1_cost(input, label, name=None, coeff=1.0, layer_attr=None):
    # reference @wrap_name_default() uses the function name as prefix
    return _cost(name, "smooth_l1_cost", "smooth_l1",
                 [_one(input), _one(label)], coeff=coeff,
                 layer_attr=layer_attr)


def ctc_layer(input, label, size=None, name=None, norm_by_times=False,
              layer_attr=None):
    inp, lab = _one(input), _one(label)
    # reference contract (`layers.py:4987-4992`): size = num classes + 1
    # (the blank); defaults from the label vocabulary, NOT the input
    if lab.size:
        if size is not None and size != lab.size + 1:
            raise ValueError(
                f"ctc_layer: size ({size}) must equal label size + 1 "
                f"({lab.size + 1}, the blank symbol)")
        size = lab.size + 1
    size = size or inp.size
    return _layer(_name(name, "ctc_layer"), "ctc",
                  [Input(inp.name), Input(lab.name)], size=size,
                  attrs={"norm_by_times": norm_by_times},
                  layer_attr=layer_attr)


def warp_ctc_layer(input, label, size=None, name=None, blank=0,
                   norm_by_times=False, layer_attr=None):
    inp, lab = _one(input), _one(label)
    # like ctc_layer: size = num classes + 1, from the label vocabulary
    if lab.size:
        if size is not None and size != lab.size + 1:
            raise ValueError(
                f"warp_ctc_layer: size ({size}) must equal label size + 1 "
                f"({lab.size + 1}, the blank symbol)")
        size = lab.size + 1
    size = size or inp.size + 1
    return _layer(_name(name, "warp_ctc_layer"), "warp_ctc",
                  [Input(inp.name), Input(lab.name)], size=size,
                  attrs={"norm_by_times": norm_by_times, "blank": blank},
                  layer_attr=layer_attr)


def crf_layer(input, label, size=None, weight=None, param_attr=None,
              name=None, coeff=1.0, layer_attr=None):
    inp, lab = _one(input), _one(label)
    size = size or inp.size
    ins = [Input(inp.name, param_attr=_pattr(param_attr)),
           Input(lab.name)]
    if weight is not None:
        ins.append(Input(_one(weight).name))
    return _layer(_name(name, "crf_layer"), "crf", ins, size=size,
                  attrs={"coeff": coeff}, layer_attr=layer_attr)


def crf_decoding_layer(input, size=None, label=None, param_attr=None,
                       name=None, layer_attr=None):
    inp = _one(input)
    size = size or inp.size
    ins = [Input(inp.name, param_attr=_pattr(param_attr))]
    if label is not None:
        ins.append(Input(_one(label).name))
    return _layer(_name(name, "crf_decoding_layer"), "crf_decoding", ins,
                  size=size, layer_attr=layer_attr)


def nce_layer(input, label, num_classes=None, act=None, param_attr=None,
              weight=None, num_neg_samples=10, neg_distribution=None,
              name=None, bias_attr=None, layer_attr=None):
    ins = _many(input)
    lab = _one(label)
    num_classes = num_classes or lab.size
    if isinstance(param_attr, (list, tuple)):
        pas = [_pattr(a) for a in param_attr]
    else:
        pas = [_pattr(param_attr) for _ in ins]
    inputs = [Input(i.name, param_attr=a) for i, a in zip(ins, pas)]
    inputs.append(Input(lab.name))
    if weight is not None:
        inputs.append(Input(_one(weight).name))
    return _layer(
        _name(name, "nce_layer"), "nce", inputs,
        act=_act(act, SigmoidActivation), bias=_battr(bias_attr),
        attrs={"num_classes": num_classes,
               "num_neg_samples": num_neg_samples,
               "neg_sampling_dist": neg_distribution},
        layer_attr=layer_attr)
