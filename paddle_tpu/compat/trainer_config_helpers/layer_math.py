"""``paddle.trainer_config_helpers.layer_math`` surface.

Unary math helpers (``layer_math.exp(x)`` etc.) and arithmetic operator
overloads on ``LayerOutput`` — the reference installs ``__add__``/
``__sub__``/``__mul__`` lowering to slope_intercept / identity-projection
mixes / scaling layers (`trainer_config_helpers/layer_math.py`). Importing
this module (the package ``__init__`` does) installs the overloads.
"""

from __future__ import annotations

import numbers

from paddle_tpu.compat.trainer_config_helpers import activations as act
from paddle_tpu.compat.trainer_config_helpers.layers import (
    LayerOutput, MixedLayerType, _name, identity_projection, mixed_layer,
    repeat_layer, scaling_layer, slope_intercept_layer)

__all__ = []


def _register_unary(op_name, activation):
    def op(input, name=None):
        return mixed_layer(input=[identity_projection(input=input)],
                           name=_name(name, op_name), act=activation)

    op.__name__ = op_name
    globals()[op_name] = op
    __all__.append(op_name)


_register_unary("exp", act.ExpActivation())
_register_unary("log", act.LogActivation())
_register_unary("abs", act.AbsActivation())
_register_unary("sigmoid", act.SigmoidActivation())
_register_unary("tanh", act.TanhActivation())
_register_unary("square", act.SquareActivation())
_register_unary("relu", act.ReluActivation())
_register_unary("sqrt", act.SqrtActivation())
_register_unary("reciprocal", act.ReciprocalActivation())


def _add(layeroutput, other):
    if isinstance(other, MixedLayerType):
        other = other._finalize()
    if isinstance(other, numbers.Number):
        return slope_intercept_layer(input=layeroutput, intercept=other)
    if not isinstance(other, LayerOutput):
        raise TypeError("LayerOutput can only be added with another "
                        "LayerOutput or a number")
    if layeroutput.size == other.size:
        return mixed_layer(input=[identity_projection(input=layeroutput),
                                  identity_projection(input=other)])
    if other.size != 1 and layeroutput.size != 1:
        raise ValueError(
            f"'+' needs equal sizes or one size-1 operand; got "
            f"{layeroutput.size} and {other.size}")
    if layeroutput.size == 1:
        layeroutput, other = other, layeroutput
    other = repeat_layer(other, layeroutput.size)
    return mixed_layer(input=[identity_projection(input=layeroutput),
                              identity_projection(input=other)])


def _sub(layeroutput, other):
    if isinstance(other, MixedLayerType):
        other = other._finalize()
    if isinstance(other, numbers.Number):
        # bug-for-bug with the reference (layer_math.py:78): y - c lowers
        # to intercept=+c, i.e. y + c. The goldens encode this, so the
        # wire format must too.
        return slope_intercept_layer(input=layeroutput, intercept=other)
    if not isinstance(other, LayerOutput):
        raise TypeError("LayerOutput can only be subtracted with another "
                        "LayerOutput or a number")
    return _add(layeroutput, slope_intercept_layer(input=other, slope=-1.0))


def _rsub(layeroutput, other):
    return _add(slope_intercept_layer(input=layeroutput, slope=-1.0), other)


def _mul(layeroutput, other):
    if isinstance(other, MixedLayerType):
        other = other._finalize()
    if isinstance(other, numbers.Number):
        return slope_intercept_layer(input=layeroutput, slope=other)
    if not isinstance(other, LayerOutput):
        raise TypeError("LayerOutput can only be multiplied with another "
                        "LayerOutput or a number")
    if layeroutput.size == 1:
        return scaling_layer(input=other, weight=layeroutput)
    if other.size == 1:
        return scaling_layer(input=layeroutput, weight=other)
    raise ValueError("'*' needs one scalar operand (a number or a "
                     "size-1 LayerOutput)")


LayerOutput.__add__ = _add
LayerOutput.__radd__ = _add
LayerOutput.__sub__ = _sub
LayerOutput.__rsub__ = _rsub
LayerOutput.__mul__ = _mul
LayerOutput.__rmul__ = _mul
