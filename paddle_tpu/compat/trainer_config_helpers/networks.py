"""``paddle.trainer_config_helpers.networks`` surface.

The composite network presets (`trainer_config_helpers/networks.py`,
1500 LoC): vgg/conv groups, simple/bidirectional LSTM & GRU, the
``simple_attention`` block (north-star NMT dependency), sequence
conv-pool, and the ``inputs``/``outputs`` declarations. Compositions
follow the reference's layer algebra; every building block is a compat
helper from layers.py so naming/parameters match.
"""

from __future__ import annotations

from paddle_tpu.compat import config_parser as _cp
from paddle_tpu.compat.trainer_config_helpers.activations import (
    IdentityActivation, LinearActivation, ReluActivation,
    SequenceSoftmaxActivation, SigmoidActivation, SoftmaxActivation,
    TanhActivation)
from paddle_tpu.compat.trainer_config_helpers.attrs import ExtraAttr
from paddle_tpu.compat.trainer_config_helpers.layers import (
    LayerOutput, batch_norm_layer, context_projection, dropout_layer,
    expand_layer, fc_layer, full_matrix_projection, grumemory,
    gru_step_layer, identity_projection, img_conv_layer, img_pool_layer,
    lstm_step_layer, lstmemory, memory, mixed_layer, pooling_layer,
    recurrent_group, scaling_layer, concat_layer)
from paddle_tpu.compat.trainer_config_helpers.poolings import (MaxPooling,
                                                               SumPooling)

__all__ = [
    'sequence_conv_pool', 'simple_lstm', 'simple_img_conv_pool',
    'img_conv_bn_pool', 'lstmemory_group', 'lstmemory_unit', 'small_vgg',
    'img_conv_group', 'vgg_16_network', 'gru_unit', 'gru_group',
    'simple_gru', 'simple_attention', 'simple_gru2', 'bidirectional_gru',
    'text_conv_pool', 'bidirectional_lstm', 'inputs', 'outputs',
]


def _name(name, prefix):
    return name if name is not None else _cp.ctx().auto_name(prefix)


# ------------------------------------------------------------------- text
def sequence_conv_pool(input, context_len, hidden_size, name=None,
                       context_start=None, pool_type=None,
                       context_proj_layer_name=None,
                       context_proj_param_attr=False, fc_layer_name=None,
                       fc_param_attr=None, fc_bias_attr=None, fc_act=None,
                       pool_bias_attr=None, fc_attr=None, context_attr=None,
                       pool_attr=None):
    """Context projection -> fc -> sequence pooling (text CNN)."""
    name = _name(name, "sequence_conv_pool")
    proj_name = context_proj_layer_name or f"{name}_conv_proj"
    with mixed_layer(name=proj_name, size=input.size * context_len,
                     act=LinearActivation(), layer_attr=context_attr) as m:
        m += context_projection(input, context_len=context_len,
                                context_start=context_start,
                                padding_attr=context_proj_param_attr)
    fl = fc_layer(input=m._finalize(), size=hidden_size,
                  name=fc_layer_name or f"{name}_conv_fc", act=fc_act,
                  layer_attr=fc_attr, param_attr=fc_param_attr,
                  bias_attr=fc_bias_attr)
    return pooling_layer(name=name, input=fl, pooling_type=pool_type,
                         bias_attr=pool_bias_attr, layer_attr=pool_attr)


text_conv_pool = sequence_conv_pool


# ----------------------------------------------------------------- images
def simple_img_conv_pool(input, filter_size, num_filters, pool_size,
                         name=None, pool_type=None, act=None, groups=1,
                         conv_stride=1, conv_padding=0, bias_attr=None,
                         num_channel=None, param_attr=None,
                         shared_bias=True, conv_layer_attr=None,
                         pool_stride=1, pool_padding=0,
                         pool_layer_attr=None):
    name = _name(name, "conv_pool")
    conv = img_conv_layer(
        input=input, filter_size=filter_size, num_filters=num_filters,
        name=f"{name}_conv", act=act, groups=groups, stride=conv_stride,
        padding=conv_padding, bias_attr=bias_attr,
        num_channels=num_channel, param_attr=param_attr,
        shared_biases=shared_bias, layer_attr=conv_layer_attr)
    return img_pool_layer(input=conv, pool_size=pool_size, name=name,
                          pool_type=pool_type, stride=pool_stride,
                          padding=pool_padding,
                          layer_attr=pool_layer_attr)


def img_conv_bn_pool(input, filter_size, num_filters, pool_size, name=None,
                     pool_type=None, act=None, groups=1, conv_stride=1,
                     conv_padding=0, conv_bias_attr=None, num_channel=None,
                     conv_param_attr=None, shared_bias=True,
                     conv_layer_attr=None, bn_param_attr=None,
                     bn_bias_attr=None, bn_layer_attr=None, pool_stride=1,
                     pool_padding=0, pool_layer_attr=None):
    name = _name(name, "conv_bn_pool")
    conv = img_conv_layer(
        input=input, filter_size=filter_size, num_filters=num_filters,
        name=f"{name}_conv", act=LinearActivation(), groups=groups,
        stride=conv_stride, padding=conv_padding,
        bias_attr=conv_bias_attr, num_channels=num_channel,
        param_attr=conv_param_attr, shared_biases=shared_bias,
        layer_attr=conv_layer_attr)
    bn = batch_norm_layer(input=conv, act=act, name=f"{name}_bn",
                          bias_attr=bn_bias_attr, param_attr=bn_param_attr,
                          layer_attr=bn_layer_attr)
    return img_pool_layer(input=bn, pool_size=pool_size, name=name,
                          pool_type=pool_type, stride=pool_stride,
                          padding=pool_padding,
                          layer_attr=pool_layer_attr)


def img_conv_group(input, conv_num_filter, pool_size, num_channels=None,
                   conv_padding=1, conv_filter_size=3, conv_act=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0,
                   pool_stride=1, pool_type=None, param_attr=None):
    """Chained conv(+bn+dropout) blocks ending in one pool — the vgg
    building block."""
    tmp = input
    if not isinstance(tmp, LayerOutput):
        raise TypeError("img_conv_group input must be a LayerOutput")
    n = len(conv_num_filter)

    def ext(v):
        return list(v) if hasattr(v, "__len__") else [v] * n

    conv_padding = ext(conv_padding)
    conv_filter_size = ext(conv_filter_size)
    conv_act = ext(conv_act)
    conv_with_batchnorm = ext(conv_with_batchnorm)
    conv_batchnorm_drop_rate = ext(conv_batchnorm_drop_rate)

    for i in range(n):
        extra = {}
        if num_channels is not None:
            extra["num_channels"] = num_channels
            num_channels = None
        extra["act"] = LinearActivation() if conv_with_batchnorm[i] \
            else conv_act[i]
        tmp = img_conv_layer(input=tmp, padding=conv_padding[i],
                             filter_size=conv_filter_size[i],
                             num_filters=conv_num_filter[i],
                             param_attr=param_attr, **extra)
        if conv_with_batchnorm[i]:
            drop = conv_batchnorm_drop_rate[i]
            if drop and abs(drop) >= 1e-5:
                tmp = batch_norm_layer(input=tmp, act=conv_act[i],
                                       layer_attr=ExtraAttr(drop_rate=drop))
            else:
                tmp = batch_norm_layer(input=tmp, act=conv_act[i])
    return img_pool_layer(input=tmp, stride=pool_stride,
                          pool_size=pool_size, pool_type=pool_type)


def small_vgg(input_image, num_channels, num_classes):
    def block(ipt, num_filter, times, dropouts, chans=None):
        return img_conv_group(
            input=ipt, num_channels=chans, pool_size=2, pool_stride=2,
            conv_num_filter=[num_filter] * times, conv_filter_size=3,
            conv_act=ReluActivation(), conv_with_batchnorm=True,
            conv_batchnorm_drop_rate=dropouts, pool_type=MaxPooling())

    tmp = block(input_image, 64, 2, [0.3, 0], num_channels)
    tmp = block(tmp, 128, 2, [0.4, 0])
    tmp = block(tmp, 256, 3, [0.4, 0.4, 0])
    tmp = block(tmp, 512, 3, [0.4, 0.4, 0])
    tmp = img_pool_layer(input=tmp, stride=2, pool_size=2,
                         pool_type=MaxPooling())
    tmp = dropout_layer(input=tmp, dropout_rate=0.5)
    tmp = fc_layer(input=tmp, size=512, act=LinearActivation(),
                   layer_attr=ExtraAttr(drop_rate=0.5))
    tmp = batch_norm_layer(input=tmp, act=ReluActivation())
    return fc_layer(input=tmp, size=num_classes, act=SoftmaxActivation())


def vgg_16_network(input_image, num_channels, num_classes=1000):
    def group(ipt, filters, chans=None):
        return img_conv_group(
            input=ipt, num_channels=chans, conv_padding=1,
            conv_num_filter=filters, conv_filter_size=3,
            conv_act=ReluActivation(), pool_size=2, pool_stride=2,
            pool_type=MaxPooling())

    tmp = group(input_image, [64, 64], num_channels)
    tmp = group(tmp, [128, 128])
    tmp = group(tmp, [256, 256, 256])
    tmp = group(tmp, [512, 512, 512])
    tmp = group(tmp, [512, 512, 512])
    tmp = fc_layer(input=tmp, size=4096, act=ReluActivation(),
                   layer_attr=ExtraAttr(drop_rate=0.5))
    tmp = fc_layer(input=tmp, size=4096, act=ReluActivation(),
                   layer_attr=ExtraAttr(drop_rate=0.5))
    return fc_layer(input=tmp, size=num_classes, act=SoftmaxActivation())


# -------------------------------------------------------------- recurrent
def simple_lstm(input, size, name=None, reverse=False, mat_param_attr=None,
                bias_param_attr=None, inner_param_attr=None, act=None,
                gate_act=None, state_act=None, mixed_layer_attr=None,
                lstm_cell_attr=None):
    """mixed(full-matrix, 4*size) -> lstmemory."""
    name = _name(name, "lstm")
    m = mixed_layer(name=f"lstm_transform_{name}", size=size * 4,
                    act=IdentityActivation(), bias_attr=False,
                    layer_attr=mixed_layer_attr,
                    input=full_matrix_projection(
                        input, param_attr=mat_param_attr))
    return lstmemory(name=name, input=m, reverse=reverse,
                     bias_attr=bias_param_attr, param_attr=inner_param_attr,
                     act=act, gate_act=gate_act, state_act=state_act,
                     layer_attr=lstm_cell_attr)


def lstmemory_unit(input, out_memory=None, name=None, size=None,
                   param_attr=None, act=None, gate_act=None, state_act=None,
                   input_proj_bias_attr=None, input_proj_layer_attr=None,
                   lstm_bias_attr=None, lstm_layer_attr=None):
    """Single-timestep LSTM block for recurrent_group (attention
    decoders)."""
    if size is None:
        size = input.size // 4
    name = _name(name, "lstm_unit")
    if out_memory is None:
        out_mem = memory(name=name, size=size)
    else:
        out_mem = out_memory
    state_mem = memory(name=f"{name}_state", size=size)
    with mixed_layer(name=f"{name}_input_recurrent", size=size * 4,
                     bias_attr=input_proj_bias_attr,
                     layer_attr=input_proj_layer_attr,
                     act=IdentityActivation()) as m:
        m += identity_projection(input=input)
        m += full_matrix_projection(input=out_mem, param_attr=param_attr)
    lstm_step = lstm_step_layer(
        name=name, input=m._finalize(), state=state_mem, size=size,
        bias_attr=lstm_bias_attr, act=act, gate_act=gate_act,
        state_act=state_act, layer_attr=lstm_layer_attr)
    from paddle_tpu.compat.trainer_config_helpers.layers import (
        get_output_layer)
    get_output_layer(name=f"{name}_state", input=lstm_step,
                     arg_name="state")
    return lstm_step


def lstmemory_group(input, size=None, name=None, out_memory=None,
                    reverse=False, param_attr=None, act=None, gate_act=None,
                    state_act=None, input_proj_bias_attr=None,
                    input_proj_layer_attr=None, lstm_bias_attr=None,
                    lstm_layer_attr=None):
    """LSTM via recurrent_group (flexible form of simple_lstm)."""
    if size is None:
        size = input.size // 4
    name = _name(name, "lstm_group")

    def step(x):
        return lstmemory_unit(
            input=x, name=name, size=size,
            param_attr=param_attr, act=act, gate_act=gate_act,
            state_act=state_act, out_memory=out_memory,
            input_proj_bias_attr=input_proj_bias_attr,
            input_proj_layer_attr=input_proj_layer_attr,
            lstm_bias_attr=lstm_bias_attr, lstm_layer_attr=lstm_layer_attr)

    # reference naming: the group is `{name}_recurrent_group`, the step
    # lstm layer is `{name}` (networks.py:833)
    return recurrent_group(name=f"{name}_recurrent_group", step=step,
                           reverse=reverse, input=input)


def gru_unit(input, memory_boot=None, size=None, name=None, gru_bias_attr=None,
             gru_param_attr=None, act=None, gate_act=None,
             gru_layer_attr=None, naive=False):
    name = _name(name, "gru_unit")
    if size is None:
        size = input.size // 3
    out_mem = memory(name=name, size=size, boot_layer=memory_boot)
    return gru_step_layer(name=name, input=input, output_mem=out_mem,
                          size=size, bias_attr=gru_bias_attr,
                          param_attr=gru_param_attr, act=act,
                          gate_act=gate_act, layer_attr=gru_layer_attr)


def gru_group(input, memory_boot=None, size=None, name=None, reverse=False,
              gru_bias_attr=None, gru_param_attr=None, act=None,
              gate_act=None, gru_layer_attr=None, naive=False):
    name = _name(name, "gru_group")

    def step(x):
        return gru_unit(input=x, memory_boot=memory_boot, name=name,
                        size=size, gru_bias_attr=gru_bias_attr,
                        gru_param_attr=gru_param_attr, act=act,
                        gate_act=gate_act, gru_layer_attr=gru_layer_attr,
                        naive=naive)

    return recurrent_group(name=f"{name}_recurrent_group", step=step,
                           reverse=reverse, input=input)


def simple_gru(input, size, name=None, reverse=False, mixed_param_attr=None,
               mixed_bias_param_attr=None, mixed_layer_attr=None,
               gru_bias_attr=None, gru_param_attr=None, act=None,
               gate_act=None, gru_layer_attr=None, naive=False):
    name = _name(name, "simple_gru")
    m = mixed_layer(name=f"{name}_transform", size=size * 3,
                    bias_attr=mixed_bias_param_attr,
                    layer_attr=mixed_layer_attr,
                    input=full_matrix_projection(
                        input, param_attr=mixed_param_attr))
    return gru_group(name=name, size=size, input=m, reverse=reverse,
                     gru_bias_attr=gru_bias_attr,
                     gru_param_attr=gru_param_attr, act=act,
                     gate_act=gate_act, gru_layer_attr=gru_layer_attr,
                     naive=naive)


def simple_gru2(input, size, name=None, reverse=False, mixed_param_attr=None,
                mixed_bias_attr=None, gru_param_attr=None,
                gru_bias_attr=None, act=None, gate_act=None,
                mixed_layer_attr=None, gru_cell_attr=None):
    """Same math as simple_gru through the fused grumemory layer."""
    name = _name(name, "gru")
    m = mixed_layer(name=f"{name}_transform", size=size * 3,
                    bias_attr=mixed_bias_attr,
                    layer_attr=mixed_layer_attr,
                    input=full_matrix_projection(
                        input, param_attr=mixed_param_attr))
    return grumemory(name=name, input=m, reverse=reverse,
                     bias_attr=gru_bias_attr, param_attr=gru_param_attr,
                     act=act, gate_act=gate_act, layer_attr=gru_cell_attr)


def bidirectional_gru(input, size, name=None, return_seq=False,
                      fwd_mixed_param_attr=None, fwd_mixed_bias_attr=None,
                      fwd_gru_param_attr=None, fwd_gru_bias_attr=None,
                      fwd_act=None, fwd_gate_act=None,
                      fwd_mixed_layer_attr=None, fwd_gru_layer_attr=None,
                      bwd_mixed_param_attr=None, bwd_mixed_bias_attr=None,
                      bwd_gru_param_attr=None, bwd_gru_bias_attr=None,
                      bwd_act=None, bwd_gate_act=None,
                      bwd_mixed_layer_attr=None, bwd_gru_layer_attr=None,
                      last_seq_attr=None, first_seq_attr=None,
                      concat_attr=None, concat_act=None):
    name = _name(name, "bidirectional_gru")
    fw = simple_gru2(input=input, size=size, name=f"{name}_fw",
                     mixed_param_attr=fwd_mixed_param_attr,
                     mixed_bias_attr=fwd_mixed_bias_attr,
                     gru_param_attr=fwd_gru_param_attr,
                     gru_bias_attr=fwd_gru_bias_attr, act=fwd_act,
                     gate_act=fwd_gate_act,
                     mixed_layer_attr=fwd_mixed_layer_attr,
                     gru_cell_attr=fwd_gru_layer_attr)
    bw = simple_gru2(input=input, size=size, name=f"{name}_bw",
                     reverse=True, mixed_param_attr=bwd_mixed_param_attr,
                     mixed_bias_attr=bwd_mixed_bias_attr,
                     gru_param_attr=bwd_gru_param_attr,
                     gru_bias_attr=bwd_gru_bias_attr, act=bwd_act,
                     gate_act=bwd_gate_act,
                     mixed_layer_attr=bwd_mixed_layer_attr,
                     gru_cell_attr=bwd_gru_layer_attr)
    if return_seq:
        return concat_layer(input=[fw, bw], layer_attr=concat_attr,
                            act=concat_act, name=name)
    from paddle_tpu.compat.trainer_config_helpers.layers import (first_seq,
                                                                 last_seq)
    fw_seq = last_seq(input=fw, layer_attr=last_seq_attr,
                      name=f"{name}_fw_last")
    bw_seq = first_seq(input=bw, layer_attr=first_seq_attr,
                       name=f"{name}_bw_first")
    return concat_layer(input=[fw_seq, bw_seq], layer_attr=concat_attr,
                        act=concat_act, name=name)


def bidirectional_lstm(input, size, name=None, return_seq=False,
                       fwd_mat_param_attr=None, fwd_bias_param_attr=None,
                       fwd_inner_param_attr=None, fwd_act=None,
                       fwd_gate_act=None, fwd_state_act=None,
                       fwd_mixed_layer_attr=None, fwd_lstm_cell_attr=None,
                       bwd_mat_param_attr=None, bwd_bias_param_attr=None,
                       bwd_inner_param_attr=None, bwd_act=None,
                       bwd_gate_act=None, bwd_state_act=None,
                       bwd_mixed_layer_attr=None, bwd_lstm_cell_attr=None,
                       last_seq_attr=None, first_seq_attr=None,
                       concat_attr=None, concat_act=None):
    name = _name(name, "bidirectional_lstm")
    fw = simple_lstm(input=input, size=size, name=f"{name}_fw",
                     mat_param_attr=fwd_mat_param_attr,
                     bias_param_attr=fwd_bias_param_attr,
                     inner_param_attr=fwd_inner_param_attr, act=fwd_act,
                     gate_act=fwd_gate_act, state_act=fwd_state_act,
                     mixed_layer_attr=fwd_mixed_layer_attr,
                     lstm_cell_attr=fwd_lstm_cell_attr)
    bw = simple_lstm(input=input, size=size, name=f"{name}_bw",
                     reverse=True, mat_param_attr=bwd_mat_param_attr,
                     bias_param_attr=bwd_bias_param_attr,
                     inner_param_attr=bwd_inner_param_attr, act=bwd_act,
                     gate_act=bwd_gate_act, state_act=bwd_state_act,
                     mixed_layer_attr=bwd_mixed_layer_attr,
                     lstm_cell_attr=bwd_lstm_cell_attr)
    if return_seq:
        return concat_layer(input=[fw, bw], layer_attr=concat_attr,
                            act=concat_act, name=name)
    from paddle_tpu.compat.trainer_config_helpers.layers import (first_seq,
                                                                 last_seq)
    fw_seq = last_seq(input=fw, layer_attr=last_seq_attr,
                      name=f"{name}_fw_last")
    bw_seq = first_seq(input=bw, layer_attr=first_seq_attr,
                       name=f"{name}_bw_first")
    return concat_layer(input=[fw_seq, bw_seq], layer_attr=concat_attr,
                        act=concat_act, name=name)


# -------------------------------------------------------------- attention
def simple_attention(encoded_sequence, encoded_proj, decoder_state,
                     transform_param_attr=None, softmax_param_attr=None,
                     weight_act=None, name=None):
    """Additive (Bahdanau) attention: returns the context vector
    (`networks.py simple_attention`; the NMT north-star block)."""
    name = _name(name, "attention")
    if encoded_proj.size != decoder_state.size:
        raise ValueError("encoded_proj and decoder_state sizes must match")
    proj_size = encoded_proj.size

    m = mixed_layer(size=proj_size, name=f"{name}_transform",
                    input=full_matrix_projection(
                        decoder_state, param_attr=transform_param_attr))
    expanded = expand_layer(input=m, expand_as=encoded_sequence,
                            name=f"{name}_expand")
    with mixed_layer(size=proj_size, act=weight_act,
                     name=f"{name}_combine") as comb:
        comb += identity_projection(expanded)
        comb += identity_projection(encoded_proj)
    attention_weight = fc_layer(input=comb._finalize(), size=1,
                                act=SequenceSoftmaxActivation(),
                                param_attr=softmax_param_attr,
                                name=f"{name}_softmax", bias_attr=False)
    scaled = scaling_layer(weight=attention_weight, input=encoded_sequence,
                           name=f"{name}_scaling")
    return pooling_layer(input=scaled, pooling_type=SumPooling(),
                         name=f"{name}_pooling")


# ------------------------------------------------------------ declarations
def inputs(layers, *args):
    if isinstance(layers, (LayerOutput, str)):
        layers = [layers]
    layers = list(layers) + list(args)
    _cp.inputs(*layers)


def outputs(layers, *args):
    if isinstance(layers, (LayerOutput, str)):
        layers = [layers]
    layers = list(layers) + list(args)
    _cp.outputs(*layers)
