"""``paddle.trainer_config_helpers.optimizers`` surface.

``settings(...)`` plus the optimizer/regularization/model-average objects
(`trainer_config_helpers/optimizers.py`). ``settings`` records everything
into the active ConfigContext; ``build_optimizer`` turns the recorded
state into the engine's Optimizer (paddle_tpu/optim/optimizers.py) whose
update formulas already match the v1 semantics.
"""

from __future__ import annotations

from paddle_tpu.compat import config_parser as _cp
from paddle_tpu.optim import optimizers as _opt

__all__ = [
    "Optimizer", "BaseSGDOptimizer", "MomentumOptimizer", "AdamaxOptimizer",
    "AdamOptimizer", "AdaGradOptimizer", "RMSPropOptimizer",
    "DecayedAdaGradOptimizer", "AdaDeltaOptimizer", "BaseRegularization",
    "L2Regularization", "L1Regularization", "settings", "ModelAverage",
    "GradientClippingThreshold",
]


class Optimizer:
    """Base marker; subclasses carry their hyper-parameters and know how
    to instantiate the engine optimizer."""

    learning_method = "momentum"

    def engine_kwargs(self):
        return {}

    def extra_settings(self):
        """OptimizationConfig fields this method implies."""
        return {"learning_method": self.learning_method}


class BaseSGDOptimizer(Optimizer):
    pass


class MomentumOptimizer(BaseSGDOptimizer):
    """SGD with momentum; ``sparse=True`` asks for sparse-momentum updates
    on sparse-gradient parameters."""

    learning_method = "momentum"

    def __init__(self, momentum=None, sparse=False):
        self.momentum = 1e-3 if momentum is None else momentum
        # an explicitly-passed coefficient rides the wire per-parameter
        # (ParameterConfig.momentum, the reference's default_momentum
        # path); the implicit 1e-3 default stays off the wire so golden
        # parity is untouched (proto_export.model_to_proto)
        self.explicit_momentum = momentum is not None
        self.sparse = sparse

    def engine_kwargs(self):
        return {"momentum": self.momentum}

    def extra_settings(self):
        return {"learning_method": "momentum", "momentum": self.momentum}

    def engine_class(self):
        return _opt.Momentum


class AdamOptimizer(Optimizer):
    learning_method = "adam"

    def __init__(self, beta1=0.9, beta2=0.999, epsilon=1e-8):
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def engine_kwargs(self):
        return {"beta1": self.beta1, "beta2": self.beta2,
                "epsilon": self.epsilon}

    def extra_settings(self):
        return {"learning_method": "adam", "adam_beta1": self.beta1,
                "adam_beta2": self.beta2, "adam_epsilon": self.epsilon}

    def engine_class(self):
        return _opt.Adam


class AdamaxOptimizer(Optimizer):
    learning_method = "adamax"

    def __init__(self, beta1=0.9, beta2=0.999):
        self.beta1, self.beta2 = beta1, beta2

    def engine_kwargs(self):
        return {"beta1": self.beta1, "beta2": self.beta2}

    def extra_settings(self):
        return {"learning_method": "adamax", "adam_beta1": self.beta1,
                "adam_beta2": self.beta2}

    def engine_class(self):
        return _opt.Adamax


class AdaGradOptimizer(Optimizer):
    learning_method = "adagrad"

    def __init__(self):
        pass

    def engine_class(self):
        return _opt.AdaGrad


class DecayedAdaGradOptimizer(Optimizer):
    learning_method = "decayed_adagrad"

    def __init__(self, rho=0.95, epsilon=1e-6):
        self.rho, self.epsilon = rho, epsilon

    def engine_kwargs(self):
        return {"rou": self.rho, "epsilon": self.epsilon}

    def extra_settings(self):
        return {"learning_method": "decayed_adagrad",
                "ada_rou": self.rho, "ada_epsilon": self.epsilon}

    def engine_class(self):
        return _opt.DecayedAdaGrad


class AdaDeltaOptimizer(Optimizer):
    learning_method = "adadelta"

    def __init__(self, rho=0.95, epsilon=1e-6):
        self.rho, self.epsilon = rho, epsilon

    def engine_kwargs(self):
        return {"rou": self.rho, "epsilon": self.epsilon}

    def extra_settings(self):
        return {"learning_method": "adadelta",
                "ada_rou": self.rho, "ada_epsilon": self.epsilon}

    def engine_class(self):
        return _opt.AdaDelta


class RMSPropOptimizer(Optimizer):
    learning_method = "rmsprop"

    def __init__(self, rho=0.95, epsilon=1e-6):
        self.rho, self.epsilon = rho, epsilon

    def engine_kwargs(self):
        return {"rou": self.rho, "epsilon": self.epsilon}

    def extra_settings(self):
        return {"learning_method": "rmsprop",
                "ada_rou": self.rho, "ada_epsilon": self.epsilon}

    def engine_class(self):
        return _opt.RMSProp


class BaseRegularization:
    pass


class L2Regularization(BaseRegularization):
    def __init__(self, rate):
        self.rate = rate

    def extra_settings(self):
        return {"l2weight": self.rate}


class L1Regularization(BaseRegularization):
    def __init__(self, rate):
        self.rate = rate

    def extra_settings(self):
        return {"l1weight": self.rate}


class ModelAverage:
    """AverageOptimizer window (`parameter/AverageOptimizer.h:23`)."""

    def __init__(self, average_window, max_average_window=None,
                 do_average_in_cpu=False):
        self.average_window = average_window
        self.max_average_window = max_average_window
        self.do_average_in_cpu = do_average_in_cpu


class GradientClippingThreshold:
    def __init__(self, threshold):
        self.threshold = threshold


def settings(batch_size,
             learning_rate=1e-3,
             learning_rate_decay_a=0.,
             learning_rate_decay_b=0.,
             learning_rate_schedule='poly',
             learning_rate_args='',
             async_lagged_grad_discard_ratio=1.5,
             learning_method=None,
             regularization=None,
             is_async=False,
             model_average=None,
             gradient_clipping_threshold=None):
    """Record the job-wide optimization settings
    (``trainer_config_helpers/optimizers.py settings``)."""
    c = _cp.ctx()
    if learning_method is None:
        learning_method = MomentumOptimizer()
    if not isinstance(learning_method, Optimizer):
        raise TypeError("learning_method must be an Optimizer instance")
    s = c.settings
    s["batch_size"] = batch_size
    s["learning_rate"] = learning_rate
    s["learning_rate_decay_a"] = learning_rate_decay_a
    s["learning_rate_decay_b"] = learning_rate_decay_b
    s["learning_rate_schedule"] = learning_rate_schedule
    s["learning_rate_args"] = learning_rate_args
    s["algorithm"] = "async_sgd" if is_async else "sgd"
    s["async_lagged_grad_discard_ratio"] = async_lagged_grad_discard_ratio
    s["learning_method"] = learning_method
    s["regularization"] = regularization
    if isinstance(model_average, ModelAverage):
        s["model_average"] = model_average
    if gradient_clipping_threshold is not None:
        if isinstance(gradient_clipping_threshold, GradientClippingThreshold):
            gradient_clipping_threshold = gradient_clipping_threshold.threshold
        s["gradient_clipping_threshold"] = gradient_clipping_threshold


def build_optimizer(s) -> _opt.Optimizer:
    """ConfigContext.settings -> engine Optimizer."""
    method = s.get("learning_method") or MomentumOptimizer()
    cls = method.engine_class() if hasattr(method, "engine_class") \
        else _opt.Momentum
    # Reference gradient semantics: parameter gradients are SUMMED over
    # the batch and the optimizer applies settings.learning_rate,
    # clipping, and decay rates to that sum (sgdUpdate,
    # ParameterUpdateFunctions.cpp:25-36 — no batch normalization
    # anywhere; hence the idiomatic learning_rate=0.1/128 with
    # batch_size=128 in v1_api_demo/mnist/vgg_16_mnist.py). The engine
    # differentiates the batch-MEAN cost, so compat-built optimizers set
    # sum_gradients: grads are re-scaled by the actual batch size inside
    # the update, and learning rate, clipping thresholds, L1/L2 rates,
    # and schedule parameters all keep their reference values. Defaults
    # follow DEFAULT_SETTING (config_parser.py:3513-3526): lr 1.0,
    # schedule "poly" (with decay a=b=0 it is constant).
    kwargs = dict(
        learning_rate=(s.get("learning_rate")
                       if s.get("learning_rate") is not None else 1.0),
        sum_gradients=True,
        learning_rate_schedule=s.get("learning_rate_schedule") or "poly",
        learning_rate_decay_a=s.get("learning_rate_decay_a", 0.0),
        learning_rate_decay_b=s.get("learning_rate_decay_b", 0.0),
        learning_rate_args=s.get("learning_rate_args", ""),
        gradient_clipping_threshold=s.get(
            "gradient_clipping_threshold", 0.0) or 0.0,
    )
    reg = s.get("regularization")
    if isinstance(reg, L2Regularization):
        kwargs["l2_rate"] = reg.rate
    elif isinstance(reg, L1Regularization):
        kwargs["l1_rate"] = reg.rate
    avg = s.get("model_average")
    if isinstance(avg, ModelAverage):
        kwargs["average_window"] = avg.average_window
        if avg.max_average_window is not None:
            kwargs["max_average_window"] = avg.max_average_window
    kwargs.update(method.engine_kwargs())
    return cls(**kwargs)
