"""``paddle.trainer_config_helpers.attrs`` surface.

ParameterAttribute / ExtraLayerAttribute with the reference's constructor
signatures (`trainer_config_helpers/attrs.py`), carrying straight into the
native ParamAttr / LayerDef attrs.
"""

from __future__ import annotations

from typing import Optional

from paddle_tpu.config.model_config import ParamAttr as _EngineParamAttr

__all__ = ["HookAttr", "HookAttribute", "ParamAttr", "ExtraAttr",
           "ParameterAttribute", "ExtraLayerAttribute"]


class HookAttribute:
    """Updater hook spec (currently 'pruning' with a sparsity ratio —
    `parameter/ParameterUpdaterHook.cpp:39`)."""

    def __init__(self, type, sparsity_ratio=None):
        self.type = type
        self.sparsity_ratio = sparsity_ratio
        if sparsity_ratio is not None and not 0 <= sparsity_ratio <= 1:
            raise ValueError("sparsity_ratio must be within [0, 1]")


class ParameterAttribute:
    """User-facing parameter attribute; ``.to_param_attr()`` converts to
    the engine's ParamAttr."""

    def __init__(self, name=None, is_static=False, initial_std=None,
                 initial_mean=None, initial_max=None, initial_min=None,
                 l1_rate=None, l2_rate=None, learning_rate=None,
                 momentum=None, gradient_clipping_threshold=None,
                 sparse_update=False, update_hooks=None,
                 initializer=None):
        if initial_max is not None or initial_min is not None:
            if initial_max is None or initial_min is None:
                raise ValueError("initial_max/min must be set together")
            if initial_max <= initial_min:
                raise ValueError("initial_max must exceed initial_min")
        self.name = name
        self.is_static = is_static
        self.initial_std = initial_std
        self.initial_mean = initial_mean
        self.initial_max = initial_max
        self.initial_min = initial_min
        self.l1_rate = l1_rate
        self.l2_rate = l2_rate
        self.learning_rate = learning_rate
        self.momentum = momentum
        self.gradient_clipping_threshold = gradient_clipping_threshold
        self.sparse_update = sparse_update
        self.update_hooks = update_hooks
        self.initializer = initializer

    def set_default_parameter_name(self, name):
        if self.name is None:
            self.name = name

    def to_param_attr(self) -> _EngineParamAttr:
        init = "normal"
        mean, std = self.initial_mean, self.initial_std
        if self.initial_max is not None:
            init = "uniform"
            mean = (self.initial_max + self.initial_min) / 2.0
            std = (self.initial_max - self.initial_min) / 2.0
        ratio = None
        hooks = self.update_hooks
        if hooks is not None:
            hooks = hooks if isinstance(hooks, (list, tuple)) else [hooks]
            for h in hooks:
                if getattr(h, "type", None) == "pruning":
                    # the proto default when the config leaves it unset
                    # (ParameterConfig.proto sparsity_ratio [default=0.6])
                    ratio = (h.sparsity_ratio
                             if h.sparsity_ratio is not None else 0.6)
        attr = _EngineParamAttr(
            name=self.name, init=init, sparsity_ratio=ratio,
            initial_mean=0.0 if mean is None else mean,
            initial_std=std, is_static=self.is_static,
            learning_rate=(1.0 if self.learning_rate is None
                           else self.learning_rate),
            l1_rate=self.l1_rate, l2_rate=self.l2_rate,
            sparse_grad=bool(self.sparse_update))
        # an attr that sets only non-init knobs (lr, decay, static, name)
        # must not clobber a layer's deliberate const init (e.g. BN gamma
        # = 1.0): record whether the INIT values themselves are explicit
        attr.init_explicit = (self.initial_mean is not None
                              or self.initial_std is not None
                              or self.initial_max is not None)
        return attr

    @staticmethod
    def to_bias(bias_attr):
        """Reference semantics: False/None-ish -> no bias; True -> default
        bias; ParameterAttribute -> that bias."""
        if isinstance(bias_attr, ParameterAttribute):
            return bias_attr.to_param_attr()
        return bool(bias_attr) if isinstance(bias_attr, bool) else \
            (bias_attr if bias_attr is None else bool(bias_attr))


class ExtraLayerAttribute:
    """Extra layer knobs: dropout, error clipping, device placement."""

    def __init__(self, error_clipping_threshold=None, drop_rate=None,
                 device=None):
        self.error_clipping_threshold = error_clipping_threshold
        self.drop_rate = drop_rate
        self.device = device

    @staticmethod
    def to_kwargs(attr: Optional["ExtraLayerAttribute"]) -> dict:
        if attr is None:
            return {}
        out = {}
        if attr.drop_rate is not None:
            out["drop_rate"] = attr.drop_rate
        if attr.error_clipping_threshold is not None:
            out["error_clipping_threshold"] = attr.error_clipping_threshold
        if attr.device is not None:
            out["device"] = attr.device
        return out


HookAttr = HookAttribute
ExtraAttr = ExtraLayerAttribute
ParamAttr = ParameterAttribute
