"""``paddle.trainer_config_helpers.data_sources`` surface.

``define_py_data_sources2`` (`trainer_config_helpers/data_sources.py`):
records the train/test PyDataProvider2 hookups in the active parse
context; the trainer builds readers from them (ParsedConfig.train_reader).
"""

from __future__ import annotations

from paddle_tpu.compat.config_parser import DataSource, ctx

__all__ = ["define_py_data_sources2", "define_py_data_sources"]


def define_py_data_sources2(train_list, test_list, module, obj, args=None):
    """train_list/test_list: file-list file path (or None); module/obj:
    the provider module and decorated object; args: init_hook kwargs.
    module/obj/args may be two-element lists to differ per split."""

    def pick(v, i):
        return v[i] if isinstance(v, (list, tuple)) else v

    c = ctx()
    if train_list is not None:
        c.train_source = DataSource(file_list=train_list,
                                    module=pick(module, 0),
                                    obj=pick(obj, 0), args=pick(args, 0))
    if test_list is not None:
        c.test_source = DataSource(file_list=test_list,
                                   module=pick(module, 1),
                                   obj=pick(obj, 1), args=pick(args, 1))


def define_py_data_sources(train_list, test_list, module, obj, args=None,
                           train_async=False, data_cls=None):
    """Legacy PyDataProvider wrapper — same recording semantics."""
    define_py_data_sources2(train_list, test_list, module, obj, args)
