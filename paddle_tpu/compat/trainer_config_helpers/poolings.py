"""``paddle.trainer_config_helpers.poolings`` surface
(`trainer_config_helpers/poolings.py`): pooling-type objects whose
``.name`` feeds PoolConfig.pool_type / sequence-pooling layer types.
"""

__all__ = ["BasePoolingType", "MaxPooling", "AvgPooling", "MaxWithMaskPooling",
           "CudnnMaxPooling", "CudnnAvgPooling", "SumPooling",
           "SquareRootNPooling"]


class BasePoolingType:
    def __init__(self, name):
        self.name = name


class MaxPooling(BasePoolingType):
    """Max over window / sequence. ``output_max_index`` makes the sequence
    pooling emit argmax indices instead of values."""

    def __init__(self, output_max_index=None):
        super().__init__("max")
        self.output_max_index = output_max_index


class MaxWithMaskPooling(BasePoolingType):
    def __init__(self):
        super().__init__("max-pool-with-mask")


class CudnnMaxPooling(BasePoolingType):
    def __init__(self):
        super().__init__("cudnn-max-pool")


class CudnnAvgPooling(BasePoolingType):
    def __init__(self):
        super().__init__("cudnn-avg-pool")


class AvgPooling(BasePoolingType):
    STRATEGY_AVG = "average"
    STRATEGY_SUM = "sum"
    STRATEGY_SQROOTN = "squarerootn"

    def __init__(self, strategy=STRATEGY_AVG):
        super().__init__("average")
        self.strategy = strategy


class SumPooling(AvgPooling):
    def __init__(self):
        super().__init__(AvgPooling.STRATEGY_SUM)


class SquareRootNPooling(AvgPooling):
    def __init__(self):
        super().__init__(AvgPooling.STRATEGY_SQROOTN)
