"""``paddle.trainer_config_helpers`` star-import surface.

The reference package re-exports every helper family so the canonical
config preamble ``from paddle.trainer_config_helpers import *`` brings in
layers, networks, activations, poolings, attrs, optimizers, evaluators and
data sources in one line (`python/paddle/trainer_config_helpers/
__init__.py:15-24`). The reference additionally inherits the whole
``config_parser`` namespace through ``layers.py``'s
``from paddle.trainer.config_parser import *`` — which is how configs see
``get_config_arg``/``inputs``/``outputs`` — so those are re-exported here
explicitly.
"""

from paddle_tpu.compat import config_parser as _config_parser
from paddle_tpu.compat.config_parser import (Inputs, Outputs,  # noqa: F401
                                             ProtoData, PyData, Settings,
                                             SimpleData, TestData,
                                             TrainData, default_decay_rate,
                                             default_device,
                                             default_initial_mean,
                                             default_initial_std,
                                             default_initial_strategy,
                                             default_momentum,
                                             get_config_arg, inputs,
                                             model_type, outputs,
                                             parse_config)
from paddle_tpu.compat.trainer_config_helpers import (activations,  # noqa: F401
                                                      attrs, data_sources,
                                                      evaluators, layers,
                                                      networks, optimizers,
                                                      poolings)
from paddle_tpu.compat.trainer_config_helpers import layer_math  # noqa: F401
from paddle_tpu.compat.trainer_config_helpers.activations import *  # noqa: F401,F403
from paddle_tpu.compat.trainer_config_helpers.attrs import *  # noqa: F401,F403
from paddle_tpu.compat.trainer_config_helpers.data_sources import *  # noqa: F401,F403
from paddle_tpu.compat.trainer_config_helpers.evaluators import *  # noqa: F401,F403
from paddle_tpu.compat.trainer_config_helpers.layers import *  # noqa: F401,F403
from paddle_tpu.compat.trainer_config_helpers.networks import *  # noqa: F401,F403
from paddle_tpu.compat.trainer_config_helpers.optimizers import *  # noqa: F401,F403
from paddle_tpu.compat.trainer_config_helpers.poolings import *  # noqa: F401,F403

__all__ = (activations.__all__ + attrs.__all__ + data_sources.__all__
           + evaluators.__all__ + layers.__all__ + networks.__all__
           + optimizers.__all__ + poolings.__all__
           + ["get_config_arg", "inputs", "outputs", "parse_config",
              "layer_math", "default_device", "default_initial_std",
              "default_initial_mean", "default_decay_rate",
              "default_momentum", "default_initial_strategy", "model_type",
              "TrainData", "TestData", "SimpleData", "ProtoData", "PyData",
              "Settings", "Inputs", "Outputs"])
