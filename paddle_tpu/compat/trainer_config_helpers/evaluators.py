"""``paddle.trainer_config_helpers.evaluators`` surface.

The 16 evaluator wrappers (`trainer_config_helpers/evaluators.py`):
each records an EvaluatorConfig-shaped dict in the parse context
(``ctx().evaluators``); the CLI hands that list to ``SGD(evaluators=...)``
which builds registry evaluators from it (``trainer/metrics.py
build_from_configs``) and feeds them every batch during train/test.
"""

from __future__ import annotations

from paddle_tpu.compat.config_parser import ctx

__all__ = [
    "evaluator_base", "classification_error_evaluator", "auc_evaluator",
    "pnpair_evaluator", "precision_recall_evaluator", "ctc_error_evaluator",
    "chunk_evaluator", "sum_evaluator", "column_sum_evaluator",
    "value_printer_evaluator", "gradient_printer_evaluator",
    "maxid_printer_evaluator", "maxframe_printer_evaluator",
    "seqtext_printer_evaluator", "classification_error_printer_evaluator",
    "detection_map_evaluator",
]


def evaluator_base(input, type, label=None, weight=None, name=None,
                   chunk_scheme=None, num_chunk_types=None, classification_threshold=None,
                   positive_label=None, dict_file=None, result_file=None,
                   num_results=None, delimited=None, top_k=None,
                   excluded_chunk_types=None, overlap_threshold=None,
                   background_id=None, evaluate_difficult=None,
                   ap_type=None):
    """Record one evaluator attachment (the reference's Evaluator config
    func)."""
    inputs = input if isinstance(input, (list, tuple)) else [input]
    names = [i.name if hasattr(i, "name") else str(i) for i in inputs]
    n_outputs = len(names)
    if label is not None:
        names.append(label.name if hasattr(label, "name") else str(label))
    if weight is not None:
        names.append(weight.name if hasattr(weight, "name") else str(weight))
    c = ctx()
    name = name or c.auto_name(f"{type}_evaluator")
    taken = {e["name"] for e in c.evaluators}
    if name in taken:  # multi-cost configs: never silently shadow
        k = 1
        while f"{name}_{k}" in taken:
            k += 1
        name = f"{name}_{k}"
    cfg = {"name": name,
           "type": type, "input_layers": names,
           # role map so the trainer binds eval_batch kwargs correctly
           # (flat input_layers is the proto contract; roles are wiring-only)
           "_roles": {"n_outputs": n_outputs,
                      "has_label": label is not None,
                      "has_weight": weight is not None}}
    for k, v in [("chunk_scheme", chunk_scheme),
                 ("num_chunk_types", num_chunk_types),
                 ("classification_threshold", classification_threshold),
                 ("positive_label", positive_label),
                 ("dict_file", dict_file), ("result_file", result_file),
                 ("num_results", num_results), ("delimited", delimited),
                 ("top_k", top_k),
                 ("excluded_chunk_types", excluded_chunk_types),
                 ("overlap_threshold", overlap_threshold),
                 ("background_id", background_id),
                 ("evaluate_difficult", evaluate_difficult),
                 ("ap_type", ap_type)]:
        if v is not None:
            cfg[k] = v
    c.evaluators.append(cfg)
    return cfg


def classification_error_evaluator(input, label, name=None, weight=None,
                                   top_k=None, threshold=None):
    return evaluator_base(input, "classification_error", label=label,
                          weight=weight, name=name, top_k=top_k,
                          classification_threshold=threshold)


def auc_evaluator(input, label, name=None, weight=None):
    return evaluator_base(input, "last-column-auc", label=label,
                          weight=weight, name=name)


def pnpair_evaluator(input, label, query_id, weight=None, name=None):
    ev = evaluator_base(input, "pnpair", label=label, weight=weight,
                        name=name)
    ev["input_layers"].append(
        query_id.name if hasattr(query_id, "name") else str(query_id))
    ev["_roles"]["has_query"] = True
    return ev


def precision_recall_evaluator(input, label, positive_label=None,
                               weight=None, name=None):
    return evaluator_base(input, "precision_recall", label=label,
                          positive_label=positive_label, weight=weight,
                          name=name)


def ctc_error_evaluator(input, label, name=None):
    return evaluator_base(input, "ctc_edit_distance", label=label,
                          name=name)


def chunk_evaluator(input, label, chunk_scheme, num_chunk_types,
                    name=None, excluded_chunk_types=None):
    return evaluator_base(input, "chunk", label=label, name=name,
                          chunk_scheme=chunk_scheme,
                          num_chunk_types=num_chunk_types,
                          excluded_chunk_types=excluded_chunk_types)


def detection_map_evaluator(input, label, overlap_threshold=0.5,
                            background_id=0, evaluate_difficult=False,
                            ap_type="11point", name=None):
    return evaluator_base(input, "detection_map", label=label, name=name,
                          overlap_threshold=overlap_threshold,
                          background_id=background_id,
                          evaluate_difficult=evaluate_difficult,
                          ap_type=ap_type)


def sum_evaluator(input, name=None, weight=None):
    return evaluator_base(input, "sum", weight=weight, name=name)


def column_sum_evaluator(input, name=None, weight=None):
    return evaluator_base(input, "last-column-sum", weight=weight,
                          name=name)


def value_printer_evaluator(input, name=None):
    return evaluator_base(input, "value_printer", name=name)


def gradient_printer_evaluator(input, name=None):
    return evaluator_base(input, "gradient_printer", name=name)


def maxid_printer_evaluator(input, num_results=None, name=None):
    return evaluator_base(input, "max_id_printer", name=name,
                          num_results=num_results)


def maxframe_printer_evaluator(input, num_results=None, name=None):
    return evaluator_base(input, "max_frame_printer", name=name,
                          num_results=num_results)


def seqtext_printer_evaluator(input, result_file, id_input=None,
                              dict_file=None, delimited=None, name=None):
    ev = evaluator_base(input, "seq_text_printer", name=name,
                        dict_file=dict_file, result_file=result_file,
                        delimited=delimited)
    if id_input is not None:
        ev["input_layers"].insert(
            0, id_input.name if hasattr(id_input, "name") else str(id_input))
    return ev


def classification_error_printer_evaluator(input, label, threshold=0.5,
                                           name=None):
    return evaluator_base(input, "classification_error_printer",
                          label=label, name=name,
                          classification_threshold=threshold)
