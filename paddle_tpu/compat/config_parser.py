"""The config compiler: v1 Python DSL -> canonical protos + executable graph.

Role of the reference's ``python/paddle/trainer/config_parser.py:3704``
(``parse_config`` / ``parse_config_and_serialize``), re-implemented for the
TPU engine: helper calls (paddle_tpu.compat.trainer_config_helpers) build
the graph through the native DSL (paddle_tpu.config.dsl) while this module
holds the per-parse global state — settings, data sources, declared
inputs/outputs, evaluators, name counters — and assembles the final
``TrainerConfig`` proto (paddle_tpu.proto) with the ``ModelConfig``
exported from the graph.

The reference executes the config inside an embedded interpreter
(``TrainerConfigHelper.cpp:33-57``); here ``parse_config`` execs it in a
namespace where ``paddle.*`` resolves to the compat package, including
Python-2 era builtins (``xrange``) so 2017-vintage configs run unmodified.
"""

from __future__ import annotations

import functools
import dataclasses
import itertools
import os
import sys
from typing import Any, Dict, List, Optional

from paddle_tpu.config import dsl
from paddle_tpu.config.model_config import ModelDef


@dataclasses.dataclass
class DataSource:
    """One data stream: a define_py_data_sources2 python provider
    (kind="py2"), or a binary proto-shard list (kind="proto",
    ProtoData())."""

    file_list: Optional[str]
    module: Optional[str]
    obj: Optional[str]
    args: Any = None
    kind: str = "py2"


class ConfigContext:
    """Per-parse global state (the reference's config_parser module
    globals, reset at the top of every parse_config call)."""

    def __init__(self, config_args: Optional[Dict[str, Any]] = None):
        self.config_args = dict(config_args or {})
        self.settings: Dict[str, Any] = {
            "batch_size": None,
            "learning_rate": None,
            "learning_method": None,
            "regularization": None,
            "gradient_clipping_threshold": 0.0,
            "model_average": None,
            "learning_rate_decay_a": 0.0,
            "learning_rate_decay_b": 0.0,
            "learning_rate_schedule": None,  # default "poly" (reference)
            "learning_rate_args": "",
            "algorithm": "sgd",
            "async_lagged_grad_discard_ratio": 1.5,
        }
        self.train_source: Optional[DataSource] = None
        self.test_source: Optional[DataSource] = None
        self.input_layer_names: List[str] = []
        self.output_layer_names: List[str] = []
        self.evaluators: List[Dict[str, Any]] = []
        # default_initial_std() etc. — global parameter defaults applied
        # where a layer gives no explicit ParamAttr
        self.param_defaults: Dict[str, Any] = {}
        self._counters: Dict[str, itertools.count] = {}
        self.config_dir: Optional[str] = None

    def auto_name(self, prefix: str) -> str:
        c = self._counters.setdefault(prefix, itertools.count())
        return f"__{prefix}_{next(c)}__"

    def default_param_attr(self, **overrides):
        """ParamAttr built from default_initial_std()/.. defaults plus
        per-site overrides (parameter name, per-param rates). Returns None
        when nothing applies — the single source both the helper and raw
        surfaces use."""
        from paddle_tpu.config.model_config import ParamAttr
        d = dict(self.param_defaults)
        d.update({k: v for k, v in overrides.items() if v is not None})
        if not d:
            return None
        init = "uniform" if d.get("initial_strategy") == 1 else "normal"
        attr = ParamAttr(
            name=d.get("name"), init=init,
            initial_std=d.get("initial_std"),
            initial_mean=d.get("initial_mean", 0.0),
            learning_rate=d.get("learning_rate", 1.0),
            sparse_grad=bool(d.get("sparse_update", False)),
            l1_rate=d.get("l1_rate"), l2_rate=d.get("l2_rate"))
        # purely-default attrs must not clobber const-initialized specs
        # (e.g. batch-norm gamma = const 1.0); a bare parameter_name does
        # not make the init values explicit, so it doesn't count
        attr.from_defaults = not any(
            v is not None for k, v in overrides.items() if k != "name")
        return attr


_CTX: Optional[ConfigContext] = None
# open raw-style recurrent groups (RecurrentLayerGroupBegin/End nesting)
_RAW_GROUPS: List[Dict[str, Any]] = []


def ctx() -> ConfigContext:
    if _CTX is None:
        raise RuntimeError(
            "no active config parse — call parse_config(), or "
            "begin_parse() when building configs programmatically")
    return _CTX


def ensure_ctx() -> ConfigContext:
    """An active context, opening an implicit one if none exists — WITHOUT
    resetting the dsl graph. The v1 reference keeps its config_parser
    globals alive permanently, so helper layers compose with the v2
    graph-object API outside any parse (e.g. ``paddle.v2.op`` arithmetic
    over v2-built layers); an explicit parse_config/begin_parse still
    resets everything, and ``dsl.reset()`` clears the implicit context
    (hook below) so auto-name counters never leak across rebuilds."""
    global _CTX
    if _CTX is None:
        _CTX = ConfigContext()
    return _CTX


@dsl.on_reset
def _clear_ctx_on_graph_reset():
    # keyed to the graph: a fresh graph must mean fresh auto-name counters
    # and defaults, or layer/param names would depend on process history
    # (begin_parse resets the graph first, then installs its own context)
    global _CTX
    _CTX = None


def begin_parse(config_args: Optional[Dict[str, Any]] = None
                ) -> ConfigContext:
    """Reset all per-parse state and open a fresh context."""
    global _CTX
    dsl.reset()
    # a previous parse that failed between RecurrentLayerGroupBegin/End
    # must not leak raw-group bookkeeping into this one (dsl.reset clears
    # the dsl-side group context)
    _RAW_GROUPS.clear()
    _CTX = ConfigContext(config_args)
    return _CTX


def get_config_arg(name: str, type_: type = str, default: Any = None):
    """Read a --config_args value with a type and default
    (``config_parser.py get_config_arg``)."""
    value = ctx().config_args.get(name, default)
    if value is None:
        return None
    if type_ is bool and isinstance(value, str):
        return value.lower() not in ("false", "0", "")
    return type_(value)


def default_device(device_id=-1):
    """Reference ``@config_func default_device``: per-layer GPU placement.
    Device placement is meaningless under SPMD (the mesh owns placement),
    so this records nothing — accepted so configs run unmodified."""
    ctx().config_args.setdefault("_default_device", device_id)


# ------------------------- old-style @config_func surface (pre-helpers) --
def _default_setter(field):
    def setter(value):
        ctx().param_defaults[field] = value

    setter.__name__ = f"default_{field}"
    return setter


default_initial_std = _default_setter("initial_std")
default_initial_mean = _default_setter("initial_mean")
default_decay_rate = _default_setter("l2_rate")
default_initial_strategy = _default_setter("initial_strategy")


def default_momentum(value):
    """Per-parameter momentum defaults have no per-param slot here (the
    optimizer's momentum is global); accepted with a loud note so training
    semantics are not silently different."""
    from paddle_tpu.utils.log import get_logger
    get_logger("compat").warning(
        "default_momentum(%s): per-parameter momentum is not supported; "
        "the optimizer's global momentum applies", value)


def model_type(name):
    """'nn' | 'recurrent_nn' — recorded; the executor infers recurrence
    from the graph itself."""
    ctx().settings["model_type"] = name


def SimpleData(**kw):
    spec = {"type": "simple", **kw}
    return spec


def ProtoData(**kw):
    return {"type": kw.pop("type", "proto"), **kw}


def PyData(**kw):
    return {"type": "py", **kw}


def _data_from_spec(spec):
    if isinstance(spec, dict):
        kind = spec.get("type", "py2")
        # SimpleData carries its knobs (feat_dim, context_len, ...) in
        # the spec itself rather than load_data_args
        args = spec if kind == "simple" else spec.get("load_data_args")
        return DataSource(file_list=spec.get("files"),
                          module=spec.get("load_data_module"),
                          obj=spec.get("load_data_object"),
                          args=args, kind=kind)
    return spec


def TrainData(spec, async_load_data=None):
    """Old spelling of the train data declaration (`config_parser.py
    @config_func TrainData`). Proto/simple shards aren't readable here —
    the source records for proto export; training needs a py provider."""
    ctx().train_source = _data_from_spec(spec)


def TestData(spec, async_load_data=None):
    ctx().test_source = _data_from_spec(spec)


def Settings(**kwargs):
    """Old spelling: maps straight onto the settings dict."""
    s = ctx().settings
    for k, v in kwargs.items():
        s[k] = v


# ---- the raw primitive surface (Layer/Input/Projection/Memory/Group) ----
# Old .conf files call config_parser's @config_layer handlers directly.
# Specs are plain dicts; Layer() lowers them onto the native graph.
def _lname(x):
    return x.name if hasattr(x, "name") else str(x)


def Input(input_layer_name, parameter_name=None, **kw):
    return {"input": _lname(input_layer_name),
            "parameter_name": parameter_name, **kw}


_PARAM_KW = {"initial_std", "initial_mean", "learning_rate",
             "decay_rate", "decay_rate_l1", "initial_strategy",
             "sparse_update"}


def _raw_proj(ptype, input_layer_name, parameter_name=None, **kw):
    spec = {"input": _lname(input_layer_name),
            "parameter_name": parameter_name,
            "proj": {"type": ptype}}
    for k, v in kw.items():
        (spec if k in _PARAM_KW else spec["proj"])[k] = v
    return spec


def FullMatrixProjection(input_layer_name, parameter_name=None, **kw):
    return _raw_proj("full_matrix", input_layer_name, parameter_name, **kw)


def TransposedFullMatrixProjection(input_layer_name, parameter_name=None,
                                   **kw):
    return _raw_proj("trans_full_matrix", input_layer_name, parameter_name,
                     **kw)


def IdentityProjection(input_layer_name, **kw):
    return _raw_proj("identity", input_layer_name, **kw)


def TableProjection(input_layer_name, parameter_name=None, **kw):
    return _raw_proj("table", input_layer_name, parameter_name, **kw)


def DotMulProjection(input_layer_name, parameter_name=None, **kw):
    return _raw_proj("dot_mul", input_layer_name, parameter_name, **kw)


def Layer(name=None, type=None, size=None, active_type="", bias=True,
          inputs=(), device=None, **kw):
    """The reference's ``@config_layer`` dispatch: build one layer from a
    raw spec. Covers the primitive spelling old .conf files use; helper
    calls remain the main path."""
    from paddle_tpu.config.model_config import Input as EInput
    from paddle_tpu.config.model_config import LayerDef, ParamAttr
    if type == "data":
        return dsl.data(name=name, size=size, height=kw.get("height"),
                        width=kw.get("width"), channels=kw.get("channels"))
    if isinstance(inputs, (str, dict)) or hasattr(inputs, "name"):
        inputs = [inputs]
    specs = []
    for item in inputs:
        if isinstance(item, dict):
            specs.append(item)
        else:
            specs.append({"input": _lname(item), "parameter_name": None})

    def pattr(spec):
        return ctx().default_param_attr(
            name=spec.get("parameter_name"),
            initial_std=spec.get("initial_std"),
            initial_mean=spec.get("initial_mean"),
            initial_strategy=spec.get("initial_strategy"),
            sparse_update=spec.get("sparse_update"),
            learning_rate=spec.get("learning_rate"),
            l1_rate=spec.get("decay_rate_l1"),
            l2_rate=spec.get("decay_rate"))

    bias_attr = bias
    if isinstance(bias, dict):  # Bias(parameter_name=..., initial_std=...)
        bias_attr = ctx().default_param_attr(
            name=bias.get("parameter_name"),
            initial_std=bias.get("initial_std"),
            initial_mean=bias.get("initial_mean"),
            learning_rate=bias.get("learning_rate")) or True

    attrs = dict(kw)
    eins = []
    if type == "mixed":
        projs = []
        for spec in specs:
            proj = dict(spec.get("proj") or {"type": "full_matrix"})
            if proj["type"] == "table":
                src = dsl.current_graph().layers.get(spec["input"])
                proj["vocab_size"] = src.size if src is not None else size
            projs.append(proj)
            eins.append(EInput(spec["input"], param_attr=pattr(spec)))
        attrs["projections"] = projs
    else:
        eins = [EInput(s["input"], param_attr=pattr(s)) for s in specs]
    ldef = LayerDef(name=name, type=type, inputs=eins, size=size,
                    act=active_type or "linear", bias=bias_attr,
                    attrs=attrs)
    return dsl._add(ldef)


def Bias(parameter_name=None, **kw):
    return {"parameter_name": parameter_name, **kw}


def Memory(name=None, size=None, boot_layer=None, **kw):
    bl = None
    if boot_layer is not None:
        bl = boot_layer if hasattr(boot_layer, "name") else \
            dsl.LayerOutput(str(boot_layer), size)
    return dsl.memory(name=name, size=size, boot_layer=bl)


def RecurrentLayerGroupBegin(name, in_links, out_links, seq_reversed=False,
                             **kw):
    """Imperative spelling of recurrent_group (RecurrentLayerGroupBegin /
    End in config_parser): switch graph building into a step sub-network
    whose boundary data layers take the in_links' outer names."""
    from paddle_tpu.config.model_config import LayerDef, ModelDef
    outer = dsl._GRAPH
    sub = ModelDef()
    prev_ctx = dsl._GROUP_CTX
    dsl._GRAPH = sub
    dsl._GROUP_CTX = {"name": name, "memories": []}
    ins_meta, outer_in_names = [], []
    for link in in_links:
        lname = _lname(link)
        outer_src = outer.layers[lname]
        dsl._add(LayerDef(name=lname, type="data", size=outer_src.size,
                          bias=False))
        ins_meta.append({"boundary": lname, "kind": "seq"})
        outer_in_names.append(lname)
    _RAW_GROUPS.append({
        "name": name, "outer": outer, "sub": sub, "prev_ctx": prev_ctx,
        "ins_meta": ins_meta, "outer_in_names": outer_in_names,
        "out_links": [_lname(o) for o in out_links],
        "reverse": bool(seq_reversed)})


def RecurrentLayerGroupEnd(name):
    from paddle_tpu.config.model_config import Input as EInput
    from paddle_tpu.config.model_config import LayerDef
    if not _RAW_GROUPS:
        raise ValueError(f"RecurrentLayerGroupEnd({name!r}) without Begin")
    g = _RAW_GROUPS.pop()
    if g["name"] != name:
        raise ValueError(f"group end mismatch: {name!r} vs {g['name']!r}")
    memories = dsl._GROUP_CTX["memories"]
    dsl._GRAPH = g["outer"]
    dsl._GROUP_CTX = g["prev_ctx"]
    ins_meta, outer_in_names = g["ins_meta"], g["outer_in_names"]
    for mem in memories:
        bl = mem.pop("boot_layer")
        if bl is not None:
            ins_meta.append({"boundary": mem["boundary"], "kind": "boot"})
            outer_in_names.append(bl.name)
    ldef = LayerDef(
        name=name, type="recurrent_layer_group",
        inputs=[EInput(n) for n in outer_in_names], bias=False,
        attrs={"sub_model": g["sub"], "ins": ins_meta,
               "memories": memories, "outputs": g["out_links"],
               "reverse": g["reverse"]})
    main = dsl._add(ldef)
    # the outer graph refers to out_links by their sub-net names
    for out in g["out_links"]:
        if out not in dsl.current_graph().layers:
            dsl._add(LayerDef(name=out, type="agent",
                              inputs=[EInput(main.name)], bias=False))
    return main


def Evaluator(name=None, type=None, inputs=(), **kw):
    if isinstance(inputs, str) or hasattr(inputs, "name"):
        inputs = [inputs]
    names = [_lname(i) for i in inputs]
    cfg = {"name": name or ctx().auto_name(f"{type}_evaluator"),
           "type": type, "input_layers": names,
           "_roles": {"n_outputs": 1, "has_label": len(names) > 1,
                      "has_weight": False}}
    cfg.update({k: v for k, v in kw.items() if v is not None})
    ctx().evaluators.append(cfg)
    return cfg


# capitalized old spellings accept plain strings, which inputs()/outputs()
# already handle
Inputs = None  # assigned below, after inputs() is defined


def inputs(*layers):
    """Declare data-provider stream order (``@config_func inputs``).
    APPENDS like the reference (``config_parser.py:212-222`` — old
    configs call Inputs() once per slot in a loop)."""
    names = [l.name if hasattr(l, "name") else str(l) for l in layers]
    ctx().input_layer_names.extend(
        n for n in names if n not in ctx().input_layer_names)


def outputs(*layers):
    """Declare network outputs (costs when training). When ``inputs()``
    was not called, the input order is inferred by the reference's
    DFS-LRV traversal from the outputs (`networks.py:1412-1498`): data
    layers appear in post-order of first reachability, not declaration
    order, and unreachable data layers are excluded."""
    names = [l.name if hasattr(l, "name") else str(l) for l in layers]
    c = ctx()
    c.output_layer_names = names
    graph = dsl.current_graph()
    graph.output_layer_names = names
    if not c.input_layer_names:
        seen: set = set()
        order: List[str] = []

        # the reference DFS walks LayerOutput.parents, which for a few
        # helpers is a strict subset of the proto inputs (e.g.
        # sub_nested_seq_layer records only `input`, not
        # selected_indices — `layers.py:6138`); mirror that
        dfs_parent_count = {"sub_nested_seq": 1}

        def dfs(n):
            if n in seen:
                return
            seen.add(n)
            ld = graph.layers.get(n)
            if ld is None:
                return
            limit = dfs_parent_count.get(ld.type, len(ld.inputs))
            for i in ld.inputs[:limit]:
                dfs(i.layer_name)
            if ld.type == "data":
                order.append(n)

        for n in names:
            dfs(n)
        c.input_layer_names = order


Inputs = inputs
Outputs = outputs


# cost layer types whose output drives the training objective (subset of
# the reference's Layer config classes flagged as cost layers)
COST_TYPES = {
    "multi-class-cross-entropy", "mse", "square_error",
    "cross-entropy", "multi_binary_label_cross_entropy", "rank-cost",
    "lambda_cost", "huber", "soft_binary_class_cross_entropy",
    "cross-entropy-with-selfnorm", "sum_cost", "smooth_l1", "ctc",
    "warp_ctc", "crf", "nce", "hsigmoid", "multibox_loss",
}


@dataclasses.dataclass
class ParsedConfig:
    """What parse_config returns: the executable pieces + the protos."""

    model: ModelDef
    context: ConfigContext
    namespace: Dict[str, Any]

    # ------------------------------------------------------- executables
    def cost_layers(self) -> List[str]:
        return [n for n in self.context.output_layer_names
                if self.model.layers[n].type in COST_TYPES]

    def optimizer(self):
        """Build the paddle_tpu Optimizer the settings() call described."""
        from paddle_tpu.compat.trainer_config_helpers.optimizers import (
            build_optimizer)
        return build_optimizer(self.context.settings)

    def topology(self):
        """The trainable Topology this config describes: all declared cost
        layers train jointly, non-cost outputs ride along as passive
        extras; an outputs()-only config roots at its declared outputs
        (inference-only)."""
        from paddle_tpu.trainer.trainer import Topology
        costs = self.cost_layers()
        out_names = list(self.context.output_layer_names)
        if costs:
            extra = [n for n in out_names if n not in costs]
            return Topology(costs, extra_outputs=extra, graph=self.model)
        if out_names:
            return Topology(out_names[0], extra_outputs=out_names[1:],
                            graph=self.model)
        raise ValueError("config declares no outputs()")

    def build_trainer(self, **sgd_kwargs):
        """Topology + settings-derived optimizer -> a ready SGD trainer."""
        from paddle_tpu.trainer.trainer import SGD
        return SGD(cost=self.topology(),
                   update_equation=self.optimizer(), **sgd_kwargs)

    # reference parse_config returns ONE TrainerConfig proto whose fields
    # raw-API programs read (and may mutate) before use — cache so
    # repeated access sees the same message and mutations stick
    @functools.cached_property
    def model_config(self):
        return self.model_proto()

    @functools.cached_property
    def opt_config(self):
        return self.trainer_proto().opt_config

    def batch_size(self) -> int:
        return int(self.context.settings.get("batch_size") or 1)

    def _reader_from(self, source: DataSource, *, is_train: bool):
        if source is None:
            return None, None
        key = (source.kind, source.file_list, source.module, source.obj,
               is_train)
        cached = getattr(self, "_reader_cache", {}).get(key)
        if cached is not None:
            return cached
        if source.kind == "simple":
            # plain-text `label f1..fn` files (SimpleDataProvider,
            # DataProvider.cpp:395) — the reference's e2e test configs
            from paddle_tpu.data.protodata import anchor_path
            from paddle_tpu.data.reader import batch
            from paddle_tpu.data.simpledata import SimpleDataReader
            file_list = source.file_list
            if file_list and isinstance(file_list, str) and \
                    self.context.config_dir:
                file_list = anchor_path(file_list, self.context.config_dir)
            args = source.args if isinstance(source.args, dict) else {}
            rdr = SimpleDataReader(
                file_list, feat_dim=int(args.get("feat_dim") or 1),
                context_len=int(args.get("context_len") or 0))
            batched = batch(rdr, self.batch_size())
            batched.input_types = rdr.input_types
            self.__dict__.setdefault("_reader_cache", {})[key] = \
                (batched, rdr)
            return batched, rdr
        if source.kind in ("proto", "proto_sequence"):
            # binary proto shards (ProtoDataProvider.h:48) need no
            # python provider module — the header drives the types
            from paddle_tpu.data.protodata import ProtoDataReader
            from paddle_tpu.data.reader import batch
            file_list = source.file_list
            if file_list and isinstance(file_list, str) and \
                    self.context.config_dir:
                # reference jobs run from the source root with paths like
                # "trainer/tests/mnist.list": anchor via the config dir
                from paddle_tpu.data.protodata import anchor_path
                file_list = anchor_path(file_list,
                                        self.context.config_dir)
            rdr = ProtoDataReader(
                file_list,
                as_sequences=source.kind == "proto_sequence")
            batched = batch(rdr, self.batch_size())
            batched.input_types = rdr.input_types
            rdr.as_reader = lambda *a, **k: rdr  # provider-shape shim
            self.__dict__.setdefault("_reader_cache", {})[key] = \
                (batched, rdr)
            return batched, rdr
        if source.module is None:
            return None, None
        saved = list(sys.path)
        if self.context.config_dir:
            sys.path.insert(0, self.context.config_dir)
        try:
            mod = __import__(source.module)
        finally:
            sys.path[:] = saved
        # Python-2-era provider scripts (xrange/reduce at generator time)
        import functools
        for legacy, repl in (("xrange", range), ("unicode", str),
                             ("reduce", functools.reduce)):
            if not hasattr(mod, legacy):
                setattr(mod, legacy, repl)
        prov = getattr(mod, source.obj)
        kwargs = {}
        if source.args not in (None, "", {}):
            kwargs = dict(source.args) if isinstance(source.args, dict) \
                else {"args": source.args}
        file_list = source.file_list
        if file_list and isinstance(file_list, str) and \
                self.context.config_dir and \
                not os.path.isabs(file_list):
            cand = os.path.join(self.context.config_dir, file_list)
            if os.path.exists(cand):
                file_list = cand
        sample_reader = prov.as_reader(file_list, is_train=is_train,
                                       **kwargs)
        from paddle_tpu.data.reader import batch
        batched = batch(sample_reader, self.batch_size())
        # init_hook-resolved types ride along for feeding construction
        batched.input_types = getattr(sample_reader, "input_types", None)
        return batched, prov

    def train_reader(self):
        reader, _ = self._reader_from(self.context.train_source,
                                      is_train=True)
        return reader

    def test_reader(self):
        reader, _ = self._reader_from(self.context.test_source,
                                      is_train=False)
        return reader

    def feeding(self):
        """{data-layer name: InputType} in provider order."""
        src = self.context.train_source or self.context.test_source
        if src is None or (src.module is None
                           and src.kind not in ("proto", "proto_sequence",
                                                "simple")):
            return None
        reader, prov = self._reader_from(src, is_train=True)
        # init_hook providers resolve their types at reader construction
        kinds = (prov.input_types if prov.input_types is not None
                 else getattr(reader, "input_types", None))
        if kinds is None:
            return None
        names = (self.context.input_layer_names
                 or self.model.input_layer_names)
        if isinstance(kinds, dict):
            # order by data-layer declaration, not dict order
            return {n: kinds[n] for n in names if n in kinds}
        return dict(zip(names, kinds))

    # ------------------------------------------------------------ protos
    def model_proto(self):
        from paddle_tpu.compat.proto_export import model_to_proto
        return model_to_proto(self.model, self.context)

    def trainer_proto(self):
        from paddle_tpu.compat.proto_export import trainer_to_proto
        return trainer_to_proto(self.model, self.context)


def parse_config(config_file: str, config_arg_str: str = "") -> ParsedConfig:
    """Execute a v1 config file and return the parsed configuration
    (``config_parser.py:3704``). ``config_arg_str`` is the
    ``--config_args`` comma-separated k=v list."""
    from paddle_tpu.compat import install_paddle_alias
    install_paddle_alias()

    config_args: Dict[str, Any] = {}
    for kv in filter(None, (config_arg_str or "").split(",")):
        k, _, v = kv.partition("=")
        config_args[k] = _coerce(v)

    c = begin_parse(config_args)
    c.config_dir = os.path.dirname(os.path.abspath(config_file))

    ns: Dict[str, Any] = {
        "__file__": os.path.abspath(config_file),
        "__name__": "__paddle_config__",
        # Python-2-era configs
        "xrange": range,
        "unicode": str,
    }
    # the reference execs configs inside config_parser's own module
    # namespace, so its @config_func surface is available WITHOUT imports
    # (old .conf files rely on this)
    for fname in __all__:
        ns.setdefault(fname, globals()[fname])
    saved_path = list(sys.path)
    sys.path.insert(0, c.config_dir)
    try:
        with open(config_file) as f:
            code = compile(f.read(), config_file, "exec")
        exec(code, ns)
    finally:
        sys.path[:] = saved_path

    graph = dsl.current_graph()
    if not c.input_layer_names:
        c.input_layer_names = list(graph.input_layer_names)
    if not c.output_layer_names:
        c.output_layer_names = list(graph.output_layer_names)
    return ParsedConfig(model=graph, context=c, namespace=ns)


def parse_config_and_serialize(config_file: str,
                               config_arg_str: str = "") -> bytes:
    """The embedded-interpreter entry the reference C++ calls
    (``TrainerConfigHelper.cpp:54``): returns serialized TrainerConfig."""
    return parse_config(config_file,
                        config_arg_str).trainer_proto().SerializeToString()


def _coerce(v: str):
    for conv in (int, float):
        try:
            return conv(v)
        except ValueError:
            pass
    if v.lower() in ("true", "false"):
        return v.lower() == "true"
    return v


# re-exported names configs sometimes pull from paddle.trainer.config_parser
__all__ = [
    "parse_config", "parse_config_and_serialize", "get_config_arg",
    "default_device", "default_initial_std", "default_initial_mean",
    "default_decay_rate", "default_momentum", "default_initial_strategy",
    "model_type", "TrainData", "TestData", "SimpleData", "ProtoData",
    "PyData", "Settings", "Inputs", "Outputs", "Layer", "Input", "Bias",
    "Memory", "Evaluator", "FullMatrixProjection",
    "TransposedFullMatrixProjection", "IdentityProjection",
    "TableProjection", "DotMulProjection", "RecurrentLayerGroupBegin",
    "RecurrentLayerGroupEnd",
    "inputs", "outputs", "begin_parse", "ctx", "ConfigContext",
    "ParsedConfig", "DataSource",
]
