"""The config compiler: v1 Python DSL -> canonical protos + executable graph.

Role of the reference's ``python/paddle/trainer/config_parser.py:3704``
(``parse_config`` / ``parse_config_and_serialize``), re-implemented for the
TPU engine: helper calls (paddle_tpu.compat.trainer_config_helpers) build
the graph through the native DSL (paddle_tpu.config.dsl) while this module
holds the per-parse global state — settings, data sources, declared
inputs/outputs, evaluators, name counters — and assembles the final
``TrainerConfig`` proto (paddle_tpu.proto) with the ``ModelConfig``
exported from the graph.

The reference executes the config inside an embedded interpreter
(``TrainerConfigHelper.cpp:33-57``); here ``parse_config`` execs it in a
namespace where ``paddle.*`` resolves to the compat package, including
Python-2 era builtins (``xrange``) so 2017-vintage configs run unmodified.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import sys
from typing import Any, Dict, List, Optional

from paddle_tpu.config import dsl
from paddle_tpu.config.model_config import ModelDef


@dataclasses.dataclass
class DataSource:
    """One define_py_data_sources2 stream."""

    file_list: Optional[str]
    module: Optional[str]
    obj: Optional[str]
    args: Any = None


class ConfigContext:
    """Per-parse global state (the reference's config_parser module
    globals, reset at the top of every parse_config call)."""

    def __init__(self, config_args: Optional[Dict[str, Any]] = None):
        self.config_args = dict(config_args or {})
        self.settings: Dict[str, Any] = {
            "batch_size": None,
            "learning_rate": None,
            "learning_method": None,
            "regularization": None,
            "gradient_clipping_threshold": 0.0,
            "model_average": None,
            "learning_rate_decay_a": 0.0,
            "learning_rate_decay_b": 0.0,
            "learning_rate_schedule": "constant",
            "learning_rate_args": "",
            "algorithm": "sgd",
            "async_lagged_grad_discard_ratio": 1.5,
        }
        self.train_source: Optional[DataSource] = None
        self.test_source: Optional[DataSource] = None
        self.input_layer_names: List[str] = []
        self.output_layer_names: List[str] = []
        self.evaluators: List[Dict[str, Any]] = []
        self._counters: Dict[str, itertools.count] = {}
        self.config_dir: Optional[str] = None

    def auto_name(self, prefix: str) -> str:
        c = self._counters.setdefault(prefix, itertools.count())
        return f"__{prefix}_{next(c)}__"


_CTX: Optional[ConfigContext] = None


def ctx() -> ConfigContext:
    if _CTX is None:
        raise RuntimeError(
            "no active config parse — call parse_config(), or "
            "begin_parse() when building configs programmatically")
    return _CTX


def begin_parse(config_args: Optional[Dict[str, Any]] = None
                ) -> ConfigContext:
    """Reset all per-parse state and open a fresh context."""
    global _CTX
    dsl.reset()
    _CTX = ConfigContext(config_args)
    return _CTX


def get_config_arg(name: str, type_: type = str, default: Any = None):
    """Read a --config_args value with a type and default
    (``config_parser.py get_config_arg``)."""
    value = ctx().config_args.get(name, default)
    if value is None:
        return None
    if type_ is bool and isinstance(value, str):
        return value.lower() not in ("false", "0", "")
    return type_(value)


def default_device(device_id=-1):
    """Reference ``@config_func default_device``: per-layer GPU placement.
    Device placement is meaningless under SPMD (the mesh owns placement),
    so this records nothing — accepted so configs run unmodified."""
    ctx().config_args.setdefault("_default_device", device_id)


def inputs(*layers):
    """Declare data-provider stream order (``@config_func inputs``)."""
    names = [l.name if hasattr(l, "name") else str(l) for l in layers]
    ctx().input_layer_names = names


def outputs(*layers):
    """Declare network outputs (costs when training)."""
    names = [l.name if hasattr(l, "name") else str(l) for l in layers]
    c = ctx()
    c.output_layer_names = names
    graph = dsl.current_graph()
    graph.output_layer_names = names


# cost layer types whose output drives the training objective (subset of
# the reference's Layer config classes flagged as cost layers)
COST_TYPES = {
    "multi-class-cross-entropy", "mse", "square_error",
    "cross-entropy", "multi_binary_label_cross_entropy", "rank-cost",
    "lambda_cost", "huber", "soft_binary_class_cross_entropy",
    "cross-entropy-with-selfnorm", "sum_cost", "smooth_l1", "ctc",
    "warp_ctc", "crf", "nce", "hsigmoid", "multibox_loss",
}


@dataclasses.dataclass
class ParsedConfig:
    """What parse_config returns: the executable pieces + the protos."""

    model: ModelDef
    context: ConfigContext
    namespace: Dict[str, Any]

    # ------------------------------------------------------- executables
    def cost_layers(self) -> List[str]:
        return [n for n in self.context.output_layer_names
                if self.model.layers[n].type in COST_TYPES]

    def optimizer(self):
        """Build the paddle_tpu Optimizer the settings() call described."""
        from paddle_tpu.compat.trainer_config_helpers.optimizers import (
            build_optimizer)
        return build_optimizer(self.context.settings)

    def batch_size(self) -> int:
        return int(self.context.settings.get("batch_size") or 1)

    def _reader_from(self, source: DataSource, *, is_train: bool):
        if source is None or source.module is None:
            return None, None
        saved = list(sys.path)
        if self.context.config_dir:
            sys.path.insert(0, self.context.config_dir)
        try:
            mod = __import__(source.module)
        finally:
            sys.path[:] = saved
        # Python-2-era provider scripts (xrange at generator time)
        for legacy, repl in (("xrange", range), ("unicode", str)):
            if not hasattr(mod, legacy):
                setattr(mod, legacy, repl)
        prov = getattr(mod, source.obj)
        kwargs = {}
        if source.args not in (None, "", {}):
            kwargs = dict(source.args) if isinstance(source.args, dict) \
                else {"args": source.args}
        file_list = source.file_list
        if file_list and self.context.config_dir and \
                not os.path.isabs(file_list):
            cand = os.path.join(self.context.config_dir, file_list)
            if os.path.exists(cand):
                file_list = cand
        sample_reader = prov.as_reader(file_list, is_train=is_train,
                                       **kwargs)
        from paddle_tpu.data.reader import batch
        batched = batch(sample_reader, self.batch_size())
        # init_hook-resolved types ride along for feeding construction
        batched.input_types = getattr(sample_reader, "input_types", None)
        return batched, prov

    def train_reader(self):
        reader, _ = self._reader_from(self.context.train_source,
                                      is_train=True)
        return reader

    def test_reader(self):
        reader, _ = self._reader_from(self.context.test_source,
                                      is_train=False)
        return reader

    def feeding(self):
        """{data-layer name: InputType} in provider order."""
        src = self.context.train_source or self.context.test_source
        if src is None or src.module is None:
            return None
        reader, prov = self._reader_from(src, is_train=True)
        # init_hook providers resolve their types at reader construction
        kinds = (prov.input_types if prov.input_types is not None
                 else getattr(reader, "input_types", None))
        if kinds is None:
            return None
        names = (self.context.input_layer_names
                 or self.model.input_layer_names)
        if isinstance(kinds, dict):
            # order by data-layer declaration, not dict order
            return {n: kinds[n] for n in names if n in kinds}
        return dict(zip(names, kinds))

    # ------------------------------------------------------------ protos
    def model_proto(self):
        from paddle_tpu.compat.proto_export import model_to_proto
        return model_to_proto(self.model, self.context)

    def trainer_proto(self):
        from paddle_tpu.compat.proto_export import trainer_to_proto
        return trainer_to_proto(self.model, self.context)


def parse_config(config_file: str, config_arg_str: str = "") -> ParsedConfig:
    """Execute a v1 config file and return the parsed configuration
    (``config_parser.py:3704``). ``config_arg_str`` is the
    ``--config_args`` comma-separated k=v list."""
    from paddle_tpu.compat import install_paddle_alias
    install_paddle_alias()

    config_args: Dict[str, Any] = {}
    for kv in filter(None, (config_arg_str or "").split(",")):
        k, _, v = kv.partition("=")
        config_args[k] = _coerce(v)

    c = begin_parse(config_args)
    c.config_dir = os.path.dirname(os.path.abspath(config_file))

    ns: Dict[str, Any] = {
        "__file__": os.path.abspath(config_file),
        "__name__": "__paddle_config__",
        # Python-2-era configs
        "xrange": range,
        "unicode": str,
    }
    saved_path = list(sys.path)
    sys.path.insert(0, c.config_dir)
    try:
        with open(config_file) as f:
            code = compile(f.read(), config_file, "exec")
        exec(code, ns)
    finally:
        sys.path[:] = saved_path

    graph = dsl.current_graph()
    if not c.input_layer_names:
        c.input_layer_names = list(graph.input_layer_names)
    if not c.output_layer_names:
        c.output_layer_names = list(graph.output_layer_names)
    return ParsedConfig(model=graph, context=c, namespace=ns)


def parse_config_and_serialize(config_file: str,
                               config_arg_str: str = "") -> bytes:
    """The embedded-interpreter entry the reference C++ calls
    (``TrainerConfigHelper.cpp:54``): returns serialized TrainerConfig."""
    return parse_config(config_file,
                        config_arg_str).trainer_proto().SerializeToString()


def _coerce(v: str):
    for conv in (int, float):
        try:
            return conv(v)
        except ValueError:
            pass
    if v.lower() in ("true", "false"):
        return v.lower() == "true"
    return v


# re-exported names configs sometimes pull from paddle.trainer.config_parser
__all__ = [
    "parse_config", "parse_config_and_serialize", "get_config_arg",
    "default_device",
    "inputs", "outputs", "begin_parse", "ctx", "ConfigContext",
    "ParsedConfig", "DataSource",
]
