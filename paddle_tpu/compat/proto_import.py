"""Wire-format import: a serialized ``ModelConfig`` proto → runnable graph.

The reference's C++ engine consumes the *expanded* wire format directly
(``GradientMachine::create`` over ``ModelConfig`` — recurrent groups arrive
as sub-models stitched to the root net through ``scatter_agent`` /
``gather_agent`` layers, ``paddle/gserver/layers/AgentLayer.cpp:209-210``,
wired at runtime by ``RecurrentGradientMachine``). This module gives the
TPU engine the same entry point: ``model_from_proto`` reconstructs a
``ModelDef`` whose recurrent sub-models execute under ``lax.scan`` with the
agent layers as the boundary slots — the scatter agents and memory agents
become the step net's feed slots, the gather agents the stacked outputs.

Round-trip contract: ``model_to_proto(model_from_proto(p))`` reproduces the
group wiring, and executing an imported graph matches executing the native
DSL graph it was exported from (tests/test_proto_import.py).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from paddle_tpu.config.model_config import (Input, LayerDef, ModelDef,
                                            ParamAttr)

# LayerConfig scalar fields that lower straight into LayerDef.attrs when
# present (names match both the proto field and the engine attr).
_SCALAR_ATTRS = (
    "data_norm_strategy", "average_strategy", "trans_type", "select_first",
    "active_gate_type", "active_state_type", "num_filters", "shared_biases",
    "max_sort_size", "norm_by_times", "blank", "num_classes", "coeff",
    "beam_size", "classes_num", "softmax_selfnorm_alpha", "delta",
)


def _param_attr(name: Optional[str],
                params: Dict[str, "object"]) -> Optional[ParamAttr]:
    if not name:
        return None
    pc = params.get(name)
    if pc is None:
        return ParamAttr(name=name)
    return ParamAttr(
        name=name,
        initial_mean=pc.initial_mean,
        initial_std=pc.initial_std if pc.HasField("initial_std") else None,
        is_static=pc.is_static,
        learning_rate=pc.learning_rate,
        sparse_grad=pc.sparse_update)


def _proj_spec(pj) -> Dict[str, object]:
    spec: Dict[str, object] = {"type": pj.type}
    if pj.type == "table":
        spec["vocab_size"] = pj.input_size
    if pj.type == "context":
        spec["context_start"] = pj.context_start
        spec["context_length"] = pj.context_length
        spec["trainable_padding"] = pj.trainable_padding
    return spec


def _layer_def(lc, params) -> LayerDef:
    attrs: Dict[str, object] = {}
    for f in _SCALAR_ATTRS:
        try:
            if lc.HasField(f):
                attrs[f] = getattr(lc, f)
        except ValueError:  # repeated / unknown on this layer type
            continue
    ins: List[Input] = []
    projs = []
    for ic in lc.inputs:
        ins.append(Input(ic.input_layer_name,
                         param_attr=_param_attr(ic.input_parameter_name,
                                                params)))
        if ic.HasField("proj_conf"):
            projs.append(_proj_spec(ic.proj_conf))
    if lc.type == "mixed" and projs:
        attrs["projections"] = projs
    if lc.operator_confs:
        attrs["operators"] = [
            {"type": oc.type,
             "input_indices": list(oc.input_indices),
             "scale": oc.dotmul_scale}
            for oc in lc.operator_confs]
    if lc.type == "data":
        if lc.height:
            attrs["height"], attrs["width"] = lc.height, lc.width
    bias = (_param_attr(lc.bias_parameter_name, params) or True) \
        if lc.bias_parameter_name else False
    return LayerDef(
        name=lc.name, type=lc.type, inputs=ins,
        size=lc.size or None,
        act=lc.active_type or "linear",
        bias=bias,
        drop_rate=lc.drop_rate,
        attrs=attrs)


def model_from_proto(mc) -> ModelDef:
    """Build a runnable ``ModelDef`` from a wire-format ``ModelConfig``
    (accepts the message or its serialized bytes). Recurrent sub-models
    are reconstituted as native ``recurrent_layer_group`` nodes executing
    *through* their agent layers: scatter/memory agents stay in the step
    sub-net as feed slots; the root ``gather_agent`` becomes the group's
    output node."""
    from paddle_tpu.proto import ModelConfig
    if isinstance(mc, (bytes, bytearray)):
        raw, mc = mc, ModelConfig()
        mc.ParseFromString(raw)

    params = {p.name: p for p in mc.parameters}
    lc_by_name = {lc.name: lc for lc in mc.layers}
    groups = [sm for sm in mc.sub_models if sm.is_recurrent_layer_group]
    # first sub-model is the root net by construction (SubModelBegin in
    # config_parser emits it first)
    root_names = (list(mc.sub_models[0].layer_names) if mc.sub_models
                  else [lc.name for lc in mc.layers])

    # gather_agent name (root) -> (group sub-model, inner out layer, index)
    gather_of: Dict[str, tuple] = {}
    for sm in groups:
        for i, ol in enumerate(sm.out_links):
            gather_of[ol.link_name] = (sm, ol.layer_name, i)
    shell_names = {sm.name for sm in groups}

    def build_group(sm) -> LayerDef:
        sub = ModelDef()
        for lname in sm.layer_names:
            sub.add(_layer_def(lc_by_name[lname], params))
        ins_meta, outer_in = [], []
        for il in sm.in_links:
            # the wire format does not distinguish seq/subseq/static
            # in-links (LinkConfig.has_subseq stays default even for
            # nested goldens); like RecurrentGradientMachine, which
            # inspects the Argument at runtime, "auto" defers the
            # decision to the group executor, which resolves it from the
            # fed Argument's mask rank at trace time
            ins_meta.append({"boundary": il.link_name, "kind": "auto"})
            outer_in.append(il.layer_name)
        memories = []
        for m in sm.memories:
            memories.append({
                "boundary": m.link_name, "link": m.layer_name,
                "init": float(m.boot_with_const_id)
                if m.HasField("boot_with_const_id") else 0.0})
            if m.boot_layer_name:
                ins_meta.append({"boundary": m.link_name, "kind": "boot"})
                outer_in.append(m.boot_layer_name)
        outputs = [ol.layer_name for ol in sm.out_links]
        main_name = sm.out_links[0].link_name
        return LayerDef(
            name=main_name, type="recurrent_layer_group",
            inputs=[Input(n) for n in outer_in], bias=False,
            size=lc_by_name[main_name].size or None,
            attrs={"sub_model": sub, "ins": ins_meta, "memories": memories,
                   "outputs": outputs, "reverse": sm.reversed})

    model = ModelDef()
    for lname in root_names:
        lc = lc_by_name[lname]
        if lc.type == "recurrent_layer_group" and lc.name in shell_names:
            continue  # shell node; the gather_agent carries the group
        if lc.name in gather_of:
            sm, inner_out, idx = gather_of[lc.name]
            if idx == 0:
                model.add(build_group(sm))
            else:
                main_name = sm.out_links[0].link_name
                model.add(LayerDef(
                    name=lc.name, type="group_output",
                    inputs=[Input(main_name)], size=lc.size or None,
                    bias=False, attrs={"sub_name": inner_out}))
            continue
        model.add(_layer_def(lc, params))

    model.input_layer_names = list(mc.input_layer_names)
    model.output_layer_names = list(mc.output_layer_names)
    for ev in mc.evaluators:
        cfg = {"name": ev.name, "type": ev.type,
               "input_layers": list(ev.input_layers)}
        for f in ("chunk_scheme", "num_chunk_types",
                  "classification_threshold", "positive_label",
                  "dict_file", "result_file", "num_results", "delimited",
                  "top_k", "overlap_threshold", "background_id",
                  "evaluate_difficult", "ap_type"):
            if ev.HasField(f):
                cfg[f] = getattr(ev, f)
        if ev.excluded_chunk_types:
            cfg["excluded_chunk_types"] = list(ev.excluded_chunk_types)
        model.evaluators.append(cfg)
    return model
