"""``py_paddle`` package surface: ``swig_paddle`` + DataProviderConverter.

The reference's ``py_paddle.dataprovider_converter.DataProviderConverter``
turns PyDataProvider2-shaped python rows into slot-ordered ``Arguments``
(numpy → Matrix/IVector, one slot per declared input type). Sequence
types need the offset-vector API the padded engine replaces — feed those
through ``paddle_tpu.data.DataFeeder`` instead (clear error below).
"""

from __future__ import annotations

import numpy as np

from paddle_tpu.compat import swig_api as swig_paddle  # noqa: F401
from paddle_tpu.data.types import DENSE, INDEX, NO_SEQUENCE, InputType


class DataProviderConverter:
    def __init__(self, input_types):
        self.input_types = list(input_types)
        for t in self.input_types:
            if not isinstance(t, InputType):
                raise TypeError(f"expected an InputType, got {t!r}")

    def __call__(self, batch, argument=None):
        args = argument or swig_paddle.Arguments.createArguments(
            len(self.input_types))
        args.resize(len(self.input_types))
        for i, t in enumerate(self.input_types):
            col = [row[i] for row in batch]
            if t.seq_type != NO_SEQUENCE:
                # flat concatenation + offset vector, the reference's
                # Argument layout (dataprovider_converter.py:308); the
                # machine re-shapes to padded+masked at feed time
                starts = np.zeros(len(col) + 1, np.int32)
                for j, seq in enumerate(col):
                    starts[j + 1] = starts[j] + len(seq)
                if t.type == INDEX:
                    flat = np.concatenate(
                        [np.asarray(s, np.int32) for s in col]) \
                        if col else np.zeros(0, np.int32)
                    args.setSlotIds(
                        i, swig_paddle.IVector.createVectorFromNumpy(flat))
                elif t.type == DENSE:
                    flat = np.concatenate(
                        [np.asarray(s, np.float32).reshape(len(s), -1)
                         for s in col]) if col \
                        else np.zeros((0, t.dim), np.float32)
                    args.setSlotValue(
                        i, swig_paddle.Matrix.createDenseFromNumpy(flat))
                else:
                    raise NotImplementedError(
                        f"sequence slot type {t.type!r}")
                args.setSlotSequenceStartPositions(
                    i, swig_paddle.IVector.createVectorFromNumpy(starts))
            elif t.type == INDEX:
                args.setSlotIds(i, swig_paddle.IVector.createVectorFromNumpy(
                    np.asarray(col, np.int32)))
            elif t.type == DENSE:
                args.setSlotValue(
                    i, swig_paddle.Matrix.createDenseFromNumpy(
                        np.asarray(col, np.float32)))
            else:
                raise NotImplementedError(
                    f"slot type {t.type!r} in DataProviderConverter")
        return args
