"""``paddle.trainer.PyDataProvider2`` surface for v1 configs/providers.

The reference module (`python/paddle/trainer/PyDataProvider2.py:329`)
defines the ``@provider`` decorator and the slot-type constructors used by
user data scripts; here they resolve to the native provider pipeline
(paddle_tpu/data/provider.py + native double-buffer prefetch).
"""

from paddle_tpu.data.provider import (CacheType, DataProvider,  # noqa: F401
                                      provider)
from paddle_tpu.data.types import (InputType, dense_vector,  # noqa: F401
                                   dense_vector_sequence,
                                   dense_vector_sub_sequence,
                                   integer_value,
                                   integer_value_sequence,
                                   integer_value_sub_sequence,
                                   sparse_binary_vector,
                                   sparse_binary_vector_sub_sequence,
                                   sparse_float_vector,
                                   sparse_float_vector_sub_sequence)
from paddle_tpu.data import types as _T

# sequence-ness constants (reference SequenceType)
NO_SEQUENCE = _T.NO_SEQUENCE
SEQUENCE = _T.SEQUENCE
SUB_SEQUENCE = _T.SUB_SEQUENCE


class SequenceType:
    NO_SEQUENCE = _T.NO_SEQUENCE
    SEQUENCE = _T.SEQUENCE
    SUB_SEQUENCE = _T.SUB_SEQUENCE


def sparse_binary_vector_sequence(dim):
    import dataclasses
    return dataclasses.replace(sparse_binary_vector(dim), seq_type=SEQUENCE)


def sparse_float_vector_sequence(dim):
    import dataclasses
    return dataclasses.replace(sparse_float_vector(dim), seq_type=SEQUENCE)


sparse_vector = sparse_float_vector
sparse_vector_sequence = sparse_float_vector_sequence
sparse_non_value_slot = sparse_binary_vector
sparse_value_slot = sparse_float_vector
index_slot = integer_value
dense_slot = dense_vector


def integer_sequence(dim):
    return integer_value_sequence(dim)


__all__ = [
    "provider", "DataProvider", "CacheType", "InputType", "SequenceType",
    "dense_vector", "dense_vector_sequence", "integer_value",
    "integer_value_sequence", "sparse_binary_vector",
    "sparse_binary_vector_sequence", "sparse_float_vector",
    "sparse_float_vector_sequence", "sparse_vector",
    "sparse_vector_sequence", "sparse_non_value_slot", "sparse_value_slot",
    "index_slot", "dense_slot", "integer_sequence",
    "integer_value_sub_sequence", "dense_vector_sub_sequence",
    "sparse_binary_vector_sub_sequence", "sparse_float_vector_sub_sequence",
    "NO_SEQUENCE", "SEQUENCE", "SUB_SEQUENCE",
]
