"""v1 compatibility surface: the reference's Python config stack.

Provides importable equivalents of ``paddle.trainer_config_helpers`` and
``paddle.trainer`` (`python/paddle/trainer_config_helpers/*`,
`python/paddle/trainer/config_parser.py:3704`) so reference v1 configs run
unmodified. ``install_paddle_alias()`` registers ``sys.modules`` entries
for the ``paddle.*`` names those configs import; ``parse_config`` executes
a config file and returns the canonical protos + the executable graph.
"""

import sys
import types


def install_paddle_alias():
    """Make ``import paddle.trainer_config_helpers`` etc. resolve to this
    package (the reference embeds Python and imports its own `paddle`;
    here the alias plays that role). Idempotent; returns the root module."""
    if "paddle" in sys.modules and getattr(
            sys.modules["paddle"], "__is_paddle_tpu_compat__", False):
        return sys.modules["paddle"]

    from paddle_tpu.compat import config_parser, pydp2
    from paddle_tpu.compat import trainer_config_helpers as tch

    root = types.ModuleType("paddle")
    root.__is_paddle_tpu_compat__ = True
    trainer = types.ModuleType("paddle.trainer")
    trainer.config_parser = config_parser
    trainer.PyDataProvider2 = pydp2
    pydp_wrapper = __import__("paddle_tpu.compat.pydp_wrapper",
                              fromlist=["pydp_wrapper"])
    trainer.PyDataProviderWrapper = pydp_wrapper
    root.trainer = trainer
    root.trainer_config_helpers = tch
    root.proto = __import__("paddle_tpu.proto", fromlist=["proto"])

    sys.modules["paddle"] = root
    sys.modules["paddle.trainer"] = trainer
    sys.modules["paddle.trainer.config_parser"] = config_parser
    sys.modules["paddle.trainer.PyDataProvider2"] = pydp2
    sys.modules["paddle.trainer.PyDataProviderWrapper"] = pydp_wrapper
    sys.modules["paddle.trainer_config_helpers"] = tch
    for sub in ["layers", "networks", "optimizers", "activations",
                "attrs", "poolings", "evaluators", "data_sources",
                "config_parser_utils"]:
        mod = getattr(tch, sub, None)
        if mod is not None:
            sys.modules[f"paddle.trainer_config_helpers.{sub}"] = mod
    sys.modules["paddle.proto"] = root.proto

    # py_paddle: the SWIG training-API surface (api_train.py-style
    # raw-API programs import this directly). Registered LAZILY — the
    # shim pulls in jax, and config-parse-only callers of this alias
    # must not pay (or require) a jax import.
    for alias, target in [
            ("py_paddle", "paddle_tpu.compat.py_paddle"),
            ("py_paddle.swig_paddle", "paddle_tpu.compat.swig_api"),
            ("py_paddle.dataprovider_converter",
             "paddle_tpu.compat.py_paddle")]:
        sys.modules[alias] = _LazyAlias(alias, target)
    return root


class _LazyAlias(types.ModuleType):
    """sys.modules placeholder that swaps in the real module on first
    attribute access (so `import py_paddle.swig_paddle as api` works
    without importing jax until the api surface is actually used)."""

    def __init__(self, name, target):
        super().__init__(name)
        self.__dict__["_target"] = target

    def __getattr__(self, item):
        import importlib
        mod = importlib.import_module(self._target)
        sys.modules[self.__name__] = mod
        # `import a.b` binds attribute b on a: keep that working for the
        # real modules once loaded
        if self.__name__ == "py_paddle":
            mod.dataprovider_converter = mod
            from paddle_tpu.compat import swig_api as _swig
            mod.swig_paddle = _swig
        return getattr(mod, item)


from paddle_tpu.compat.config_parser import (parse_config,  # noqa: E402,F401
                                             parse_config_and_serialize)
