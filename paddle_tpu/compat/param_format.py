"""The reference's binary parameter-file format.

``Parameter::save/load`` (``paddle/parameter/Parameter.cpp:279-360``)
writes one file per parameter: a 16-byte header ``{int32 version=0,
uint32 valueSize=sizeof(real)=4, uint64 size}`` followed by the raw
float32 value buffer. ``ParamUtil`` saves one such file per parameter,
named exactly like the parameter, into a pass directory — the on-disk
model format every reference tool exchanges (``--init_model_path``,
``MergeModel``, the model-zoo downloads, the checked-in
``rnn_gen_test_model_dir``).

This module reads and writes that format so reference-trained models
load here unmodified (and models trained here can be handed back).
"""

from __future__ import annotations

import os
import struct
from typing import Dict

import numpy as np

_HEADER = struct.Struct("<iIQ")   # version, valueSize, size
_VERSION = 0


def load_v1_param(path: str) -> np.ndarray:
    """One parameter file -> flat float32 array (header-validated)."""
    with open(path, "rb") as f:
        raw = f.read(_HEADER.size)
        if len(raw) != _HEADER.size:
            raise IOError(f"{path}: truncated parameter header")
        version, value_size, size = _HEADER.unpack(raw)
        if version != _VERSION:
            raise IOError(f"{path}: unsupported format version {version}")
        if value_size != 4:
            raise IOError(
                f"{path}: valueSize {value_size} (only float32 supported)")
        data = np.frombuffer(f.read(size * 4), dtype="<f4")
        if data.size != size:
            raise IOError(f"{path}: expected {size} values, got {data.size}")
        return np.array(data)   # writable copy


def save_v1_param(path: str, value: np.ndarray):
    arr = np.ascontiguousarray(np.asarray(value, dtype="<f4").reshape(-1))
    with open(path, "wb") as f:
        f.write(_HEADER.pack(_VERSION, 4, arr.size))
        f.write(arr.tobytes())


def load_v1_model_dir(model_dir: str) -> Dict[str, np.ndarray]:
    """A pass/model directory -> {parameter name: flat float32 array}
    (every regular file that parses as a v1 parameter; the reference
    names files exactly after the parameters)."""
    out: Dict[str, np.ndarray] = {}
    for name in sorted(os.listdir(model_dir)):
        path = os.path.join(model_dir, name)
        if not os.path.isfile(path):
            continue
        try:
            out[name] = load_v1_param(path)
        except IOError:
            continue  # not a parameter file (e.g. done-marker, config)
    return out


def save_v1_model_dir(model_dir: str, params: Dict[str, np.ndarray]):
    os.makedirs(model_dir, exist_ok=True)
    for name, value in params.items():
        save_v1_param(os.path.join(model_dir, name), value)
