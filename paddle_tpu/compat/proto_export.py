"""DSL graph -> wire-contract protos.

The reference's ``config_parser.py`` mutates a ``TrainerConfig`` proto while
the config executes; here the graph is built first (paddle_tpu/config/
model_config.py) and this module lowers it into the contract schemas
(paddle_tpu/proto, parity-tested against the reference's compiled schemas)
afterwards — same output contract as ``parse_config_and_serialize``
(``TrainerConfigHelper.cpp:33-57``), different pipeline shape.

``model_to_proto`` emits ``ModelConfig`` (layers in topological order +
``ParameterConfig`` per learnable parameter, shapes from the engine's shape
inference); ``trainer_to_proto`` wraps it with ``OptimizationConfig`` and
the ``DataConfig`` pair recorded by ``define_py_data_sources2``.
"""

from __future__ import annotations

import json
from typing import Optional

from paddle_tpu.config.model_config import ModelDef, ParamAttr
from paddle_tpu.core.network import Network
from paddle_tpu.proto import DataConfig_pb2, ModelConfig_pb2, TrainerConfig_pb2

# LayerDef.act "linear" is the DSL spelling of the reference's empty
# active_type (LinearActivation().name == "").
def _active_type(act: str) -> str:
    return "" if act in ("linear", "") else act


def _img_geom(info):
    """(channels, height, width) with 1x1 fallback for flat inputs."""
    if info.channels is None:
        return 1, 1, max(1, info.size)
    return info.channels, info.height, info.width


def _derive(in_info, extra):
    """(channels, h, w) with the reference's sqrt inference for flat
    inputs (config_parser.py:1160-1161). Falls back to the 1x1 flat view
    when the geometry is genuinely unknowable (e.g. a concat output with
    no channel metadata) — the engine re-derives at execution time from
    the real channels."""
    from paddle_tpu.layers.conv import derive_geom
    try:
        return derive_geom(in_info, extra.get("channels"))
    except ValueError:
        return _img_geom(in_info)


def _set_conv_conf(conf, extra, in_info, out_info, num_filters,
                   trans=False):
    """Mirror ``parse_conv`` (config_parser.py:1247-1277): for trans=True
    the conf describes the *forward* conv whose backward this layer is —
    output_x/y hold the input geometry, img_size the output, and
    filter_channels = num_filters/groups."""
    channels, in_h, in_w = _derive(in_info, extra)
    fs = int(extra.get("filter_size", 1))
    groups = int(extra.get("groups", 1) or 1)
    conf.filter_size = fs
    conf.channels = int(extra.get("channels") or channels)
    conf.stride = int(extra.get("stride", 1))
    conf.padding = int(extra.get("padding", 0))
    conf.groups = groups
    conf.caffe_mode = True
    conf.filter_size_y = int(extra.get("filter_size_y") or fs)
    conf.padding_y = int(extra.get("padding_y")
                         if extra.get("padding_y") is not None
                         else conf.padding)
    conf.stride_y = int(extra.get("stride_y")
                        if extra.get("stride_y") is not None
                        else conf.stride)
    if not trans:
        conf.filter_channels = conf.channels // groups
        conf.img_size = int(in_w or 1)
        conf.img_size_y = int(in_h or conf.img_size)
        conf.output_x = int(out_info.width or 1)
        conf.output_y = int(out_info.height or conf.output_x)
    else:
        conf.filter_channels = int(num_filters or conf.channels) // groups
        conf.output_x = int(in_w or 1)
        conf.output_y = int(in_h or conf.output_x)
        conf.img_size = int(out_info.width or 1)
        conf.img_size_y = int(out_info.height or conf.img_size)


def _set_pool_conf(conf, extra, in_info, out_info):
    channels, in_h, in_w = _derive(in_info, extra)
    conf.pool_type = str(extra.get("pool_type", "max-projection"))
    conf.channels = int(extra.get("channels") or channels)
    conf.size_x = int(extra.get("filter_size", 1))
    conf.stride = int(extra.get("stride", 1))
    conf.padding = int(extra.get("padding", 0))
    conf.output_x = int(out_info.width or 1)
    conf.img_size = int(in_w or 1)
    # the reference always resolves the y variants (parse_pool defaults
    # them from the x values)
    conf.size_y = int(extra.get("size_y") or conf.size_x)
    conf.stride_y = int(extra.get("stride_y") or conf.stride)
    conf.padding_y = int(extra["padding_y"]
                         if extra.get("padding_y") is not None
                         else conf.padding)
    conf.output_y = int(out_info.height or conf.output_x)
    conf.img_size_y = int(in_h or conf.img_size)


def _set_norm_conf(conf, extra, in_info, out_info):
    channels, in_h, in_w = _derive(in_info, extra)
    conf.norm_type = str(extra.get("norm_type", "cmrnorm-projection"))
    conf.channels = int(extra.get("channels") or channels)
    conf.size = int(extra.get("size", 5))
    # parse_norm (config_parser.py:1239-1242) folds the window size into
    # the stored scale
    scale = float(extra.get("scale", 1e-4))
    conf.scale = scale / (conf.size if conf.norm_type == "cmrnorm-projection"
                          else conf.size ** 2)
    conf.pow = float(extra.get("pow", 0.75))
    conf.blocked = bool(extra.get("blocked", False))
    conf.output_x = int(out_info.width or 1)
    conf.img_size = int(in_w or 1)
    conf.output_y = int(out_info.height or conf.output_x)
    conf.img_size_y = int(in_h or conf.img_size)


def _conv_out_geom(ih, iw, extra, trans):
    """(oh, ow) for a conv/convt spec, per-axis with the *_y variants
    defaulting to their x twins — delegating the per-axis formula to the
    engine's single source of truth (layers/conv.py)."""
    from paddle_tpu.layers.conv import _conv_geom
    fs = int(extra["filter_size"])
    fsy = int(extra.get("filter_size_y") or fs)
    st = int(extra.get("stride") or 1)
    sty = int(extra.get("stride_y") or st)
    pd = int(extra.get("padding") or 0)
    pdy = int(extra["padding_y"]
              if extra.get("padding_y") is not None else pd)

    def _out(sz, f, s, p):
        return (sz - 1) * s + f - 2 * p if trans else _conv_geom(sz, f, p, s)

    return _out(ih, fsy, sty, pdy), _out(iw, fs, st, pd)


def _export_conv_spec(conf, spec, in_info, in_size, trans):
    """Shared conv/convt export for projections AND operators: derive
    input geometry, compute output geometry, fill conv_conf. Returns
    (num_filters, output_size)."""
    from paddle_tpu.core.registry import ShapeInfo as _SI
    from paddle_tpu.layers.conv import derive_geom
    extra = {k: spec.get(k) for k in (
        "filter_size", "stride", "padding", "filter_size_y",
        "stride_y", "padding_y", "groups")}
    extra["channels"] = spec.get("num_channels") or spec.get("channels")
    c, ih, iw = derive_geom(in_info or _SI(size=in_size),
                            extra.get("channels"))
    oh, ow = _conv_out_geom(ih, iw, extra, trans)
    nf = int(spec.get("num_filters") or 0)
    _set_conv_conf(conf, extra,
                   _SI(size=in_size, channels=c, height=ih, width=iw),
                   _SI(size=nf * oh * ow, channels=nf, height=oh,
                       width=ow), nf, trans=trans)
    return nf, nf * oh * ow


def _set_proj_conf(conf, spec, name, in_size, out_size, in_info=None):
    ptype = spec.get("type", "full_matrix")
    conf.type = {"full_matrix": "fc", "trans_full_matrix": "trans_fc",
                 "table": "table", "identity": "identity",
                 "identity_offset": "identity_offset",
                 "dot_mul": "dot_mul", "scaling": "scaling",
                 "context": "context", "conv": "conv", "convt": "convt",
                 "slice": "slice"}.get(ptype, ptype)
    conf.name = name
    conf.input_size = int(in_size)
    conf.output_size = int(out_size)
    if ptype == "context":
        conf.context_start = int(spec.get("context_start", 0))
        conf.context_length = int(spec.get("context_length", 1))
        conf.trainable_padding = bool(spec.get("trainable_padding", False))
    if ptype == "identity_offset":
        conf.offset = int(spec.get("offset", 0))
    if ptype in ("conv", "convt") and spec.get("filter_size"):
        nf, _ = _export_conv_spec(conf.conv_conf, spec, in_info, in_size,
                                  ptype == "convt")
        conf.num_filters = nf
    for s, e in spec.get("slices", []):
        sl = conf.slices.add()
        sl.start, sl.end = int(s), int(e)


_LAYER_SCALAR_FIELDS = {
    # LayerDef.attrs key -> LayerConfig field (same-typed scalars)
    "num_filters": "num_filters",
    "shared_biases": "shared_biases",
    "num_classes": "num_classes",
    "reversed": "reversed",
    "active_gate_type": "active_gate_type",
    "active_state_type": "active_state_type",
    "num_neg_samples": "num_neg_samples",
    "output_max_index": "output_max_index",
    "norm_by_times": "norm_by_times",
    "coeff": "coeff",
    "average_strategy": "average_strategy",
    "error_clipping_threshold": "error_clipping_threshold",
    "NDCG_num": "NDCG_num",
    "max_sort_size": "max_sort_size",
    "slope": "slope",
    "intercept": "intercept",
    "cos_scale": "cos_scale",
    "bos_id": "bos_id",
    "eos_id": "eos_id",
    "beam_size": "beam_size",
    "select_first": "select_first",
    "trans_type": "trans_type",
    "use_global_stats": "use_global_stats",
    "moving_average_fraction": "moving_average_fraction",
    "blank": "blank",
    "seq_pool_stride": "seq_pool_stride",
    "axis": "axis",
    "partial_sum": "partial_sum",
}


# layer types whose reference LayerConfig carries no size (config_parser
# leaves it unset: side-effect/scoring/cost layers with no feature width)
_SIZELESS_TYPES = {"print", "kmax_seq_score",
                   "multi_class_cross_entropy_with_selfnorm"}


def _export_layer(model: ModelDef, net: Network, name: str, proto_layer,
                  rename=None):
    layer = model.layers[name]
    out_info = net.shape_infos[name]
    proto_layer.name = layer.name
    proto_layer.type = "mixed" if layer.type == "embedding" else layer.type
    if layer.type not in _SIZELESS_TYPES and (layer.size or out_info.size):
        proto_layer.size = int(layer.size or out_info.size)
    # recurrent helpers keep the main activation in attrs (the engine
    # applies it inside the scan); the proto's active_type is that one
    proto_layer.active_type = _active_type(
        layer.attrs.get("active_type", layer.act))
    if layer.drop_rate:
        proto_layer.drop_rate = float(layer.drop_rate)

    lp = net._layer_params.get(name, {})
    if "wbias" in lp:
        proto_layer.bias_parameter_name = lp["wbias"]

    for attr_key, field in _LAYER_SCALAR_FIELDS.items():
        if attr_key in layer.attrs and layer.attrs[attr_key] is not None:
            if attr_key == "partial_sum" and layer.type == "prelu":
                # ParameterReluLayer uses partial_sum only to size its
                # parameter; the reference never writes the proto field
                continue
            if attr_key == "num_classes" and layer.type in (
                    "multibox_loss", "detection_output"):
                continue  # lives inside the per-input *_conf
            try:
                setattr(proto_layer, field, layer.attrs[attr_key])
            except TypeError:
                pass  # attr used differently by this layer type
    for key in ("offset", "shape"):
        v = layer.attrs.get(key)
        if isinstance(v, (list, tuple)):
            getattr(proto_layer, key).extend(int(x) for x in v)
    if layer.attrs.get("user_arg"):
        proto_layer.user_arg = str(layer.attrs["user_arg"])
    if layer.type == "multi_class_cross_entropy_with_selfnorm":
        proto_layer.softmax_selfnorm_alpha = float(
            layer.attrs.get("softmax_selfnorm_alpha", 0.1))
    if layer.type == "lambda_cost":
        # LambdaCost (config_parser.py:2287) always writes NDCG_num and
        # max_sort_size and never coeff
        proto_layer.ClearField("coeff")
        proto_layer.max_sort_size = int(layer.attrs.get("max_sort_size",
                                                        -1))
    if layer.type == "selective_fc":
        proto_layer.selective_fc_pass_generation = bool(
            layer.attrs.get("pass_generation", False))
        proto_layer.has_selected_colums = bool(
            layer.attrs.get("has_selected_colums", True))
        proto_layer.selective_fc_full_mul_ratio = float(
            layer.attrs.get("full_mul_ratio", 0.02))
    # image geometry on the layer itself: data layers carry the
    # user-declared height/width; cnn layers the output geometry
    # (set_cnn_layer / set_layer_height_width in the reference)
    if layer.type == "data":
        hh = layer.attrs.get("height")
        if hh:
            proto_layer.height = int(hh)
            proto_layer.width = int(layer.attrs.get("width") or 0)
    elif layer.type == "spp":
        # set_cnn_layer for spp: height 1, width = total pyramid bins
        ph = int(layer.attrs.get("pyramid_height", 3))
        proto_layer.height = 1
        proto_layer.width = (4 ** ph - 1) // 3
    elif layer.type in ("exconv", "exconvt", "cudnn_conv", "pool", "norm",
                        "maxout", "blockexpand", "pad", "crop",
                        "bilinear_interp"):
        if out_info.height is not None:
            proto_layer.height = int(out_info.height)
            proto_layer.width = int(out_info.width)

    projections = layer.attrs.get("projections")
    operators = layer.attrs.get("operators") or []
    for i, inp in enumerate(layer.inputs):
        pin = proto_layer.inputs.add()
        pin.input_layer_name = (rename or {}).get(inp.layer_name,
                                                  inp.layer_name)
        if f"w{i}" in lp:
            pin.input_parameter_name = lp[f"w{i}"]
        extra = inp.extra or {}
        if extra.get("input_layer_argument"):
            # get_output: which named output of the producer to read
            pin.input_layer_argument = str(extra["input_layer_argument"])
        in_info = net.shape_infos[inp.layer_name]
        if layer.type in ("exconv", "exconvt", "cudnn_conv"):
            _set_conv_conf(pin.conv_conf, extra, in_info, out_info,
                           layer.attrs.get("num_filters"),
                           trans=layer.type == "exconvt")
        elif layer.type == "pool" and extra:
            _set_pool_conf(pin.pool_conf, extra, in_info, out_info)
        elif layer.type == "norm":
            _set_norm_conf(pin.norm_conf, extra, in_info, out_info)
        elif layer.type == "clip":
            pin.clip_conf.min = float(layer.attrs.get("min", -1.0))
            pin.clip_conf.max = float(layer.attrs.get("max", 1.0))
        elif layer.type == "row_conv":
            pin.row_conv_conf.context_length = int(
                layer.attrs.get("context_length", 1))
        elif layer.type == "blockexpand" and i == 0:
            be = pin.block_expand_conf
            be.channels = int(layer.attrs.get("channels") or 1)
            be.stride_x = int(layer.attrs.get("stride_x", 0))
            be.stride_y = int(layer.attrs.get("stride_y", 0))
            be.padding_x = int(layer.attrs.get("padding_x", 0))
            be.padding_y = int(layer.attrs.get("padding_y", 0))
            be.block_x = int(layer.attrs.get("block_x", 0))
            be.block_y = int(layer.attrs.get("block_y", 0))
            # geometry resolves at runtime in the reference
            # (parse_block_expand leaves it zero)
            be.output_x = be.output_y = 0
            be.img_size_x = be.img_size_y = 0
        elif layer.type == "maxout" and i == 0:
            c, hh, ww = _derive(in_info, layer.attrs)
            ic = pin.maxout_conf.image_conf
            ic.channels = int(layer.attrs.get("channels") or c)
            ic.img_size, ic.img_size_y = int(ww), int(hh)
            pin.maxout_conf.groups = int(layer.attrs.get("groups", 1))
        elif layer.type == "pad" and i == 0:
            c, hh, ww = _derive(in_info, layer.attrs)
            ic = pin.pad_conf.image_conf
            ic.channels, ic.img_size, ic.img_size_y = int(c), int(ww), \
                int(hh)
            for key in ("pad_c", "pad_h", "pad_w"):
                getattr(pin.pad_conf, key).extend(
                    int(x) for x in layer.attrs.get(key, [0, 0]))
        elif layer.type == "bilinear_interp" and i == 0:
            c, hh, ww = _derive(in_info, layer.attrs)
            ic = pin.bilinear_interp_conf.image_conf
            ic.channels, ic.img_size, ic.img_size_y = int(c), int(ww), \
                int(hh)
            pin.bilinear_interp_conf.out_size_x = int(
                layer.attrs.get("out_size_x") or 0)
            pin.bilinear_interp_conf.out_size_y = int(
                layer.attrs.get("out_size_y") or 0)
        elif layer.type == "spp" and i == 0:
            c, hh, ww = _derive(in_info, layer.attrs)
            ic = pin.spp_conf.image_conf
            ic.channels, ic.img_size, ic.img_size_y = int(c), int(ww), \
                int(hh)
            pin.spp_conf.pool_type = str(
                layer.attrs.get("pool_type", "max-projection"))
            pin.spp_conf.pyramid_height = int(
                layer.attrs.get("pyramid_height", 3))
        elif layer.type == "multibox_loss" and i == 0:
            mb = pin.multibox_loss_conf
            mb.num_classes = int(layer.attrs.get("num_classes", 0))
            mb.overlap_threshold = float(
                layer.attrs.get("overlap_threshold", 0.5))
            mb.neg_pos_ratio = float(layer.attrs.get("neg_pos_ratio", 3.0))
            mb.neg_overlap = float(layer.attrs.get("neg_overlap", 0.5))
            mb.background_id = int(layer.attrs.get("background_id", 0))
            mb.input_num = 1
        elif layer.type == "detection_output" and i == 0:
            dc = pin.detection_output_conf
            dc.num_classes = int(layer.attrs.get("num_classes", 0))
            dc.nms_threshold = float(layer.attrs.get("nms_threshold",
                                                     0.45))
            dc.nms_top_k = int(layer.attrs.get("nms_top_k", 400))
            dc.background_id = int(layer.attrs.get("background_id", 0))
            dc.input_num = 1
            dc.keep_top_k = int(layer.attrs.get("keep_top_k", 200))
            dc.confidence_threshold = float(
                layer.attrs.get("confidence_threshold", 0.01))
        elif layer.type in ("mixed", "concat2") and projections is not None \
                and i < len(projections):
            spec = projections[i]
            if spec.get("type") not in (None, "identity_op_arg"):
                out_size = (spec.get("size") if layer.type == "concat2"
                            else None) or layer.size or out_info.size
                # proj_conf.name is the projection's own scoped name,
                # NOT the (possibly shared) parameter name
                _set_proj_conf(pin.proj_conf, spec,
                               f"_{layer.name}.w{i}",
                               in_info.size, out_size, in_info=in_info)
        elif layer.type == "embedding":
            # the reference represents embedding_layer as a mixed layer
            # with one table projection (`layers.py` embedding_layer);
            # the engine keeps a native type — translate at the wire
            _set_proj_conf(pin.proj_conf, {"type": "table"},
                           f"_{layer.name}.w{i}",
                           in_info.size, layer.size or out_info.size)
    if layer.type == "batch_norm" and layer.inputs:
        # the reference wires moving mean/var as static inputs 1 and 2 of
        # the layer (BatchNormBaseLayer.cpp); the engine keeps them as
        # static params w1/w2 — emit the same 3-input contract shape
        src0 = layer.inputs[0].layer_name
        ci, hh, ww = _img_geom(net.shape_infos[src0])
        pin0 = proto_layer.inputs[0]
        pin0.image_conf.channels = ci
        pin0.image_conf.img_size = ww
        pin0.image_conf.img_size_y = hh
        for suffix in ("w1", "w2"):
            pin = proto_layer.inputs.add()
            pin.input_layer_name = src0
            if suffix in lp:
                pin.input_parameter_name = lp[suffix]
        if net.shape_infos[src0].height is not None:
            proto_layer.height = net.shape_infos[src0].height
            proto_layer.width = net.shape_infos[src0].width

    for op in operators:
        pop = proto_layer.operator_confs.add()
        # the engine distinguishes dot_mul projection vs operator with a
        # _op suffix; the wire type string is the reference's "dot_mul"
        pop.type = {"dot_mul_op": "dot_mul"}.get(
            str(op.get("type", "")), str(op.get("type", "")))
        pop.input_indices.extend(int(i) for i in op.get("input_indices", []))
        pop.input_sizes.extend(
            int(net.shape_infos[layer.inputs[i].layer_name].size)
            for i in op.get("input_indices", []))
        pop.output_size = int(layer.size or out_info.size)
        if "scale" in op:
            pop.dotmul_scale = float(op["scale"])
        if op.get("type") in ("conv_op", "convt_op"):
            pop.type = "convt" if op["type"] == "convt_op" else "conv"
            idx0 = int(op["input_indices"][0])
            img_info = net.shape_infos[layer.inputs[idx0].layer_name]
            nf, out_size = _export_conv_spec(
                pop.conv_conf, op, img_info, img_info.size,
                op["type"] == "convt_op")
            pop.num_filters = nf
            pop.output_size = out_size


def _export_parameter(pname: str, spec, proto_param):
    import math
    proto_param.name = pname
    size = 1
    for d in spec.shape:
        size *= int(d)
    proto_param.size = size
    wire_dims = getattr(spec, "wire_dims", None)
    if wire_dims is not None:
        # reference layout override: conv shared biases record [size, 1];
        # an explicit empty tuple means "no dims recorded" (prelu slopes,
        # create_input_parameter without dims)
        proto_param.dims.extend(int(d) for d in wire_dims)
    elif len(spec.shape) == 1:
        # the reference stores vectors (biases) as 1 x size matrices
        # (create_bias_parameter -> dims [1, size])
        proto_param.dims.extend([1, size])
    else:
        proto_param.dims.extend(int(d) for d in spec.shape)
    if float(spec.learning_rate) != 1.0:
        # the reference leaves ParameterConfig.learning_rate at its proto
        # default unless the user set one (goldens carry no field)
        proto_param.learning_rate = float(spec.learning_rate)
    proto_param.initial_mean = float(spec.initial_mean)
    if spec.init in ("zeros", "const"):
        # biases / constant inits: std 0, smart off (golden bias params:
        # initial_std: 0.0, initial_smart: false)
        proto_param.initial_std = 0.0
        proto_param.initial_smart = False
    elif spec.initial_std is not None:
        proto_param.initial_std = float(spec.initial_std)
        proto_param.initial_smart = False
    else:
        # "initial_smart": the reference RESOLVES the std into the proto
        # (config_parser.py:3391: std = 1/sqrt(dims[0]) of the RECORDED
        # dims — 1 for vectors stored as [1, size]), truncated to 12
        # significant digits because the goldens were written by
        # Python 2's str(float)
        fan = proto_param.dims[0] if proto_param.dims else size
        std = 1.0 / math.sqrt(max(int(fan), 1))
        proto_param.initial_std = float(f"{std:.12g}")
        proto_param.initial_smart = True
    proto_param.initial_strategy = 1 if spec.init == "uniform" else 0
    if spec.is_static:
        proto_param.is_static = True
    if getattr(spec, "user_sparse", False):
        proto_param.sparse_update = True
    if spec.l2_rate is not None:
        proto_param.decay_rate = float(spec.l2_rate)
    if spec.l1_rate is not None:
        proto_param.decay_rate_l1 = float(spec.l1_rate)
    if getattr(spec, "wire_sparse", None) is not None:
        proto_param.is_sparse = bool(spec.wire_sparse)
    if getattr(spec, "wire_shared", None) is not None:
        proto_param.is_shared = bool(spec.wire_shared)
    if getattr(spec, "sparsity_ratio", None):
        hook = proto_param.update_hooks.add()
        hook.type = "pruning"
        hook.sparsity_ratio = float(spec.sparsity_ratio)


def _expand_group(model, net, gname, layer, mc, rename, root_names,
                  sub_entries, params_out):
    """Emit a recurrent group the way the reference config_parser does
    (`config_parser.py` RecurrentLayerGroupBegin/End): a shell layer, one
    scatter_agent per in-link (named ``{outer}@{group}``), one ``agent``
    per memory (named ``{link}+delay1@{group}``), the step layers scoped
    ``{sub}@{group}`` with their parameters scoped ``_{sub}@{group}.sfx``
    (projection names stay unscoped — the reference quirk), a gather_agent
    in the root named after the out-link sub layer, and a SubModelConfig
    entry recording links and memories."""
    sub: ModelDef = layer.attrs["sub_model"]
    ins_meta = layer.attrs["ins"]
    memories = layer.attrs["memories"]
    outs = layer.attrs["outputs"]
    subnet = Network(sub, outputs=list(sub.layers))
    entry = {"name": gname, "layer_names": [], "in_links": [],
             "out_links": [], "memories": [],
             "reversed": bool(layer.attrs.get("reverse"))}

    shell = mc.layers.add()
    shell.name = gname
    shell.type = "recurrent_layer_group"
    shell.active_type = ""
    root_names.append(gname)

    boundary_map = {}   # sub boundary data layer -> emitted agent name
    boot_of = {}        # memory boundary -> outer boot layer name
    for meta, inp in zip(ins_meta, layer.inputs):
        outer = rename.get(inp.layer_name, inp.layer_name)
        if meta["kind"] == "boot":
            boot_of[meta["boundary"]] = outer
            continue
        sc = f"{outer}@{gname}"
        pl = mc.layers.add()
        pl.name = sc
        pl.type = "scatter_agent"
        pl.size = int(sub.layers[meta["boundary"]].size)
        pl.active_type = ""
        boundary_map[meta["boundary"]] = sc
        entry["in_links"].append(
            (outer, sc, meta["kind"] == "subseq"))
        entry["layer_names"].append(sc)
    for mem in memories:
        base = mem.get("agent_name") or f"{mem['link']}+delay1"
        agent = f"{base}@{gname}"
        pl = mc.layers.add()
        pl.name = agent
        pl.type = "agent"
        pl.size = int(sub.layers[mem["boundary"]].size)
        pl.active_type = ""
        boundary_map[mem["boundary"]] = agent
        m = {"layer_name": f"{mem['link']}@{gname}", "link_name": agent}
        if mem["boundary"] in boot_of:
            m["boot_layer_name"] = boot_of[mem["boundary"]]
        init = mem.get("init", 0.0)
        if init:
            # MemoryConfig.boot_with_const_id is a uint32 token id in
            # the reference (generation bootstrapping,
            # RecurrentGradientMachine.cpp:255); it can carry our dense
            # boot constant only when that constant is a non-negative
            # integer — anything else is a native-DSL extension that
            # cannot round-trip through the wire format
            if float(init) == int(init) and init >= 0:
                m["boot_with_const_id"] = int(init)
            else:
                from paddle_tpu.utils import logger
                logger.warning(
                    "memory %s: boot_with_const_value %r is not a "
                    "non-negative integer and cannot be represented in "
                    "the wire format; an imported copy of this model "
                    "boots at 0.0", mem["link"], init)
        entry["memories"].append(m)
        entry["layer_names"].append(agent)

    sub_names = set(sub.layers)

    def scope_param(pname):
        for s in sub_names:
            pre = f"_{s}."
            if pname.startswith(pre):
                return f"_{s}@{gname}." + pname[len(pre):]
        return pname

    step_rename = {n: boundary_map.get(n, f"{n}@{gname}")
                   for n in sub.layers}
    for subname, sl in sub.layers.items():
        if subname in boundary_map or subname in boot_of:
            continue  # boundary data layers became agents
        pl = mc.layers.add()
        _export_layer(sub, subnet, subname, pl, rename=step_rename)
        pl.name = f"{subname}@{gname}"
        for pin in pl.inputs:
            if pin.input_parameter_name:
                pin.input_parameter_name = scope_param(
                    pin.input_parameter_name)
        if pl.bias_parameter_name:
            pl.bias_parameter_name = scope_param(pl.bias_parameter_name)
        entry["layer_names"].append(pl.name)
    for pname, spec in subnet.param_specs.items():
        params_out[scope_param(pname)] = spec

    main = outs[0]
    pl = mc.layers.add()
    pl.name = main
    pl.type = "gather_agent"
    pl.size = int(subnet.shape_infos[main].size)
    pl.active_type = ""
    root_names.append(main)
    rename[gname] = main
    entry["out_links"].append((f"{main}@{gname}", main))
    sub_entries.append(entry)
    return entry, set(subnet.param_specs)


def model_to_proto(model: ModelDef, context=None) -> "ModelConfig_pb2.ModelConfig":
    mc = ModelConfig_pb2.ModelConfig()
    has_groups = any(l.type == "recurrent_layer_group"
                     for l in model.layers.values())
    mc.type = "recurrent_nn" if has_groups else "nn"
    # infer over ALL declared layers, emit in declaration order — the
    # reference's config_parser emits layers as the config declares them
    # (declaration order is a valid topological order: the DSL requires
    # inputs to exist before use)
    net = Network(model, outputs=list(model.layers))
    rename = {}            # group/group_output name -> gather-agent name
    root_names = []        # root sub_model layer list
    sub_entries = []       # SubModelConfig data per group
    entry_of = {}          # gname -> entry (secondary out_links)
    all_params = {}        # name -> spec (root + scoped group params)
    hoisted = set()        # group param names already emitted scoped
    for name, layer in model.layers.items():
        if layer.type == "recurrent_layer_group":
            entry, sub_param_names = _expand_group(
                model, net, name, layer, mc, rename, root_names,
                sub_entries, all_params)
            entry_of[name] = entry
            hoisted.update(sub_param_names)
        elif layer.type == "group_output":
            gname = layer.inputs[0].layer_name
            sub_out = layer.attrs["sub_name"]
            pl = mc.layers.add()
            pl.name = sub_out
            pl.type = "gather_agent"
            pl.size = int(layer.size or net.shape_infos[name].size)
            pl.active_type = ""
            rename[name] = sub_out
            entry_of[gname]["out_links"].append(
                (f"{sub_out}@{gname}", sub_out))
            root_names.append(sub_out)
        else:
            _export_layer(model, net, name, mc.layers.add(), rename=rename)
            root_names.append(name)
    for pname, spec in net.param_specs.items():
        if pname not in hoisted:
            all_params.setdefault(pname, spec)
    # momentum is per-parameter on the wire (ParameterConfig.momentum,
    # the reference's default_momentum path — OptimizationConfig has no
    # such field): an explicitly-set coefficient is written to every
    # parameter so serialize -> createFromProtoString round-trips it
    method = (context.settings.get("learning_method")
              if context is not None and getattr(context, "settings", None)
              else None)
    wire_momentum = (float(method.momentum)
                     if getattr(method, "explicit_momentum", False) else 0.0)
    for pname in sorted(all_params):
        pc = mc.parameters.add()
        _export_parameter(pname, all_params[pname], pc)
        if wire_momentum:
            pc.momentum = wire_momentum
    input_names = (context.input_layer_names if context is not None
                   and context.input_layer_names else model.input_layer_names)
    mc.input_layer_names.extend(
        n for n in input_names if n in net.shape_infos)
    mc.output_layer_names.extend(
        rename.get(n, n) for n in model.output_layer_names)
    root_entry = mc.sub_models.add()
    root_entry.name = "root"
    root_entry.layer_names.extend(root_names)
    root_entry.input_layer_names.extend(mc.input_layer_names)
    root_entry.output_layer_names.extend(mc.output_layer_names)
    # the reference writes the flag explicitly even on the root
    root_entry.is_recurrent_layer_group = False
    if context is not None:
        root_entry.evaluator_names.extend(
            ev.get("name", ev.get("type", ""))
            for ev in context.evaluators)
    for e in sub_entries:
        sm = mc.sub_models.add()
        sm.name = e["name"]
        sm.layer_names.extend(e["layer_names"])
        sm.is_recurrent_layer_group = True
        sm.reversed = e["reversed"]
        for m in e["memories"]:
            pm = sm.memories.add()
            pm.layer_name = m["layer_name"]
            pm.link_name = m["link_name"]
            if m.get("boot_layer_name"):
                pm.boot_layer_name = m["boot_layer_name"]
            if m.get("boot_with_const_id") is not None:
                pm.boot_with_const_id = m["boot_with_const_id"]
        for outer, link, _subseq in e["in_links"]:
            pl = sm.in_links.add()
            pl.layer_name = outer
            pl.link_name = link
            # the reference leaves LinkConfig.has_subseq at its default
            # even for nested-sequence in-links (observed in the golden
            # protostr of sequence_nest configs); mirror that
        for lay, link in e["out_links"]:
            pl = sm.out_links.add()
            pl.layer_name = lay
            pl.link_name = link
    if context is not None:
        for ev in context.evaluators:
            pe = mc.evaluators.add()
            pe.name = ev.get("name", ev.get("type", "evaluator"))
            pe.type = ev.get("type", "")
            pe.input_layers.extend(ev.get("input_layers", []))
            for field in ("chunk_scheme", "num_chunk_types",
                          "classification_threshold", "positive_label",
                          "dict_file", "result_file", "num_results",
                          "delimited", "top_k", "overlap_threshold",
                          "background_id", "evaluate_difficult", "ap_type"):
                if ev.get(field) is not None:
                    setattr(pe, field, ev[field])
            if ev.get("excluded_chunk_types"):
                pe.excluded_chunk_types.extend(ev["excluded_chunk_types"])
    return mc


def _data_config(source, *, for_test: bool) -> Optional["DataConfig_pb2.DataConfig"]:
    if source is None:
        return None
    dc = DataConfig_pb2.DataConfig()
    dc.type = "py2"
    if source.file_list:
        dc.files = source.file_list
    if source.module:
        dc.load_data_module = source.module
    if source.obj:
        dc.load_data_object = source.obj
    if source.args not in (None, ""):
        dc.load_data_args = (source.args if isinstance(source.args, str)
                             else json.dumps(source.args))
    if for_test:
        dc.for_test = True
    dc.async_load_data = True
    return dc


def opt_config_from_settings(s) -> "TrainerConfig_pb2.OptimizationConfig":
    oc = TrainerConfig_pb2.OptimizationConfig()
    oc.batch_size = int(s.get("batch_size") or 1)
    oc.algorithm = s.get("algorithm") or "sgd"
    # unset-settings defaults follow the reference DEFAULT_SETTING
    # (config_parser.py:3513-3526), same as build_optimizer
    oc.learning_rate = float(s.get("learning_rate")
                             if s.get("learning_rate") is not None else 1.0)
    oc.learning_rate_decay_a = float(s.get("learning_rate_decay_a") or 0.0)
    oc.learning_rate_decay_b = float(s.get("learning_rate_decay_b") or 0.0)
    oc.learning_rate_schedule = s.get("learning_rate_schedule") or "poly"
    oc.learning_rate_args = s.get("learning_rate_args") or ""
    oc.async_lagged_grad_discard_ratio = float(
        s.get("async_lagged_grad_discard_ratio") or 1.5)
    if s.get("gradient_clipping_threshold"):
        oc.gradient_clipping_threshold = float(
            s["gradient_clipping_threshold"])
    method = s.get("learning_method")
    if method is not None and hasattr(method, "extra_settings"):
        for k, v in method.extra_settings().items():
            if k == "momentum":
                continue  # OptimizationConfig has no momentum field
            try:
                setattr(oc, k, v)
            except (AttributeError, TypeError):
                pass
    reg = s.get("regularization")
    if reg is not None and hasattr(reg, "extra_settings"):
        for k, v in reg.extra_settings().items():
            setattr(oc, k, float(v))
    avg = s.get("model_average")
    if avg is not None:
        oc.average_window = float(avg.average_window)
        if avg.max_average_window is not None:
            oc.max_average_window = int(avg.max_average_window)
        oc.do_average_in_cpu = bool(avg.do_average_in_cpu)
    return oc


def trainer_to_proto(model: ModelDef, context) -> "TrainerConfig_pb2.TrainerConfig":
    tc = TrainerConfig_pb2.TrainerConfig()
    tc.model_config.CopyFrom(model_to_proto(model, context))
    tc.opt_config.CopyFrom(opt_config_from_settings(context.settings))
    train_dc = _data_config(context.train_source, for_test=False)
    if train_dc is not None:
        tc.data_config.CopyFrom(train_dc)
    test_dc = _data_config(context.test_source, for_test=True)
    if test_dc is not None:
        tc.test_data_config.CopyFrom(test_dc)
    return tc
