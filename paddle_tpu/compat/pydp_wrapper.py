"""``paddle.trainer.PyDataProviderWrapper`` — the LEGACY (pre-PyDP2)
provider surface.

The reference module (``python/paddle/trainer/PyDataProviderWrapper.py``)
has user code declare ``@provider(slots=[DenseSlot(9), IndexSlot(2)])``
over a ``process(obj, filename)`` generator yielding one sample per
yield: a list with one entry per slot (with ``use_seq=True``, each entry
is a list of timesteps). The reference serialized batches over a binary
protocol to the C++ ``PyDataProviderWrapper``; here the decorator plugs
straight into the native reader pipeline (``as_reader``), so old configs
declaring ``PyData(load_data_module=..., load_data_object=...)`` with
wrapper-era providers feed the trainer unmodified
(``paddle/trainer/tests/testPyDataWrapper.py`` is the contract)."""

from __future__ import annotations

import random
from typing import Any, List, Optional

from paddle_tpu.data import types as T

__all__ = [
    "DenseSlot", "SparseNonValueSlot", "SparseValueSlot", "IndexSlot",
    "StringSlot", "SlotType", "PoolSize", "provider", "init_hook_wrapper",
    "default_init_hook", "GeneralPyDataProvider",
]


class SlotType:
    dim: int = 0

    def input_type(self, use_seq: bool):
        raise NotImplementedError


class DenseSlot(SlotType):
    def __init__(self, dim):
        self.dim = int(dim)

    def input_type(self, use_seq):
        return (T.dense_vector_sequence(self.dim) if use_seq
                else T.dense_vector(self.dim))


class SparseNonValueSlot(SlotType):
    def __init__(self, dim):
        self.dim = int(dim)

    def input_type(self, use_seq):
        return (T.sparse_binary_vector_sequence(self.dim) if use_seq
                else T.sparse_binary_vector(self.dim))


class SparseValueSlot(SlotType):
    def __init__(self, dim):
        self.dim = int(dim)

    def input_type(self, use_seq):
        return (T.sparse_float_vector_sequence(self.dim) if use_seq
                else T.sparse_float_vector(self.dim))


class IndexSlot(SlotType):
    def __init__(self, dim):
        self.dim = int(dim)

    def input_type(self, use_seq):
        return (T.integer_value_sequence(self.dim) if use_seq
                else T.integer_value(self.dim))


class StringSlot(SlotType):
    """Raw strings ride through untyped (debug/printer consumption)."""

    def __init__(self, dim=1):
        self.dim = int(dim)

    def input_type(self, use_seq):
        return None


class PoolSize:
    def __init__(self, size):
        self.size = int(size)


def default_init_hook(cls, *args, **kwargs):
    del cls, args, kwargs


def init_hook_wrapper(func):
    """Reference helper: lets an init hook receive load_data_args as
    typed kwargs."""

    def hook(obj, *args, **kwargs):
        func(obj, *args, **kwargs)

    return hook


class GeneralPyDataProvider:
    """The decorated provider object: carries slots/logger like the
    reference instance, and exposes the native ``as_reader`` protocol."""

    def __init__(self, generator, slots, use_seq, should_shuffle,
                 init_hook, args=None, kwargs=None):
        from paddle_tpu.utils import logger
        self.generator = generator
        self.slots: Optional[List[SlotType]] = slots
        self.use_seq = bool(use_seq)
        self.should_shuffle = bool(should_shuffle)
        self.logger = logger
        init_hook(self, *(args or ()), **(kwargs or {}))
        self.input_types = (
            [s.input_type(self.use_seq) for s in self.slots]
            if self.slots else None)

    def _files(self, file_list):
        if file_list is None:
            return []
        if isinstance(file_list, str):
            with open(file_list) as f:
                return [ln.strip() for ln in f if ln.strip()]
        return list(file_list)

    def as_reader(self, file_list, is_train=True, **kwargs):
        del kwargs
        files = self._files(file_list)
        provider = self

        def reader():
            samples = []
            for path in files:
                for sample in provider.generator(provider, path):
                    # generators may yield lazy map objects (py2-era
                    # style); materialize per slot (scalars/strings ride
                    # through)
                    samples.append(tuple(
                        list(col) if hasattr(col, "__iter__")
                        and not isinstance(col, (str, bytes)) else col
                        for col in sample))
            if provider.should_shuffle and is_train:
                random.shuffle(samples)
            yield from samples

        reader.input_types = self.input_types
        return reader

    __call__ = as_reader


def provider(slots=None, use_seq=False, should_shuffle=True, pool_size=1,
             can_over_batch_size=True, calc_batch_size=None, debug=False,
             init_hook=default_init_hook, profile_filename=None):
    """The legacy ``@provider`` decorator
    (``PyDataProviderWrapper.py:568``). pool/batch knobs are accepted
    for compatibility; batching is the trainer's job here."""
    del pool_size, can_over_batch_size, calc_batch_size, debug, \
        profile_filename

    def deco(func):
        return GeneralPyDataProvider(func, slots, use_seq, should_shuffle,
                                     init_hook)

    return deco
