"""Parameter/layer attributes (`trainer_config_helpers/attrs.py`)."""

from paddle_tpu.config.model_config import ParamAttr as _ParamAttr


def Param(name=None, initial_std=None, initial_mean=0.0, is_static=False,
          learning_rate=1.0, l1_rate=None, l2_rate=None,
          sparse_update=False, **_ignored):
    return _ParamAttr(name=name, initial_mean=initial_mean,
                      initial_std=initial_std, is_static=is_static,
                      learning_rate=learning_rate, l1_rate=l1_rate,
                      l2_rate=l2_rate, sparse_grad=sparse_update)


ParamAttr = Param


class ExtraAttr:
    """Extra layer attributes; drop_rate is the one with executor effect."""

    def __init__(self, drop_rate=0.0, **kwargs):
        self.drop_rate = drop_rate
        self.kwargs = kwargs


ExtraLayerAttribute = ExtraAttr
