"""MNIST (`python/paddle/v2/dataset/mnist.py`): records
``(image[784] float in [-1,1], label int)``."""

from __future__ import annotations

import gzip
import struct

import numpy as np

from paddle_tpu.v2.dataset import common

_TRAIN_N, _TEST_N = 8192, 2048  # synthetic sizes (real: 60000/10000)


def _real_reader(images_path, labels_path):
    def reader():
        with gzip.open(images_path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            images = np.frombuffer(f.read(), np.uint8).reshape(n, rows * cols)
        with gzip.open(labels_path, "rb") as f:
            struct.unpack(">II", f.read(8))
            labels = np.frombuffer(f.read(), np.uint8)
        for img, lab in zip(images, labels):
            yield img.astype(np.float32) / 127.5 - 1.0, int(lab)

    return reader


def _synthetic_reader(n, seed):
    common.note_synthetic("mnist")
    proto_rng = np.random.RandomState(42)
    templates = proto_rng.randn(10, 784).astype(np.float32)

    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            lab = int(rng.randint(10))
            img = templates[lab] * 0.6 + rng.randn(784).astype(np.float32)
            yield np.clip(img, -1.0, 1.0).astype(np.float32), lab

    return reader


def train():
    imgs = common.cache_path("mnist", "train-images-idx3-ubyte.gz")
    labs = common.cache_path("mnist", "train-labels-idx1-ubyte.gz")
    if imgs and labs:
        return _real_reader(imgs, labs)
    return _synthetic_reader(_TRAIN_N, seed=0)


def test():
    imgs = common.cache_path("mnist", "t10k-images-idx3-ubyte.gz")
    labs = common.cache_path("mnist", "t10k-labels-idx1-ubyte.gz")
    if imgs and labs:
        return _real_reader(imgs, labs)
    return _synthetic_reader(_TEST_N, seed=1)
