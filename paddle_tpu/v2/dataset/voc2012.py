"""Pascal VOC2012 segmentation (`python/paddle/v2/dataset/voc2012.py`).

Records mirror the reference: ``(image, label_mask)`` — image float32
CHW in [0,1], mask int32 HW with class ids in [0, 21) (20 object classes
+ background). Synthetic tier paints rectangles whose class matches their
color, so a segmentation head genuinely learns."""

from __future__ import annotations

import numpy as np

from paddle_tpu.v2.dataset import common

N_CLASSES = 21
_SIDE = 32


def _sample(rng):
    img = rng.rand(3, _SIDE, _SIDE).astype(np.float32) * 0.15
    mask = np.zeros((_SIDE, _SIDE), np.int32)
    for _ in range(rng.randint(1, 4)):
        cls = int(rng.randint(1, N_CLASSES))
        y0, x0 = rng.randint(0, _SIDE - 8, size=2)
        h, w = rng.randint(6, 12, size=2)
        hue = np.array([(cls * 53 % 255) / 255.0,
                        (cls * 131 % 255) / 255.0,
                        (cls * 211 % 255) / 255.0], np.float32)
        img[:, y0:y0 + h, x0:x0 + w] = hue[:, None, None]
        mask[y0:y0 + h, x0:x0 + w] = cls
    return img, mask


def _reader(n, seed):
    common.note_synthetic("voc2012")

    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            img, mask = _sample(rng)
            yield img, mask

    return reader


def train():
    return _reader(1024, seed=0)


def test():
    return _reader(256, seed=1)


def val():
    return _reader(256, seed=2)
