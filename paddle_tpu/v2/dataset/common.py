"""Dataset infrastructure (`python/paddle/v2/dataset/common.py`).

The reference downloads public corpora into ``~/.cache/paddle/dataset``.
This environment has no network egress, so each dataset here has two
tiers with the same record schema:

1. **cached real data** — if the standard files exist under
   ``$PADDLE_TPU_DATA_DIR`` (default ``~/.cache/paddle_tpu/dataset``),
   they are parsed exactly like the reference's loaders;
2. **deterministic synthetic data** — otherwise, records are generated
   from a seeded RNG with class-conditional structure (so models
   genuinely learn from them) and a loud one-time log line. Shapes,
   dtypes, ranges, and reader protocol match tier 1.

``download()`` therefore never fetches: it returns the cache path if
present, else None.
"""

from __future__ import annotations

import os
from typing import Optional

from paddle_tpu.utils.log import get_logger

logger = get_logger("dataset")

DATA_HOME = os.environ.get(
    "PADDLE_TPU_DATA_DIR",
    os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu", "dataset"))

_warned = set()


def cache_path(module: str, filename: str) -> Optional[str]:
    """Path of a cached real-data file, or None (triggers synthetic)."""
    path = os.path.join(DATA_HOME, module, filename)
    return path if os.path.exists(path) else None


def download(url: str, module: str, md5sum: str = None) -> Optional[str]:
    """Reference-compatible signature; zero-egress: cache hit or None."""
    return cache_path(module, url.rsplit("/", 1)[-1])


def note_synthetic(module: str):
    if module not in _warned:
        _warned.add(module)
        logger.warning(
            "dataset %r: no cached files under %s — serving deterministic "
            "SYNTHETIC data with the same schema (drop the real files "
            "there to train on the true corpus)", module,
            os.path.join(DATA_HOME, module))
