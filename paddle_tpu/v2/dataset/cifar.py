"""CIFAR-10/100 (`python/paddle/v2/dataset/cifar.py`): records
``(image[3072] float in [0,1], label int)`` in CHW order."""

from __future__ import annotations

import pickle
import tarfile

import numpy as np

from paddle_tpu.v2.dataset import common

_TRAIN_N, _TEST_N = 4096, 1024


def _real_reader(tar_path, member_match, classes):
    def reader():
        with tarfile.open(tar_path) as tar:
            for member in tar.getmembers():
                if member_match not in member.name:
                    continue
                batch = pickle.load(tar.extractfile(member),
                                    encoding="latin1")
                key = "labels" if "labels" in batch else "fine_labels"
                for img, lab in zip(batch["data"], batch[key]):
                    yield img.astype(np.float32) / 255.0, int(lab)

    return reader


def _synthetic_reader(n, classes, seed):
    common.note_synthetic("cifar")
    proto_rng = np.random.RandomState(7)
    templates = proto_rng.rand(classes, 3072).astype(np.float32)

    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            lab = int(rng.randint(classes))
            img = (templates[lab] * 0.7
                   + rng.rand(3072).astype(np.float32) * 0.3)
            yield img.astype(np.float32), lab

    return reader


def train10():
    path = common.cache_path("cifar", "cifar-10-python.tar.gz")
    if path:
        return _real_reader(path, "data_batch", 10)
    return _synthetic_reader(_TRAIN_N, 10, seed=0)


def test10():
    path = common.cache_path("cifar", "cifar-10-python.tar.gz")
    if path:
        return _real_reader(path, "test_batch", 10)
    return _synthetic_reader(_TEST_N, 10, seed=1)


def train100():
    path = common.cache_path("cifar", "cifar-100-python.tar.gz")
    if path:
        return _real_reader(path, "train", 100)
    return _synthetic_reader(_TRAIN_N, 100, seed=2)


def test100():
    path = common.cache_path("cifar", "cifar-100-python.tar.gz")
    if path:
        return _real_reader(path, "test", 100)
    return _synthetic_reader(_TEST_N, 100, seed=3)
