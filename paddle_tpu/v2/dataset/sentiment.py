"""NLTK movie-review sentiment (`python/paddle/v2/dataset/sentiment.py`).

Records mirror the reference: ``(word_ids, label)`` with label 0/1
(positive sorts first in the reference's corpus walk). Same
class-conditional unigram generator idea as imdb, different vocabulary."""

from __future__ import annotations

import numpy as np

from paddle_tpu.v2.dataset import common

_VOCAB = 3000


def get_word_dict():
    """word -> id, ordered by synthetic 'frequency' like the reference
    sorts by corpus frequency."""
    return {f"w{i}": i for i in range(_VOCAB)}


def _reader(n, seed):
    common.note_synthetic("sentiment")
    proto = np.random.RandomState(23)
    logits = proto.randn(2, _VOCAB)

    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            lab = int(rng.randint(2))
            p = np.exp(logits[lab] - logits[lab].max())
            p /= p.sum()
            length = int(rng.randint(10, 60))
            toks = rng.choice(_VOCAB, size=length, p=p)
            yield [int(t) for t in toks], lab

    return reader


def train():
    return _reader(2048, seed=0)


def test():
    return _reader(512, seed=1)
