"""IMDB sentiment (`python/paddle/v2/dataset/imdb.py`): records
``(token_ids list[int], label 0|1)``."""

from __future__ import annotations

import numpy as np

from paddle_tpu.v2.dataset import common

_VOCAB = 5000
_TRAIN_N, _TEST_N = 4096, 1024


def word_dict():
    """token -> id, '<unk>' included as the last id (so
    ``integer_value(len(word_dict()))`` always covers every emitted id).
    Synthetic tier: ids name themselves."""
    path = common.cache_path("imdb", "aclImdb_v1.tar.gz")
    if path:
        # real tier: build frequency dict from the tarball like the
        # reference's build_dict
        import collections
        import re
        import tarfile
        counts = collections.Counter()
        pat = re.compile(r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$")
        with tarfile.open(path) as tar:
            for member in tar.getmembers():
                if pat.match(member.name):
                    text = tar.extractfile(member).read().decode(
                        "latin1").lower()
                    counts.update(text.split())
        words = [w for w, _ in counts.most_common(_VOCAB - 1)]
        d = {w: i for i, w in enumerate(words)}
    else:
        d = {f"w{i}": i for i in range(_VOCAB - 1)}
    d["<unk>"] = len(d)
    return d


def _synthetic_reader(n, seed):
    """Sentiment signal: positive docs draw tokens from a 'positive'
    unigram distribution, negative from a shifted one — linearly
    separable but noisy, like real bag-of-words sentiment."""
    common.note_synthetic("imdb")
    proto = np.random.RandomState(11)
    logits = proto.randn(2, _VOCAB) * 1.5

    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            lab = int(rng.randint(2))
            p = np.exp(logits[lab] - logits[lab].max())
            p /= p.sum()
            length = int(rng.randint(20, 120))
            toks = rng.choice(_VOCAB, size=length, p=p)
            yield [int(t) for t in toks], lab

    return reader


def _real_reader(split, word_idx=None):
    import re
    import tarfile
    path = common.cache_path("imdb", "aclImdb_v1.tar.gz")
    wd = word_idx if word_idx is not None else word_dict()
    unk = wd.get("<unk>", len(wd) - 1)

    def reader():
        pat = re.compile(rf"aclImdb/{split}/((pos)|(neg))/.*\.txt$")
        with tarfile.open(path) as tar:
            for member in tar.getmembers():
                m = pat.match(member.name)
                if not m:
                    continue
                lab = 1 if "/pos/" in member.name else 0
                text = tar.extractfile(member).read().decode(
                    "latin1").lower()
                yield [wd.get(w, unk) for w in text.split()], lab

    return reader


def _remap(reader_fn, vocab):
    """Clamp synthetic ids into a caller-provided smaller vocab."""
    def reader():
        for toks, lab in reader_fn():
            yield [t % vocab for t in toks], lab
    return reader


def train(word_idx=None):
    if common.cache_path("imdb", "aclImdb_v1.tar.gz"):
        return _real_reader("train", word_idx)
    r = _synthetic_reader(_TRAIN_N, seed=0)
    return _remap(r, len(word_idx)) if word_idx is not None else r


def test(word_idx=None):
    if common.cache_path("imdb", "aclImdb_v1.tar.gz"):
        return _real_reader("test", word_idx)
    r = _synthetic_reader(_TEST_N, seed=1)
    return _remap(r, len(word_idx)) if word_idx is not None else r
