"""WMT-14 French->English translation (`python/paddle/v2/dataset/wmt14.py`).

Records mirror the reference: ``(src_ids, trg_ids, trg_ids_next)`` where
trg_ids starts with <s> and trg_ids_next ends with <e> (ids 0/1/2 =
<s>/<e>/<unk>, as in the reference). Synthetic tier generates parallel
pairs under a deterministic token mapping with local reordering, so an
attention model genuinely learns an alignment.
"""

from __future__ import annotations

import numpy as np

from paddle_tpu.v2.dataset import common

START_ID, END_ID, UNK_ID = 0, 1, 2


def _reader(dict_size, n, seed):
    common.note_synthetic("wmt14")

    def reader():
        rng = np.random.RandomState(seed)
        shift = 7
        for _ in range(n):
            T = int(rng.randint(4, 16))
            src = rng.randint(3, dict_size, size=T)
            trg = [(int(s) - 3 + shift) % (dict_size - 3) + 3 for s in src]
            # local reordering: swap adjacent pairs (French-ish)
            for i in range(0, len(trg) - 1, 2):
                if rng.rand() < 0.3:
                    trg[i], trg[i + 1] = trg[i + 1], trg[i]
            src_ids = [int(s) for s in src]
            yield (src_ids, [START_ID] + trg, trg + [END_ID])

    return reader


def train(dict_size):
    return _reader(dict_size, 4096, seed=0)


def test(dict_size):
    return _reader(dict_size, 512, seed=1)


def gen(dict_size):
    return _reader(dict_size, 128, seed=2)


def get_dict(dict_size, reverse=False):
    """(src_dict, trg_dict); reverse=True maps id -> token."""
    src = {"<s>": 0, "<e>": 1, "<unk>": 2}
    src.update({f"f{i}": i for i in range(3, dict_size)})
    trg = {"<s>": 0, "<e>": 1, "<unk>": 2}
    trg.update({f"e{i}": i for i in range(3, dict_size)})
    if reverse:
        src = {v: k for k, v in src.items()}
        trg = {v: k for k, v in trg.items()}
    return src, trg
