"""LETOR MQ2007 learning-to-rank (`python/paddle/v2/dataset/mq2007.py`).

Three record formats, mirroring the reference's ``format`` argument:

- ``pointwise``: ``(relevance_score, feature_vector[46])``
- ``pairwise``: ``(label, better_features, worse_features)``
- ``listwise``: ``(score_list, feature_matrix)`` per query

Real tier parses the genuine LETOR text format
(``rel qid:<id> 1:<v> 2:<v> ... #docid``); synthetic tier draws features
whose first components drive relevance, so rank models genuinely learn.
"""

from __future__ import annotations

import numpy as np

from paddle_tpu.v2.dataset import common

FEATURE_DIM = 46


def _parse_letor(path):
    """LETOR text -> {qid: (scores, features)} (the reference's
    QueryList)."""
    queries = {}
    with open(path) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            parts = line.split()
            rel = float(parts[0])
            qid = parts[1].split(":")[1]
            feats = np.zeros(FEATURE_DIM, np.float32)
            for kv in parts[2:]:
                k, _, v = kv.partition(":")
                idx = int(k) - 1
                if 0 <= idx < FEATURE_DIM:
                    feats[idx] = float(v)
            queries.setdefault(qid, []).append((rel, feats))
    return {q: (np.asarray([r for r, _ in rows], np.float32),
                np.stack([f for _, f in rows]))
            for q, rows in queries.items()}


def _synthetic_queries(n_queries, seed):
    common.note_synthetic("mq2007")
    rng = np.random.RandomState(seed)
    out = {}
    for q in range(n_queries):
        n_docs = int(rng.randint(5, 20))
        feats = rng.rand(n_docs, FEATURE_DIM).astype(np.float32)
        score = (feats[:, 0] * 2 + feats[:, 1]
                 + rng.rand(n_docs) * 0.2)
        rel = np.digitize(score, [1.0, 2.0]).astype(np.float32)  # 0/1/2
        out[f"q{q}"] = (rel, feats)
    return out


def _queries(split, seed):
    path = common.cache_path("mq2007", f"{split}.txt")
    if path:
        return _parse_letor(path)
    return _synthetic_queries(200 if split == "train" else 50, seed)


def _emit(queries, format):
    if format == "pointwise":
        for rel, feats in queries.values():
            for r, f in zip(rel, feats):
                yield float(r), f
    elif format == "pairwise":
        for rel, feats in queries.values():
            order = np.argsort(-rel)
            for i in range(len(order)):
                for j in range(i + 1, len(order)):
                    a, b = order[i], order[j]
                    if rel[a] == rel[b]:
                        continue
                    yield np.array([1.0], np.float32), feats[a], feats[b]
    elif format == "listwise":
        for rel, feats in queries.values():
            yield rel, feats
    else:
        raise ValueError(f"unknown mq2007 format {format!r}")


def train(format="pairwise"):
    def reader():
        yield from _emit(_queries("train", seed=0), format)

    return reader


def test(format="pairwise"):
    def reader():
        yield from _emit(_queries("test", seed=1), format)

    return reader
