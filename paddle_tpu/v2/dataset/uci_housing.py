"""UCI housing regression (`python/paddle/v2/dataset/uci_housing.py`):
records ``(features[13] float normalized, [price] float)``."""

from __future__ import annotations

import numpy as np

from paddle_tpu.v2.dataset import common

feature_names = ["CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE", "DIS",
                 "RAD", "TAX", "PTRATIO", "B", "LSTAT"]

_N = 506
_SPLIT = 406  # reference uses an 80/20-ish train/test split


def _load_real(path):
    data = np.fromfile(path, sep=" ").reshape(-1, 14)
    feats = data[:, :-1]
    feats = (feats - feats.mean(axis=0)) / (feats.std(axis=0) + 1e-8)
    return feats.astype(np.float32), data[:, -1].astype(np.float32)


def _load_synthetic():
    common.note_synthetic("uci_housing")
    rng = np.random.RandomState(13)
    X = rng.randn(_N, 13).astype(np.float32)
    w = rng.randn(13).astype(np.float32)
    y = X @ w * 3.0 + 22.5 + rng.randn(_N).astype(np.float32)
    return X, y.astype(np.float32)


def _data():
    path = common.cache_path("uci_housing", "housing.data")
    return _load_real(path) if path else _load_synthetic()


def train():
    def reader():
        X, y = _data()
        for i in range(_SPLIT):
            yield X[i], [float(y[i])]

    return reader


def test():
    def reader():
        X, y = _data()
        for i in range(_SPLIT, len(X)):
            yield X[i], [float(y[i])]

    return reader
