"""Oxford 102 Flowers (`python/paddle/v2/dataset/flowers.py`).

Records mirror the reference's mapped output: ``(image, label)`` with
image a flattened float32 CHW array in [0, 1] (3x32x32 here — the
reference's mapper crops/resizes to a fixed square too) and label in
[0, 102). Synthetic tier renders class-conditional color blobs so a conv
net genuinely learns."""

from __future__ import annotations

import numpy as np

from paddle_tpu.v2.dataset import common

N_CLASSES = 102
_SIDE = 32


def _render(rng, label):
    """Class-conditional 'flower': a colored disc on textured background;
    hue/radius derive from the label."""
    img = rng.rand(3, _SIDE, _SIDE).astype(np.float32) * 0.2
    cy, cx = rng.randint(8, _SIDE - 8, size=2)
    rad = 4 + (label % 7)
    hue = np.array([(label * 37 % 255) / 255.0,
                    (label * 101 % 255) / 255.0,
                    (label * 197 % 255) / 255.0], np.float32)
    yy, xx = np.mgrid[0:_SIDE, 0:_SIDE]
    disc = ((yy - cy) ** 2 + (xx - cx) ** 2) <= rad ** 2
    img[:, disc] = hue[:, None] * (0.7 + 0.3 * rng.rand())
    return img.reshape(-1)


def _reader(n, seed):
    common.note_synthetic("flowers")

    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            label = int(rng.randint(0, N_CLASSES))
            yield _render(rng, label), label

    return reader


def train(mapper=None, buffered_size=1024, use_xmap=True):
    return _reader(2048, seed=0)


def test(mapper=None, buffered_size=1024, use_xmap=True):
    return _reader(512, seed=1)


def valid(mapper=None, buffered_size=1024, use_xmap=True):
    return _reader(512, seed=2)
