"""v2 datasets (`python/paddle/v2/dataset`): cached-real or
deterministic-synthetic two-tier loaders (see common.py)."""

from paddle_tpu.v2.dataset import cifar  # noqa: F401
from paddle_tpu.v2.dataset import common  # noqa: F401
from paddle_tpu.v2.dataset import conll05  # noqa: F401
from paddle_tpu.v2.dataset import flowers  # noqa: F401
from paddle_tpu.v2.dataset import imdb  # noqa: F401
from paddle_tpu.v2.dataset import imikolov  # noqa: F401
from paddle_tpu.v2.dataset import mnist  # noqa: F401
from paddle_tpu.v2.dataset import movielens  # noqa: F401
from paddle_tpu.v2.dataset import mq2007  # noqa: F401
from paddle_tpu.v2.dataset import sentiment  # noqa: F401
from paddle_tpu.v2.dataset import uci_housing  # noqa: F401
from paddle_tpu.v2.dataset import voc2012  # noqa: F401
from paddle_tpu.v2.dataset import wmt14  # noqa: F401
