"""PTB language-model n-grams (`python/paddle/v2/dataset/imikolov.py`):
records are n-gram tuples of token ids."""

from __future__ import annotations

import numpy as np

from paddle_tpu.v2.dataset import common

_VOCAB = 2048
_TRAIN_SENTS, _TEST_SENTS = 2048, 512


def build_dict(min_word_freq: int = 50):
    path = common.cache_path("imikolov", "simple-examples.tgz")
    if path:
        import collections
        import tarfile
        counts = collections.Counter()
        with tarfile.open(path) as tar:
            f = tar.extractfile(
                "./simple-examples/data/ptb.train.txt")
            for line in f.read().decode().splitlines():
                counts.update(line.split())
        words = [w for w, c in counts.items() if c >= min_word_freq]
        d = {w: i for i, w in enumerate(sorted(words))}
        d["<unk>"] = len(d)
        return d
    return {f"w{i}": i for i in range(_VOCAB)}


def _synthetic_sentences(n_sents, seed):
    """First-order Markov chain over the vocab — n-gram models can
    genuinely reduce perplexity on it."""
    common.note_synthetic("imikolov")
    proto = np.random.RandomState(23)
    # sparse-ish transition structure: each token prefers 8 successors
    succ = proto.randint(0, _VOCAB, size=(_VOCAB, 8))

    def gen():
        rng = np.random.RandomState(seed)
        for _ in range(n_sents):
            length = int(rng.randint(5, 25))
            sent = [int(rng.randint(_VOCAB))]
            for _ in range(length - 1):
                if rng.rand() < 0.8:
                    sent.append(int(succ[sent[-1], rng.randint(8)]))
                else:
                    sent.append(int(rng.randint(_VOCAB)))
            yield sent

    return gen


def _real_sentences(filename, word_idx=None):
    import tarfile
    path = common.cache_path("imikolov", "simple-examples.tgz")
    d = word_idx if word_idx is not None else build_dict()
    unk = d.get("<unk>", len(d) - 1)

    def gen():
        with tarfile.open(path) as tar:
            f = tar.extractfile(f"./simple-examples/data/{filename}")
            for line in f.read().decode().splitlines():
                yield [d.get(w, unk) for w in line.split()]

    return gen


def _ngram_reader(sent_gen, n):
    def reader():
        for sent in sent_gen():
            if len(sent) < n:
                continue
            for i in range(n, len(sent) + 1):
                yield tuple(sent[i - n:i])

    return reader


def _clamped(sent_gen, vocab):
    """Clamp synthetic ids into a caller-provided smaller vocab."""
    def gen():
        for sent in sent_gen():
            yield [t % vocab for t in sent]
    return gen


def train(word_idx=None, n: int = 5):
    if common.cache_path("imikolov", "simple-examples.tgz"):
        return _ngram_reader(_real_sentences("ptb.train.txt", word_idx), n)
    sents = _synthetic_sentences(_TRAIN_SENTS, 0)
    if word_idx is not None:
        sents = _clamped(sents, len(word_idx))
    return _ngram_reader(sents, n)


def test(word_idx=None, n: int = 5):
    if common.cache_path("imikolov", "simple-examples.tgz"):
        return _ngram_reader(_real_sentences("ptb.valid.txt", word_idx), n)
    sents = _synthetic_sentences(_TEST_SENTS, 1)
    if word_idx is not None:
        sents = _clamped(sents, len(word_idx))
    return _ngram_reader(sents, n)
