"""CoNLL-2005 semantic role labeling (`python/paddle/v2/dataset/conll05.py`).

Records mirror the reference's ``reader_creator`` 9-tuple:
``(word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, verb_ids, mark, label_ids)``
— the five context windows around the predicate, the predicate id repeated
per token, a 0/1 predicate mark, and IOB label ids. Synthetic tier builds
sentences whose labels depend on distance to the predicate, so an SRL
tagger genuinely learns.
"""

from __future__ import annotations

import numpy as np

from paddle_tpu.v2.dataset import common

_WORD_V, _VERB_V = 2000, 100
_LABELS = ["O", "B-A0", "I-A0", "B-A1", "I-A1", "B-V", "I-V",
           "B-AM", "I-AM"]


def word_dict():
    d = {f"w{i}": i for i in range(_WORD_V)}
    return d


def verb_dict():
    return {f"v{i}": i for i in range(_VERB_V)}


def label_dict():
    return {l: i for i, l in enumerate(_LABELS)}


def get_dict():
    """(word_dict, verb_dict, label_dict) — the reference's get_dict."""
    return word_dict(), verb_dict(), label_dict()


def get_embedding():
    """Deterministic stand-in for the reference's pretrained emb32 table."""
    rng = np.random.RandomState(5)
    return rng.randn(_WORD_V, 32).astype(np.float32)


def _reader(n, seed):
    common.note_synthetic("conll05")
    ld = label_dict()

    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            T = int(rng.randint(5, 20))
            words = rng.randint(0, _WORD_V, size=T)
            vpos = int(rng.randint(0, T))
            verb = int(rng.randint(0, _VERB_V))

            def ctx(off):
                j = min(max(vpos + off, 0), T - 1)
                return [int(words[j])] * T

            mark = [1 if t == vpos else 0 for t in range(T)]
            labels = []
            for t in range(T):
                if t == vpos:
                    labels.append(ld["B-V"])
                elif t == vpos - 1:
                    labels.append(ld["B-A0"])
                elif t == vpos + 1:
                    labels.append(ld["B-A1"])
                elif t == vpos + 2:
                    labels.append(ld["I-A1"])
                else:
                    labels.append(ld["O"])
            yield ([int(w) for w in words], ctx(-2), ctx(-1), ctx(0),
                   ctx(1), ctx(2), [verb] * T, mark, labels)

    return reader


def test():
    return _reader(1024, seed=3)


def train():
    """The reference ships only the public test split; synthetic tier
    offers a train split with the same generator."""
    return _reader(4096, seed=2)
