"""MovieLens-1M ratings (`python/paddle/v2/dataset/movielens.py`).

Records mirror the reference's ``__reader_creator__``:
``[user_id, gender, age, job, movie_id, category_ids, title_ids, [rating]]``
(user/movie features then the score). Real tier parses the ml-1m archive's
``ratings.dat``/``users.dat``/``movies.dat``; synthetic tier fabricates a
consistent catalog with taste structure (ratings correlate with a latent
user x category affinity, so factorization models genuinely learn).
"""

from __future__ import annotations

import re

import numpy as np

from paddle_tpu.v2.dataset import common

_N_USERS, _N_MOVIES, _N_CATEGORIES, _TITLE_VOCAB = 600, 400, 18, 1000
_AGES = [1, 18, 25, 35, 45, 50, 56]
_N_JOBS = 21


def max_user_id():
    return _N_USERS


def max_movie_id():
    return _N_MOVIES


def max_job_id():
    return _N_JOBS - 1


def age_table():
    return list(_AGES)


def categories():
    return [f"cat{i}" for i in range(_N_CATEGORIES)]


def _catalog():
    """Deterministic synthetic catalog: per-movie categories/titles and
    per-user demographics."""
    rng = np.random.RandomState(77)
    movies = []
    for m in range(_N_MOVIES):
        cats = sorted(rng.choice(_N_CATEGORIES,
                                 size=rng.randint(1, 4), replace=False))
        title = list(rng.randint(0, _TITLE_VOCAB, size=rng.randint(1, 5)))
        movies.append(([int(c) for c in cats], [int(t) for t in title]))
    users = []
    for u in range(_N_USERS):
        users.append((int(rng.randint(0, 2)),
                      int(rng.randint(0, len(_AGES))),
                      int(rng.randint(0, _N_JOBS))))
    affinity = rng.randn(_N_USERS, _N_CATEGORIES)
    return movies, users, affinity


def _reader(n, seed):
    common.note_synthetic("movielens")
    movies, users, affinity = _catalog()

    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            u = int(rng.randint(0, _N_USERS))
            m = int(rng.randint(0, _N_MOVIES))
            cats, title = movies[m]
            gender, age, job = users[u]
            score = float(np.clip(
                3.0 + affinity[u, cats].mean() + rng.randn() * 0.3,
                1.0, 5.0))
            yield [u, gender, age, job, m, cats, title, [score]]

    return reader


def train():
    path = common.cache_path("movielens", "ml-1m.zip")
    if path:
        return _real_reader(path, is_test=False)
    return _reader(8192, seed=0)


def test():
    path = common.cache_path("movielens", "ml-1m.zip")
    if path:
        return _real_reader(path, is_test=True)
    return _reader(1024, seed=1)


def _real_reader(path, *, is_test):
    """Parse the genuine ml-1m archive (reference format: ``::``-separated
    .dat files inside the zip). Every 10th rating goes to test, like the
    reference's modulo split."""
    import zipfile

    def reader():
        with zipfile.ZipFile(path) as z:
            users = {}
            for line in z.read("ml-1m/users.dat").decode(
                    "latin1").splitlines():
                uid, gender, age, job, _ = line.split("::")
                users[int(uid)] = (int(gender == "M"),
                                   _AGES.index(int(age)), int(job))
            import zlib
            movies = {}

            def stable(s, mod):
                # process-stable id (hash() varies with PYTHONHASHSEED)
                return zlib.crc32(s.encode()) % mod

            for line in z.read("ml-1m/movies.dat").decode(
                    "latin1").splitlines():
                mid, title, genres = line.split("::")
                words = re.sub(r"\(\d{4}\)$", "", title.strip()).split()
                movies[int(mid)] = (
                    [stable(g, _N_CATEGORIES) for g in genres.split("|")],
                    [stable(w, _TITLE_VOCAB) for w in words])
            for i, line in enumerate(z.read("ml-1m/ratings.dat").decode(
                    "latin1").splitlines()):
                uid, mid, score, _ = line.split("::")
                if (i % 10 == 9) != is_test:
                    continue
                uid, mid = int(uid), int(mid)
                if uid not in users or mid not in movies:
                    continue
                gender, age, job = users[uid]
                cats, title = movies[mid]
                yield [uid, gender, age, job, mid, cats, title,
                       [float(score)]]

    return reader
