"""v2 events (`python/paddle/v2/event.py`)."""

from paddle_tpu.trainer.events import (  # noqa: F401
    BeginIteration, BeginPass, EndIteration, EndPass, Event, TestResult)
