"""v2 Parameters (`python/paddle/v2/parameters.py`): numpy get/set over
the trainer's parameter dict + tar serialization.

The tar layout is inspectable-but-NOT-interchangeable with the
reference's: one raw-bytes member per parameter plus a json
``<name>.meta`` member each (the reference instead writes a
binary-headed value member plus a ``<name>.protobuf`` config). A tar
produced by the reference cannot be loaded here and vice versa;
``from_tar`` raises a clear error when a member lacks its ``.meta``.
"""

from __future__ import annotations

import io
import json
import tarfile
from typing import Dict, Iterator

import numpy as np


class Parameters:
    def __init__(self, params: Dict[str, np.ndarray] = None):
        self._params: Dict[str, np.ndarray] = {
            k: np.asarray(v) for k, v in (params or {}).items()}

    @classmethod
    def from_trainer(cls, trainer) -> "Parameters":
        import jax
        return cls({k: np.asarray(jax.device_get(v))
                    for k, v in trainer.params.items()})

    def install_into(self, trainer):
        trainer.load_state(dict(self._params))

    # ------------------------------------------------------------- dict
    def names(self):
        return list(self._params)

    def keys(self):
        return self._params.keys()

    def __iter__(self) -> Iterator[str]:
        return iter(self._params)

    def __contains__(self, name) -> bool:
        return name in self._params

    def __len__(self):
        return len(self._params)

    def get(self, name) -> np.ndarray:
        return self._params[name]

    __getitem__ = get

    def set(self, name, value):
        value = np.asarray(value)
        if name in self._params and value.shape != self._params[name].shape:
            raise ValueError(
                f"shape mismatch for {name}: {value.shape} vs "
                f"{self._params[name].shape}")
        self._params[name] = value

    __setitem__ = set

    def get_shape(self, name):
        return self._params[name].shape

    # -------------------------------------------------------------- tar
    def to_tar(self, f):
        with tarfile.open(fileobj=f, mode="w") as tar:
            for name, arr in self._params.items():
                hdr = json.dumps({"shape": list(arr.shape),
                                  "dtype": str(arr.dtype)}).encode()
                info = tarfile.TarInfo(name=f"{name}.meta")
                info.size = len(hdr)
                tar.addfile(info, io.BytesIO(hdr))
                raw = np.ascontiguousarray(arr).tobytes()
                info = tarfile.TarInfo(name=name)
                info.size = len(raw)
                tar.addfile(info, io.BytesIO(raw))

    @classmethod
    def from_tar(cls, f) -> "Parameters":
        params = {}
        metas = {}
        with tarfile.open(fileobj=f, mode="r") as tar:
            for member in tar.getmembers():
                data = tar.extractfile(member).read()
                if member.name.endswith(".meta"):
                    metas[member.name[:-5]] = json.loads(data.decode())
                else:
                    params[member.name] = data
        out = {}
        for name, raw in params.items():
            if name not in metas:
                raise ValueError(
                    f"tar member {name!r} has no companion '{name}.meta' — "
                    "this tar was not written by Parameters.to_tar (the "
                    "reference's to_tar layout is not interchangeable)")
            meta = metas[name]
            # copy: frombuffer views over the tar bytes are read-only,
            # but Parameters are mutable (set()/in-place edits)
            out[name] = np.frombuffer(
                raw, dtype=np.dtype(meta["dtype"])
            ).reshape(meta["shape"]).copy()
        return cls(out)
