"""v2 reader decorators (`python/paddle/v2/reader/decorator.py`)."""

from paddle_tpu.data.reader import (  # noqa: F401
    batch, buffered, chain, compose, firstn, map_readers, mix, shuffle)


class creator:
    """Reader creators (`python/paddle/v2/reader/creator.py`)."""

    @staticmethod
    def np_array(x):
        def reader():
            yield from x
        return reader

    @staticmethod
    def recordio(paths, shuffle=False, seed=0):
        """Reader over native record-chunk files (the RecordIO role)."""
        from paddle_tpu.data.recordio import pool_reader
        if isinstance(paths, str):
            paths = [paths]
        return pool_reader(paths, shuffle=shuffle, seed=seed)

    @staticmethod
    def text_file(path):
        def reader():
            with open(path) as f:
                for line in f:
                    yield line.rstrip("\n")
        return reader
