"""v2 reader decorators (`python/paddle/v2/reader/decorator.py`)."""

from paddle_tpu.data.reader import (  # noqa: F401
    ComposeNotAligned, batch, buffered, chain, compose, firstn, map_readers,
    mix, shuffle)


class creator:
    """Reader creators (`python/paddle/v2/reader/creator.py`)."""

    @staticmethod
    def np_array(x):
        def reader():
            yield from x
        return reader

    @staticmethod
    def recordio(paths, shuffle=False, seed=0):
        """Reader over native record-chunk files (the RecordIO role)."""
        from paddle_tpu.data.recordio import pool_reader
        if isinstance(paths, str):
            paths = [paths]
        return pool_reader(paths, shuffle=shuffle, seed=seed)

    @staticmethod
    def text_file(path):
        def reader():
            with open(path) as f:
                for line in f:
                    yield line.rstrip("\n")
        return reader

    @staticmethod
    def cloud_reader(paths, master_endpoint, timeout_sec=5, buf_size=64):
        """Fault-tolerant reader over master-dispatched chunks (reference
        ``creator.cloud_reader``, with the master's address in etcd's
        discovery role). Each call of the returned reader streams one
        pass; task timeout/failure handling lives in the master."""
        from paddle_tpu.v2 import master

        c = master.client(master_endpoint, timeout_sec, buf_size)
        c.set_dataset(list(paths))
        state = {"pass": 0}

        def reader():
            c.paddle_start_get_records(state["pass"])
            state["pass"] += 1
            while True:
                r, e = c.next_record()
                if e != master.OK:
                    return
                yield r

        return reader
