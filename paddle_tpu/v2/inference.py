"""v2 inference (`python/paddle/v2/inference.py`): ``paddle.infer``."""

from __future__ import annotations

from typing import Dict, List, Optional, Union

import jax
import numpy as np

from paddle_tpu.config import dsl as _dsl
from paddle_tpu.core.network import Network
from paddle_tpu.data.feeder import DataFeeder


class Inference:
    def __init__(self, output_layer, parameters=None, graph=None):
        outputs = output_layer if isinstance(output_layer, (list, tuple)) \
            else [output_layer]
        self.output_names = [o.name if hasattr(o, "name") else o
                             for o in outputs]
        if graph is None:
            # prefer the graph the layer was built in — the global graph
            # may already describe a different model after dsl.reset()
            graph = next((o.graph for o in outputs
                          if getattr(o, "graph", None) is not None), None)
        self.network = Network(graph or _dsl.current_graph(),
                               outputs=self.output_names)
        if parameters is None:
            # explicit Inference(None) is allowed for tests/untrained runs,
            # but loudly: forgetting the checkpoint here would otherwise
            # yield well-shaped garbage predictions
            from paddle_tpu.utils.log import get_logger
            get_logger("v2.inference").warning(
                "Inference created WITHOUT parameters — using random "
                "init; pass parameters= to predict with trained weights")
            self.params = self.network.init_params(jax.random.PRNGKey(0))
        elif hasattr(parameters, "_params"):  # v2 Parameters
            self.params = {k: jax.numpy.asarray(v)
                           for k, v in parameters._params.items()}
        else:  # trainer or plain dict
            src = getattr(parameters, "params", parameters)
            self.params = dict(src)

    def infer(self, input, *, feeding: Dict = None, field: str = "value"):
        feeder = DataFeeder(feeding) if isinstance(feeding, dict) else feeding
        feed = feeder(input) if feeder is not None else input
        out = self.network.apply(self.params, feed, train=False)
        results = [np.asarray(getattr(out[name], field))
                   for name in self.output_names]
        return results[0] if len(results) == 1 else results


def infer(output_layer, *, parameters, input=None, feeding=None,
          field: str = "value"):
    """v2 ``paddle.infer``; ``parameters`` is required, as in the
    reference (use Inference(..., parameters=None) explicitly to probe an
    untrained network)."""
    return Inference(output_layer, parameters).infer(
        input, feeding=feeding, field=field)
