"""v2 optimizer namespace (`python/paddle/v2/optimizer.py`): thin
constructors over the optim package; regularization/model-average kwargs
pass through.

Gradient-scale note: the engine differentiates the batch-MEAN cost, so
``learning_rate`` here is a per-mean-gradient rate (the modern
convention). Reference v1 jobs apply the rate to batch-SUMMED gradients
(hence ``0.1/128``-style settings); pass ``sum_gradients=True`` to
reproduce that exactly — the compat config path sets it automatically.
"""

from paddle_tpu.optim.optimizers import (  # noqa: F401
    AdaDelta, AdaGrad, Adam, Adamax, DecayedAdaGrad, Momentum, Optimizer,
    RMSProp)

# v2 capitalization variants
Adagrad = AdaGrad
Adadelta = AdaDelta
RMSprop = RMSProp
AdamOptimizer = Adam
