"""v2 optimizer namespace (`python/paddle/v2/optimizer.py`): thin
constructors over the optim package; regularization/model-average kwargs
pass through."""

from paddle_tpu.optim.optimizers import (  # noqa: F401
    AdaDelta, AdaGrad, Adam, Adamax, DecayedAdaGrad, Momentum, Optimizer,
    RMSProp)

# v2 capitalization variants
Adagrad = AdaGrad
Adadelta = AdaDelta
RMSprop = RMSProp
AdamOptimizer = Adam
