"""v2 optimizer namespace (`python/paddle/v2/optimizer.py`): thin
constructors over the optim package; regularization/model-average kwargs
pass through.

Gradient-scale note: the engine differentiates the batch-MEAN cost, so
``learning_rate`` here is a per-mean-gradient rate (the modern
convention). Reference v1 jobs apply the rate to batch-SUMMED gradients
(hence ``0.1/128``-style settings); pass ``sum_gradients=True`` to
reproduce that exactly — the compat config path sets it automatically.
"""

from paddle_tpu.optim import optimizers as _opt
from paddle_tpu.optim.optimizers import Optimizer  # noqa: F401


def _translate(kwargs):
    """v2 constructor kwargs (`python/paddle/v2/optimizer.py`): accept
    regularization / model_average / gradient_clipping objects and the
    remote-updater batch_size, mapping them onto the optimizer fields."""
    out = dict(kwargs)
    out.pop("batch_size", None)  # remote sparse-updater knob; no pserver
    reg = out.pop("regularization", None)
    if reg is not None:
        extra = reg.extra_settings() if hasattr(reg, "extra_settings") \
            else {}
        if "l2weight" in extra:
            out["l2_rate"] = extra["l2weight"]
        if "l1weight" in extra:
            out["l1_rate"] = extra["l1weight"]
    ma = out.pop("model_average", None)
    if ma is not None:
        out["average_window"] = getattr(ma, "average_window", 0.0)
        if getattr(ma, "max_average_window", None) is not None:
            out["max_average_window"] = ma.max_average_window
    clip = out.pop("gradient_clipping_threshold", None)
    if clip is not None:
        out["gradient_clipping_threshold"] = getattr(
            clip, "threshold", clip)
    return out


def _v2(cls):
    """A real subclass (not a factory): isinstance/subclassing keep
    working as they do against the reference's optimizer classes."""
    sub = type(cls.__name__, (cls,), {
        "__init__": lambda self, **kw: cls.__init__(self, **_translate(kw)),
        "__doc__": cls.__doc__,
    })
    return sub


Adam = _v2(_opt.Adam)
Momentum = _v2(_opt.Momentum)
AdaGrad = _v2(_opt.AdaGrad)
AdaDelta = _v2(_opt.AdaDelta)
Adamax = _v2(_opt.Adamax)
DecayedAdaGrad = _v2(_opt.DecayedAdaGrad)
RMSProp = _v2(_opt.RMSProp)

# v2 capitalization variants
Adagrad = AdaGrad
Adadelta = AdaDelta
RMSprop = RMSProp
AdamOptimizer = Adam
