"""Pooling objects (`trainer_config_helpers/poolings.py`)."""


class BasePool:
    name = "max"

    def __repr__(self):
        return f"{type(self).__name__}()"


def _make(cls_name, pool_name):
    return type(cls_name, (BasePool,), {"name": pool_name})


Max = _make("Max", "max")
Avg = _make("Avg", "average")
Sum = _make("Sum", "sum")
SquareRootN = _make("SquareRootN", "sqrt")


def resolve(p):
    if p is None:
        return None
    return p if isinstance(p, str) else p.name
