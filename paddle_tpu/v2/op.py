"""v2 op namespace (``python/paddle/v2/op.py``).

The reference registers unary math ops (exp/log/abs/sigmoid/tanh/square/
relu/sqrt/reciprocal/softmax) lowering to identity-projection mixed
layers, and installs ``+ - *`` operator overloads on layer outputs
(slope_intercept for layer+number, identity-projection mix for
layer+layer, scaling for layer*layer). All of that machinery lives in the
v1 ``layer_math`` helpers — the v2 module is the same surface re-exposed;
importing it (the package ``__init__`` does) installs the operators.
"""

from __future__ import annotations

from paddle_tpu.compat.trainer_config_helpers import layer_math as _math

__all__ = list(_math.__all__) + ["softmax"]

for _name in _math.__all__:
    globals()[_name] = getattr(_math, _name)


def softmax(input, name=None):
    """v2-only addition over the v1 set (``v2/op.py:44``)."""
    from paddle_tpu.compat.trainer_config_helpers import activations as _act
    from paddle_tpu.compat.trainer_config_helpers.layers import (
        _name as _nm, identity_projection, mixed_layer)
    return mixed_layer(input=[identity_projection(input=input)],
                       name=_nm(name, "softmax"),
                       act=_act.SoftmaxActivation())
