"""The v2 user API (`python/paddle/v2`): the familiar import surface.

    import paddle_tpu.v2 as paddle
    paddle.init(use_gpu=False)          # accepted for compatibility
    img = paddle.layer.data(name="pixel",
                            type=paddle.data_type.dense_vector(784))
    out = paddle.layer.fc(input=img, size=10,
                          act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(
        input=out, label=paddle.layer.data(
            name="label", type=paddle.data_type.integer_value(10)))
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=None,
        update_equation=paddle.optimizer.Momentum(learning_rate=0.1))
    trainer.train(reader=paddle.batch(paddle.dataset.mnist.train(), 128),
                  num_passes=5, event_handler=...)

Flags passed to ``init`` mirror the reference's gflags bridge
(`python/paddle/v2/__init__.py` → `utils/Flags.cpp`); on TPU most are
no-ops (``use_gpu``/``trainer_count`` → mesh selection is explicit via
``trainer.SGD(mesh=...)``) but are accepted so reference scripts run.
"""

from paddle_tpu.v2 import activation  # noqa: F401
from paddle_tpu.v2 import attr  # noqa: F401
from paddle_tpu.v2 import data_type  # noqa: F401
from paddle_tpu.v2 import dataset  # noqa: F401
from paddle_tpu.v2 import event  # noqa: F401
from paddle_tpu.v2 import inference  # noqa: F401
from paddle_tpu.v2 import layer  # noqa: F401
from paddle_tpu.v2 import master  # noqa: F401
from paddle_tpu.v2 import op  # noqa: F401
from paddle_tpu.v2 import optimizer  # noqa: F401
from paddle_tpu.v2 import parameters  # noqa: F401
from paddle_tpu.v2 import pooling  # noqa: F401
from paddle_tpu.v2 import reader  # noqa: F401
from paddle_tpu.v2 import trainer  # noqa: F401
from paddle_tpu.v2.inference import infer  # noqa: F401
from paddle_tpu.v2.parameters import Parameters  # noqa: F401
from paddle_tpu.data.reader import batch  # noqa: F401

_initialized = False
_init_flags = {}


def init(**kwargs):
    """Process-level init (`paddle.init(use_gpu=..., trainer_count=...)`).

    Mirrors the reference's gflags bridge (`python/paddle/v2/__init__.py`
    → `utils/Flags.cpp:18-80`): recorded flags become trainer defaults —
    ``trainer_count>1`` selects an N-way data-parallel mesh over the
    visible devices (the `MultiGradientMachine` fan-out), ``seed`` seeds
    parameter init, ``log_period`` paces train logging. ``use_gpu`` is
    accepted and ignored: device selection is JAX's (TPU when present)."""
    global _initialized
    _init_flags.update(kwargs)
    _initialized = True


def init_flags():
    return dict(_init_flags)
