"""v2 trainer (`python/paddle/v2/trainer.py`): SGD with the v2 signature.

``feeding`` accepts either {name: data_type} (builds a DataFeeder) or a
ready DataFeeder. Reader items are sample tuples in feeding order, as in
the reference's DataFeeder protocol.
"""

from __future__ import annotations

from typing import Optional

from paddle_tpu.data.feeder import DataFeeder
from paddle_tpu.data.types import InputType
from paddle_tpu.trainer.trainer import SGD as _SGD
from paddle_tpu.trainer.trainer import Topology  # noqa: F401


class SGD(_SGD):
    def __init__(self, cost, parameters=None, update_equation=None,
                 **kwargs):
        if hasattr(parameters, "_params"):  # v2 Parameters object
            import jax.numpy as jnp
            parameters = {k: jnp.asarray(v)
                          for k, v in parameters._params.items()}
        # paddle.init(...) flags become trainer defaults, the way the
        # reference's gflags reach Trainer::init (`utils/Flags.cpp:18-80`):
        # trainer_count>1 selects a data-parallel mesh (the
        # MultiGradientMachine thread fan-out, `MultiGradientMachine.h:44`),
        # seed seeds parameter init, log_period paces train logging.
        from paddle_tpu import v2 as _v2
        flags = _v2.init_flags()
        if "seed" in flags:
            kwargs.setdefault("seed", int(flags["seed"]))
        if kwargs.get("mesh") is None and int(
                flags.get("trainer_count", 1) or 1) > 1:
            import jax as _jax

            from paddle_tpu.parallel import create_mesh
            want = int(flags["trainer_count"])
            have = len(_jax.devices())
            n = min(want, have)
            if n < want:
                from paddle_tpu.utils.log import logger
                logger.warning(
                    "trainer_count=%d but only %d devices visible; "
                    "using %d-way data parallelism", want, have, n)
            if n > 1:
                kwargs["mesh"] = create_mesh(
                    n_data=n, devices=_jax.devices()[:n])
                self._mesh_from_flags = True
        super().__init__(cost, parameters=parameters,
                         update_equation=update_equation, **kwargs)

    def train(self, reader, *, num_passes: int = 1, event_handler=None,
              feeding=None, **kwargs):
        from paddle_tpu import v2 as _v2
        flags = _v2.init_flags()
        if "log_period" in flags:
            kwargs.setdefault("log_period", int(flags["log_period"]))
        reader = self._trim_to_dp_degree(reader)
        feeder = feeding
        if isinstance(feeding, dict):
            if not all(isinstance(v, InputType) for v in feeding.values()):
                raise TypeError(
                    "feeding must map data-layer names to paddle.data_type "
                    "objects (the index-based v2 form is not supported; "
                    "order the reader columns by the feeding dict instead)")
            feeder = DataFeeder(feeding)
        return super().train(reader, feeder=feeder, num_passes=num_passes,
                             event_handler=event_handler, **kwargs)

    def test(self, reader, *, feeding=None, **kwargs):
        feeder = feeding
        if isinstance(feeding, dict):
            feeder = DataFeeder(feeding)
        reader = self._trim_to_dp_degree(reader)
        return super().test(reader, feeder=feeder, **kwargs)

    def _trim_to_dp_degree(self, reader):
        """When the mesh came from paddle.init(trainer_count=N) rather than
        an explicit mesh argument, ragged final batches (paddle.batch
        defaults to drop_last=False) must not crash — trim them to the DP
        degree like a drop-remainder, with a one-time warning."""
        if not getattr(self, "_mesh_from_flags", False):
            return reader
        from paddle_tpu.parallel import mesh as _mesh_lib
        n = _mesh_lib.data_parallel_degree(self.mesh)
        warned = [False]

        def trimming_reader():
            for batch in reader():
                extra = len(batch) % n
                if extra:
                    if not warned[0]:
                        warned[0] = True
                        from paddle_tpu.utils.log import logger
                        logger.warning(
                            "dropping %d sample(s) from a batch of %d "
                            "not divisible by trainer_count=%d",
                            extra, len(batch), n)
                    batch = batch[:len(batch) - extra]
                if batch:
                    yield batch

        return trimming_reader
