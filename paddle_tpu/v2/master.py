"""v2 master-client surface (``python/paddle/v2/master/client.py``).

The reference's v2 reader discovers the Go master through etcd and pulls
records via a cgo client (``libpaddle_master.so``: ``paddle_set_dataset``
/ ``paddle_next_record`` / ``paddle_request_save_model``). Here the master
is ``paddle_tpu.dist.master.MasterService`` (same task-queue protocol:
GetTask / TaskFinished / TaskFailed / timeout-requeue / save arbitration)
and etcd discovery is absorbed by the single-controller address — so
``client`` takes the master's ``(host, port)`` instead of etcd endpoints
and keeps the reference method surface, return-code conventions included.
"""

from __future__ import annotations

from typing import Optional

from paddle_tpu.data.recordio import read_chunk
from paddle_tpu.dist.master import MasterClient, master_reader

# next_record error codes (the cgo client's convention: 0 = ok, < 0 =
# error; end-of-pass is distinguishable so callers can roll the pass)
OK = 0
PASS_END = -2


class client:
    """A client to the master server (reference ``client`` class)."""

    def __init__(self, endpoints, timeout_sec: float = 5, buf_size: int = 0,
                 load_chunk=read_chunk):
        if isinstance(endpoints, str):
            host, _, port = endpoints.rpartition(":")
            endpoints = (host or "127.0.0.1", int(port))
        self._mc = MasterClient(endpoints, connect_timeout=timeout_sec)
        self._pass_reader = master_reader(self._mc, load_chunk)
        self._buf_size = buf_size
        self._gen = None
        self._pass_ended = False

    def set_dataset(self, paths) -> None:
        self._mc.set_dataset(list(paths))

    def paddle_start_get_records(self, pass_id: int) -> None:
        raw = self._pass_reader(pass_id)
        if self._buf_size > 0:
            # buf_size>0 = background prefetch, the cgo client's read-ahead
            # buffer (note: `lambda: raw`, a distinct name — closing over a
            # rebound variable would hand the worker its own generator)
            from paddle_tpu.data.reader import buffered
            self._gen = buffered(lambda: raw, self._buf_size)()
        else:
            self._gen = raw
        self._pass_ended = False

    def next_record(self):
        """(record, 0) while the pass has records, (None, PASS_END) after —
        and on every later call until the caller starts the next pass
        (restarting pass 0 implicitly would duplicate its records)."""
        if self._pass_ended:
            return None, PASS_END
        if self._gen is None:
            self.paddle_start_get_records(0)
        try:
            return next(self._gen), OK
        except StopIteration:
            self._gen = None
            self._pass_ended = True
            return None, PASS_END

    def request_save_model(self, trainer_id, block_ms: float) -> int:
        """1 = approved, 0 = another trainer is saving, -1 = error."""
        try:
            ok = self._mc.request_save_model(str(trainer_id),
                                             block_ms / 1000.0)
            return 1 if ok else 0
        except Exception:  # noqa: BLE001 — reference returns -1, not raise
            return -1

    def release(self) -> None:
        self._mc.close()
