"""v2 data types (`python/paddle/v2/data_type.py` — re-export of the
PyDataProvider2 input types)."""

from paddle_tpu.data.types import (  # noqa: F401
    InputType, dense_vector, dense_vector_sequence, integer_value,
    integer_value_sequence, sparse_binary_vector, sparse_float_vector)
