"""Activation objects (`python/paddle/trainer_config_helpers/
activations.py` re-exported by v2): each carries the registry name the
layer executor resolves."""


class BaseActivation:
    name = "linear"

    def __repr__(self):
        return f"{type(self).__name__}()"


def _make(cls_name, act_name):
    return type(cls_name, (BaseActivation,), {"name": act_name})


Tanh = _make("Tanh", "tanh")
Sigmoid = _make("Sigmoid", "sigmoid")
Softmax = _make("Softmax", "softmax")
SequenceSoftmax = _make("SequenceSoftmax", "sequence_softmax")
Relu = _make("Relu", "relu")
BRelu = _make("BRelu", "brelu")
SoftRelu = _make("SoftRelu", "softrelu")
STanh = _make("STanh", "stanh")
Linear = _make("Linear", "linear")
Identity = Linear
Exp = _make("Exp", "exponential")
Log = _make("Log", "log")
Abs = _make("Abs", "abs")
Square = _make("Square", "square")
Sqrt = _make("Sqrt", "sqrt")
Reciprocal = _make("Reciprocal", "reciprocal")


def resolve(act):
    """Activation object | string | None -> registry string."""
    if act is None:
        return None
    return act if isinstance(act, str) else act.name
