"""v2 layer namespace (`python/paddle/v2/layer.py`).

The reference auto-wraps every v1 config helper into graph-object style;
here the DSL (`paddle_tpu.config.dsl`) already IS graph-object style, so
this module adapts only the v2-isms:

- ``data(name=, type=paddle.data_type.X, height=, width=)``
- activation/pooling OBJECTS (``act=paddle.activation.Relu()``)
- v2 layer names (``img_conv``/``img_pool``/``max_id``/``cross_entropy_cost``…)

Everything else passes straight through — ``paddle.layer.<anything>``
resolves to the DSL function of the same name.
"""

from __future__ import annotations

import functools

from paddle_tpu.config import dsl as _dsl
from paddle_tpu.v2 import activation as _act
from paddle_tpu.v2 import pooling as _pool


def _fix_kwargs(kwargs):
    if "act" in kwargs:
        kwargs["act"] = _act.resolve(kwargs["act"])
    for k in ("gate_act", "state_act"):
        if k in kwargs:
            kwargs[k] = _act.resolve(kwargs[k])
    if "pooling_type" in kwargs:
        kwargs["pooling_type"] = _pool.resolve(kwargs["pooling_type"])
    la = kwargs.get("layer_attr")
    if la is not None and not isinstance(la, dict):
        # ExtraAttr object → the dict form dsl accepts. Two classes reach
        # here: v2/attr.ExtraAttr (extras live in .kwargs) and the compat
        # trainer_config_helpers ExtraAttr (named fields, no .kwargs) —
        # handle both so device/drop_rate survive either spelling.
        d = dict(getattr(la, "kwargs", {}))
        if getattr(la, "drop_rate", None):
            d["drop_rate"] = la.drop_rate
        if getattr(la, "device", None) is not None:
            d["device"] = la.device
        kwargs["layer_attr"] = d
    return kwargs


def _wrap(fn):
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        return fn(*args, **_fix_kwargs(kwargs))
    return wrapped


def parse_network(*outputs):
    """The reference's ``layer.parse_network`` (`v2/layer.py:263`): emit
    the ``ModelConfig`` proto of the (sub-)network producing ``outputs``.
    The DSL holds one current graph, so this serializes it whole with the
    requested layers appended to output_layer_names."""
    from paddle_tpu.compat.proto_export import model_to_proto
    from paddle_tpu.config import dsl as _d
    graph = _d.current_graph()
    names = [o.name if hasattr(o, "name") else str(o) for o in outputs]
    for n in names:
        if n not in graph.layers:
            raise ValueError(f"parse_network: {n!r} is not a layer of the "
                             "current graph (stale LayerOutput?)")
    # serialization is read-only: splice the requested outputs in for the
    # emit, then restore (repeated parse_network calls must not accumulate)
    saved = list(graph.output_layer_names)
    try:
        graph.output_layer_names.extend(
            n for n in names if n not in graph.output_layer_names)
        return model_to_proto(graph)
    finally:
        graph.output_layer_names[:] = saved


def data(*, name: str, type, height: int = None, width: int = None):
    """v2 data layer: dims come from the data_type object."""
    channels = None
    if height and width and type.dim % (height * width) == 0:
        channels = type.dim // (height * width)
    from paddle_tpu.data.types import SEQUENCE
    out = _dsl.data(name=name, size=type.dim, height=height, width=width,
                    channels=channels,
                    is_sequence=type.seq_type >= SEQUENCE)
    # the reference's v2 data layer carries its data_type for
    # DataProviderConverter(input_types=[images.type, ...])
    object.__setattr__(out, "type", type)  # LayerOutput is frozen
    return out


def pooling(input, *, pooling_type=None, **kwargs):
    return _dsl.pooling(input=input,
                        pooling_type=_pool.resolve(pooling_type) or "max",
                        **_fix_kwargs(kwargs))


# v2 name → dsl name for the renamed ones (cost layers pass through:
# dsl already exports square_error_cost/mse_cost/cross_entropy_cost)
_ALIASES = {
    "img_conv": "conv",
    "img_pool": "img_pool",
    "max_id": "maxid",
    "crf": "crf_layer",
    "crf_decoding": "crf_decoding_layer",
    "ctc": "ctc_layer",
    "warp_ctc": "warp_ctc_layer",
    "eos": "eos_id_layer",
    "sampling_id": "sampling_id_layer",
    "clip": "clip_layer",
    "resize": "resize_layer",
    "rotate": "rotate_layer",
    "pad": "pad_layer",
    "crop": "crop_layer",
    "power": "power_layer",
    "prelu": "prelu_layer",
    "maxout": "maxout_layer",
    "multiplex": "multiplex_layer",
    "tensor": "tensor_layer",
    "selective_fc": "selective_fc_layer",
    "block_expand": "block_expand_layer",
    "sub_nested_seq": "sub_nested_seq_layer",
    "get_output": "get_output_layer",
    "gru_step": "gru_step_layer",
    "lstm_step": "lstm_step_layer",
    "nce": "nce_layer",
    "row_conv": "row_conv_layer",
    "conv_shift": "conv_shift_layer",
    "bilinear_interp": "bilinear_interp_layer",
    "mdlstm": "mdlstm_layer",
    "priorbox": "priorbox_layer",
    "multibox_loss": "multibox_loss_layer",
    "detection_output": "detection_output_layer",
    "print": "print_layer",
}


def __getattr__(name):
    target = _ALIASES.get(name, name)
    fn = getattr(_dsl, target, None)
    if fn is None or not callable(fn):
        raise AttributeError(f"paddle.layer.{name} (dsl has no '{target}')")
    return _wrap(fn)


LayerOutput = _dsl.LayerOutput
