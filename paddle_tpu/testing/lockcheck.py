"""Runtime lock-order tracker: the dynamic twin of
``paddle_tpu.analysis.lockorder``.

The static pass sees ``with self._lock:`` blocks; it cannot see locks
reached through callbacks, duck-typed parameters, or module globals.
This tracker can: it wraps lock construction so every acquisition
records (per thread) the stack of locks currently held, builds a global
*acquisition-order graph* keyed by lock **creation site** (file:line —
instances of the same class share a site, so an inversion between two
instances of the same pool still keys consistently), and raises
:class:`LockOrderError` the moment an acquisition creates a cycle —
i.e. some other thread/path acquired the same two sites in the
opposite order. A deadlock that would otherwise need an unlucky
interleaving to bite becomes a deterministic test failure on ANY
interleaving that exercises both orders.

Chaos-style opt-in, zero cost when off:

- ``install()`` / ``uninstall()`` patch ``threading.Lock`` /
  ``threading.RLock`` so locks created *after* install are tracked
  (``threading.Condition`` composes transparently — it drives the
  wrapped lock's ``acquire``/``release``).
- ``tracking()`` is the context-manager form tests use.
- ``PADDLE_TPU_LOCKCHECK=1`` arms it process-wide at import of
  ``paddle_tpu.testing`` (the ``$PADDLE_TPU_CHAOS_PLAN`` pattern).
- ``wrap(lock, name)`` adopts a pre-existing lock object into the
  tracker (for singletons created before install).

Also detected: same-thread re-acquisition of a non-reentrant tracked
lock — WARNED (``SelfDeadlockWarning``, the PT302 static rule's
runtime twin), not raised: ``release()`` legally supports cross-thread
handoff, so the blocking re-acquire may be a rendezvous; a genuine
self-deadlock hangs at the warned acquire with the warning naming it.
"""

from __future__ import annotations

import os
import threading
import traceback
import warnings
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

__all__ = ["LockOrderError", "SelfDeadlockWarning", "install",
           "uninstall", "tracking", "wrap", "edges", "reset",
           "installed"]

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock


class LockOrderError(RuntimeError):
    """Two lock sites were acquired in both orders (a deadlock
    window, detected transitively over the recorded graph)."""


class SelfDeadlockWarning(UserWarning):
    """A holding thread re-acquired its own non-reentrant lock. Legal
    only under a cross-thread handoff release — warned, not raised,
    because the tracker patches locks process-wide and must never
    fail a correct rendezvous; a genuine self-deadlock hangs at the
    warned acquire, with the warning naming it."""


class _State:
    def __init__(self):
        self.lock = _REAL_LOCK()  # guards the graph, never tracked
        # (site_a, site_b) -> short evidence string of first witness
        self.edges: Dict[Tuple[str, str], str] = {}
        self.tls = threading.local()

    def held(self) -> List["_TrackedLock"]:
        h = getattr(self.tls, "held", None)
        if h is None:
            h = self.tls.held = []
        return h


_STATE = _State()
_INSTALLED = False


def _reaches_locked(src: str, dst: str):
    """Edge-path src ->* dst over the recorded graph (caller holds
    _STATE.lock); returns the site path or None."""
    if src == dst:
        return [src]
    adj: Dict[str, List[str]] = {}
    for (a, b) in _STATE.edges:
        adj.setdefault(a, []).append(b)
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        for nxt in adj.get(node, []):
            if nxt == dst:
                return path + [nxt]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _creation_site(skip: int) -> str:
    """file:line of the lock constructor's caller, repo-relative-ish."""
    for frame in reversed(traceback.extract_stack()[:-skip]):
        fn = frame.filename
        if os.sep + "lockcheck" in fn or fn.endswith("lockcheck.py"):
            continue
        if os.sep + "threading" in fn and fn.endswith("threading.py"):
            continue
        parts = fn.replace(os.sep, "/").split("/")
        short = "/".join(parts[-3:])
        return f"{short}:{frame.lineno}"
    return "<unknown>"


class _TrackedLock:
    """Wraps a real lock; quacks enough for ``with``, ``acquire``,
    ``release`` and ``threading.Condition``."""

    def __init__(self, real, site: str, reentrant: bool,
                 name: Optional[str] = None):
        self._real = real
        self.site = name or site
        self._reentrant = reentrant
        # the held-lists this lock currently sits on, newest last —
        # threading.Lock may legally be release()d from a DIFFERENT
        # thread (handoff pattern), and the entry must come off the
        # ACQUIRER's per-thread stack, not the releaser's
        self._owner_lists: List[list] = []

    # ------------------------------------------------------- tracking
    def _before_acquire(self, blocking: bool):
        if not blocking:
            return  # try-locks never deadlock; don't order-constrain
        with _STATE.lock:
            # snapshot: a cross-thread handoff release may mutate this
            # thread's held list while we walk it
            held = list(_STATE.held())
        for h in held:
            if h is self and not self._reentrant:
                # NOT a hard error: release() legally supports
                # cross-thread handoff, so a holder blocking on a
                # second acquire may be a rendezvous another thread
                # will release. A REAL self-deadlock hangs right here
                # — with this warning already on record naming it.
                warnings.warn(
                    f"lockcheck: thread "
                    f"{threading.current_thread().name} re-acquires "
                    f"non-reentrant lock {self.site} it already holds "
                    "— self-deadlock unless another thread releases "
                    "it (handoff)", SelfDeadlockWarning, stacklevel=4)
                continue
            if h.site == self.site:
                continue  # same-site pool churn: no order info
            fwd = (h.site, self.site)
            with _STATE.lock:
                if fwd not in _STATE.edges:
                    # adding h->self closes a cycle iff self already
                    # REACHES h through recorded edges — the 2-lock
                    # inversion is just the length-1 case; A->B->C->A
                    # deadlock windows need the transitive check
                    path = _reaches_locked(self.site, h.site)
                    if path is not None:
                        chain = " -> ".join(path)
                        raise LockOrderError(
                            "lock-order inversion: this thread holds "
                            f"{h.site} and acquires {self.site}, but "
                            f"the opposite order is already on record "
                            f"({chain}; first witness: "
                            f"{_STATE.edges[(path[0], path[1])]}) — a "
                            "deadlock window")
                _STATE.edges.setdefault(
                    fwd, f"{h.site} -> {self.site} in thread "
                         f"{threading.current_thread().name}")

    def acquire(self, blocking: bool = True, timeout: float = -1):
        self._before_acquire(blocking)
        got = (self._real.acquire(blocking, timeout)
               if timeout != -1 else self._real.acquire(blocking))
        if got:
            held = _STATE.held()
            with _STATE.lock:
                # append under the graph lock: a cross-thread handoff
                # release may be mutating this very list concurrently
                held.append(self)
                self._owner_lists.append(held)
        return got

    def release(self):
        # take the entry off the list it was acquired on (usually this
        # thread's; a cross-thread handoff release pops the acquirer's).
        # The scan-and-delete stays under the graph lock: two handoff
        # releases racing on one acquirer's stack would otherwise
        # index-shift each other and delete the wrong entry
        with _STATE.lock:
            held = (self._owner_lists.pop() if self._owner_lists
                    else _STATE.held())
            for i in range(len(held) - 1, -1, -1):
                if held[i] is self:
                    del held[i]
                    break
        self._real.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._real.locked() if hasattr(self._real, "locked") \
            else None

    # ---- threading.Condition integration. Condition probes for
    # _release_save/_acquire_restore: on an RLock they release/restore
    # ALL recursion levels around wait(). Without forwarding them, a
    # Condition on a tracked RLock held recursively would release only
    # ONE level in wait() — the waiter keeps the lock, the notifier
    # can never acquire it, and the tracker itself manufactures a
    # deadlock in code that is correct untracked.
    def _pop_all_current_thread(self) -> int:
        """Remove every held entry for this lock from the calling
        thread's stack (+ matching owner-list refs); returns count."""
        with _STATE.lock:
            held = _STATE.held()
            n = 0
            for i in range(len(held) - 1, -1, -1):
                if held[i] is self:
                    del held[i]
                    n += 1
            removed = 0
            for i in range(len(self._owner_lists) - 1, -1, -1):
                if removed >= n:
                    break
                if self._owner_lists[i] is held:
                    del self._owner_lists[i]
                    removed += 1
        return n

    def _push_n_current_thread(self, n: int):
        held = _STATE.held()
        with _STATE.lock:
            for _ in range(n):
                held.append(self)
                self._owner_lists.append(held)

    def _release_save(self):
        if hasattr(self._real, "_release_save"):
            n = self._pop_all_current_thread()
            state = self._real._release_save()
            return (state, n)
        self.release()  # plain Lock: single-level, like Condition's own fallback
        return (None, 1)

    def _acquire_restore(self, token):
        state, n = token
        if state is not None and hasattr(self._real,
                                         "_acquire_restore"):
            self._real._acquire_restore(state)
            self._push_n_current_thread(n)
            return
        self.acquire()

    def _is_owned(self):
        if hasattr(self._real, "_is_owned"):
            return self._real._is_owned()
        if self._real.acquire(False):
            self._real.release()
            return False
        return True

    def __repr__(self):
        return f"<TrackedLock {self.site} wrapping {self._real!r}>"


def _tracked_lock_factory():
    return _TrackedLock(_REAL_LOCK(), _creation_site(2), False)


def _tracked_rlock_factory():
    return _TrackedLock(_REAL_RLOCK(), _creation_site(2), True)


def installed() -> bool:
    return _INSTALLED


def install():
    """Patch lock construction; locks created from here on are
    tracked. Idempotent."""
    global _INSTALLED
    if _INSTALLED:
        return
    threading.Lock = _tracked_lock_factory
    threading.RLock = _tracked_rlock_factory
    _INSTALLED = True


def uninstall():
    global _INSTALLED
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    _INSTALLED = False


def reset():
    """Drop the recorded order graph (NOT the held stacks — only call
    between quiesced phases)."""
    with _STATE.lock:
        _STATE.edges.clear()


def edges() -> Dict[Tuple[str, str], str]:
    with _STATE.lock:
        return dict(_STATE.edges)


def wrap(lock, name: str) -> _TrackedLock:
    """Adopt an existing lock object (singleton created pre-install)."""
    reentrant = type(lock).__name__ == "RLock" or hasattr(
        lock, "_is_owned")
    return _TrackedLock(lock, name, reentrant, name=name)


@contextmanager
def tracking(fresh: bool = True):
    """Install for the duration of a test; on exit restores the
    PRIOR state (so a ``PADDLE_TPU_LOCKCHECK=1`` process-wide install,
    or an outer ``tracking()`` block, stays armed) and — by default,
    only when this block did the installing — clears the graph."""
    was_installed = _INSTALLED
    install()
    try:
        yield
    finally:
        if not was_installed:
            uninstall()
            if fresh:
                reset()


def maybe_install_from_env():
    val = os.environ.get("PADDLE_TPU_LOCKCHECK", "")
    if val.strip().lower() not in ("", "0", "false", "off", "no"):
        install()
