"""Deterministic fault injection: the chaos plane.

The reference proved its third-generation fault tolerance by killing
real processes in CI shell scripts; that is irreproducible and slow.
Here the production code carries *named hook points* — the trainer's
step loop, the master RPC codec (``dist/master.py:_send_msg/_recv_msg``),
the checkpoint writer (``dist/checkpoint.py``), the serving batcher —
and a seeded :class:`FaultPlan` decides, purely from (site, hit-count,
seed), whether a given hit kills the process, drops or delays a
message, corrupts the checkpoint file just written, or injects a
straggler stall. The same plan therefore produces the same fault
schedule on every run: a chaos failure reproduces from its seed.

Zero cost when disabled: every hook site guards with
``if chaos._ACTIVE is not None`` — one module-global load per hit, no
function call, no allocation. Nothing in this module imports jax.

Fault spec (JSON-able, the format ``tools/chaos_soak.py`` writes into
``PADDLE_TPU_CHAOS_PLAN``)::

    {"seed": 7, "faults": [
      {"type": "kill",     "site": "step",  "at": 12, "mode": "exit"},
      {"type": "drop",     "site": "msg_send", "rate": 0.05},
      {"type": "delay",    "site": "msg_recv", "every": 7, "seconds": 0.02},
      {"type": "partition","site": "msg_send", "after": 40, "count": 10},
      {"type": "corrupt",  "site": "checkpoint", "at": 2,
       "mode": "truncate"},
      {"type": "straggle", "site": "serve_batch", "rate": 0.2,
       "seconds": 0.01}
    ]}

Sites wired in this codebase:

==============  ========================================================
``step``        end of each trainer iteration, BEFORE the checkpoint
                cadence runs (a kill here loses the batch's checkpoint
                → resume replays it)
``step_done``   end of each trainer iteration, AFTER checkpointing (a
                kill here tests resume from the just-written file)
``step_stats``  the trainer's training-health plane, just before an
                armed step dispatches (info: ``pass_id``,
                ``batch_id``; fires only while the divergence sentry
                is armed). A ``corrupt`` fault here carries no file
                path — instead the trainer reads the fired kinds from
                ``hit()``'s return and poisons ONE gradient leaf to
                NaN in-graph (``trainer.py:_poison_grads``), the
                deterministic divergence-sentry drill
``msg_send``    master RPC message about to be serialized (client *and*
                server side)
``msg_recv``    master RPC message about to be read
``checkpoint``  a checkpoint generation just became durable (info
                carries ``path``); ``corrupt`` faults mutate it
``store_save``  the master is about to persist its task-queue snapshot
``serve_batch`` the serving worker picked up a batch (a ``kill`` with
                ``mode: "raise"`` here is the replica-death fault: the
                worker dies, in-flight requests are answered 500, and
                the replica router fails them over / respawns)
``route_dispatch`` the replica router is about to hand one request to a
                replica (info: ``replica``, ``kind``); a ``drop`` is a
                dispatch that never reached the replica — the failover
                path, deterministic from the plan seed
``replica_spawn`` the router is about to respawn a dead replica (info:
                ``replica``); ``drop`` fails the spawn attempt (retried
                next health sweep), ``delay`` models a slow cold start
``supervisor_spawn`` the replica supervisor is about to spawn/respawn a
                replica PROCESS (info: ``replica``, ``why``); ``drop``
                fails the spawn (the slot stays down, retried next
                sweep), ``delay`` models a slow exec/cold start
``lease_renew`` a lease renewal is about to be recorded — the replica
                supervisor renewing a replica's liveness lease after a
                live health probe (info: ``replica``), or a router
                renewing its active-role lease (info: ``holder``,
                ``role``). A ``drop`` is a LOST renewal: enough of them
                and the lease expires exactly as if the holder hung —
                the supervisor's kill/respawn (no-double-spawn) path
                and the router's self-fencing path both run
``router_failover`` a standby router won the active-role lease and is
                about to adopt the fleet (info: ``holder``, ``epoch``);
                ``delay`` models a slow takeover
``replay_append`` the serving engine's replay sink is about to append
                one answered row to the open replay segment (info:
                ``segment``, ``records``). A ``corrupt`` fault carries
                no usable path semantics for ``_corrupt_file`` (replay
                shards are not .npz) — the writer reads the fired kinds
                from ``hit()``'s return and flips a byte of the record
                it just wrote (the ``step_stats`` pattern); a ``drop``
                is a lost append the engine counts and sheds (the row
                is NOT trained on — at-most-once upstream of the
                sealed-segment exactly-once boundary); a ``kill`` is
                replica death mid-append
``replay_tail`` the online tailer is about to read one sealed replay
                segment as a ledger task (info: ``segment``). A
                ``corrupt`` fault makes the tailer flip a byte of the
                segment file BEFORE parsing (same caller-applied
                pattern) — the whole-segment CRC validation must then
                quarantine it (rename ``.bad`` + warning), never yield
                a torn batch; a ``kill`` here is the
                trainer-died-mid-tail resume drill
``publish``     the online publisher just wrote a PTM1 artifact and is
                about to roll it across the fleet (info: ``version``,
                ``path``). ``corrupt`` carries no ``path`` effect
                (PTM1, not .npz) — the publisher reads the fired kind
                and flips a byte of its own artifact, driving the
                ``rolling_reload`` rollback path (bad digest →
                build fails → incumbent restored); a ``kill`` is
                trainer death mid-publish
==============  ========================================================

Fault types: ``kill`` (``mode`` ``"exit"`` = ``os._exit(exit_code)``,
the hard process death; ``"raise"`` = raise :class:`ChaosKilled`, the
in-process variant tests catch), ``drop`` (raise :class:`ChaosDropped`,
a ``ConnectionError`` — the RPC layer treats it exactly like a peer
reset), ``delay`` / ``straggle`` (sleep ``seconds``), ``partition``
(drop every hit in a count window), ``corrupt`` (mutate the checkpoint
file at ``info["path"]``: ``truncate`` | ``bitflip`` | ``bitflip_meta``
| ``delete_meta``).

Triggers (combinable; all compare against the per-site hit counter,
which starts at 1): ``at`` (exactly the Nth hit), ``after``+``count``
(a window), ``every`` (every Nth hit), ``rate`` (seeded Bernoulli per
hit — deterministic in (seed, fault-index, hit-count), independent of
thread interleaving), ``match`` (a dict compared against the hit's
``info`` kwargs — e.g. ``{"match": {"holder": "A"}}`` partitions ONE
router's lease renewals while its standby's sail through, or
``{"match": {"replica": "r0"}}`` targets one replica's faults; a key
the site does not report never matches). ``match`` filters which hits
a fault CAN fire on; the per-site hit counter still counts every hit.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Any, Dict, List, Optional

from paddle_tpu.obs import flight as _flight
from paddle_tpu.utils.log import get_logger

logger = get_logger("testing.chaos")

ENV_VAR = "PADDLE_TPU_CHAOS_PLAN"

# The CLOSED catalog of chaos hook sites wired in this codebase (the
# table above documents each). Every ``_ACTIVE.hit("<site>")`` call in
# paddle_tpu/ must name a member (graftlint PT107 — the static twin),
# and every member must have a firing row in the closure-enforced
# flight-recorder matrix (tests/test_obs_flight.py:SITE_CASES) — a new
# chaos site cannot ship without its postmortem event.
SITES = (
    "step", "step_done", "step_stats", "msg_send", "msg_recv",
    "checkpoint", "store_save", "serve_batch", "route_dispatch",
    "replica_spawn", "supervisor_spawn", "lease_renew",
    "router_failover", "replay_append", "replay_tail", "publish",
)

# the one global the hook sites poll; None == chaos disabled
_ACTIVE: Optional["FaultPlan"] = None


class ChaosKilled(BaseException):
    """In-process stand-in for a process kill (``mode: "raise"``).

    Derives from BaseException so ordinary ``except Exception`` recovery
    paths cannot swallow it — like a real SIGKILL, nothing downstream of
    the kill site runs except ``finally`` blocks."""


class ChaosDropped(ConnectionError):
    """An injected message loss. A ``ConnectionError`` on purpose: the
    RPC client's redial/retry path must treat an injected drop exactly
    like a real peer reset."""


def _corrupt_file(path: str, mode: str):
    """Mutate a just-written checkpoint generation in place."""
    npz = path if path.endswith(".npz") else path + ".npz"
    if mode == "truncate":
        size = os.path.getsize(npz)
        with open(npz, "r+b") as f:
            f.truncate(max(1, size // 2))
    elif mode == "bitflip":
        with open(npz, "r+b") as f:
            f.seek(max(0, os.path.getsize(npz) // 2))
            b = f.read(1)
            f.seek(-1, os.SEEK_CUR)
            f.write(bytes([(b[0] ^ 0xFF) if b else 0xFF]))
    elif mode == "bitflip_meta":
        meta = npz + ".meta"
        if os.path.exists(meta):
            with open(meta, "r+b") as f:
                b = f.read(1)
                f.seek(0)
                f.write(bytes([(b[0] ^ 0x01) if b else 0x58]))
    elif mode == "delete_meta":
        try:
            os.remove(npz + ".meta")
        except FileNotFoundError:
            pass
    else:
        raise ValueError(f"unknown corrupt mode {mode!r}")
    logger.warning("chaos: corrupted checkpoint %s (%s)", npz, mode)


class FaultPlan:
    """A seeded, deterministic schedule of faults over named hook sites.

    Thread-safe: hit counters are per-site under one lock; Bernoulli
    decisions derive from (seed, fault index, hit count) so concurrent
    sites cannot perturb each other's schedules."""

    def __init__(self, seed: int = 0,
                 faults: Optional[List[Dict[str, Any]]] = None,
                 exit_code: int = 17):
        self.seed = int(seed)
        self.faults = [dict(f) for f in (faults or [])]
        self.exit_code = int(exit_code)
        self._lock = threading.Lock()
        self._hits: Dict[str, int] = {}
        # what fired, for assertions: [(site, hit_n, fault_type)]
        self.log: List[tuple] = []

    # -------------------------------------------------------- plumbing
    def to_json(self) -> str:
        return json.dumps({"seed": self.seed, "exit_code": self.exit_code,
                           "faults": self.faults})

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        d = json.loads(text)
        return cls(seed=d.get("seed", 0), faults=d.get("faults"),
                   exit_code=d.get("exit_code", 17))

    def _bernoulli(self, idx: int, n: int, rate: float) -> bool:
        # seeded by value, not by a shared Random instance: the decision
        # for hit n of fault idx never depends on what other sites did
        return random.Random(f"{self.seed}:{idx}:{n}").random() < rate

    def _matches(self, idx: int, fault: Dict[str, Any], site: str,
                 n: int, info: Optional[Dict[str, Any]] = None) -> bool:
        # triggers are combinable (conjunction): every trigger present
        # must agree, so {"after": 10, "rate": 0.3} is a seeded coin
        # flip on hits 11.. — not "after wins, rate ignored". The empty
        # conjunction is TRUE: a fault with no trigger at all fires on
        # every hit ("drop every send"), it is not silently inert.
        if fault.get("site") != site:
            return False
        m = fault.get("match")
        if m:
            # info-scoped targeting: every match key must equal the
            # hit's reported info (string-compared — plans arrive as
            # JSON); a key the site never reports can never match
            if any((info or {}).get(k) is None
                   or str((info or {}).get(k)) != str(v)
                   for k, v in m.items()):
                return False
        if "at" in fault and n != int(fault["at"]):
            return False
        if "after" in fault:
            lo = int(fault["after"])
            if not (lo < n <= lo + int(fault.get("count", 1))):
                return False
        if "every" in fault and n % int(fault["every"]) != 0:
            return False
        if "rate" in fault and \
                not self._bernoulli(idx, n, float(fault["rate"])):
            return False
        return True

    # ------------------------------------------------------------ hits
    def hit(self, site: str, **info):
        """One arrival at ``site``. May sleep, raise, corrupt a file, or
        kill the process, per the plan. Returns the tuple of fired
        fault TYPES (empty when nothing fired) so value-carrying sites
        — ``step_stats``'s in-graph gradient poison — can read the
        decision without a side channel; kill/drop paths never
        return."""
        with self._lock:
            n = self._hits.get(site, 0) + 1
            self._hits[site] = n
            due = [(i, f) for i, f in enumerate(self.faults)
                   if self._matches(i, f, site, n, info)]
            for _, f in due:
                self.log.append((site, n, f["type"]))
        for _, f in due:
            kind = f["type"]
            if _flight._ACTIVE is not None:
                # the fired fault IS postmortem evidence: record BEFORE
                # the effect runs, so even a kill leaves its trace in
                # the black box (dumped below for the no-atexit exit)
                _flight._ACTIVE.record("chaos_fire", site=site, hit=n,
                                       fault=kind,
                                       mode=f.get("mode"))
            if kind == "kill":
                logger.warning("chaos: kill at %s hit %d (%s)", site, n,
                               f.get("mode", "exit"))
                if f.get("mode", "exit") == "raise":
                    raise ChaosKilled(f"chaos kill at {site} hit {n}")
                # os._exit skips atexit — the flight dump must happen
                # HERE or the kill erases the black box describing it
                _flight.dump_now()
                os._exit(f.get("exit_code", self.exit_code))
            elif kind in ("delay", "straggle"):
                time.sleep(float(f.get("seconds", 0.01)))
            elif kind in ("drop", "partition"):
                raise ChaosDropped(f"chaos dropped {site} hit {n}")
            elif kind == "corrupt":
                # with a path the fault mutates that file; without one
                # (step_stats) the caller reads the returned kind and
                # applies the corruption itself (in-graph poison)
                if "path" in info:
                    _corrupt_file(info["path"], f.get("mode", "truncate"))
            else:
                raise ValueError(f"unknown fault type {kind!r}")
        return tuple(f["type"] for _, f in due)

    def hits(self, site: str) -> int:
        with self._lock:
            return self._hits.get(site, 0)


# ------------------------------------------------------------ install

def install(plan: Optional[FaultPlan]):
    """Make ``plan`` the active plan (None disables chaos)."""
    global _ACTIVE
    _ACTIVE = plan
    return plan


def active() -> Optional[FaultPlan]:
    return _ACTIVE


def install_from_env(env: Optional[Dict[str, str]] = None
                     ) -> Optional[FaultPlan]:
    """Install the plan serialized in ``$PADDLE_TPU_CHAOS_PLAN`` (how
    ``tools/chaos_soak.py`` arms child processes); no-op when unset."""
    text = (env or os.environ).get(ENV_VAR, "")
    if not text:
        return None
    plan = FaultPlan.from_json(text)
    logger.warning("chaos plan armed from env: seed=%d, %d faults",
                   plan.seed, len(plan.faults))
    return install(plan)


class chaos_plan:
    """``with chaos_plan(FaultPlan(...)) as plan:`` — scoped install for
    tests; always uninstalls, even when the body dies to a ChaosKilled."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan

    def __enter__(self) -> FaultPlan:
        install(self.plan)
        return self.plan

    def __exit__(self, *exc):
        install(None)
        return False
