"""Test-support runtime pieces that ship with the library.

``paddle_tpu.testing.chaos`` is the deterministic fault-injection plane
(the analogue of the reference CI's kill-based fault-tolerance drills,
`go/master/service_internal_test.go` / `paddle/scripts/cluster_train`):
it lives in the package, not in tests/, because production code carries
its hook points and ``tools/chaos_soak.py`` drives it across processes.
Import cost is a few stdlib modules; nothing here imports jax.
"""

# lock-order tracking is the same opt-in pattern as the chaos plane:
# armed by $PADDLE_TPU_LOCKCHECK, zero cost otherwise
from paddle_tpu.testing import lockcheck as _lockcheck  # noqa: E402

_lockcheck.maybe_install_from_env()
