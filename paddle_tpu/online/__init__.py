"""Online learning loop (`--job=serve_train`): serving traffic streams
into the sparse CTR trainer with zero-downtime hot-swap.

The 2017 production story the reference framework existed for —
PaddlePaddle's sparse CTR models trained continuously on live traffic
behind a parameter server — recast onto this repo's primitives:

- ``replay.py``   — durable replay shards the serving engine appends
                    answered rows to (length-delimited CRC records,
                    fsync'd segment roll, schema'd header).
- ``tailer.py``   — the exactly-once tailer: sealed segments become
                    ledger tasks in the r11 ``dist/master.py``
                    lease/commit machinery, over a stream whose tail
                    grows while training.
- ``publish.py``  — the versioned publisher: merge a PTM1 artifact on
                    a cadence (optionally quantized through the r19
                    warmup gate) and ``rolling_reload`` the fleet with
                    an explicit ``model_hash`` pin; gate refusals stay
                    typed and the incumbent keeps serving.
- ``loop.py``     — the supervised loop wiring trainer + tailer +
                    publisher + divergence sentry into one process
                    group.

Architecture record: ``docs/online_learning.md``.
"""

from paddle_tpu.online.loop import OnlineLoopConfig, ServeTrainLoop
from paddle_tpu.online.publish import ModelPublisher, PublishResult
from paddle_tpu.online.replay import (ReplayCorrupt, ReplayWriter,
                                      load_segment, parse_segment,
                                      quarantine, scan_segments)
from paddle_tpu.online.tailer import LocalMasterClient, ReplayTailer

__all__ = [
    "OnlineLoopConfig", "ServeTrainLoop", "ModelPublisher",
    "PublishResult", "ReplayCorrupt", "ReplayWriter", "load_segment",
    "parse_segment", "quarantine", "scan_segments", "LocalMasterClient",
    "ReplayTailer",
]
