"""The versioned publisher: training→serving edge of the online loop.

On a cadence (every ``every_batches`` trained batches), merge the live
trainer parameters into a PTM1 artifact — optionally quantized through
the r19 warmup accuracy gate — and roll it across the serving fleet
with ``ReplicaRouter.rolling_reload``, pinned to the artifact's
``merged_digest`` as the explicit ``model_hash``.

The swap is weight-only by construction: the fleet's AOT bucket menu,
feeding order, and generation pins come from the serving plan, not the
artifact, so a reload recompiles NOTHING — every replica re-warms
through the shared AOT cache and its hardened ``RecompileGuard``s
would raise on any hot-path compile (the bench asserts their silence).

Rollback state machine (``docs/online_learning.md`` has the diagram):

- merge fails / artifact corrupt / warmup gate refuses → the build of
  the FIRST replica raises (``QuantGateError`` stays typed through the
  router as ``ReloadRejected``), ``fallback_build`` restores the
  incumbent artifact, the router counts ``reload_rollbacks_total`` —
  and the INCUMBENT keeps serving. The publisher keeps training; the
  next cadence tries again with newer weights.
- success → ``last_good`` advances to the new artifact (the next
  rollback target) and every replica reports the new model_version.

Every attempt is a flight-recorder event (``publish`` /
``publish_rejected``) — a bad cycle is postmortem-able from
``tools/blackbox.py`` alone. The divergence sentry upstream
(``trainer.train(health=...)``) keeps poisoned updates out of the
parameters the merge reads, so a "bad publish" requires a poisoned
batch to get PAST the sentry — the online test matrix pins that it
cannot.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable, List, Optional

from paddle_tpu import quant as quant_lib
from paddle_tpu.obs import flight as _flight
from paddle_tpu.serving.errors import ReloadRejected
from paddle_tpu.testing import chaos as _chaos
from paddle_tpu.trainer.merge_model import merge_model, merged_digest
from paddle_tpu.utils.log import get_logger

logger = get_logger("online.publish")


@dataclasses.dataclass
class PublishResult:
    version: Optional[str]  # merged_digest hex, None when rolled back
    path: str
    ok: bool
    error: Optional[str] = None


class ModelPublisher:
    """Merge-and-roll on a batch cadence.

    ``build_transport(model_path, replica_id)`` is the serving plan's
    reload builder (``trainer/cli.py:build_serving_fleet``): it
    constructs a started engine transport from an artifact path. The
    publisher wraps it into ``rolling_reload``'s ``build`` /
    ``fallback_build`` pair around the artifact it just wrote and the
    last known-good one.

    ``router=None`` publishes artifacts without a fleet (the merge
    cadence alone — useful for tests and the bench's trainer-only
    mode); the version history still advances.
    """

    def __init__(self, trainer, *, model_dir: str,
                 outputs: List[str],
                 router=None,
                 build_transport: Optional[Callable] = None,
                 every_batches: int = 50,
                 quantize: Optional[str] = None,
                 feeding=None,
                 golden_fn: Optional[Callable] = None):
        self.trainer = trainer
        self.model_dir = model_dir
        self.outputs = list(outputs)
        self.router = router
        self.build_transport = build_transport
        if router is not None and build_transport is None:
            raise ValueError("a fleet publisher needs build_transport")
        self.every_batches = int(every_batches)
        self.quantize = quantize
        self.feeding = feeding
        self.golden_fn = golden_fn
        self.versions: List[str] = []  # digests actually serving, in order
        self.last_good: Optional[str] = None  # artifact path
        self.publishes_total = 0
        self.rollbacks_total = 0
        self._batches_since = 0
        self._vnum = 0
        os.makedirs(model_dir, exist_ok=True)

    # ---------------------------------------------------------- cadence
    def on_batch(self) -> Optional[PublishResult]:
        """Call once per trained batch (the ``EndIteration`` hook);
        publishes when the cadence is due."""
        self._batches_since += 1
        if self._batches_since < self.every_batches:
            return None
        self._batches_since = 0
        return self.publish()

    # ---------------------------------------------------------- publish
    def _merge(self, path: str) -> str:
        params = self.trainer._params_for_save()
        graph = self.trainer.topology.graph
        quant_meta = golden = None
        if self.quantize:
            if self.golden_fn is not None:
                golden = self.golden_fn(graph, params)
            elif self.feeding is not None:
                golden = quant_lib.golden_section(
                    graph, params, self.outputs, self.feeding)
            sparse = {name for name, spec in self.trainer.meta.items()
                      if getattr(spec, "sparse_grad", False)}
            params, quant_meta = quant_lib.quantize_params(
                params, self.quantize, sparse_names=sparse)
        tmp = path + ".tmp"
        merge_model(tmp, graph, params, outputs=self.outputs,
                    quant=quant_meta, golden=golden)
        os.replace(tmp, path)
        return merged_digest(path)

    def publish(self) -> PublishResult:
        path = os.path.join(self.model_dir,
                            f"model-v{self._vnum:04d}.ptmodel")
        self._vnum += 1
        digest = self._merge(path)
        if _chaos._ACTIVE is not None:
            # fires AFTER the artifact exists so "corrupt" has a file
            # to mutate (PTM1, not .npz → caller-applied, the
            # step_stats pattern — info key is NOT "path", which would
            # invoke the plan's built-in checkpoint corruptor): the
            # flipped byte fails the payload MD5 inside the reload
            # build, driving the rollback path
            kinds = _chaos._ACTIVE.hit("publish", version=digest[:12],
                                       artifact=os.path.basename(path))
            if "corrupt" in kinds:
                with open(path, "r+b") as f:
                    f.seek(os.path.getsize(path) // 2)
                    b = f.read(1)
                    f.seek(-1, os.SEEK_CUR)
                    f.write(bytes([(b[0] ^ 0xFF) if b else 0xFF]))
                logger.warning("chaos: corrupted published artifact %s",
                               os.path.basename(path))
        if self.router is None:
            self.versions.append(digest)
            self.last_good = path
            self.publishes_total += 1
            if _flight._ACTIVE is not None:
                _flight._ACTIVE.record("publish", version=digest[:12],
                                       path=os.path.basename(path),
                                       fleet=False)
            return PublishResult(version=digest, path=path, ok=True)

        incumbent = self.last_good

        def build(replica_id: str):
            return self.build_transport(path, replica_id)

        fallback = None
        if incumbent is not None:
            def fallback(replica_id: str):
                return self.build_transport(incumbent, replica_id)

        try:
            self.router.rolling_reload(build, fallback_build=fallback)
        except ReloadRejected as e:
            # typed refusal (QuantGateError → ReloadRejected, or a
            # corrupt artifact's integrity error): the incumbent is
            # back in every swapped slot and KEEPS SERVING; training
            # continues and the next cadence retries with newer weights
            self.rollbacks_total += 1
            logger.warning("publish %s rejected, incumbent restored: %s",
                           digest[:12], e)
            if _flight._ACTIVE is not None:
                _flight._ACTIVE.record("publish_rejected",
                                       version=digest[:12],
                                       error=type(
                                           e.__cause__ or e).__name__,
                                       reason=str(e)[:200])
            return PublishResult(version=None, path=path, ok=False,
                                 error=str(e))
        self.versions.append(digest)
        self.last_good = path
        self.publishes_total += 1
        logger.info("published %s (%s) across the fleet", digest[:12],
                    os.path.basename(path))
        if _flight._ACTIVE is not None:
            _flight._ACTIVE.record("publish", version=digest[:12],
                                   path=os.path.basename(path),
                                   fleet=True)
        return PublishResult(version=digest, path=path, ok=True)
