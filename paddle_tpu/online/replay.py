"""Durable replay shards: the serving→training edge of the online loop.

The serving engine appends every successfully-answered score row to a
replay log; the online tailer (``online/tailer.py``) trains on sealed
segments exactly-once through the dist master's ledger. The format is
deliberately checkpoint-grade — a chaos-corruptible artifact with the
same honesty rules as ``dist/checkpoint.py``:

Segment file (``replay-NNNNNNNN.ptrl``)::

    b"PTRL1\\n"                                   magic
    >I header_len | header JSON                   {"schema": [slot
                                                  names], "seq": N,
                                                  "created": ts}
    >II payload_len, crc32 | payload JSON         one record per
    ...                                           answered row

Durability contract: rows accumulate in ``replay-NNNNNNNN.open``; at
``segment_records`` the writer flush+fsyncs, then ``os.replace``s to
the sealed ``.ptrl`` name and fsyncs the directory — a sealed segment
is durable the way a renamed checkpoint generation is, and ONLY sealed
segments are visible to the tailer. The unsealed tail is therefore
at-most-once (a crash loses it, exactly like requests answered between
checkpoints); the exactly-once guarantee starts at the seal boundary.

Corruption contract: :func:`parse_segment` validates the WHOLE segment
(magic, header, every record length + CRC) before returning anything,
so a torn or bit-flipped file can never yield a partial batch;
:func:`load_segment` answers corruption with **quarantine + skip** —
rename to ``.bad``, warn, return no rows — never an exception into the
training loop. Chaos sites ``replay_append`` (writer) and
``replay_tail`` (reader) drive both paths deterministically; their
``corrupt`` kind is caller-applied (the ``step_stats`` pattern) since
``_corrupt_file`` assumes ``.npz`` checkpoints.

Nothing in this module imports jax.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

from paddle_tpu.obs import flight as _flight
from paddle_tpu.testing import chaos as _chaos
from paddle_tpu.utils.log import get_logger

logger = get_logger("online.replay")

MAGIC = b"PTRL1\n"
SEALED_SUFFIX = ".ptrl"
OPEN_SUFFIX = ".open"
_REC_HEAD = struct.Struct(">II")  # payload length, crc32(payload)
_HDR_LEN = struct.Struct(">I")


class ReplayCorrupt(IOError):
    """A replay segment failed whole-file validation (bad magic, torn
    record, CRC mismatch, undecodable payload). The tailer answers
    this with quarantine + skip, never a torn train batch."""


def segment_name(seq: int, *, sealed: bool = True) -> str:
    return f"replay-{seq:08d}" + (SEALED_SUFFIX if sealed else OPEN_SUFFIX)


def scan_segments(directory: str) -> List[str]:
    """Sorted absolute paths of the SEALED segments in ``directory`` —
    the only files the tailer may train on (the open tail is not yet
    durable; ``.bad`` quarantines are never revisited)."""
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return []
    return [os.path.join(directory, n) for n in sorted(names)
            if n.startswith("replay-") and n.endswith(SEALED_SUFFIX)]


class ReplayWriter:
    """Append answered rows to the replay log; seal segments durably.

    Thread-safe: replicas of an in-process fleet share ONE writer (the
    log is the merge point of the fleet's answered traffic), so append
    serializes under ``_lock``. The chaos hit fires under it — the
    replay→chaos edge mirrors the master→chaos precedent in the
    lock-order graph.
    """

    def __init__(self, directory: str, *, segment_records: int = 256,
                 schema: Optional[List[str]] = None):
        if segment_records < 1:
            raise ValueError("segment_records must be >= 1")
        self.directory = directory
        self.segment_records = int(segment_records)
        self.schema = list(schema or [])
        self._lock = threading.Lock()
        self._file = None
        self._records = 0  # records in the open segment
        self.records_total = 0
        self.segments_sealed = 0
        os.makedirs(directory, exist_ok=True)
        self._seq = self._recover()

    # ------------------------------------------------------------ setup
    def _recover(self) -> int:
        """Orphan any unsealed tail a crashed writer left behind (its
        rows were answered but never made durable — at-most-once
        upstream of the seal boundary) and continue numbering after
        every name ever used."""
        top = 0
        for name in os.listdir(self.directory):
            if not name.startswith("replay-"):
                continue
            stem = name.split(".", 1)[0]
            try:
                top = max(top, int(stem.split("-", 1)[1]) + 1)
            except (IndexError, ValueError):
                continue
            if name.endswith(OPEN_SUFFIX):
                path = os.path.join(self.directory, name)
                os.replace(path, path + ".orphan")
                logger.warning(
                    "replay: orphaned unsealed tail %s (rows before the "
                    "seal boundary are at-most-once)", name)
        return top

    # ----------------------------------------------------------- append
    def _open_locked(self):
        path = os.path.join(self.directory,
                            segment_name(self._seq, sealed=False))
        f = open(path, "wb")
        header = json.dumps({"schema": self.schema, "seq": self._seq,
                             "created": time.time()},
                            separators=(",", ":")).encode()
        f.write(MAGIC + _HDR_LEN.pack(len(header)) + header)
        self._file = f
        self._records = 0

    def append(self, row) -> None:
        """Append one answered row (a feeding-order sample tuple). May
        raise ``ChaosDropped`` (a lost append — the caller counts and
        sheds it; the row is NOT in the log) per the active plan."""
        payload = json.dumps(row, separators=(",", ":")).encode()
        with self._lock:
            # fire BEFORE the write: a "drop" here is an append that
            # never reached the log, and a "kill" loses the row exactly
            # like replica death would
            kinds = ()
            if _chaos._ACTIVE is not None:
                kinds = _chaos._ACTIVE.hit("replay_append",
                                           segment=self._seq,
                                           records=self._records)
            if self._file is None:
                self._open_locked()
            rec_off = self._file.tell()
            self._file.write(_REC_HEAD.pack(
                len(payload), zlib.crc32(payload) & 0xFFFFFFFF) + payload)
            if "corrupt" in kinds:
                # caller-applied corruption (replay shards are not the
                # .npz files _corrupt_file mutates): flip one payload
                # byte of the record just written, so the sealed
                # segment fails its CRC at tail time
                self._file.flush()
                path = self._file.name
                with open(path, "r+b") as g:
                    g.seek(rec_off + _REC_HEAD.size + len(payload) // 2)
                    b = g.read(1)
                    g.seek(-1, os.SEEK_CUR)
                    g.write(bytes([(b[0] ^ 0xFF) if b else 0xFF]))
                logger.warning("chaos: corrupted replay record in %s",
                               os.path.basename(path))
            self._records += 1
            self.records_total += 1
            if self._records >= self.segment_records:
                self._seal_locked()

    def _seal_locked(self):
        f, self._file = self._file, None
        if f is None or self._records == 0:
            if f is not None:
                f.close()
                os.remove(f.name)
            return
        f.flush()
        os.fsync(f.fileno())
        f.close()
        sealed = os.path.join(self.directory, segment_name(self._seq))
        os.replace(f.name, sealed)
        dirfd = os.open(self.directory, os.O_RDONLY)
        try:
            os.fsync(dirfd)
        finally:
            os.close(dirfd)
        if _flight._ACTIVE is not None:
            _flight._ACTIVE.record("replay_seal",
                                   segment=os.path.basename(sealed),
                                   records=self._records)
        self.segments_sealed += 1
        self._seq += 1
        self._records = 0

    def seal(self) -> None:
        """Seal the open partial segment (loop shutdown: the answered
        tail becomes durable and trainable before the stream closes)."""
        with self._lock:
            self._seal_locked()

    def close(self) -> None:
        self.seal()


# ---------------------------------------------------------------- read

def parse_segment(path: str) -> Tuple[Dict[str, Any], List[Any]]:
    """-> (header, rows). Validates the ENTIRE segment — magic, header,
    every record's length and CRC — before returning anything, so a
    torn file can never surface as a partial batch. Raises
    :class:`ReplayCorrupt` on any violation."""
    with open(path, "rb") as f:
        raw = f.read()
    if raw[:len(MAGIC)] != MAGIC:
        raise ReplayCorrupt(f"{path}: bad magic")
    off = len(MAGIC)
    try:
        (hdr_len,) = _HDR_LEN.unpack_from(raw, off)
        off += _HDR_LEN.size
        if off + hdr_len > len(raw):
            raise ReplayCorrupt(f"{path}: truncated header")
        header = json.loads(raw[off:off + hdr_len].decode())
        off += hdr_len
        rows: List[Any] = []
        while off < len(raw):
            if off + _REC_HEAD.size > len(raw):
                raise ReplayCorrupt(f"{path}: torn record head "
                                    f"at byte {off}")
            length, crc = _REC_HEAD.unpack_from(raw, off)
            off += _REC_HEAD.size
            if off + length > len(raw):
                raise ReplayCorrupt(f"{path}: torn record payload "
                                    f"at byte {off}")
            payload = raw[off:off + length]
            if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                raise ReplayCorrupt(f"{path}: CRC mismatch on record "
                                    f"{len(rows)}")
            rows.append(json.loads(payload.decode()))
            off += length
    except ReplayCorrupt:
        raise
    except (struct.error, ValueError, UnicodeDecodeError) as e:
        raise ReplayCorrupt(f"{path}: {e}") from e
    return header, rows


def quarantine(path: str, *, reason: str = "") -> str:
    """Rename a corrupt segment to ``.bad`` so it is skipped forever —
    with a warning and a flight event, never silently."""
    bad = path + ".bad"
    os.replace(path, bad)
    logger.warning("replay: quarantined corrupt segment %s -> %s (%s)",
                   os.path.basename(path), os.path.basename(bad),
                   reason or "failed validation")
    if _flight._ACTIVE is not None:
        _flight._ACTIVE.record("replay_quarantine",
                               segment=os.path.basename(path),
                               reason=reason or "failed validation")
    return bad


def load_segment(path: str) -> List[Any]:
    """Read one sealed segment for training. A corrupt segment is
    quarantined and yields NO rows (the ledger task completes empty and
    is never retried) — the torn-batch-free contract. The
    ``replay_tail`` chaos site fires first; its ``corrupt`` kind flips
    a byte of the file before parsing (caller-applied, deterministic
    drill for the quarantine path)."""
    if _chaos._ACTIVE is not None:
        kinds = _chaos._ACTIVE.hit("replay_tail",
                                   segment=os.path.basename(path))
        if "corrupt" in kinds:
            size = os.path.getsize(path)
            with open(path, "r+b") as f:
                f.seek(max(len(MAGIC) + _HDR_LEN.size, size // 2))
                b = f.read(1)
                f.seek(-1, os.SEEK_CUR)
                f.write(bytes([(b[0] ^ 0xFF) if b else 0xFF]))
            logger.warning("chaos: corrupted replay segment %s",
                           os.path.basename(path))
    try:
        _header, rows = parse_segment(path)
    except ReplayCorrupt as e:
        quarantine(path, reason=str(e))
        return []
    except FileNotFoundError:
        # already quarantined by an earlier attempt of this task
        # (timeout redispatch): skip, matching the quarantine outcome
        logger.warning("replay: segment %s gone (already quarantined?)",
                       os.path.basename(path))
        return []
    return rows
