"""`--job=serve_train`: the supervised loop wiring it all together.

One process group closes serving→training→publish→serving:

1. the serving fleet answers score traffic and its engines append every
   successfully-answered row to the replay log (``replay.ReplayWriter``
   as the engines' ``replay_sink``);
2. the tailer feeds sealed segments through the ledger exactly-once
   into ``trainer.train`` (the streaming pass — the trainer's existing
   commit-after-durable-checkpoint coupling does the rest);
3. the publisher merges + hot-swaps on a batch cadence, divergence
   sentry upstream, rollback downstream.

``run()`` blocks in ``trainer.train`` until the stream ends; ``stop()``
(any thread — typically the traffic driver finishing, or a signal
handler) seals the replay tail and closes the stream, letting the
reader drain to "end" so the trainer unwinds through its normal
end-of-pass commit. A ``ChaosKilled`` mid-loop unwinds like a process
death: re-build the loop over the same directories and ``run()``
resumes exactly-once from the checkpoint + ledger
(``auto_resume=True`` → ``resume_lease`` reconciliation).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from paddle_tpu.trainer import events as _ev
from paddle_tpu.utils.log import get_logger

logger = get_logger("online.loop")


@dataclasses.dataclass
class OnlineLoopConfig:
    """The serve_train flag surface (``docs/flag_absorption.md`` rows
    X3–X5; ``docs/online_learning.md`` has the full table)."""
    replay_dir: str
    model_dir: str
    publish_every: int = 50        # --publish_every (batches)
    segment_records: int = 200     # --replay_segment_records
    batch_rows: int = 100          # train batch assembled per segment read
    quantize: Optional[str] = None  # ride --quantize on publish merges
    scan_period_s: float = 0.2
    checkpoint_period_batches: Optional[int] = 20


class ServeTrainLoop:
    """Glue object: owns nothing it didn't build, stops cleanly, and
    resumes exactly-once when rebuilt over the same directories."""

    def __init__(self, trainer, *, tailer, publisher, feeder=None,
                 writer=None, checkpointer=None, health=None,
                 max_batches: Optional[int] = None, log_period: int = 0):
        self.trainer = trainer
        self.tailer = tailer
        self.publisher = publisher
        self.feeder = feeder
        self.writer = writer
        self.checkpointer = checkpointer
        self.health = health
        self.max_batches = max_batches
        self.log_period = log_period
        self.batches_trained = 0
        self._stopping = False

    # ----------------------------------------------------------- control
    def stop(self):
        """Seal the replay tail, close the stream. Idempotent; callable
        from any thread. The reader drains every already-sealed segment
        before answering "end", so nothing durable is dropped."""
        if self._stopping:
            return
        self._stopping = True
        if self.writer is not None:
            self.writer.seal()
        self.tailer.end_stream()

    # -------------------------------------------------------------- run
    def _handle(self, event):
        if isinstance(event, _ev.EndIteration):
            self.batches_trained += 1
            self.publisher.on_batch()
            if (self.max_batches is not None and not self._stopping
                    and self.batches_trained >= self.max_batches):
                logger.info("serve_train: max_batches=%d reached, "
                            "closing the stream", self.max_batches)
                self.stop()

    def run(self):
        """Block until the stream ends (``stop()``, or ``max_batches``).
        Returns the trainer (its params now hold the stream)."""
        self.tailer.start()
        try:
            self.trainer.train(
                self.tailer.reader, feeder=self.feeder, num_passes=1,
                event_handler=self._handle,
                checkpointer=self.checkpointer, health=self.health,
                log_period=self.log_period)
        finally:
            self.tailer.close()
        return self.trainer
