"""The exactly-once replay tailer: sealed segments become ledger tasks.

The r11 master already owns every guarantee the online loop needs —
lease/commit-after-durable-checkpoint, crash-resume reconciliation,
idempotent finishes (``tests/test_exact_resume_matrix.py`` pins them).
What a STREAM adds is only that the task list grows while training:
``MasterService.extend_dataset`` over an open stream, fed by a scanner
thread watching the replay directory for newly-sealed segments. One
segment = one task; ``load_chunk`` reads it through
``replay.load_segment`` (whole-segment validation, quarantine + skip on
corruption) and re-batches the rows for the feeder.

Two deliberate choices:

- **In-process client.** The tailer owns its master (one process group
  is the serve_train deployment unit), so :class:`LocalMasterClient`
  satisfies ``master_reader``'s client surface by direct call — no TCP,
  no heartbeat thread (liveness renews on every ``get_task`` poll), and
  the streaming methods stay off ``RPC_METHODS``.
- **Stable trainer id.** ``MasterClient``'s default id is pid-derived;
  a resumed tailer must present the SAME id its checkpoint ledger was
  written under or ``resume_lease`` reconciles against a stranger.
  (The reader still passes ``prev_trainer_id`` from the ledger, so even
  an operator-changed id reconciles — stable is belt and braces.)

The scanner thread holds NO lock of its own: dedupe against
already-queued segments lives inside the master's RLock
(``extend_dataset``), so concurrent scans and a racing ``end_stream``
serialize there.
"""

from __future__ import annotations

import os
import threading
from typing import Any, List, Optional

from paddle_tpu.dist.master import (FileStore, MasterService, Task,
                                    master_reader)
from paddle_tpu.online.replay import load_segment, scan_segments
from paddle_tpu.utils.log import get_logger

logger = get_logger("online.tailer")


class LocalMasterClient:
    """``MasterClient``'s call surface over an in-process
    :class:`MasterService` — everything ``master_reader`` touches,
    minus sockets and the heartbeat thread."""

    def __init__(self, service: MasterService,
                 trainer_id: str = "serve_train-0"):
        self.service = service
        self.trainer_id = trainer_id

    def get_task(self, pass_id: int = 0):
        status, tdict = self.service.get_task(pass_id, self.trainer_id)
        return status, (Task.from_dict(tdict) if tdict else None)

    def task_finished(self, task_id: int,
                      defer_commit: bool = False) -> bool:
        return self.service.task_finished(task_id, self.trainer_id,
                                          defer_commit=defer_commit)

    def task_failed(self, task_id: int) -> bool:
        return self.service.task_failed(task_id)

    def commit_tasks(self, task_ids: Optional[List[int]] = None) -> int:
        return self.service.commit_tasks(self.trainer_id, task_ids)

    def current_pass(self) -> int:
        return self.service.current_pass()

    def resume_lease(self, pass_id: int, done_ids: List[int],
                     inflight_id: Optional[int] = None,
                     prev_trainer_id: Optional[str] = None) -> dict:
        return self.service.resume_lease(self.trainer_id, pass_id,
                                         done_ids, inflight_id,
                                         prev_trainer_id)

    def release_lease(self) -> int:
        return self.service.release_lease(self.trainer_id)

    def heartbeat(self) -> bool:
        return self.service.heartbeat(self.trainer_id)

    def close(self):
        pass


class ReplayTailer:
    """Watch a replay directory; feed its sealed segments through the
    ledger exactly-once.

    ``tailer.reader`` is a ``master_reader`` — hand it straight to
    ``trainer.train`` and the commit protocol couples to the
    checkpointer automatically (commit-after-durable-checkpoint). Call
    :meth:`start` to begin scanning, :meth:`end_stream` to let the
    reader drain to "end" (shutdown), :meth:`close` to stop the
    scanner.
    """

    def __init__(self, replay_dir: str, *, batch_rows: int = 100,
                 scan_period_s: float = 0.2, poll_s: float = 0.05,
                 trainer_id: str = "serve_train-0",
                 ledger_path: Optional[str] = None,
                 trainer_timeout_s: float = 3600.0):
        self.replay_dir = replay_dir
        self.batch_rows = int(batch_rows)
        self.scan_period_s = float(scan_period_s)
        os.makedirs(replay_dir, exist_ok=True)
        # trainer_timeout_s is LONG on purpose: this is a single-trainer
        # loop whose liveness is the process itself — a compile pause
        # must not expire the lease and requeue uncommitted work the
        # resume path will reconcile anyway
        self.master = MasterService(
            store=FileStore(ledger_path
                            or os.path.join(replay_dir, "ledger.snap")),
            chunks_per_task=1,
            # a segment read has a side effect (quarantine renames) and
            # the stream is single-trainer: never speculate a second copy
            straggle_after_s=None,
            trainer_timeout_s=trainer_timeout_s)
        self.master.open_stream()
        self.client = LocalMasterClient(self.master, trainer_id)
        self.reader = master_reader(self.client, self._load_chunk,
                                    poll_s=poll_s, defer_commit=True)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ scan
    def scan_once(self) -> int:
        """One tail scan: every sealed segment not yet queued becomes a
        task (dedupe is the master's, under its lock)."""
        return self.master.extend_dataset(scan_segments(self.replay_dir))

    def start(self) -> "ReplayTailer":
        try:
            self.scan_once()
        except RuntimeError:
            # stream already closed (drain mode: all traffic pre-sealed
            # and end_stream called up front) — the queued tasks drain
            # without a scanner
            return self
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._scan_loop, name="replay-tail-scan",
                daemon=True)
            self._thread.start()
        return self

    def _scan_loop(self):
        while not self._stop.wait(self.scan_period_s):
            try:
                self.scan_once()
            except RuntimeError:
                return  # stream closed under us: shutdown race, done
            except OSError as e:
                logger.warning("replay tail scan failed: %r", e)

    def end_stream(self):
        """Final scan, then close the stream: the reader sees every
        sealed segment, drains, and answers "end" to the trainer."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        try:
            self.scan_once()
        except RuntimeError:
            pass
        self.master.end_stream()

    def close(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # ------------------------------------------------------------ read
    def _load_chunk(self, segment_path: str) -> List[List[Any]]:
        """One sealed segment -> a list of training batches (the
        reader's records). Row tuples JSON-round-trip as lists; the
        feeder accepts either. A quarantined segment yields NO batches
        — the task completes empty and the ledger moves on."""
        rows = load_segment(segment_path)
        return [rows[i:i + self.batch_rows]
                for i in range(0, len(rows), self.batch_rows)]
