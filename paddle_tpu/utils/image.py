"""Image preprocessing (`python/paddle/v2/image.py` + ``utils``):
resize/crop/flip/transform pipeline, numpy-only (no PIL dependency — the
bilinear resize is a small gather, fine on host for input pipelines)."""

from __future__ import annotations

import numpy as np


def _as_hwc(im: np.ndarray) -> np.ndarray:
    if im.ndim == 2:
        return im[..., None]
    return im


def resize_short(im: np.ndarray, size: int) -> np.ndarray:
    """Scale so the SHORT side equals ``size`` (aspect preserved),
    bilinear."""
    im = _as_hwc(im)
    h, w = im.shape[:2]
    if h < w:
        nh, nw = size, max(1, round(w * size / h))
    else:
        nh, nw = max(1, round(h * size / w)), size
    return resize(im, nh, nw)


def resize(im: np.ndarray, nh: int, nw: int) -> np.ndarray:
    """Bilinear resize to (nh, nw)."""
    im = _as_hwc(im).astype(np.float32)
    h, w = im.shape[:2]
    ys = np.linspace(0, h - 1, nh)
    xs = np.linspace(0, w - 1, nw)
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    fy = (ys - y0)[:, None, None]
    fx = (xs - x0)[None, :, None]
    a = im[y0][:, x0]
    b = im[y0][:, x1]
    c = im[y1][:, x0]
    d = im[y1][:, x1]
    return (a * (1 - fy) * (1 - fx) + b * (1 - fy) * fx
            + c * fy * (1 - fx) + d * fy * fx)


def center_crop(im: np.ndarray, size: int) -> np.ndarray:
    im = _as_hwc(im)
    h, w = im.shape[:2]
    y0 = max((h - size) // 2, 0)
    x0 = max((w - size) // 2, 0)
    return im[y0:y0 + size, x0:x0 + size]


def random_crop(im: np.ndarray, size: int, rng=None) -> np.ndarray:
    rng = rng or np.random
    im = _as_hwc(im)
    h, w = im.shape[:2]
    y0 = rng.randint(0, max(h - size, 0) + 1)
    x0 = rng.randint(0, max(w - size, 0) + 1)
    return im[y0:y0 + size, x0:x0 + size]


def left_right_flip(im: np.ndarray) -> np.ndarray:
    return _as_hwc(im)[:, ::-1]


def to_chw(im: np.ndarray) -> np.ndarray:
    return np.transpose(_as_hwc(im), (2, 0, 1))


def simple_transform(im: np.ndarray, resize_size: int, crop_size: int,
                     is_train: bool, mean=None, rng=None) -> np.ndarray:
    """The reference's train/test transform: resize-short, (random|center)
    crop, random flip in training, optional mean subtraction, CHW."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, rng)
        if (rng or np.random).rand() > 0.5:
            im = left_right_flip(im)
    else:
        im = center_crop(im, crop_size)
    im = to_chw(im).astype(np.float32)
    if mean is not None:
        im = im - np.asarray(mean, np.float32).reshape(-1, 1, 1)
    return im
