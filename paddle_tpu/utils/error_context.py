"""Layer-stack error context.

Equivalent of ``CustomStackTrace<std::string>`` (``paddle/utils/
CustomStackTrace.{h,cpp}``): the reference pushes/pops layer names around
each layer's forward/backward so a CHECK failure prints the offending layer
chain (``NeuralNetwork.cpp:244-252``). Here the graph executor pushes layer
names while *tracing*; a Python exception raised inside a layer impl is
re-raised wrapped with the active chain. Inside the compiled program the
same names appear as ``jax.named_scope`` annotations in the XLA HLO, so
device-side failures (nan-checker, OOM) also carry layer names.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import List

import jax

_tls = threading.local()


def current_layer_stack() -> List[str]:
    return list(getattr(_tls, "stack", []))


class LayerStackError(RuntimeError):
    """Wraps an exception raised while executing a layer, carrying the
    forward chain that led there."""

    def __init__(self, chain: List[str], original: BaseException):
        self.chain = chain
        self.original = original
        super().__init__(
            f"error in layer {chain[-1]!r} (forward chain: "
            f"{' -> '.join(chain)}): {type(original).__name__}: {original}")


@contextmanager
def layer_scope(name: str):
    """Push a layer name for error reporting AND annotate the traced ops
    with a named scope (so the profiler/HLO shows per-layer attribution)."""
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(name)
    try:
        with jax.named_scope(name):
            yield
    except LayerStackError:
        raise
    except Exception as e:  # noqa: BLE001 - deliberately broad, re-raised
        raise LayerStackError(list(stack), e) from e
    finally:
        stack.pop()
