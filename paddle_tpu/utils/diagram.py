"""Model topology diagram (`python/paddle/utils/make_model_diagram.py`):
emit a graphviz dot description of a ModelDef (render with ``dot`` if
installed; the dot text itself is the artifact)."""

from __future__ import annotations

from paddle_tpu.config.model_config import ModelDef


def make_diagram(model: ModelDef, out_path: str = None) -> str:
    lines = ["digraph model {", "  rankdir=BT;",
             '  node [shape=box, fontsize=10];']
    for name, ld in model.layers.items():
        shape = "ellipse" if ld.type == "data" else "box"
        size = f"\\n[{ld.size}]" if ld.size else ""
        lines.append(
            f'  "{name}" [label="{name}\\n{ld.type}{size}", shape={shape}];')
    for name, ld in model.layers.items():
        for inp in ld.inputs:
            lines.append(f'  "{inp.layer_name}" -> "{name}";')
    for out in model.output_layer_names:
        lines.append(f'  "{out}" [style=bold, color=red];')
    lines.append("}")
    dot = "\n".join(lines)
    if out_path:
        with open(out_path, "w") as f:
            f.write(dot)
    return dot


def make_diagram_from_config(config_path: str, out_path: str = None) -> str:
    from paddle_tpu.compat import parse_config
    return make_diagram(parse_config(config_path).model, out_path)
