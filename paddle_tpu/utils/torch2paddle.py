"""Convert PyTorch parameters to paddle model files.

The reference's ``python/paddle/utils/torch2paddle.py`` converts
lua-torch ``.t7`` files into v1 binary parameter files (one
``_<layer>.w0`` / ``_<layer>.wbias`` per layer); the modern counterpart
converts a PyTorch ``state_dict`` (``torch.save``'d) the same way,
writing the reference's ``Parameter::save`` binary format so the result
loads through ``--init_model_path`` / ``compat.param_format``.

Layout note: torch ``nn.Linear`` stores ``weight[out, in]``; the engine's
fc weights are ``[in, out]`` (``_<layer>.w0``), so 2-D weights are
transposed on the way through. 4-D conv weights ``[out, in, kh, kw]``
become the engine's ``[kh, kw, in, out]`` (HWIO).

Usage:
    python -m paddle_tpu.utils.torch2paddle \
        -i model.pt -l layers.txt -o path/to/paddle_model

``layers.txt`` lists one target layer name per line, consumed in order
against the state_dict's (weight, bias) pairs — the reference's
contract.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Dict, List

import numpy as np


def _to_engine_layout(arr: np.ndarray) -> np.ndarray:
    a = np.asarray(arr, np.float32)
    if a.ndim == 2:
        return a.T                      # [out, in] -> [in, out]
    if a.ndim == 4:
        return a.transpose(2, 3, 1, 0)  # OIHW -> HWIO
    return a


def convert_state_dict(state_dict, layers: List[str]
                       ) -> Dict[str, np.ndarray]:
    """(ordered) torch state_dict + layer names -> {param file name:
    value}. Tensors pair up as (weight, bias) per layer, like the
    reference's ``params[i*2] / params[i*2+1]``; a layer without a bias
    (its next tensor is another weight, ndim > 1) gets only ``w0``."""
    tensors = [(k, v) for k, v in state_dict.items()]
    out: Dict[str, np.ndarray] = {}
    i = 0
    for layer in layers:
        if i >= len(tensors):
            raise ValueError(f"state_dict ran out of tensors at {layer!r}")
        key, w = tensors[i]
        i += 1
        out[f"_{layer}.w0"] = _to_engine_layout(_np(w))
        if i < len(tensors) and _np(tensors[i][1]).ndim == 1:
            out[f"_{layer}.wbias"] = _np(tensors[i][1])
            i += 1
    if i != len(tensors):
        raise ValueError(
            f"{len(tensors) - i} tensors left over after {len(layers)} "
            "layers — the layer list does not match the state_dict")
    return out


def _np(t) -> np.ndarray:
    if hasattr(t, "detach"):
        t = t.detach().cpu().numpy()
    return np.asarray(t, np.float32)


def save_net_parameters(layers: List[str], state_dict, output_path: str):
    from paddle_tpu.compat.param_format import save_v1_param
    os.makedirs(output_path, exist_ok=True)
    for name, value in convert_state_dict(state_dict, layers).items():
        save_v1_param(os.path.join(output_path, name), value)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Convert PyTorch parameters to paddle model files.")
    p.add_argument("-i", "--input", required=True,
                   help="torch.save'd state_dict (or module) file")
    p.add_argument("-l", "--layers", required=True,
                   help="text file with one target layer name per line")
    p.add_argument("-o", "--output", required=True,
                   help="output model directory")
    args = p.parse_args(argv)

    import torch
    obj = torch.load(args.input, map_location="cpu", weights_only=False)
    state_dict = obj.state_dict() if hasattr(obj, "state_dict") else obj
    with open(args.layers) as f:
        layers = [line.strip() for line in f if line.strip()]
    save_net_parameters(layers, state_dict, args.output)
    print(f"wrote {len(layers)} layers to {args.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
