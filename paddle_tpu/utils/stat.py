"""Wall-time stat registry.

Equivalent of the reference's ``REGISTER_TIMER*`` macros and ``globalStat``
(``paddle/utils/Stat.h:114-277``): named timers accumulate count/total/max/min
into a process-global registry; the trainer dumps and resets them every
``log_period`` batches (``Trainer.cpp:443-451``). Differences by design:

- timers are context managers / decorators, not RAII macros;
- they measure *host-side* scopes (feed conversion, step dispatch, eval);
  inside a jitted program XLA fuses layers, so the reference's per-layer
  forward/backward timers (``NeuralNetwork.cpp:248``) map to the jax
  profiler trace instead (see ``profiler.py``).
"""

from __future__ import annotations

import functools
import threading
import time
from contextlib import contextmanager
from typing import Dict, Optional


class Stat:
    """One named accumulator: count, total seconds, max, min."""

    __slots__ = ("name", "count", "total", "max", "min", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self.reset()

    def reset(self):
        self.count = 0
        self.total = 0.0
        self.max = 0.0
        self.min = float("inf")

    def add(self, seconds: float):
        with self._lock:
            self.count += 1
            self.total += seconds
            if seconds > self.max:
                self.max = seconds
            if seconds < self.min:
                self.min = seconds

    @property
    def avg(self) -> float:
        return self.total / self.count if self.count else 0.0

    def __repr__(self):
        return (f"Stat({self.name}: count={self.count} "
                f"total={self.total * 1e3:.3f}ms avg={self.avg * 1e3:.3f}ms "
                f"max={self.max * 1e3:.3f}ms)")


class StatRegistry:
    """Registry of named Stats (the ``StatSet`` of ``Stat.h:137``)."""

    def __init__(self, name: str = "global"):
        self.name = name
        self._stats: Dict[str, Stat] = {}
        self._lock = threading.Lock()
        self.enabled = True  # -DPADDLE_DISABLE_TIMER equivalent

    def get(self, name: str) -> Stat:
        with self._lock:
            s = self._stats.get(name)
            if s is None:
                s = self._stats[name] = Stat(name)
            return s

    def reset(self):
        with self._lock:
            for s in self._stats.values():
                with s._lock:
                    s.reset()

    def stats(self) -> Dict[str, Stat]:
        with self._lock:
            return dict(self._stats)

    def status(self, reset: bool = False) -> str:
        """Formatted dump, the ``printAllStatus`` of the reference. Reads
        (and the optional reset) take each Stat's lock so a concurrent
        ``add`` from a data-loader thread can't produce a torn window."""
        lines = [f"======= StatSet: [{self.name}] status ======"]
        with self._lock:
            snapshot = dict(self._stats)
        for name in sorted(snapshot):
            s = snapshot[name]
            with s._lock:
                count, total, smax, smin, avg = (s.count, s.total, s.max,
                                                 s.min, s.avg)
                if reset:
                    s.reset()
            if count == 0:
                continue
            lines.append(
                f"  {name:<32} count={count:<8} "
                f"total={total * 1e3:10.3f}ms avg={avg * 1e3:9.3f}ms "
                f"max={smax * 1e3:9.3f}ms min={smin * 1e3:9.3f}ms")
        return "\n".join(lines)


global_stat = StatRegistry()


@contextmanager
def timer(name: str, registry: Optional[StatRegistry] = None):
    """``with timer("forwardBackward"): ...`` — REGISTER_TIMER_INFO."""
    reg = registry or global_stat
    if not reg.enabled:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        reg.get(name).add(time.perf_counter() - t0)


def timer_guard(name: str, registry: Optional[StatRegistry] = None):
    """Decorator form for whole functions."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with timer(name, registry):
                return fn(*args, **kwargs)
        return wrapped
    return deco
