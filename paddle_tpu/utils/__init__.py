"""Host-side utilities: timers, logging, error context, profiling.

TPU-native successor of ``paddle/utils`` (``Stat.h`` timer registry, glog
``Logging.h``, ``CustomStackTrace`` layer-chain error reporting) — the parts
that stay host-side in a JAX framework. Device-side timing is the jax
profiler (``profiler.py``), because under XLA individual layers fuse and
per-layer host timers would measure nothing.
"""

from paddle_tpu.utils.stat import (Stat, StatRegistry, global_stat, timer,
                                   timer_guard)
from paddle_tpu.utils.log import get_logger, logger
from paddle_tpu.utils.error_context import (current_layer_stack, layer_scope,
                                            LayerStackError)
from paddle_tpu.utils.profiler import StepBreakdown, profiler_trace

__all__ = [
    "Stat", "StatRegistry", "global_stat", "timer", "timer_guard",
    "get_logger", "logger",
    "current_layer_stack", "layer_scope", "LayerStackError",
    "profiler_trace", "StepBreakdown",
]
