"""Training-curve plotting (`python/paddle/v2/plot/plot.py`): ``Ploter``
accumulates (step, value) series and renders via matplotlib when present
(notebooks); headless environments still accumulate and can ``save()``
or read ``.series`` directly."""

from __future__ import annotations

from typing import Dict, List, Tuple


class Ploter:
    def __init__(self, *titles: str):
        self.titles = list(titles)
        self.series: Dict[str, List[Tuple[float, float]]] = {
            t: [] for t in titles}

    def append(self, title: str, step: float, value: float):
        if title not in self.series:
            raise KeyError(f"unknown series {title!r}; have {self.titles}")
        self.series[title].append((float(step), float(value)))

    def _plt(self):
        try:
            import matplotlib
            matplotlib.use("Agg")
            import matplotlib.pyplot as plt
            return plt
        except Exception:  # noqa: BLE001 — matplotlib genuinely optional
            return None

    def plot(self, path: str = None):
        plt = self._plt()
        if plt is None:
            return  # headless/minimal env: data stays in .series
        plt.figure()
        for t in self.titles:
            if self.series[t]:
                xs, ys = zip(*self.series[t])
                plt.plot(xs, ys, label=t)
        plt.legend()
        if path:
            plt.savefig(path)
        plt.close()

    save = plot

    def reset(self):
        for t in self.titles:
            self.series[t].clear()
