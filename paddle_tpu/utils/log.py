"""glog-style logging (``paddle/utils/Logging.h``).

One shared logger with the glog line format
``I0729 12:00:00.123456 module.py:42] message``; unbuffered like the
reference's trainer main (``TrainerMain.cpp:34``).
"""

from __future__ import annotations

import logging
import sys

_FMT = ("%(levelname).1s%(asctime)s.%(msecs)03d "
        "%(filename)s:%(lineno)d] %(message)s")
_DATEFMT = "%m%d %H:%M:%S"

_configured = False


def _configure():
    global _configured
    if _configured:
        return
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(logging.Formatter(_FMT, datefmt=_DATEFMT))
    root = logging.getLogger("paddle_tpu")
    root.addHandler(handler)
    root.setLevel(logging.INFO)
    root.propagate = False
    _configured = True


def get_logger(name: str = "paddle_tpu") -> logging.Logger:
    _configure()
    if name == "paddle_tpu" or name.startswith("paddle_tpu."):
        return logging.getLogger(name)
    return logging.getLogger("paddle_tpu." + name)


logger = get_logger()
