"""glog-style logging (``paddle/utils/Logging.h``) + structured mode.

One shared logger with the glog line format
``I0729 12:00:00.123456 module.py:42] message``; unbuffered like the
reference's trainer main (``TrainerMain.cpp:34``).

Structured (JSONL) mode — ``PADDLE_TPU_LOG_JSON=1`` or
:func:`enable_structured` — emits one JSON object per record
(``{ts, level, logger, src, msg, event?, fields?, trace_id?,
span_id?}``), stamping the ACTIVE trace context
(``paddle_tpu/obs/trace.py``) into every record so a grep for one
trace_id pulls a request's log lines across the fleet's processes.

:func:`event` is the taggable-event helper the router / supervisor
failover paths use instead of ad-hoc f-string warnings: one call logs
a structured record (``event`` + machine-readable ``fields``) AND
records the same event into the flight recorder when one is armed
(``paddle_tpu/obs/flight.py``) — the log line is for humans tailing a
process, the flight event is for the merged postmortem timeline.
"""

from __future__ import annotations

import json
import logging
import os
import sys

_FMT = ("%(levelname).1s%(asctime)s.%(msecs)03d "
        "%(filename)s:%(lineno)d] %(message)s")
_DATEFMT = "%m%d %H:%M:%S"

ENV_JSON = "PADDLE_TPU_LOG_JSON"

_configured = False
_handler: logging.Handler = None


class _StructuredFormatter(logging.Formatter):
    """One JSON object per record; trace ids stamped when a trace
    context is active on the emitting thread."""

    def format(self, record: logging.LogRecord) -> str:
        out = {"ts": round(record.created, 6),
               "level": record.levelname,
               "logger": record.name,
               "src": f"{record.filename}:{record.lineno}",
               "msg": record.getMessage()}
        ev = getattr(record, "event", None)
        if ev:
            out["event"] = ev
        fields = getattr(record, "fields", None)
        if fields:
            out["fields"] = fields
        try:
            from paddle_tpu.obs import trace as _trace
            ctx = _trace.current()
            if ctx is not None:
                out["trace_id"] = ctx.trace_id
                out["span_id"] = ctx.span_id
        except Exception:  # noqa: BLE001 — logging must never raise
            pass
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        try:
            return json.dumps(out)
        except (TypeError, ValueError):
            out["fields"] = repr(fields)
            return json.dumps(out)


def _configure():
    global _configured, _handler
    if _configured:
        return
    _handler = logging.StreamHandler(sys.stderr)
    if os.environ.get(ENV_JSON, "").lower() in ("1", "true", "on"):
        _handler.setFormatter(_StructuredFormatter())
    else:
        _handler.setFormatter(logging.Formatter(_FMT, datefmt=_DATEFMT))
    root = logging.getLogger("paddle_tpu")
    root.addHandler(_handler)
    root.setLevel(logging.INFO)
    root.propagate = False
    _configured = True


def enable_structured():
    """Flip the shared handler to JSONL records (idempotent)."""
    _configure()
    _handler.setFormatter(_StructuredFormatter())


def disable_structured():
    """Back to the glog line format (tests restore state with this)."""
    _configure()
    _handler.setFormatter(logging.Formatter(_FMT, datefmt=_DATEFMT))


def get_logger(name: str = "paddle_tpu") -> logging.Logger:
    _configure()
    if name == "paddle_tpu" or name.startswith("paddle_tpu."):
        return logging.getLogger(name)
    return logging.getLogger("paddle_tpu." + name)


def event(log: logging.Logger, name: str, msg: str, *args,
          level: int = logging.WARNING, **fields):
    """A taggable structured event: ``event(logger, "breaker_open",
    "breaker opened for %s", rid, replica=rid)``. In structured mode
    the record carries ``event`` + ``fields`` (+ active trace ids); in
    glog mode the same human line prints. When a flight recorder is
    armed the event also lands in the ring, so failover paths feed the
    postmortem timeline with the exact call that warned the operator.

    Call OUTSIDE any lock hold: the log handler serializes on the
    logging module's own lock."""
    log.log(level, msg, *args,
            extra={"event": name, "fields": fields or None})
    from paddle_tpu.obs import flight as _flight
    if _flight._ACTIVE is not None:
        _flight._ACTIVE.record(name, **fields)


logger = get_logger()
