"""Device profiling bracket + host-side step-time breakdown.

The reference brackets regions with ``hl_profiler_start/end`` +
``GpuProfiler`` (``paddle/utils/Stat.h:282-300``, ``WITH_PROFILER``); the
TPU-native equivalent is a jax profiler trace: every op inside the bracket
lands in a TensorBoard-loadable trace with the per-layer ``named_scope``
annotations from the graph executor.

:class:`StepBreakdown` is the coarse host-side complement: per-step wall
time split into {data-wait, h2d, compute, callback} so the first-order
utilization question — is the chip waiting on the host? — is answerable
without a trace. The trainer feeds it (``--show_step_breakdown``), the
bench emits its summary as the off-tunnel input-pipeline metric.
"""

from __future__ import annotations

import time
from contextlib import contextmanager

import jax

from paddle_tpu.utils.stat import StatRegistry, global_stat


@contextmanager
def profiler_trace(log_dir: str):
    """``with profiler_trace("/tmp/trace"): step()`` — the
    ``REGISTER_GPU_PROFILER`` bracket."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def _leaf_device_bytes(leaf) -> int:
    """Bytes ONE device holds for an array: the shard size under its
    NamedSharding (a replicated array costs full size per device; a
    ZeRO-1 slot or model-sharded table costs 1/N)."""
    shape = getattr(leaf, "shape", None)
    if shape is None:
        return 0
    sharding = getattr(leaf, "sharding", None)
    if sharding is not None and hasattr(sharding, "shard_shape"):
        try:
            shape = sharding.shard_shape(tuple(shape))
        except (TypeError, ValueError):
            pass
    dtype = getattr(leaf, "dtype", None)
    if dtype is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n * dtype.itemsize


def tree_device_bytes(tree) -> int:
    """Per-device bytes of a pytree of (possibly sharded) arrays."""
    return sum(_leaf_device_bytes(x)
               for x in jax.tree_util.tree_leaves(tree))


def device_peak_bytes():
    """Device-reported peak allocation (TPU/GPU ``memory_stats``).

    Returns ``None`` — NOT 0 — on backends that don't expose the
    counter (XLA:CPU among them, so every off-tunnel run): ``None``
    means "unmeasured", and treating it as 0 would make a CPU dryrun
    look like it fits any admission budget. Callers must branch on
    ``is None`` (``memory_stats`` omits the key entirely in that
    case)."""
    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:  # noqa: BLE001 — absent on some backends
        return None
    if not stats:
        return None
    return stats.get("peak_bytes_in_use")


def memory_stats(params, opt_state=None, activations=None,
                 temp_estimator=None, gather_peak=None) -> dict:
    """Per-device memory accounting for the training state. The
    bench's ``--zero1`` A/B, ``--show_step_breakdown``, and graftlint
    pass 5 (PT605 reconciles the compiled manifest against this exact
    accounting) all read it, so the return schema is a contract:

    - ``param_bytes_per_device`` (always) — parameter bytes one
      device holds under the leaves' shardings (an FSDP run's packed
      ``(N, chunk)`` leaves carry ``P(fsdp)``, so the ~1/N drop reads
      straight off the real placement — no special case).
    - ``slot_bytes_per_device`` (when ``opt_state`` is a dict) —
      optimizer-slot bytes (``opt_state["slots"]``; the quantity
      ZeRO-1 divides by the data-parallel degree).
    - ``avg_bytes_per_device`` (when ``opt_state`` carries ``avg``) —
      model-averaging shadow bytes.
    - ``act_bytes_per_device`` (when ``activations`` is given) —
      bytes of a representative input batch / activation pytree, the
      live-input side of the serving admission number.
    - ``temp_bytes_per_device`` (when ``temp_estimator`` is given and
      returns a number) — XLA scratch estimate for the compiled step;
      pass e.g. ``lambda: compiled.memory_analysis()
      .temp_size_in_bytes`` so admission can account scratch without
      this module importing the executable.
    - ``device_peak_bytes`` (only when the backend reports one) — the
      device's peak allocation; ABSENT on XLA:CPU (see
      ``device_peak_bytes`` — None/absent means unmeasured, never 0).
    - ``gathered_peak_bytes_per_device`` (when ``gather_peak`` is
      given) — the FSDP transient gathered-buffer peak: ONE layer's
      full parameter under the sync gather spelling, the largest
      adjacent schedule PAIR under overlap (two layers live while the
      next gather flies behind the current compute) — pass
      ``FsdpUpdater.gather_peak_bytes()`` so this report and the
      compiled truth agree under ``--fsdp_overlap``.
    """
    out = {"param_bytes_per_device": tree_device_bytes(params)}
    if opt_state is not None and isinstance(opt_state, dict):
        out["slot_bytes_per_device"] = tree_device_bytes(
            opt_state.get("slots", {}))
        if "avg" in opt_state:
            out["avg_bytes_per_device"] = tree_device_bytes(opt_state["avg"])
    if activations is not None:
        out["act_bytes_per_device"] = tree_device_bytes(activations)
    if temp_estimator is not None:
        temp = temp_estimator()
        if temp is not None:
            out["temp_bytes_per_device"] = int(temp)
    if gather_peak is not None:
        out["gathered_peak_bytes_per_device"] = int(gather_peak)
    peak = device_peak_bytes()
    if peak is not None:
        out["device_peak_bytes"] = int(peak)
    return out


def _fmt_bytes(v: int) -> str:
    return f"{v / 1e6:.2f}MB" if v >= 1e5 else f"{v / 1e3:.2f}KB"


def memory_status(params, opt_state=None, gather_peak=None) -> str:
    s = memory_stats(params, opt_state, gather_peak=gather_peak)
    parts = " ".join(f"{k.replace('_bytes_per_device', '')}="
                     f"{_fmt_bytes(v)}" for k, v in s.items()
                     if k.endswith("_bytes_per_device"))
    if "device_peak_bytes" in s:
        parts += f" peak={_fmt_bytes(s['device_peak_bytes'])}"
    return f"DeviceMemory(per-device): {parts}"


def pipeline_bubble_stats(n_stages: int, n_microbatches: int) -> dict:
    """GPipe schedule occupancy accounting (``parallel/pipeline.py``).

    The fill-drain schedule runs ``S + M - 1`` ticks; stage ``s`` computes
    a real microbatch on M of them and idles ``s`` ticks while the pipe
    fills plus ``S - 1 - s`` while it drains — so every stage idles
    exactly ``S - 1`` microbatch slots of the ``S + M - 1`` total, and the
    per-stage bubble fraction (idle slots / total slots) is the classic
    ``(S-1)/(S+M-1)``, uniform across stages. The backward pipeline
    (``jax.grad`` of the scan) replays the drain in reverse, doubling both
    numerator and denominator — the fraction is unchanged, which is why
    one number serves the whole step."""
    S, M = int(n_stages), int(n_microbatches)
    ticks = S + M - 1
    # per-stage idle is s (fill) + S-1-s (drain) = S-1 for EVERY stage:
    # the per-stage list is uniform by construction, kept as a list so
    # bench consumers get one entry per stage
    per_stage = [(S - 1) / ticks] * S
    return {
        "pipeline_stages": S,
        "pipeline_microbatches": M,
        "pipeline_ticks": ticks,
        "pipeline_bubble_frac": (S - 1) / ticks,
        "pipeline_bubble_frac_per_stage": per_stage,
    }


def fsdp_overlap_stats(n_gathers: int, overlap: bool) -> dict:
    """FSDP exposed-communication accounting (``optim/zero1.py:
    FsdpUpdater``), the collective-plane analogue of
    ``pipeline_bubble_stats``.

    The step issues one all-gather per planned parameter on the forward
    and one reduce-scatter (the gather's transpose) on the backward —
    ``2L`` collectives for ``L = n_gathers``. Under the sync spelling
    every one of them sits exposed on the critical path. Under the
    double-buffer chain (``full_params`` overlap spelling) gather k+1
    flies behind layer k's compute and reduce-scatter k-1 behind layer
    k's backward, so only the FIRST forward gather (nothing to hide it
    behind) and the LAST backward reduce-scatter (its producer is the
    final backward op) stay exposed — 2 of 2L, the double-buffering
    steady state. Analytic by construction, like the pipeline bubble:
    the 1-core CPU host can't measure real collective/compute overlap,
    and on TPU the schedule, not the wall clock, is the contract."""
    L = int(n_gathers)
    exposed = (2 if L else 0) if overlap else 2 * L
    return {
        "fsdp_gathers_per_step": L,
        "fsdp_overlap": bool(overlap),
        "fsdp_exposed_collectives": exposed,
        "fsdp_exposed_comm_frac": (exposed / (2 * L)) if L else 0.0,
    }


class StepBreakdown:
    """Per-step host-side wall-time split.

    Parts:

    - ``data_wait`` — blocked pulling the next batch (the reader's own
      cost when synchronous; queue-wait when the async pipeline runs —
      near zero once prefetch keeps up).
    - ``h2d``      — feed conversion + device placement done on the
      trainer thread (``prepareBatchData``); with prefetch on this moves
      into the worker (``prefetch/decode`` / ``prefetch/h2d`` stats) and
      the trainer-side number collapses.
    - ``compute``  — step dispatch through the device fetch
      (``block_until_ready``-equivalent: a host read of the cost).
    - ``callback`` — host evaluators, event handlers, periodic logging.

    Every ``add`` also lands in the stat registry (``step/<part>``) so
    the existing ``log_period`` dump shows the same numbers. ``summary``
    yields the bench metrics: ``steps_per_sec`` and ``data_wait_frac``.
    """

    PARTS = ("data_wait", "h2d", "compute", "callback")

    def __init__(self, registry: StatRegistry = None):
        self.registry = registry or global_stat
        self.reset()

    def reset(self):
        self.steps = 0
        self.wall = 0.0  # true per-step wall time, when the caller times it
        self.totals = {p: 0.0 for p in self.PARTS}
        # most recent single measurement per part: the health plane's
        # per-step timeline reads {data_wait, compute} from here
        # without having to delta the cumulative totals
        self.last = {p: 0.0 for p in self.PARTS}
        # set by SGD.enable_pipeline; reset() survives it (a pass reset
        # must not silently drop the schedule identity from summaries)
        if not hasattr(self, "pipeline"):
            self.pipeline = None
        # set by SGD.enable_fsdp; survives reset() like the pipeline
        if not hasattr(self, "fsdp"):
            self.fsdp = None

    def set_pipeline(self, n_stages: int, n_microbatches: int):
        """Record the active GPipe schedule so ``summary()`` carries the
        bubble-fraction estimate next to steps/s (None disables)."""
        self.pipeline = ((int(n_stages), int(n_microbatches))
                         if n_stages else None)

    def set_fsdp(self, n_gathers: int, overlap: bool):
        """Record the active FSDP gather plan so ``summary()`` carries
        the exposed-comm estimate (``fsdp_overlap_stats``) next to
        steps/s (0 gathers disables)."""
        self.fsdp = ((int(n_gathers), bool(overlap))
                     if n_gathers else None)

    def add(self, part: str, seconds: float):
        self.totals[part] += seconds
        self.last[part] = seconds
        self.registry.get(f"step/{part}").add(seconds)

    @contextmanager
    def measure(self, part: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(part, time.perf_counter() - t0)

    def step_done(self, wall_seconds: float = None):
        """Count a finished step; pass the step's true wall time so
        throughput and fractions use it as the denominator — work outside
        the four measured brackets then shows up as a shortfall from 1.0
        instead of silently inflating steps/s."""
        self.steps += 1
        if wall_seconds is not None:
            self.wall += wall_seconds

    @property
    def total(self) -> float:
        return self.wall if self.wall > 0 else sum(self.totals.values())

    def summary(self) -> dict:
        total = self.total
        out = {"steps": self.steps,
               "steps_per_sec": (self.steps / total) if total > 0 else 0.0}
        for p in self.PARTS:
            out[f"{p}_frac"] = (self.totals[p] / total) if total > 0 else 0.0
            out[f"{p}_ms_per_step"] = (
                1e3 * self.totals[p] / self.steps if self.steps else 0.0)
        if self.pipeline is not None:
            out.update(pipeline_bubble_stats(*self.pipeline))
        if self.fsdp is not None:
            out.update(fsdp_overlap_stats(*self.fsdp))
        return out

    def status(self) -> str:
        s = self.summary()
        parts = " ".join(
            f"{p}={s[f'{p}_ms_per_step']:.2f}ms({s[f'{p}_frac'] * 100:.1f}%)"
            for p in self.PARTS)
        pipe = ""
        if self.pipeline is not None:
            pipe = (f" pipeline=S{s['pipeline_stages']}/M"
                    f"{s['pipeline_microbatches']}"
                    f" bubble={s['pipeline_bubble_frac'] * 100:.1f}%")
        if self.fsdp is not None:
            pipe += (f" fsdp_gathers={s['fsdp_gathers_per_step']}"
                     f" overlap={'on' if s['fsdp_overlap'] else 'off'}"
                     f" exposed_comm="
                     f"{s['fsdp_exposed_comm_frac'] * 100:.1f}%")
        return (f"StepBreakdown: steps={self.steps} "
                f"steps/s={s['steps_per_sec']:.3f} {parts}{pipe}")
