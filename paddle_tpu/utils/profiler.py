"""Device profiling bracket + host-side step-time breakdown.

The reference brackets regions with ``hl_profiler_start/end`` +
``GpuProfiler`` (``paddle/utils/Stat.h:282-300``, ``WITH_PROFILER``); the
TPU-native equivalent is a jax profiler trace: every op inside the bracket
lands in a TensorBoard-loadable trace with the per-layer ``named_scope``
annotations from the graph executor.

:class:`StepBreakdown` is the coarse host-side complement: per-step wall
time split into {data-wait, h2d, compute, callback} so the first-order
utilization question — is the chip waiting on the host? — is answerable
without a trace. The trainer feeds it (``--show_step_breakdown``), the
bench emits its summary as the off-tunnel input-pipeline metric.
"""

from __future__ import annotations

import time
from contextlib import contextmanager

import jax

from paddle_tpu.utils.stat import StatRegistry, global_stat


@contextmanager
def profiler_trace(log_dir: str):
    """``with profiler_trace("/tmp/trace"): step()`` — the
    ``REGISTER_GPU_PROFILER`` bracket."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepBreakdown:
    """Per-step host-side wall-time split.

    Parts:

    - ``data_wait`` — blocked pulling the next batch (the reader's own
      cost when synchronous; queue-wait when the async pipeline runs —
      near zero once prefetch keeps up).
    - ``h2d``      — feed conversion + device placement done on the
      trainer thread (``prepareBatchData``); with prefetch on this moves
      into the worker (``prefetch/decode`` / ``prefetch/h2d`` stats) and
      the trainer-side number collapses.
    - ``compute``  — step dispatch through the device fetch
      (``block_until_ready``-equivalent: a host read of the cost).
    - ``callback`` — host evaluators, event handlers, periodic logging.

    Every ``add`` also lands in the stat registry (``step/<part>``) so
    the existing ``log_period`` dump shows the same numbers. ``summary``
    yields the bench metrics: ``steps_per_sec`` and ``data_wait_frac``.
    """

    PARTS = ("data_wait", "h2d", "compute", "callback")

    def __init__(self, registry: StatRegistry = None):
        self.registry = registry or global_stat
        self.reset()

    def reset(self):
        self.steps = 0
        self.wall = 0.0  # true per-step wall time, when the caller times it
        self.totals = {p: 0.0 for p in self.PARTS}

    def add(self, part: str, seconds: float):
        self.totals[part] += seconds
        self.registry.get(f"step/{part}").add(seconds)

    @contextmanager
    def measure(self, part: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(part, time.perf_counter() - t0)

    def step_done(self, wall_seconds: float = None):
        """Count a finished step; pass the step's true wall time so
        throughput and fractions use it as the denominator — work outside
        the four measured brackets then shows up as a shortfall from 1.0
        instead of silently inflating steps/s."""
        self.steps += 1
        if wall_seconds is not None:
            self.wall += wall_seconds

    @property
    def total(self) -> float:
        return self.wall if self.wall > 0 else sum(self.totals.values())

    def summary(self) -> dict:
        total = self.total
        out = {"steps": self.steps,
               "steps_per_sec": (self.steps / total) if total > 0 else 0.0}
        for p in self.PARTS:
            out[f"{p}_frac"] = (self.totals[p] / total) if total > 0 else 0.0
            out[f"{p}_ms_per_step"] = (
                1e3 * self.totals[p] / self.steps if self.steps else 0.0)
        return out

    def status(self) -> str:
        s = self.summary()
        parts = " ".join(
            f"{p}={s[f'{p}_ms_per_step']:.2f}ms({s[f'{p}_frac'] * 100:.1f}%)"
            for p in self.PARTS)
        return (f"StepBreakdown: steps={self.steps} "
                f"steps/s={s['steps_per_sec']:.3f} {parts}")
