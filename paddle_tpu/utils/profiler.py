"""Device profiling bracket.

The reference brackets regions with ``hl_profiler_start/end`` +
``GpuProfiler`` (``paddle/utils/Stat.h:282-300``, ``WITH_PROFILER``); the
TPU-native equivalent is a jax profiler trace: every op inside the bracket
lands in a TensorBoard-loadable trace with the per-layer ``named_scope``
annotations from the graph executor.
"""

from __future__ import annotations

from contextlib import contextmanager

import jax


@contextmanager
def profiler_trace(log_dir: str):
    """``with profiler_trace("/tmp/trace"): step()`` — the
    ``REGISTER_GPU_PROFILER`` bracket."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
