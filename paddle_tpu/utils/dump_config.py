"""``python -m paddle_tpu.utils.dump_config config.py [config_args]
[--binary]`` — print the TrainerConfig proto a config compiles to
(`python/paddle/utils/dump_config.py`)."""

from __future__ import annotations

import sys


def dump_config(config_path: str, config_args: str = "",
                binary: bool = False):
    from paddle_tpu.compat import parse_config
    parsed = parse_config(config_path, config_args)
    proto = parsed.trainer_proto()
    if binary:
        sys.stdout.buffer.write(proto.SerializeToString())
    else:
        print(proto)


def main(argv=None):
    args = list(argv if argv is not None else sys.argv[1:])
    binary = "--binary" in args
    if binary:
        args.remove("--binary")
    if not args:
        print("usage: dump_config <config.py> [config_args] [--binary]",
              file=sys.stderr)
        return 1
    dump_config(args[0], args[1] if len(args) > 1 else "", binary)
    return 0


if __name__ == "__main__":
    sys.exit(main())
