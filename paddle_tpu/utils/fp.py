"""FP-anomaly mode — the TPU spelling of the reference's hardware FP
exceptions (``feenableexcept(FE_INVALID | FE_DIVBYZERO | FE_OVERFLOW)``,
``TrainerMain.cpp:49``; tested by ``math/tests/test_FPException.cpp``).

On TPU there is no trap to enable; jax's debug_nans/debug_infs re-run the
offending jitted computation op-by-op when a NaN/Inf appears in an output
and raise with the responsible primitive — same failure-at-the-source
contract, compiler-style."""

from __future__ import annotations

import jax

_enabled = False


def enable_fp_anomaly(nans: bool = True, infs: bool = True):
    """Raise at the op that first produces NaN (and optionally Inf).
    Noticeable slowdown on failure paths only; fine to leave on in CI."""
    global _enabled
    jax.config.update("jax_debug_nans", bool(nans))
    jax.config.update("jax_debug_infs", bool(infs))
    _enabled = True


def disable_fp_anomaly():
    global _enabled
    jax.config.update("jax_debug_nans", False)
    jax.config.update("jax_debug_infs", False)
    _enabled = False


def fp_anomaly_enabled() -> bool:
    return _enabled
