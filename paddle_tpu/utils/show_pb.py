"""Print serialized contract protos (`python/paddle/utils/show_pb.py`).

The reference tool dumps proto-buffer data files; here the common case is
inspecting a serialized ``ModelConfig``/``TrainerConfig`` blob (e.g. the
bytes `parse_config_and_serialize` emits, or the config half of a merged
deploy model)::

    python -m paddle_tpu.utils.show_pb model.bin
"""

from __future__ import annotations

import sys


def show(path: str, out=None) -> str:
    """Parse ``path`` as TrainerConfig, falling back to ModelConfig, and
    return (and optionally print) the text format."""
    from paddle_tpu.proto import ModelConfig_pb2, TrainerConfig_pb2
    blob = open(path, "rb").read()
    last_err = None
    for cls in (TrainerConfig_pb2.TrainerConfig,
                ModelConfig_pb2.ModelConfig):
        try:
            msg = cls.FromString(blob)
        except Exception as e:  # noqa: BLE001 - try the next schema
            last_err = e
            continue
        # prefer the parse that actually consumed recognizable fields —
        # known-field presence, not ByteSize(), because python protobuf
        # retains unknown fields and counts them in ByteSize(), which
        # would accept a ModelConfig blob "parsed" into TrainerConfig
        # purely as unknown fields
        if msg.ListFields() or not blob:
            txt = f"# {cls.__name__}\n{msg}"
            if out is not None:
                print(txt, file=out)
            return txt
    raise ValueError(f"{path}: not a TrainerConfig/ModelConfig blob "
                     f"({last_err})")


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    if len(args) != 1:
        print("usage: python -m paddle_tpu.utils.show_pb <proto-file>",
              file=sys.stderr)
        return 2
    show(args[0], out=sys.stdout)
    return 0


if __name__ == "__main__":
    sys.exit(main())
