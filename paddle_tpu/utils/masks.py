"""Mask dtype invariant, enforced from both sides.

Masks in this codebase are **f32 count data**: they are summed for
token counts, per-row lengths and batch denominators, where bfloat16's
8-bit mantissa saturates at 256 — a silently wrong denominator, not an
error. The invariant is enforced three ways:

- statically: graftlint PT102 (``paddle_tpu/analysis/ast_lints.py``)
  flags source that casts a mask below f32;
- at trace time: graftlint PT203 walks the jaxpr for converts of mask
  inputs;
- at run/trace time: :func:`assert_mask_f32` here, called where masks
  enter compute (``trainer/trainer.py:_cast_compute``,
  ``serving/predictor.py``) — dtype is static under tracing, so the
  check is free inside jit and raises at trace time, before a single
  step runs with a saturating mask.
"""

from __future__ import annotations

from typing import Any, Optional


class MaskDtypeError(RuntimeError):
    """A mask tensor is not float32 (the count-data invariant).

    Deliberately NOT a TypeError/ValueError: the serving batcher's
    bad-request funnel catches those and answers clients 400 — but a
    sub-f32 mask is a SERVER bug (the feeder built it), and it must
    take the loud worker-fatal path, never be blamed on the request."""


# the invariant is "never BELOW f32": float64 (numpy's default — jax
# canonicalizes it to f32 at trace time) and int/bool masks carry full
# count precision and pass; only mantissa-losing float dtypes violate
_SUB_F32 = {"bfloat16", "float16", "half"}


def assert_mask_f32(mask: Any, where: str = "mask") -> Any:
    """Validate (and return) a mask leaf: reject sub-f32 FLOAT dtypes
    (bf16/f16 — the saturating ones). ``None`` passes through — dense
    inputs have no mask. Works on traced values: ``dtype`` is static,
    so inside jit this raises at trace time with zero runtime cost."""
    if mask is None:
        return None
    dtype = getattr(mask, "dtype", None)
    if dtype is None:
        return mask  # python scalars/lists — feeder normalizes later
    if str(dtype) in _SUB_F32:
        raise MaskDtypeError(
            f"{where}: mask dtype {dtype} — masks are f32 COUNT data "
            "(summed for lengths/denominators; bf16 saturates at 256) "
            "and must never be cast below float32. See "
            "docs/static_analysis.md (PT102/PT203).")
    return mask


def assert_feed_masks_f32(feed: Any, where: str = "feed") -> Any:
    """Validate every ``Argument.mask`` in a feed dict (recursing into
    Argument state the way ``_cast_compute`` does); returns the feed."""
    from paddle_tpu.core.argument import Argument

    def go(name: str, x):
        if isinstance(x, Argument):
            assert_mask_f32(x.mask, f"{where}[{name}].mask")
            if isinstance(x.state, dict):
                for k, v in x.state.items():
                    go(f"{name}.state[{k}]", v)
    if isinstance(feed, dict):
        for name, x in feed.items():
            go(str(name), x)
    return feed
