"""Capped jittered exponential backoff, shared by the retrying clients.

One formula in one place (``MasterClient`` re-dial, ``ServingClient``
429/connection-reset retry): attempt ``n`` waits
``min(cap, base * 2**n)`` jittered down to ``uniform(0.5, 1.0)`` of
itself, so a fleet of clients retrying one restarted server spreads out
instead of returning in lockstep.  Units (seconds vs milliseconds)
follow whatever ``base``/``cap`` are expressed in.
"""

from __future__ import annotations

import random


def jittered(value: float, rng: random.Random) -> float:
    """``value * uniform(0.5, 1.0)`` — spreads a client's OWN schedule;
    for a server-provided wait use :func:`jittered_up` (shrinking a
    drain estimate re-sends into a still-full queue)."""
    return value * (0.5 + 0.5 * rng.random())


def jittered_up(value: float, rng: random.Random) -> float:
    """``value * uniform(1.0, 1.5)`` — for server-provided waits (a 429
    ``retry_after_ms`` drain estimate): never earlier than the advertised
    horizon — an early re-send hits the still-full queue and burns a
    retry-budget slot on a fresh 429 — but spread above it so a fleet of
    shed clients does not return in lockstep."""
    return value * (1.0 + 0.5 * rng.random())


def backoff_delay(attempt: int, *, base: float, cap: float,
                  rng: random.Random) -> float:
    """Capped jittered exponential delay for retry ``attempt`` (0-based)."""
    return jittered(min(cap, base * (2 ** attempt)), rng)
