"""Parameter initialization matching reference semantics.

The reference initializes weights from ``ParameterConfig`` (``proto/
ParameterConfig.proto``): normal(initial_mean, initial_std) by default with
``initial_std = 1/sqrt(fan_in)`` filled in by the config parser
(``python/paddle/trainer/config_parser.py`` Parameter handling), uniform when
``initial_strategy=1``, constant bias init 0.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp


def default_std(shape: Sequence[int]) -> float:
    """1/sqrt(fan_in); fan_in = first dim for matrices (reference layout is
    [in, out] for fc weights), product of all-but-last for conv filters."""
    if len(shape) <= 1:
        return 1.0
    fan_in = 1
    for d in shape[:-1]:
        fan_in *= d
    return 1.0 / math.sqrt(max(fan_in, 1))


def init_param(
    key: jax.Array,
    shape: Sequence[int],
    *,
    init: str = "normal",
    initial_mean: float = 0.0,
    initial_std: Optional[float] = None,
    dtype=jnp.float32,
) -> jnp.ndarray:
    shape = tuple(shape)
    if init == "zeros" or init == "const":
        return jnp.full(shape, initial_mean, dtype=dtype)
    if initial_std is None:
        initial_std = default_std(shape)
    if init == "uniform":
        return jax.random.uniform(
            key, shape, dtype=dtype, minval=initial_mean - initial_std,
            maxval=initial_mean + initial_std)
    # default: normal
    return initial_mean + initial_std * jax.random.normal(key, shape, dtype=dtype)
