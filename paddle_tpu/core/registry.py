"""Layer registry keyed by the reference's ``LayerConfig.type`` strings.

Mirrors ``REGISTER_LAYER`` / ``Layer::create`` (``paddle/gserver/layers/
Layer.h:31,231``, ``Layer.cpp:109``): a class registrar mapping type names
("fc", "exconv", "lstmemory", ...) to implementations. Here an implementation
is a *pure-function bundle* — shape inference, parameter spec, and an apply
function differentiated by ``jax.grad`` — rather than a stateful object with
hand-written forward/backward.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp


@dataclasses.dataclass
class ShapeInfo:
    """Static shape metadata flowing through config-time shape inference
    (the reference does this in ``config_parser.py:159-177``).

    size: feature dimension (LayerConfig.size).
    channels/height/width: image geometry for conv/pool/norm layers.
    is_sequence: whether the layer emits per-timestep values.
    """

    size: int
    channels: Optional[int] = None
    height: Optional[int] = None
    width: Optional[int] = None
    is_sequence: bool = False

    def img(self) -> Tuple[int, int, int]:
        if self.channels is None:
            raise ValueError("layer input has no image geometry")
        return self.channels, self.height, self.width


@dataclasses.dataclass
class ParamSpec:
    """What to allocate for one learnable parameter.

    Mirrors ``ParameterConfig`` (``proto/ParameterConfig.proto``): shape,
    init strategy, per-parameter lr multiplier, static flag, sparsity.
    """

    shape: Tuple[int, ...]
    init: str = "normal"  # normal | uniform | zeros | const
    initial_mean: float = 0.0
    initial_std: Optional[float] = None
    is_static: bool = False
    learning_rate: float = 1.0
    is_bias: bool = False
    sparse_grad: bool = False  # embedding-style row-sparse gradients
    l1_rate: Optional[float] = None  # per-param regularizer overrides
    l2_rate: Optional[float] = None
    sparsity_ratio: Optional[float] = None  # StaticPruningHook mask
    # when set, the parameter keeps this exact global name instead of the
    # `_{layer}.{suffix}` convention — used by recurrent groups to hoist
    # sub-network parameters (shared across timesteps like the reference's
    # frame-shared weights, RecurrentGradientMachine.cpp:294-346)
    absolute_name: Optional[str] = None
    # wire-format ParameterConfig.is_sparse: emitted explicitly (even
    # when False) for layer types whose reference handler writes it
    # (selective_fc's create_input_parameter with a sparse format)
    wire_sparse: Optional[bool] = None
    # wire-format ParameterConfig.is_shared (batch-norm moving stats are
    # marked shared in the reference)
    wire_shared: Optional[bool] = None
    # wire-format dims override where the reference's recorded layout
    # differs from the physical shape (conv shared biases: [size, 1])
    wire_dims: Optional[Tuple[int, ...]] = None
    # True only when the USER requested sparse_update (ParamAttr); the
    # engine's sparse_grad default (embedding touched-rows updates) is an
    # internal optimization the reference wire format doesn't record
    user_sparse: bool = False


class LayerImpl:
    """Base for registered layer implementations. Subclasses override:

    - infer(cfg, in_infos)  -> ShapeInfo  (config-time shape inference)
    - params(cfg, in_infos) -> {suffix: ParamSpec}
    - apply(cfg, params, ins, ctx) -> Argument (pre-activation; the executor
      applies cfg.act afterwards, matching Layer::forwardActivation)
    """

    type_name: str = ""
    needs_rng: bool = False

    def infer(self, cfg, in_infos: List[ShapeInfo]) -> ShapeInfo:
        raise NotImplementedError

    def params(self, cfg, in_infos: List[ShapeInfo]) -> Dict[str, ParamSpec]:
        return {}

    def apply(self, cfg, params, ins, ctx):
        raise NotImplementedError


_LAYER_REGISTRY: Dict[str, LayerImpl] = {}


def register_layer(*type_names: str):
    """Class decorator: ``@register_layer("fc")``. Multiple aliases allowed
    (the reference registers e.g. both "exconv" and "cudnn_conv" for conv)."""

    def deco(cls):
        impl = cls()
        impl.type_name = type_names[0]
        for t in type_names:
            if t in _LAYER_REGISTRY:
                raise ValueError(f"duplicate layer type {t!r}")
            _LAYER_REGISTRY[t] = impl
        return cls

    return deco


def get_layer_impl(type_name: str) -> LayerImpl:
    if type_name not in _LAYER_REGISTRY:
        raise KeyError(
            f"unknown layer type {type_name!r}; registered: "
            f"{sorted(_LAYER_REGISTRY)}")
    return _LAYER_REGISTRY[type_name]


def registered_layer_types() -> List[str]:
    return sorted(_LAYER_REGISTRY)
