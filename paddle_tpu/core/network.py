"""Network: the proto-driven graph executor.

TPU-native replacement for ``NeuralNetwork`` (``paddle/gserver/
gradientmachines/NeuralNetwork.cpp``): where the reference walks a layer list
calling virtual ``forward``/``backward`` per layer (hot loops at ``:235`` and
``:285``), here the *whole* forward (and loss) is built as one pure function
``(params, feed, rng) -> outputs`` which is jitted once and differentiated by
``jax.grad`` — no hand-written backward, and XLA fuses across layer
boundaries instead of materializing every intermediate in HBM.
"""

from __future__ import annotations

import dataclasses
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.config.model_config import LayerDef, ModelDef, ParamAttr
from paddle_tpu.core.argument import Argument
from paddle_tpu.core.initializers import init_param
from paddle_tpu.core.registry import ParamSpec, ShapeInfo, get_layer_impl


@dataclasses.dataclass
class Context:
    """Per-apply execution context handed to layer impls."""

    train: bool = False
    rng: Optional[jax.Array] = None
    # device mesh for layers with sharded compute paths (e.g. the
    # seq_parallel attention); None = single-device semantics
    mesh: Any = None
    in_infos: List[ShapeInfo] = dataclasses.field(default_factory=list)
    out_info: Optional[ShapeInfo] = None
    outputs: Dict[str, Argument] = dataclasses.field(default_factory=dict)
    # functional side-channel for moving statistics (batch_norm): param name
    # -> new value; applied by the train step after the gradient update.
    state_updates: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # cross-batch recurrent state (--prev_batch_state truncated BPTT,
    # Trainer.cpp:396-418): layer name -> initial state for this batch
    carried: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def layer_rng(self, layer_name: str) -> jax.Array:
        if self.rng is None:
            raise ValueError("this apply needs an rng (dropout/sampling)")
        return jax.random.fold_in(self.rng, zlib.crc32(layer_name.encode()))


def _resolve_param_name(layer: LayerDef, suffix: str, spec: ParamSpec,
                        attr: Optional[ParamAttr]) -> str:
    if spec.absolute_name:
        return spec.absolute_name
    if attr is not None and attr.name:
        return attr.name
    return f"_{layer.name}.{suffix}"


def _apply_attr(spec: ParamSpec, attr: Optional[ParamAttr]) -> ParamSpec:
    if attr is None:
        return spec
    if getattr(attr, "from_defaults", False) and spec.init in ("const",
                                                               "zeros"):
        # parse-wide defaults don't override deliberate constant inits
        return spec
    # an attr carrying NO explicit init values (just lr/static/name/...)
    # keeps the layer's deliberate init — e.g. batch-norm gamma's const
    # 1.0 must survive ParamAttr(learning_rate=...) (init_explicit is set
    # by to_param_attr; raw ParamAttr objects count std as the marker)
    init_explicit = getattr(attr, "init_explicit",
                            attr.initial_std is not None
                            or attr.init != "normal")
    keep_init = (not init_explicit) and spec.init in ("const", "zeros")
    return dataclasses.replace(
        spec,
        init=spec.init if keep_init else (
            attr.init if attr.init != "normal"
            or attr.initial_std is not None else spec.init),
        initial_mean=spec.initial_mean if keep_init else attr.initial_mean,
        initial_std=attr.initial_std if attr.initial_std is not None
        else spec.initial_std,
        is_static=attr.is_static or spec.is_static,
        learning_rate=attr.learning_rate,
        sparse_grad=attr.sparse_grad or spec.sparse_grad,
        user_sparse=attr.sparse_grad or spec.user_sparse,
        l1_rate=attr.l1_rate,
        l2_rate=attr.l2_rate,
        sparsity_ratio=(attr.sparsity_ratio
                        if attr.sparsity_ratio is not None
                        else spec.sparsity_ratio),
    )


class Network:
    """Compiled view of a ModelDef: shape inference, parameter table, and a
    pure ``apply``. Construction = the work ``GradientMachine::create`` +
    config_parser shape inference do in the reference."""

    def __init__(self, model: ModelDef,
                 outputs: Optional[List[str]] = None):
        self.model = model
        self.order = model.topo_order(outputs)
        self.shape_infos: Dict[str, ShapeInfo] = {}
        # param name -> (spec, owning layer, suffix)
        self.param_specs: Dict[str, ParamSpec] = {}
        self._layer_params: Dict[str, Dict[str, str]] = {}  # layer -> suffix -> pname

        for name in self.order:
            layer = model.layers[name]
            impl = get_layer_impl(layer.type)
            in_infos = [self.shape_infos[i] for i in layer.input_names()]
            self.shape_infos[name] = impl.infer(layer, in_infos)
            specs = impl.params(layer, in_infos)
            self._layer_params[name] = {}
            for suffix, spec in specs.items():
                if spec.is_bias:
                    attr = layer.bias if isinstance(layer.bias, ParamAttr) else None
                else:
                    # weight i takes input i's param_attr
                    idx = _weight_index(suffix)
                    attr = (layer.inputs[idx].param_attr
                            if idx is not None and idx < len(layer.inputs) else None)
                pname = _resolve_param_name(layer, suffix, spec, attr)
                spec = _apply_attr(spec, attr)
                if pname in self.param_specs:
                    if self.param_specs[pname].shape != spec.shape:
                        raise ValueError(
                            f"shared parameter {pname!r} shape mismatch: "
                            f"{self.param_specs[pname].shape} vs {spec.shape}")
                else:
                    self.param_specs[pname] = spec
                self._layer_params[name][suffix] = pname

    # ------------------------------------------------------------------ init
    def init_params(self, key: jax.Array, dtype=jnp.float32,
                    shardings: Optional[Dict[str, Any]] = None
                    ) -> Dict[str, jnp.ndarray]:
        # One jitted program for the whole table: per-parameter eager init
        # would trigger hundreds of tiny XLA compilations. With shardings
        # (name -> NamedSharding), each parameter is created directly in its
        # final placement — a model-sharded embedding table never
        # materializes whole on one device.
        def _init(key):
            params = {}
            for i, (pname, spec) in enumerate(sorted(self.param_specs.items())):
                params[pname] = init_param(
                    jax.random.fold_in(key, i), spec.shape, init=spec.init,
                    initial_mean=spec.initial_mean, initial_std=spec.initial_std,
                    dtype=dtype)
            return params

        out_shardings = (
            {name: shardings[name] for name in self.param_specs}
            if shardings else None)
        # partitionable threefry ONLY for init: with the default
        # (non-partitionable) impl, jitted random values DEPEND on the
        # out_sharding, so a model-sharded table initializes to different
        # numbers than the same table replicated — breaking every
        # sharded-vs-unsharded parity claim at step 0 (observed on the
        # (dcn, data, model) mesh, tests/test_multislice.py). Scoped here
        # so existing dropout/sampling streams are untouched.
        with jax.threefry_partitionable(True):
            return jax.jit(_init, out_shardings=out_shardings)(key)

    # ----------------------------------------------------------------- apply
    def apply(self, params: Dict[str, jnp.ndarray],
              feed: Dict[str, Argument], *, train: bool = False,
              rng: Optional[jax.Array] = None,
              carried: Optional[Dict[str, Any]] = None,
              mesh: Any = None,
              ) -> Dict[str, Argument]:
        outs, _ = self.apply_with_state(params, feed, train=train, rng=rng,
                                        carried=carried, mesh=mesh)
        return outs

    def apply_with_state(
            self, params: Dict[str, jnp.ndarray],
            feed: Dict[str, Argument], *, train: bool = False,
            rng: Optional[jax.Array] = None,
            carried: Optional[Dict[str, Any]] = None,
            mesh: Any = None,
            probes: Optional[Dict[str, jnp.ndarray]] = None,
    ) -> Tuple[Dict[str, Argument], Dict[str, jnp.ndarray]]:
        """Pure forward over the whole graph. ``feed`` maps data-layer names
        to Arguments. Returns (every layer's output keyed by layer name,
        state updates for moving statistics). ``carried`` maps recurrent
        layer names to cross-batch initial state (--prev_batch_state).
        ``probes`` maps layer names to zero-valued perturbations added to
        that layer's output — differentiating the cost w.r.t. a probe
        yields d(cost)/d(layer output), the quantity the reference's
        ``gradient_printer`` evaluator prints (``Argument.grad``)."""
        ctx = Context(train=train, rng=rng, carried=carried or {},
                      mesh=mesh)
        from paddle_tpu.layers.activations import apply_activation  # cycle-free
        from paddle_tpu.utils.error_context import layer_scope

        for name in self.order:
            layer = self.model.layers[name]
            impl = get_layer_impl(layer.type)
            if layer.type == "data" or (
                    getattr(impl, "feed_slot", False) and not layer.inputs):
                # data layers and input-less agents (scatter_agent / memory
                # agents of an expanded recurrent sub-model) are fed by name
                if name not in feed:
                    what = ("data layer" if layer.type == "data"
                            else f"{layer.type} feed slot")
                    raise KeyError(f"missing feed for {what} {name!r}")
                ctx.outputs[name] = feed[name]
                continue
            ins = [ctx.outputs[i] for i in layer.input_names()]
            lparams = {s: params[p] for s, p in self._layer_params[name].items()}
            ctx.in_infos = [self.shape_infos[i] for i in layer.input_names()]
            ctx.out_info = self.shape_infos[name]
            # layer_scope = CustomStackTrace push/pop + HLO named_scope
            # (NeuralNetwork.cpp:244-252)
            with layer_scope(name):
                def compute(lp, ins_t, layer=layer, impl=impl, name=name):
                    # state updates thread through as explicit outputs so
                    # this stays pure enough for jax.checkpoint below
                    saved = ctx.state_updates
                    ctx.state_updates = {}
                    try:
                        out = impl.apply(layer, lp, ins_t, ctx)
                        if layer.act and layer.act not in ("linear", ""):
                            out = out.with_value(apply_activation(
                                layer.act, out.value, out.mask))
                        if layer.drop_rate > 0.0:
                            out = out.with_value(_dropout(
                                out.value, layer.drop_rate, ctx, name))
                        return out, ctx.state_updates
                    finally:
                        ctx.state_updates = saved

                if layer.attrs.get("recompute") and train:
                    # per-layer rematerialization: trade recompute FLOPs
                    # for activation HBM (jax.checkpoint; the TPU-native
                    # render of memory-pressure knobs). Static Python
                    # metadata in Argument.state (e.g. a nested group's
                    # shape ints) must NOT pass through checkpoint as
                    # pytree leaves — it would come back as tracers and
                    # break downstream shape arithmetic — so array leaves
                    # go through and statics rejoin outside (the cell is
                    # filled at trace time).
                    cell = {}

                    def arrays_only(lp, ins_t):
                        res = compute(lp, ins_t)
                        leaves, td = jax.tree_util.tree_flatten(res)
                        is_arr = [isinstance(v, jax.Array) for v in leaves]
                        cell["td"] = td
                        cell["static"] = [None if a else v
                                          for v, a in zip(leaves, is_arr)]
                        cell["is_arr"] = is_arr
                        return [v for v, a in zip(leaves, is_arr) if a]

                    arrs = jax.checkpoint(arrays_only)(lparams, ins)
                    it = iter(arrs)
                    leaves = [next(it) if a else s
                              for a, s in zip(cell["is_arr"],
                                              cell["static"])]
                    out, new_state = jax.tree_util.tree_unflatten(
                        cell["td"], leaves)
                else:
                    out, new_state = compute(lparams, ins)
                ctx.state_updates.update(new_state)
            if probes and name in probes:
                out = out.with_value(out.value + probes[name])
            ctx.outputs[name] = out
        return ctx.outputs, ctx.state_updates

    def param_meta(self) -> Dict[str, ParamSpec]:
        return dict(self.param_specs)


def _weight_index(suffix: str) -> Optional[int]:
    if suffix.startswith("w") and suffix[1:].isdigit():
        return int(suffix[1:])
    return None


def _dropout(x: jnp.ndarray, rate: float, ctx: Context, layer_name: str):
    """Reference-style (non-inverted) dropout: train multiplies by a 0/1
    keep mask; test scales by (1-rate). See ``Layer::forwardDropOut``
    (``paddle/gserver/layers/Layer.cpp``)."""
    if not ctx.train:
        return x * (1.0 - rate)
    keep = jax.random.bernoulli(
        ctx.layer_rng(layer_name + "/drop"), 1.0 - rate, x.shape)
    return x * keep.astype(x.dtype)
