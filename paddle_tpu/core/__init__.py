from paddle_tpu.core.argument import Argument  # noqa: F401
from paddle_tpu.core.initializers import init_param  # noqa: F401
from paddle_tpu.core.registry import (  # noqa: F401
    LayerImpl,
    ParamSpec,
    ShapeInfo,
    get_layer_impl,
    register_layer,
    registered_layer_types,
)
from paddle_tpu.core.network import Network  # noqa: F401
