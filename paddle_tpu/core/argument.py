"""Argument: the inter-layer value type.

The reference's ``Argument`` (``paddle/parameter/Argument.h:29``) carries a
dense value matrix plus ragged-sequence metadata (``sequenceStartPositions``
at ``:84``, ``subSequenceStartPositions`` at ``:90``): a batch of sequences is
a flat ``(totalTokens, dim)`` matrix with offset vectors.

On TPU, XLA wants static shapes, so the native representation is
**padded + masked**: a sequence batch is ``value[B, T, D]`` with a boolean
``mask[B, T]`` (True = real token). Non-sequence batches are ``value[B, ...]``
with ``mask=None``. Two-level nested sequences keep an extra ``sub_mask``
marking sub-sequence boundaries. Conversion helpers translate between the
offset world (Python data providers produce lists of variable-length
sequences) and the padded world at the host boundary only — on device
everything is static.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct


def check_dead(count_live, what: str) -> None:
    """Runtime guard for padded-length alignment shims.

    Length mismatches between padded inputs are benign only when every
    trimmed / zero-filled position is masked dead (feeder ``pad_multiple``
    bucketing). ``count_live`` is a traced scalar counting live positions
    that would be silently dropped or fabricated; when it is non-zero the
    mismatch is real data (the reference would CHECK-fail on misaligned
    ``sequenceStartPositions``), so fail loudly at run time via a debug
    callback — a trace-time ``raise`` cannot see traced mask values."""

    def _raise(n):
        if int(n) > 0:
            raise ValueError(
                f"{what}: {int(n)} live (unmasked) positions would be "
                "silently dropped/zero-filled by padded-length alignment; "
                "the inputs are genuinely misaligned, not just padded")

    jax.debug.callback(_raise, count_live)


@struct.dataclass
class Argument:
    """A batch flowing between layers.

    value: [B, ...] dense data; for sequence data [B, T, D] (or [B, T] for ids).
    mask:  [B, T] float32 (1.0 = real token), None for non-sequence data.
    sub_starts_mask: [B, T] float32 marking positions that begin a sub-sequence
        (nested/2-level sequences), None unless nested.
    state: optional carried recurrent state (cross-batch, --prev_batch_state).
    """

    value: jnp.ndarray
    mask: Optional[jnp.ndarray] = None
    sub_starts_mask: Optional[jnp.ndarray] = None
    state: Any = None

    # ---- helpers -----------------------------------------------------------
    @property
    def is_sequence(self) -> bool:
        return self.mask is not None

    @property
    def batch_size(self) -> int:
        return self.value.shape[0]

    def seq_lengths(self) -> jnp.ndarray:
        """[B] int32 true lengths."""
        if self.mask is None:
            raise ValueError("not a sequence Argument")
        return jnp.sum(self.mask.astype(jnp.int32), axis=1)

    def num_tokens(self) -> jnp.ndarray:
        return jnp.sum(self.mask) if self.mask is not None else self.value.shape[0]

    def with_value(self, value: jnp.ndarray) -> "Argument":
        return self.replace(value=value)


def from_ragged(sequences, dtype=np.float32, pad_to: Optional[int] = None) -> Argument:
    """Host-side: list of per-example arrays (len Ti, each [Ti, D] or [Ti])
    -> padded Argument. Mirrors how ``PyDataProvider2`` assembles ragged
    batches into (totalTokens, dim)+offsets (``paddle/gserver/dataproviders/
    PyDataProvider2.cpp``), but emits the TPU-native padded layout.
    """
    seqs = [np.asarray(s, dtype=dtype) for s in sequences]
    bsz = len(seqs)
    max_len = max((s.shape[0] for s in seqs), default=0)
    if pad_to is not None:
        max_len = max(max_len, pad_to)
    feat = seqs[0].shape[1:] if seqs and seqs[0].ndim > 1 else ()
    value = np.zeros((bsz, max_len) + feat, dtype=dtype)
    mask = np.zeros((bsz, max_len), dtype=np.float32)
    for i, s in enumerate(seqs):
        value[i, : s.shape[0]] = s
        mask[i, : s.shape[0]] = 1.0
    return Argument(value=jnp.asarray(value), mask=jnp.asarray(mask))


def to_ragged(arg: Argument) -> list:
    """Host-side inverse of :func:`from_ragged` (device -> lists)."""
    value = np.asarray(arg.value)
    if arg.mask is None:
        return [value[i] for i in range(value.shape[0])]
    lengths = np.asarray(jax.device_get(arg.seq_lengths()))
    return [value[i, : lengths[i]] for i in range(value.shape[0])]


def dense(value) -> Argument:
    return Argument(value=jnp.asarray(value))
