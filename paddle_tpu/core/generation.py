"""Sequence generation: greedy and beam search over a recurrent step net.

TPU-native ``RecurrentGradientMachine::generateSequence``
(``RecurrentGradientMachine.cpp:964``): greedy ``oneWaySearch`` (``:1042``)
is the beam_size=1 case of ``beamSearch`` (``:1393``). Where the reference
expands/prunes beams with host-side std::vector bookkeeping per step, here
the whole search is ONE jitted ``lax.scan`` with static beam and length
dims: beams live as a [B, K] axis, finished beams are frozen by masking
(-inf over non-EOS continuations), and parent-beam reordering is a gather.

The user beam-control hooks (``RecurrentGradientMachine.h:92-145``)
survive as callables traced into the step:

- ``candidate_adjust`` — ``beamSearchCandidateAdjust``: arbitrary
  adjustment of the expanded candidate log-probs before selection.
- ``drop_callback`` — ``DropCallback``: per-node drop decision over the
  expanded candidates (True = prune that (beam, token) node).
- ``norm_or_drop`` — ``NormOrDropNode``: rescoring (e.g. length
  normalization) or dropping (-inf) of a candidate at the moment it
  finishes (picks EOS).
- ``stop_beam_search`` — the ``stopBeamSearch`` flag: a predicate that
  freezes the whole search early (all beams behave as finished from the
  step it first returns True).

Hooks can be pinned in the config (``dsl.beam_search(...,
drop_callback=...)``) — the attrs are the defaults every ``generate``
call (and the serving generation endpoint) honors — or passed per call.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.argument import Argument


def _flatten_beams(x):
    return x.reshape((-1,) + x.shape[2:])


def _unflatten_beams(x, B, K):
    return x.reshape((B, K) + x.shape[1:])


class SequenceGenerator:
    """Drives a generation-mode recurrent group (``beam_search`` in the
    DSL). Mirrors the SWIG ``SequenceGenerator`` (api/PaddleAPI.h) surface:
    construct from the model + generating layer, call ``generate``."""

    def __init__(self, model, gen_layer: str):
        from paddle_tpu.layers.group import _group_subnet

        self.cfg = model.layers[gen_layer]
        if self.cfg.type != "beam_search_group":
            raise ValueError(f"{gen_layer!r} is not a beam_search group")
        self.net = _group_subnet(self.cfg)
        self.gen = self.cfg.attrs["gen"]  # GeneratedInput spec dict
        self._jitted: Dict[Any, Callable] = {}

    # ------------------------------------------------------------------
    def static_input_layers(self):
        """Outer layer names feeding the group's static/boot inputs —
        the encoder outputs ``generate`` needs in ``outer_outputs``."""
        return [inp.layer_name
                for inp, meta in zip(self.cfg.inputs, self.cfg.attrs["ins"])
                if meta["kind"] in ("static", "boot")]

    # ------------------------------------------------------------------
    def generate(self, params, outer_outputs: Dict[str, Argument], *,
                 beam_size: Optional[int] = None,
                 max_length: Optional[int] = None,
                 candidate_adjust: Optional[Callable] = None,
                 drop_callback: Optional[Callable] = None,
                 norm_or_drop: Optional[Callable] = None,
                 stop_beam_search: Optional[Callable] = None):
        """Run the search.

        params: global parameter table (sub-net params are hoisted names).
        outer_outputs: outer-layer Arguments for static/boot inputs, keyed
            by outer layer name (run your encoder Network first).

        Beam-control hooks (``RecurrentGradientMachine.h:92-145``); each
        defaults to the config attr of the same name so hooks pinned by
        ``dsl.beam_search`` apply to every call, flat or via SWIG:

        - ``candidate_adjust(logp [B*K, V], state) -> logp``
        - ``drop_callback(state, total [B, K, V]) -> bool [B, K, V]``
          (True = drop that expanded node; the forced-EOS continuation
          of an already-finished beam is exempt — its frozen score must
          carry)
        - ``norm_or_drop(eos_scores [B, K], length) -> [B, K]`` applied
          to candidates finishing at this step (``length`` counts the
          EOS); return -inf to drop the ending, or a renormalized score
        - ``stop_beam_search(state, t) -> bool`` (scalar or [B]); True
          freezes the search from this step on

        Returns (tokens [B, K, L] int32, scores [B, K], lengths [B, K]) —
        beams sorted best-first, EOS included in the length.
        """
        if beam_size is None:
            beam_size = self.cfg.attrs.get("beam_size", 1)
        if max_length is None:
            max_length = self.cfg.attrs.get("max_length", 100)
        attrs = self.cfg.attrs
        if candidate_adjust is None:
            candidate_adjust = attrs.get("candidate_adjust")
        if drop_callback is None:
            drop_callback = attrs.get("drop_callback")
        if norm_or_drop is None:
            norm_or_drop = attrs.get("norm_or_drop")
        if stop_beam_search is None:
            stop_beam_search = attrs.get("stop_beam_search")
        hooks = (candidate_adjust, drop_callback, norm_or_drop,
                 stop_beam_search)
        # key by the callables themselves (strong refs) — an id() key
        # could be recycled after GC and silently serve a stale search
        key = (beam_size, max_length) + hooks
        if key not in self._jitted:
            self._jitted[key] = jax.jit(
                lambda p, feed: self._search(
                    p, feed, beam_size, max_length, hooks))
        static_feed = {}
        for inp, meta in zip(self.cfg.inputs, self.cfg.attrs["ins"]):
            if meta["kind"] in ("static", "boot"):
                static_feed[meta["boundary"]] = outer_outputs[inp.layer_name]
        return self._jitted[key](params, static_feed)

    # ------------------------------------------------------------------
    def _search(self, params, static_feed, K: int, L: int, hooks):
        adjust, drop_cb, norm_or_drop, stop_fn = hooks
        cfg, net, gen = self.cfg, self.net, self.gen
        memories = cfg.attrs["memories"]
        out_name = cfg.attrs["outputs"][0]
        emb = params[gen["embedding_name"]]
        bos, eos = gen["bos_id"], gen["eos_id"]
        gen_boundary = gen["boundary"]

        boots = {m["boundary"]: static_feed[m["boundary"]].value
                 for m in memories if m["boundary"] in static_feed}
        some_static = next((a for a in static_feed.values()), None)
        if some_static is None:
            raise ValueError("generation needs at least one static/boot "
                             "input to define the batch size")
        B = some_static.value.shape[0]

        # beams: replicate statics over K and flatten to a [B*K] batch
        def rep(a: Argument) -> Argument:
            def r(x):
                return _flatten_beams(
                    jnp.broadcast_to(x[:, None], (B, K) + x.shape[1:]))
            return Argument(value=r(a.value),
                            mask=None if a.mask is None else r(a.mask))

        flat_static = {
            b: rep(a) for b, a in static_feed.items()
            if b not in boots}

        carry0 = {}
        for m in memories:
            bname = m["boundary"]
            if bname in boots:
                v = boots[bname]
            else:
                size = net.shape_infos[bname].size
                v = jnp.full((B, size), m.get("init", 0.0), jnp.float32)
            carry0[bname] = _flatten_beams(
                jnp.broadcast_to(v[:, None], (B, K) + v.shape[1:]))

        NEG = jnp.float32(-1e9)
        state0 = {
            "tokens": jnp.full((B, K, L), eos, jnp.int32),
            "prev": jnp.full((B, K), bos, jnp.int32),
            # only beam 0 is live at t=0 so duplicates don't fill the beam
            "scores": jnp.concatenate(
                [jnp.zeros((B, 1)), jnp.full((B, K - 1), NEG)], axis=1)
            if K > 1 else jnp.zeros((B, K)),
            "finished": jnp.zeros((B, K), bool),
            "mem": carry0,
        }

        def step(state, t):
            prev_emb = emb[state["prev"].reshape(-1)]  # [B*K, E]
            feed = dict(flat_static)
            feed[gen_boundary] = Argument(value=prev_emb)
            for m in memories:
                feed[m["boundary"]] = Argument(value=state["mem"][m["boundary"]])
            outs = net.apply(params, feed, train=False)
            prob = outs[out_name].value  # [B*K, V] post-softmax
            logp = jnp.log(jnp.maximum(prob, 1e-20))
            if adjust is not None:
                logp = adjust(logp, state)
            V = logp.shape[-1]
            logp = _unflatten_beams(logp, B, K)  # [B, K, V]
            # finished beams may only "continue" with EOS at zero cost
            fin = state["finished"][:, :, None]
            eos_only = jnp.full((1, 1, V), NEG).at[0, 0, eos].set(0.0)
            logp = jnp.where(fin, eos_only, logp)
            total = state["scores"][:, :, None] + logp  # [B, K, V]
            # the forced EOS continuation of an already-finished beam is
            # bookkeeping, not a candidate — no hook may touch it, or a
            # frozen beam's score would drift after it ended
            forced = fin & (jnp.arange(V) == eos)[None, None, :]
            if norm_or_drop is not None:
                # NormOrDropNode: a candidate that ENDS here (picks EOS at
                # step t, path length t+1 counting the EOS) gets its
                # cumulative score renormalized or dropped (-inf)
                ended = norm_or_drop(total[:, :, eos], t + 1)
                total = total.at[:, :, eos].set(
                    jnp.where(state["finished"], total[:, :, eos], ended))
            if drop_cb is not None:
                drop = drop_cb(state, total)
                total = jnp.where(jnp.logical_and(drop, ~forced), NEG,
                                  total)
            flat = total.reshape(B, K * V)
            top_scores, top_idx = lax.top_k(flat, K)     # [B, K]
            parent = top_idx // V
            token = (top_idx % V).astype(jnp.int32)

            def gather_parents(x):
                # x: [B*K, ...] -> per-batch gather along beam axis
                xb = _unflatten_beams(x, B, K)
                return _flatten_beams(
                    jnp.take_along_axis(
                        xb, parent.reshape((B, K) + (1,) * (xb.ndim - 2)),
                        axis=1))

            new_mem = {
                m["boundary"]: gather_parents(
                    outs[m["link"]].value) for m in memories}
            # frozen memories for finished beams
            old_mem_g = {b: gather_parents(v) for b, v in state["mem"].items()}
            fin_parent = jnp.take_along_axis(state["finished"], parent, axis=1)
            finf = _flatten_beams(fin_parent)  # [B*K]
            new_mem = {
                b: jnp.where(finf.reshape((-1,) + (1,) * (v.ndim - 1)),
                             old_mem_g[b], v)
                for b, v in new_mem.items()}
            tokens = jnp.take_along_axis(
                state["tokens"], parent[:, :, None], axis=1)
            tokens = tokens.at[:, :, t].set(token)
            finished = fin_parent | (token == eos)
            new_state = {"tokens": tokens, "prev": token,
                         "scores": top_scores, "finished": finished,
                         "mem": new_mem}
            if stop_fn is not None:
                # stopBeamSearch: once the predicate fires, every beam
                # behaves as finished — only zero-cost EOS continuations
                # from here on, so the search is over in all but shape
                stop = jnp.asarray(stop_fn(new_state, t), bool)
                if stop.ndim <= 1:  # scalar or per-batch [B] -> [B, K]
                    stop = jnp.broadcast_to(stop.reshape((-1, 1)), (B, K))
                new_state["finished"] = new_state["finished"] | stop
            return new_state, None

        state, _ = lax.scan(step, state0, jnp.arange(L))
        tokens = state["tokens"]
        # length = index of first EOS + 1 (EOS kept, as the reference's
        # sequence results include the end mark), else L
        is_eos = tokens == eos
        first = jnp.argmax(is_eos, axis=-1)
        has = jnp.any(is_eos, axis=-1)
        lengths = jnp.where(has, first + 1, L)
        return tokens, state["scores"], lengths
