"""Sequence generation: greedy and beam search over a recurrent step net.

TPU-native ``RecurrentGradientMachine::generateSequence``
(``RecurrentGradientMachine.cpp:964``): greedy ``oneWaySearch`` (``:1042``)
is the beam_size=1 case of ``beamSearch`` (``:1393``). Where the reference
expands/prunes beams with host-side std::vector bookkeeping per step, here
the whole search is jitted with static beam and length dims: beams live as
a [B, K] axis, finished beams are frozen by masking (-inf over non-EOS
continuations), and parent-beam reordering is a gather.

**Decode cost is proportional to actual output length.** The reference
stops the moment every beam finishes; a single ``lax.scan`` over the full
static ``max_length`` cannot. The default search therefore runs a
``lax.while_loop`` over fixed-size scan *chunks* (``decode_chunk`` steps
each, one compiled chunk body reused for every chunk) and exits as soon as
``finished.all()`` — provably byte-identical to the full scan, because a
step in which every beam is already finished only appends the forced
zero-cost EOS continuation: tokens stay EOS (the buffer is EOS-initialized
and gathers are identity at that point), scores carry unchanged through
``top_k`` (hooks are exempted from the forced continuation), and lengths
read the first EOS. ``full_scan=True`` restores the single length-L scan
(the escape hatch and the A/B baseline). Greedy (K=1) decoding skips the
parent-beam gathers entirely — the parent index is always 0.

The user beam-control hooks (``RecurrentGradientMachine.h:92-145``)
survive as callables traced into the step:

- ``candidate_adjust`` — ``beamSearchCandidateAdjust``: arbitrary
  adjustment of the expanded candidate log-probs before selection.
- ``drop_callback`` — ``DropCallback``: per-node drop decision over the
  expanded candidates (True = prune that (beam, token) node).
- ``norm_or_drop`` — ``NormOrDropNode``: rescoring (e.g. length
  normalization) or dropping (-inf) of a candidate at the moment it
  finishes (picks EOS).
- ``stop_beam_search`` — the ``stopBeamSearch`` flag: a predicate that
  freezes the whole search early (all beams behave as finished from the
  step it first returns True).

Hooks can be pinned in the config (``dsl.beam_search(...,
drop_callback=...)``) — the attrs are the defaults every ``generate``
call (and the serving generation endpoint) honors — or passed per call.
Hook time arguments (``norm_or_drop``'s ``length``,
``stop_beam_search``'s ``t``) are traced scalars in the dedicated search
and per-lane ``[B, 1]`` / ``[B]`` arrays inside a :class:`DecodeSession`
— write hooks with broadcasting ops (``jnp.where``, arithmetic), not
Python branches, and they work identically in both.

Compile-key policy (``docs/generation.md``): one executable per
``(beam_size, max_length, decode_chunk-or-full_scan, hooks,
fused-RNN-flag)`` key, the cache LRU-bounded at ``_JIT_CACHE_CAP`` —
per-call hook *lambdas* mint fresh keys every call and would otherwise
leak compiled executables; pin hooks at module level (or in the config)
to reuse the cache. The fused-RNN inference-cell switch
(``kernels.dispatch.rnn_cells_enabled``) is folded into the key inside
``_jit_for`` itself: the step net resolves it at trace time, so two
flag states are two programs.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from paddle_tpu.core.argument import Argument
from paddle_tpu.kernels.dispatch import rnn_cells_enabled
from paddle_tpu.utils.log import get_logger

logger = get_logger("generation")

#: default number of decoder steps per compiled chunk of the early-exit
#: search; the exit condition is checked every chunk boundary, so a
#: request that finishes at step f pays ceil((f+1)/chunk)*chunk steps
#: instead of max_length.
DEFAULT_DECODE_CHUNK = 8

NEG = jnp.float32(-1e9)

_HOOK_NAMES = ("candidate_adjust", "drop_callback", "norm_or_drop",
               "stop_beam_search")


def _flatten_beams(x):
    return x.reshape((-1,) + x.shape[2:])


def _unflatten_beams(x, B, K):
    return x.reshape((B, K) + x.shape[1:])


class SequenceGenerator:
    """Drives a generation-mode recurrent group (``beam_search`` in the
    DSL). Mirrors the SWIG ``SequenceGenerator`` (api/PaddleAPI.h) surface:
    construct from the model + generating layer, call ``generate``."""

    #: LRU bound on compiled search variants. Hooks are part of the key,
    #: so per-call closures/lambdas would grow the cache without limit —
    #: the bound converts that leak into eviction + one warning.
    _JIT_CACHE_CAP = 16

    def __init__(self, model, gen_layer: str):
        from paddle_tpu.layers.group import _group_subnet

        self.cfg = model.layers[gen_layer]
        if self.cfg.type != "beam_search_group":
            raise ValueError(f"{gen_layer!r} is not a beam_search group")
        self.net = _group_subnet(self.cfg)
        self.gen = self.cfg.attrs["gen"]  # GeneratedInput spec dict
        self._jitted: "OrderedDict[Any, Callable]" = OrderedDict()
        self._evict_warned = False
        #: optional params-view hook applied INSIDE the jitted step (the
        #: single interior site where params are consumed). The serving
        #: predictor installs ``quant.materialize`` here for quantized
        #: artifacts: weights stay in storage dtype as traced arguments
        #: and the dequant converts fuse into their consumers. None =
        #: identity (the traced structure is untouched).
        self._param_view = None
        #: observability for the last ``generate`` call:
        #: ``{decode_steps, steps_saved, max_length, decode_chunk,
        #: full_scan}`` — the serving predictor forwards it per request.
        self.last_info: Dict[str, Any] = {}

    # ------------------------------------------------------------------
    def static_input_layers(self):
        """Outer layer names feeding the group's static/boot inputs —
        the encoder outputs ``generate`` needs in ``outer_outputs``."""
        return [inp.layer_name
                for inp, meta in zip(self.cfg.inputs, self.cfg.attrs["ins"])
                if meta["kind"] in ("static", "boot")]

    def static_feed_from_outer(self, outer_outputs, row=None):
        """Map outer-layer-keyed encoder outputs to boundary-keyed static
        feed; ``row`` (host int) selects a single lane as a batch of 1."""
        static_feed = {}
        for inp, meta in zip(self.cfg.inputs, self.cfg.attrs["ins"]):
            if meta["kind"] in ("static", "boot"):
                a = outer_outputs[inp.layer_name]
                if row is not None:
                    a = jax.tree_util.tree_map(
                        lambda x: x[row:row + 1], a)
                static_feed[meta["boundary"]] = a
        return static_feed

    def _resolve_hooks(self, candidate_adjust, drop_callback, norm_or_drop,
                       stop_beam_search):
        attrs = self.cfg.attrs
        if candidate_adjust is None:
            candidate_adjust = attrs.get("candidate_adjust")
        if drop_callback is None:
            drop_callback = attrs.get("drop_callback")
        if norm_or_drop is None:
            norm_or_drop = attrs.get("norm_or_drop")
        if stop_beam_search is None:
            stop_beam_search = attrs.get("stop_beam_search")
        return (candidate_adjust, drop_callback, norm_or_drop,
                stop_beam_search)

    def _resolve_chunk(self, L: int, decode_chunk, full_scan):
        """(chunk or None-for-full-scan) from per-call args and config
        attrs (``dsl.beam_search(..., decode_chunk=, full_scan=)``).
        Precedence: an explicit ``full_scan`` wins; an explicit
        ``decode_chunk`` is an explicit request for that policy
        (``> 0`` chunked, ``<= 0`` full scan); only when both are unset
        does the config's pinned policy apply."""
        attrs = self.cfg.attrs
        if full_scan is None:
            if decode_chunk is not None:
                full_scan = int(decode_chunk) <= 0
            else:
                full_scan = bool(attrs.get("full_scan", False))
        elif decode_chunk is not None and int(decode_chunk) <= 0:
            full_scan = True  # 0/-1 spell "no chunking" on the CLI
        if decode_chunk is None:
            decode_chunk = attrs.get("decode_chunk")
            if decode_chunk is not None and int(decode_chunk) <= 0:
                full_scan = True
        if full_scan:
            return None
        chunk = int(decode_chunk or DEFAULT_DECODE_CHUNK)
        return max(1, min(chunk, L))

    # ------------------------------------------------------------------
    def generate(self, params, outer_outputs: Dict[str, Argument], *,
                 beam_size: Optional[int] = None,
                 max_length: Optional[int] = None,
                 candidate_adjust: Optional[Callable] = None,
                 drop_callback: Optional[Callable] = None,
                 norm_or_drop: Optional[Callable] = None,
                 stop_beam_search: Optional[Callable] = None,
                 decode_chunk: Optional[int] = None,
                 full_scan: Optional[bool] = None):
        """Run the search.

        params: global parameter table (sub-net params are hoisted names).
        outer_outputs: outer-layer Arguments for static/boot inputs, keyed
            by outer layer name (run your encoder Network first).
        decode_chunk: steps per compiled chunk of the early-exit search
            (default ``DEFAULT_DECODE_CHUNK``, or the config's
            ``decode_chunk`` attr). The search exits at the first chunk
            boundary where every beam is finished — byte-identical
            results to the full scan, at cost proportional to the actual
            output length. ``<= 0`` means full scan.
        full_scan: force the single length-L scan (escape hatch /
            baseline); defaults to the config's ``full_scan`` attr.

        Beam-control hooks (``RecurrentGradientMachine.h:92-145``); each
        defaults to the config attr of the same name so hooks pinned by
        ``dsl.beam_search`` apply to every call, flat or via SWIG:

        - ``candidate_adjust(logp [B*K, V], state) -> logp``
        - ``drop_callback(state, total [B, K, V]) -> bool [B, K, V]``
          (True = drop that expanded node; the forced-EOS continuation
          of an already-finished beam is exempt — its frozen score must
          carry)
        - ``norm_or_drop(eos_scores [B, K], length) -> [B, K]`` applied
          to candidates finishing at this step (``length`` counts the
          EOS); return -inf to drop the ending, or a renormalized score
        - ``stop_beam_search(state, t) -> bool`` (scalar or [B]); True
          freezes the search from this step on

        Returns (tokens [B, K, L] int32, scores [B, K], lengths [B, K]) —
        beams sorted best-first, EOS included in the length. Decode-step
        accounting for the call lands in :attr:`last_info`.
        """
        if beam_size is None:
            beam_size = self.cfg.attrs.get("beam_size", 1)
        if max_length is None:
            max_length = self.cfg.attrs.get("max_length", 100)
        hooks = self._resolve_hooks(candidate_adjust, drop_callback,
                                    norm_or_drop, stop_beam_search)
        chunk = self._resolve_chunk(max_length, decode_chunk, full_scan)
        # key by the callables themselves (strong refs) — an id() key
        # could be recycled after GC and silently serve a stale search
        key = (beam_size, max_length, chunk) + hooks
        fn = self._jit_for(key, beam_size, max_length, hooks, chunk)
        static_feed = self.static_feed_from_outer(outer_outputs)
        tokens, scores, lengths, steps = fn(params, static_feed)
        steps = int(steps)
        self.last_info = {
            "decode_steps": steps, "max_length": int(max_length),
            "steps_saved": int(max_length) - steps,
            "decode_chunk": chunk, "full_scan": chunk is None}
        return tokens, scores, lengths

    def _jit_for(self, key, K, L, hooks, chunk):
        """LRU-bounded lookup of the compiled search for ``key``."""
        # the fused-RNN-cell switch is resolved at TRACE time inside
        # net.apply (layers/recurrent.py picks lstm_cell/_infer per
        # rnn_cells_enabled()), so it is part of the compiled program's
        # identity: appended HERE — the one funnel both generate() and
        # the serving warmup's direct _jit_for call pass through — so
        # toggling the flag can never serve a stale compiled search
        key = key + (rnn_cells_enabled(),)
        fn = self._jitted.get(key)
        if fn is not None:
            self._jitted.move_to_end(key)
            return fn
        # graftlint: jit-cache: LRU-bounded (_JIT_CACHE_CAP) with a
        # loud eviction warning; serving brings the warmed entries
        # under hardened guards via _ensure_engine_guard
        fn = jax.jit(lambda p, feed: self._search(p, feed, K, L, hooks,
                                                  chunk))
        self._jitted[key] = fn
        while len(self._jitted) > self._JIT_CACHE_CAP:
            evicted_key, _ = self._jitted.popitem(last=False)
            if not self._evict_warned:
                self._evict_warned = True
                logger.warning(
                    "SequenceGenerator jit cache passed %d variants; "
                    "evicting the oldest (beam=%s, length=%s). Per-call "
                    "hook lambdas mint a fresh compile key every "
                    "generate() — pin hooks at module level or in the "
                    "config (dsl.beam_search) to reuse compiles.",
                    self._JIT_CACHE_CAP, evicted_key[0], evicted_key[1])
        return fn

    # ------------------------------------------------------------------
    def _make_step(self, B: int, K: int, L: int, hooks, *,
                   per_lane_t: bool):
        """Build the one-decoder-step function shared by the dedicated
        search (``t`` a traced scalar) and :class:`DecodeSession`
        (``t`` a per-lane ``[B]`` vector, ``per_lane_t=True``).

        ``step(params, flat_static, state, t) -> new_state`` where
        ``state`` has keys {tokens, prev, scores, finished, mem} and
        ``flat_static`` maps group boundary -> Argument with
        ``[B*K, ...]`` leaves. ``params`` must be a traced jit argument,
        never closed-over device arrays — XLA treats closure captures as
        program constants, which measurably deoptimizes the loop body
        (~4x per step on XLA:CPU for the session chunk).
        """
        adjust, drop_cb, norm_or_drop, stop_fn = hooks
        cfg, net, gen = self.cfg, self.net, self.gen
        memories = cfg.attrs["memories"]
        out_name = cfg.attrs["outputs"][0]
        eos = gen["eos_id"]
        gen_boundary = gen["boundary"]

        def step(params, flat_static, state, t):
            if self._param_view is not None:
                params = self._param_view(params)
            emb = params[gen["embedding_name"]]
            prev_emb = emb[state["prev"].reshape(-1)]  # [B*K, E]
            feed = dict(flat_static)
            feed[gen_boundary] = Argument(value=prev_emb)
            for m in memories:
                feed[m["boundary"]] = Argument(
                    value=state["mem"][m["boundary"]])
            outs = net.apply(params, feed, train=False)
            prob = outs[out_name].value  # [B*K, V] post-softmax
            logp = jnp.log(jnp.maximum(prob, 1e-20))
            if adjust is not None:
                logp = adjust(logp, state)
            V = logp.shape[-1]
            logp = _unflatten_beams(logp, B, K)  # [B, K, V]
            # finished beams may only "continue" with EOS at zero cost
            fin = state["finished"][:, :, None]
            eos_only = jnp.full((1, 1, V), NEG).at[0, 0, eos].set(0.0)
            logp = jnp.where(fin, eos_only, logp)
            total = state["scores"][:, :, None] + logp  # [B, K, V]
            # the forced EOS continuation of an already-finished beam is
            # bookkeeping, not a candidate — no hook may touch it, or a
            # frozen beam's score would drift after it ended
            forced = fin & (jnp.arange(V) == eos)[None, None, :]
            if norm_or_drop is not None:
                # NormOrDropNode: a candidate that ENDS here (picks EOS at
                # step t, path length t+1 counting the EOS) gets its
                # cumulative score renormalized or dropped (-inf)
                length = (t + 1)[:, None] if per_lane_t else t + 1
                ended = norm_or_drop(total[:, :, eos], length)
                total = total.at[:, :, eos].set(
                    jnp.where(state["finished"], total[:, :, eos], ended))
            if drop_cb is not None:
                drop = drop_cb(state, total)
                total = jnp.where(jnp.logical_and(drop, ~forced), NEG,
                                  total)
            flat = total.reshape(B, K * V)
            top_scores, top_idx = lax.top_k(flat, K)     # [B, K]
            parent = top_idx // V
            token = (top_idx % V).astype(jnp.int32)

            if K == 1:
                # greedy fast path: the single beam is its own parent
                # (parent == idx // V == 0), so every gather below is the
                # identity — skip them all
                def gather_parents(x):
                    return x
                fin_parent = state["finished"]
                tokens = state["tokens"]
            else:
                def gather_parents(x):
                    # x: [B*K, ...] -> per-batch gather along beam axis
                    xb = _unflatten_beams(x, B, K)
                    return _flatten_beams(
                        jnp.take_along_axis(
                            xb,
                            parent.reshape((B, K) + (1,) * (xb.ndim - 2)),
                            axis=1))
                fin_parent = jnp.take_along_axis(state["finished"], parent,
                                                 axis=1)
                tokens = jnp.take_along_axis(
                    state["tokens"], parent[:, :, None], axis=1)

            new_mem = {
                m["boundary"]: gather_parents(
                    outs[m["link"]].value) for m in memories}
            # frozen memories for finished beams
            old_mem_g = {b: gather_parents(v)
                         for b, v in state["mem"].items()}
            finf = _flatten_beams(fin_parent)  # [B*K]
            new_mem = {
                b: jnp.where(finf.reshape((-1,) + (1,) * (v.ndim - 1)),
                             old_mem_g[b], v)
                for b, v in new_mem.items()}
            if per_lane_t:
                # each lane writes at its own position t[b]
                pos = (jnp.arange(L)[None, None, :]
                       == t[:, None, None])  # [B, 1, L]
                tokens = jnp.where(pos, token[:, :, None], tokens)
            else:
                tokens = tokens.at[:, :, t].set(token)
            finished = fin_parent | (token == eos)
            new_state = {"tokens": tokens, "prev": token,
                         "scores": top_scores, "finished": finished,
                         "mem": new_mem}
            if stop_fn is not None:
                # stopBeamSearch: once the predicate fires, every beam
                # behaves as finished — only zero-cost EOS continuations
                # from here on, so the search is over in all but shape
                stop = jnp.asarray(stop_fn(new_state, t), bool)
                if stop.ndim <= 1:  # scalar or per-batch [B] -> [B, K]
                    stop = jnp.broadcast_to(stop.reshape((-1, 1)), (B, K))
                new_state["finished"] = new_state["finished"] | stop
            return new_state

        return step

    def _init_state(self, static_feed, K: int, L: int):
        """(B, flat_static, state0) for a dedicated search over the
        static/boot feed."""
        cfg, net, gen = self.cfg, self.net, self.gen
        memories = cfg.attrs["memories"]
        bos, eos = gen["bos_id"], gen["eos_id"]

        boots = {m["boundary"]: static_feed[m["boundary"]].value
                 for m in memories if m["boundary"] in static_feed}
        some_static = next((a for a in static_feed.values()), None)
        if some_static is None:
            raise ValueError("generation needs at least one static/boot "
                             "input to define the batch size")
        B = some_static.value.shape[0]

        # beams: replicate statics over K and flatten to a [B*K] batch
        def rep(a: Argument) -> Argument:
            def r(x):
                return _flatten_beams(
                    jnp.broadcast_to(x[:, None], (B, K) + x.shape[1:]))
            return Argument(value=r(a.value),
                            mask=None if a.mask is None else r(a.mask))

        flat_static = {
            b: rep(a) for b, a in static_feed.items()
            if b not in boots}

        carry0 = {}
        for m in memories:
            bname = m["boundary"]
            if bname in boots:
                v = boots[bname]
            else:
                size = net.shape_infos[bname].size
                v = jnp.full((B, size), m.get("init", 0.0), jnp.float32)
            carry0[bname] = _flatten_beams(
                jnp.broadcast_to(v[:, None], (B, K) + v.shape[1:]))

        state0 = {
            "tokens": jnp.full((B, K, L), eos, jnp.int32),
            "prev": jnp.full((B, K), bos, jnp.int32),
            # only beam 0 is live at t=0 so duplicates don't fill the beam
            "scores": jnp.concatenate(
                [jnp.zeros((B, 1)), jnp.full((B, K - 1), NEG)], axis=1)
            if K > 1 else jnp.zeros((B, K)),
            "finished": jnp.zeros((B, K), bool),
            "mem": carry0,
        }
        return B, flat_static, state0

    def _search(self, params, static_feed, K: int, L: int, hooks,
                chunk: Optional[int] = None):
        """The jitted search body. ``chunk=None`` = single length-L scan;
        otherwise a ``lax.while_loop`` over ``chunk``-step scan bodies
        exiting at the first chunk boundary where every beam is finished
        (or ``stop_beam_search`` fired — it sets ``finished``).

        Returns (tokens, scores, lengths, steps) with ``steps`` the
        number of decoder steps actually executed (== L for full scan).
        """
        B, flat_static, state0 = self._init_state(static_feed, K, L)
        step = self._make_step(B, K, L, hooks, per_lane_t=False)

        if chunk is None:
            def body(state, t):
                return step(params, flat_static, state, t), None
            state, _ = lax.scan(body, state0, jnp.arange(L))
            steps = jnp.int32(L)
        else:
            C = int(chunk)

            def chunk_body(carry):
                state, t0 = carry

                def body(state, i):
                    t = t0 + i
                    new = step(params, flat_static, state, t)
                    # the last chunk may overhang L (L % C != 0): steps
                    # at t >= L are no-ops so the executed prefix is
                    # exactly t = 0..L-1, same as the full scan
                    new = jax.tree_util.tree_map(
                        lambda n, o: jnp.where(t < L, n, o), new, state)
                    return new, None

                state, _ = lax.scan(body, state, jnp.arange(C))
                return state, t0 + C

            def chunk_cond(carry):
                state, t0 = carry
                return (t0 < L) & ~jnp.all(state["finished"])

            state, t_end = lax.while_loop(
                chunk_cond, chunk_body, (state0, jnp.int32(0)))
            steps = jnp.minimum(t_end, L)

        tokens = state["tokens"]
        # length = index of first EOS + 1 (EOS kept, as the reference's
        # sequence results include the end mark), else L
        eos = self.gen["eos_id"]
        is_eos = tokens == eos
        first = jnp.argmax(is_eos, axis=-1)
        has = jnp.any(is_eos, axis=-1)
        lengths = jnp.where(has, first + 1, L)
        return tokens, state["scores"], lengths, steps

    # ------------------------------------------------------------------
    def session(self, params, width: int, *,
                beam_size: Optional[int] = None,
                max_length: Optional[int] = None,
                decode_chunk: Optional[int] = None,
                candidate_adjust: Optional[Callable] = None,
                drop_callback: Optional[Callable] = None,
                norm_or_drop: Optional[Callable] = None,
                stop_beam_search: Optional[Callable] = None
                ) -> "DecodeSession":
        """A continuous-batching decode session: ``width`` lanes stepped
        ``decode_chunk`` steps per :meth:`DecodeSession.run_chunk`, with
        per-lane admit/retire between chunks (``docs/serving.md``)."""
        if beam_size is None:
            beam_size = self.cfg.attrs.get("beam_size", 1)
        if max_length is None:
            max_length = self.cfg.attrs.get("max_length", 100)
        hooks = self._resolve_hooks(candidate_adjust, drop_callback,
                                    norm_or_drop, stop_beam_search)
        chunk = self._resolve_chunk(max_length, decode_chunk, False)
        if chunk is None:
            chunk = max(1, min(DEFAULT_DECODE_CHUNK, int(max_length)))
        return DecodeSession(self, params, int(width), int(beam_size),
                             int(max_length), int(chunk), hooks)


class DecodeSession:
    """Fixed-width continuous-batching decode state.

    ``width`` lanes share one compiled chunk body; each lane carries its
    own decode clock ``t`` (lanes admitted mid-flight start at 0 while
    neighbors are deep into their outputs). The host loop between chunks
    is the lane lifecycle: :meth:`admit` splices a freshly encoded
    request into a free lane, :meth:`run_chunk` advances every live lane
    ``chunk`` steps, :meth:`finished_lanes` / :meth:`peek` /
    :meth:`release` retire lanes whose beams all finished (or that hit
    ``max_length``). Lanes are independent — every per-step op is
    batched row-wise, so a lane's tokens/scores match the dedicated
    search on the same request regardless of what its neighbors decode.

    All three device functions (admit / chunk / release) are jitted once
    per session with traced lane indices — a session serves any traffic
    with exactly three compiled programs (the serving predictor wraps
    them in hardened ``RecompileGuard``s).
    """

    _CORE = ("tokens", "prev", "scores", "finished", "mem")

    def __init__(self, gen: SequenceGenerator, params, width: int, K: int,
                 L: int, chunk: int, hooks):
        self.gen = gen
        self.params = params
        self.width, self.K, self.L, self.chunk = width, K, L, chunk
        self.hooks = hooks
        self._state = None          # built lazily at first admit
        self._admit_fn = None
        self._chunk_fn = None
        self._release_fn = None

    # ------------------------------------------------------------ state
    def _build(self, static_feed):
        """Build the empty W-lane state + jitted fns from the shapes of
        the first admitted request's static feed."""
        W, K, L = self.width, self.K, self.L
        cfg, net, gen = self.gen.cfg, self.gen.net, self.gen.gen
        memories = cfg.attrs["memories"]
        bos, eos = gen["bos_id"], gen["eos_id"]
        boot_names = {m["boundary"] for m in memories}

        statics = {}
        for b, a in static_feed.items():
            if b in boot_names:
                continue

            def z(x):
                return jnp.zeros((W * K,) + x.shape[1:], x.dtype)
            statics[b] = Argument(
                value=z(a.value),
                mask=None if a.mask is None else z(a.mask))
        mem = {}
        for m in memories:
            bname = m["boundary"]
            if bname in static_feed:
                size = static_feed[bname].value.shape[-1]
            else:
                size = net.shape_infos[bname].size
            mem[bname] = jnp.zeros((W * K, size), jnp.float32)
        self._state = {
            "tokens": jnp.full((W, K, L), eos, jnp.int32),
            "prev": jnp.full((W, K), bos, jnp.int32),
            "scores": jnp.zeros((W, K)),
            # inactive lanes read as finished so they are forced-EOS
            # no-ops inside the chunk body
            "finished": jnp.ones((W, K), bool),
            "mem": mem,
            "static": statics,
            "t": jnp.zeros(W, jnp.int32),
            "active": jnp.zeros(W, bool),
        }

        def _put_rows(dst, src, lane):
            """src [1, ...] broadcast to K rows at dst[lane*K:...]."""
            upd = jnp.broadcast_to(
                src.astype(dst.dtype), (K,) + src.shape[1:])
            return lax.dynamic_update_slice(
                dst, upd, (lane * K,) + (0,) * (dst.ndim - 1))

        def _admit(state, lane, static_row, boot_row):
            state = dict(state)
            new_static = {}
            for b, a in state["static"].items():
                src = static_row[b]
                new_static[b] = Argument(
                    value=_put_rows(a.value, src.value, lane),
                    mask=(None if a.mask is None
                          else _put_rows(a.mask, src.mask, lane)))
            state["static"] = new_static
            new_mem = {}
            for m in memories:
                bname = m["boundary"]
                if bname in boot_row:
                    src = boot_row[bname]
                else:
                    src = jnp.full((1, state["mem"][bname].shape[-1]),
                                   m.get("init", 0.0), jnp.float32)
                new_mem[bname] = _put_rows(state["mem"][bname], src, lane)
            state["mem"] = new_mem
            state["tokens"] = lax.dynamic_update_slice(
                state["tokens"], jnp.full((1, K, L), eos, jnp.int32),
                (lane, 0, 0))
            state["prev"] = lax.dynamic_update_slice(
                state["prev"], jnp.full((1, K), bos, jnp.int32), (lane, 0))
            row_scores = (jnp.concatenate(
                [jnp.zeros((1, 1)), jnp.full((1, K - 1), NEG)], axis=1)
                if K > 1 else jnp.zeros((1, K)))
            state["scores"] = lax.dynamic_update_slice(
                state["scores"], row_scores, (lane, 0))
            state["finished"] = lax.dynamic_update_slice(
                state["finished"], jnp.zeros((1, K), bool), (lane, 0))
            state["t"] = state["t"].at[lane].set(0)
            state["active"] = state["active"].at[lane].set(True)
            return state

        step = self.gen._make_step(W, K, L, self.hooks, per_lane_t=True)
        C = self.chunk

        def _lane_sel(adv, new, old):
            sel = {}
            sel["tokens"] = jnp.where(adv[:, None, None], new["tokens"],
                                      old["tokens"])
            for k in ("prev", "scores", "finished"):
                sel[k] = jnp.where(adv[:, None], new[k], old[k])
            advf = jnp.repeat(adv, K)
            sel["mem"] = {
                b: jnp.where(advf.reshape((-1,) + (1,) * (v.ndim - 1)),
                             new["mem"][b], v)
                for b, v in old["mem"].items()}
            return sel

        def _chunk(params, state):
            def body(state, _):
                # a lane runs while it is live, not past max_length, and
                # not fully finished; everything else is frozen so a
                # retired-but-not-yet-replaced lane cannot drift
                adv = (state["active"] & (state["t"] < L)
                       & ~jnp.all(state["finished"], axis=1))
                core = {k: state[k] for k in DecodeSession._CORE}
                new_core = step(params, state["static"], core,
                                state["t"])
                merged = dict(state)
                merged.update(_lane_sel(adv, new_core, core))
                merged["t"] = jnp.where(adv, state["t"] + 1, state["t"])
                return merged, None

            state, _ = lax.scan(body, state, None, length=C)
            return state

        def _release(state, lane):
            state = dict(state)
            state["active"] = state["active"].at[lane].set(False)
            state["finished"] = lax.dynamic_update_slice(
                state["finished"], jnp.ones((1, K), bool), (lane, 0))
            return state

        # graftlint: jit-cache: exactly 3 compiles per session, exposed
        # via jitted_fns() and hardened by the serving predictor's
        # RecompileGuards after warmup (build_session)
        self._admit_fn = jax.jit(_admit)
        self._chunk_fn = jax.jit(_chunk)  # graftlint: jit-cache: ^
        self._release_fn = jax.jit(_release)  # graftlint: jit-cache: ^

    # ------------------------------------------------------------ lanes
    def jitted_fns(self) -> List[Callable]:
        """The session's compiled device functions, for recompile
        guarding (empty before the first admit)."""
        return [f for f in (self._admit_fn, self._chunk_fn,
                            self._release_fn) if f is not None]

    def poll(self):
        """One fused device->host fetch of the lane flags:
        ``(active [W] bool, all_finished [W] bool, t [W] int)``. The
        continuous batcher calls this once per chunk boundary and derives
        free/expired/finished lanes from the result — per-accessor
        fetches would serialize several host round-trips onto the decode
        hot path."""
        s = self._state
        if s is None:
            return (np.zeros(self.width, bool), np.zeros(self.width, bool),
                    np.zeros(self.width, np.int32))
        active, fin, t = jax.device_get(
            (s["active"], jnp.all(s["finished"], axis=1), s["t"]))
        return np.asarray(active), np.asarray(fin), np.asarray(t)

    def _lane_flags(self):
        return self.poll()

    def free_lanes(self) -> List[int]:
        active, _, _ = self._lane_flags()
        return [i for i in range(self.width) if not active[i]]

    def active_lanes(self) -> List[int]:
        active, _, _ = self._lane_flags()
        return [i for i in range(self.width) if active[i]]

    def finished_lanes(self) -> List[int]:
        """Lanes whose search is over (all beams finished, or the lane
        hit max_length) and which carry an unretired result."""
        active, fin, t = self._lane_flags()
        return [i for i in range(self.width)
                if active[i] and (fin[i] or t[i] >= self.L)]

    def admit(self, lane: int, outer_outputs, row: int = 0):
        """Splice request ``row`` of the encoded ``outer_outputs`` (outer
        layer name -> Argument) into ``lane``, starting its clock at 0."""
        static_feed = self.gen.static_feed_from_outer(outer_outputs,
                                                      row=row)
        if self._state is None:
            self._build(static_feed)
        boot_names = {m["boundary"]
                      for m in self.gen.cfg.attrs["memories"]}
        static_row = {b: a for b, a in static_feed.items()
                      if b not in boot_names}
        boot_row = {b: a.value for b, a in static_feed.items()
                    if b in boot_names}
        self._state = self._admit_fn(self._state, jnp.int32(lane),
                                     static_row, boot_row)

    def run_chunk(self) -> int:
        """Advance every live lane ``chunk`` steps; returns the chunk
        size (0 when nothing was ever admitted)."""
        if self._state is None:
            return 0
        self._state = self._chunk_fn(self.params, self._state)
        return self.chunk

    def lane_steps(self, lane: int) -> int:
        """Decode steps a lane has executed — a scalar fetch, cheap
        enough for hot-loop diagnostics (unlike :meth:`peek`, which
        copies the lane's whole token buffer)."""
        if self._state is None:
            return 0
        return int(np.asarray(self._state["t"][lane]))

    def peek(self, lane: int):
        """(tokens [K, L], scores [K], lengths [K], steps) for a lane —
        host np arrays; lengths use the same first-EOS+1 rule as
        ``generate``."""
        s = self._state
        tokens = np.asarray(s["tokens"][lane])
        scores = np.asarray(s["scores"][lane])
        steps = int(np.asarray(s["t"][lane]))
        eos = self.gen.gen["eos_id"]
        is_eos = tokens == eos
        first = np.argmax(is_eos, axis=-1)
        has = np.any(is_eos, axis=-1)
        lengths = np.where(has, first + 1, self.L).astype(np.int64)
        return tokens, scores, lengths, steps

    def release(self, lane: int):
        """Free a lane (after :meth:`peek`); it reads finished/inactive
        until the next :meth:`admit`."""
        self._state = self._release_fn(self._state, jnp.int32(lane))
