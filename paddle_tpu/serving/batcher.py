"""Dynamic micro-batching engine: queue, coalesce, deadline, shed, drain.

The chip-side economics: one jitted call over a padded batch of N costs
barely more than a batch of 1 (the MXU is wildly under-filled at small
N), so concurrent single-row requests should ride ONE program launch.
The engine holds a bounded request queue; a single worker thread
coalesces whatever is waiting — same endpoint kind, up to ``max_batch``
— within a ``batch_timeout`` window into the smallest admissible batch
bucket, runs it, and fans results back out. This is the TensorFlow
Serving / "dynamic batcher" shape of the problem, sitting on the deploy
surface the reference ships as merged-model + C API (SURVEY L7b).

Production behaviors, all typed (``serving/errors.py``):

- **Deadlines** — per-request; a request that expires in the queue is
  answered ``DeadlineExceeded`` without wasting compute, and one whose
  batch finishes too late is answered the same (the work is sunk, the
  answer honest).
- **Admission control / load shedding** — the queue is bounded; past
  ``shed_watermark`` new requests get ``Overloaded`` with a
  ``retry_after_ms`` drain estimate (EWMA batch time × queued batches).
- **Drain** — ``begin_drain()`` (the SIGTERM handler) closes admission
  (``ShuttingDown``) while the worker finishes every queued request;
  ``shutdown()`` waits for that and stops the worker.
- **Lane isolation** — a malformed request discovered at batch-assembly
  time (conversion failure, e.g. an id outside the declared range)
  cannot poison the batch it was coalesced into: bad rows are probed
  out per-lane, replaced with synthetic padding rows, and their row-mask
  lanes zeroed; the bad request alone gets ``BadRequest``, its
  neighbors' answers are bit-identical to a clean batch's.
- **Continuous batching** (``continuous_batching=True``) — the generate
  path stops being convoy-scheduled. Instead of holding a coalesced
  batch until the slowest lane's full-length beam search returns, the
  worker drives a fixed-width :class:`~paddle_tpu.core.generation.
  DecodeSession` chunk by chunk: at every chunk boundary finished lanes
  retire (their callers answered immediately), expired lanes are
  answered ``DeadlineExceeded`` *mid-decode* and freed, and queued
  generate requests are admitted into the freed slots — each encoded
  ONCE at admission and spliced into the live decode state. One slow
  request no longer convoys its batch, and a deadline is enforceable at
  chunk granularity instead of batch granularity.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

from paddle_tpu.obs import flight as _flight
from paddle_tpu.obs import trace as _trace
from paddle_tpu.serving.errors import (BadRequest, ConfigRejected,
                                       DeadlineExceeded, Overloaded,
                                       ServingError, ShuttingDown)
from paddle_tpu.serving.metrics import ServingMetrics
from paddle_tpu.testing import chaos as _chaos
from paddle_tpu.utils.log import get_logger

logger = get_logger("serving")

# the serving phase split IS the span taxonomy: these four children
# partition a request's replica-side parent span by construction
_PHASES = ("queue_wait", "pad_overhead", "compute", "decode")


class _Request:
    __slots__ = ("sample", "kind", "enqueue_t", "deadline", "event",
                 "result", "error", "timings", "trace", "wall_t")

    def __init__(self, sample, kind: str, deadline: Optional[float]):
        self.sample = sample
        self.kind = kind
        self.enqueue_t = time.perf_counter()
        self.deadline = deadline  # absolute perf_counter time, or None
        self.event = threading.Event()
        self.result = None
        self.error: Optional[ServingError] = None
        self.timings: Dict[str, float] = {}
        # the submitter's ambient trace context (the HTTP handler's /
        # router attempt's span): the worker thread parents this
        # request's replica-side spans under it at answer time
        self.trace = _trace.current()
        self.wall_t = time.time()  # wall twin of enqueue_t (span ts)

    def expired(self, now: Optional[float] = None) -> bool:
        return (self.deadline is not None
                and (now or time.perf_counter()) > self.deadline)


class ServingEngine:
    """One predictor + one worker thread + one bounded queue."""

    def __init__(self, predictor, *, max_batch: Optional[int] = None,
                 batch_timeout_ms: float = 5.0, queue_depth: int = 64,
                 shed_watermark: Optional[int] = None,
                 default_deadline_ms: Optional[float] = None,
                 continuous_batching: bool = False,
                 metrics: Optional[ServingMetrics] = None,
                 replay_sink=None, workload_recorder=None):
        self.predictor = predictor
        # the online loop's serving→training edge: successfully-answered
        # score rows are appended here (``online/replay.py:ReplayWriter``
        # — replicas of one fleet share the writer). Best-effort by
        # contract: a failed append is counted and shed, never an error
        # to the caller whose request DID get answered.
        self.replay_sink = replay_sink
        # admission-stream tap (``serving/workload.py:WorkloadRecorder``)
        # — records every offered request (admitted AND shed) for the
        # trace-replay harness. Off the latency path like the replay
        # sink: one lock-free deque append, outside the engine lock.
        self.workload_recorder = workload_recorder
        self.max_batch = int(max_batch or predictor.batch_buckets[-1])
        if self.max_batch > predictor.batch_buckets[-1]:
            raise ValueError(
                f"max_batch {self.max_batch} exceeds the largest warmed "
                f"batch bucket {predictor.batch_buckets[-1]}")
        self.batch_timeout_ms = float(batch_timeout_ms)
        self.queue_depth = int(queue_depth)
        # the queue bound is queue_depth, full stop — a watermark above
        # it would silently unbound the "bounded" queue
        self.shed_watermark = min(int(shed_watermark or queue_depth),
                                  self.queue_depth)
        self.default_deadline_ms = default_deadline_ms
        self.continuous_batching = bool(continuous_batching)
        self._session = None  # DecodeSession, built in start()
        self.metrics = metrics or ServingMetrics()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: List[_Request] = []
        self._draining = False
        self._batch_ewma_ms = 10.0  # drain-time estimator seed
        # rows of the batch the worker is running RIGHT NOW (0 between
        # batches). Written only by the worker thread, read lock-free by
        # health() — a remote drain_wait polls queue_depth+inflight to
        # know every queued AND in-flight request has been answered.
        self._inflight = 0
        # requests answered while the engine lock is held (queue expiry,
        # drain=False shed, worker-fatal): their spans are recorded
        # later by _drain_trace_backlog OUTSIDE the lock — the obs
        # plane must never nest under a subsystem lock (deque ops are
        # GIL-atomic, so no extra lock either)
        self._trace_backlog: deque = deque()
        self._thread: Optional[threading.Thread] = None
        self.fatal: Optional[BaseException] = None

    # ------------------------------------------------------------ control
    def start(self, warmup: bool = True) -> "ServingEngine":
        if warmup and not self.predictor.warmed:
            self.predictor.warmup(log=logger.info)
        if self.continuous_batching and self._session is None:
            if getattr(self.predictor, "engine", None) is None:
                logger.warning(
                    "continuous_batching requested but the model has no "
                    "generation group — standing down to plain batching")
                self.continuous_batching = False
            else:
                # one warmed fixed-width session for the engine lifetime;
                # its three device programs come under hardened guards
                # inside build_session (None = the predictor stood down
                # with its own warning, e.g. bucket-dependent static
                # shapes)
                self._session = self.predictor.build_session(
                    self.max_batch)
                if self._session is None:
                    self.continuous_batching = False
        self._thread = threading.Thread(target=self._work,
                                        name="serving-batcher", daemon=True)
        self._thread.start()
        return self

    @property
    def draining(self) -> bool:
        return self._draining

    def queue_len(self) -> int:
        with self._lock:
            return len(self._queue)

    def backlog_hint_ms(self) -> float:
        """Drain-time estimate (EWMA batch time x queued batches) for
        admission hints: the single-replica 429 ``retry_after_ms`` and
        the router's fleet-wide capacity math. Lock-free read of an
        estimator — a stale value only skews a hint."""
        return self._retry_after_ms()

    def health(self) -> dict:
        """Liveness vs readiness, split (the ``/healthz`` payload and
        the router's poll target):

        - **live** — the process is worth keeping: the worker thread has
          not died to a bug. A DRAINING replica is still live (killing
          it mid-drain would drop its queued requests).
        - **ready** — dispatchable: warmed, not draining, worker alive.
          The router stops routing to a replica the moment this flips,
          instead of discovering it via a refused request.
        """
        live = self.fatal is None
        warmed = bool(self.predictor.warmed)
        ready = live and warmed and not self._draining
        if ready:
            status = "ok"
        elif self._draining:
            status = "draining"
        elif live and not warmed:
            status = "warming"
        else:
            status = "unhealthy"
        h = {
            "status": status, "live": live, "ready": ready,
            "warmed": warmed, "draining": self._draining,
            "queue_depth": self.queue_len(),
            "inflight": self._inflight,
            "backlog_ms": round(self.backlog_hint_ms(), 1),
            "model_version": getattr(self.predictor, "model_version",
                                     None),
            "fatal": repr(self.fatal) if self.fatal else None,
        }
        quant = getattr(self.predictor, "quant_health", None)
        if quant is not None:
            # precision tier + warmup accuracy-gate verdict: a canary
            # (and the rolling-reload report) reads this to know which
            # precision answered and whether the gate vouched for it
            h["quant"] = quant()
        cache = getattr(self.predictor, "aot_cache", None)
        if cache is not None:
            h["aot_cache"] = dict(cache.stats)
        return h

    # ------------------------------------------------------- hot reconfig
    def current_config(self) -> dict:
        """The incumbent knob values — the before/after halves of every
        ``apply_config`` answer, and the rollback anchor the router's
        fan-out uses when a later replica refuses the delta."""
        return {
            "max_batch": self.max_batch,
            "batch_timeout_ms": self.batch_timeout_ms,
            "queue_depth": self.queue_depth,
            "shed_watermark": self.shed_watermark,
            "default_deadline_ms": self.default_deadline_ms,
            "decode_chunk": getattr(self.predictor, "gen_decode_chunk",
                                    None),
        }

    def apply_config(self, cfg) -> dict:
        """Apply a :class:`~paddle_tpu.serving.tuner.FleetConfig` delta
        to the live engine. Validate-then-commit: every value is checked
        BEFORE anything mutates, so a refusal leaves the incumbent
        config serving untouched (typed 409
        :class:`~paddle_tpu.serving.errors.ConfigRejected`).

        The load-bearing refusal is the warmed-menu check: a
        ``max_batch`` above ``predictor.batch_buckets[-1]`` (or any
        ``decode_chunk`` change — the chunk length is compiled into the
        warmed decode programs) would drive the hardened
        ``RecompileGuard`` into a worker-fatal ``RecompileError``
        mid-traffic, so it is refused HERE, with the warmed menu on
        ``allowed``. Admissible knobs mutate under the engine lock in
        one step (the worker's ``_collect`` reads them there), then the
        event/metric emission happens outside it."""
        from paddle_tpu.serving.tuner import FleetConfig, \
            record_tune_decision
        cfg = FleetConfig.coerce(cfg)
        changes = cfg.engine_items()
        before = self.current_config()
        if not changes:
            return {"status": "ok", "before": before, "after": before}

        def reject(reason: str, allowed=None):
            self.metrics.inc("config_rejected_total")
            record_tune_decision(action="apply_rejected", reason=reason,
                                 requested=dict(changes), before=before)
            raise ConfigRejected(
                f"{reason}; incumbent config keeps serving",
                allowed=allowed)

        cap = self.predictor.batch_buckets[-1]
        new_max = int(changes.get("max_batch", self.max_batch))
        if not 1 <= new_max <= cap:
            reject(f"max_batch {new_max} is outside the warmed "
                   f"batch-bucket menu (largest warmed bucket {cap}); "
                   "an off-menu batch would recompile mid-traffic",
                   allowed={"max_batch": list(self.predictor
                                              .batch_buckets)})
        if "decode_chunk" in changes:
            warmed = getattr(self.predictor, "gen_decode_chunk", None)
            if changes["decode_chunk"] != warmed:
                reject("decode_chunk is compiled into the warmed decode "
                       f"programs (warmed: {warmed}); changing it needs "
                       "a reload (/admin/reload), not a knob nudge",
                       allowed={"decode_chunk": [warmed]})
        new_qd = int(changes.get("queue_depth", self.queue_depth))
        if new_qd < 1:
            reject(f"queue_depth {new_qd} must be >= 1")
        new_to = float(changes.get("batch_timeout_ms",
                                   self.batch_timeout_ms))
        if new_to < 0:
            reject(f"batch_timeout_ms {new_to} must be >= 0")
        new_sw = changes.get("shed_watermark", self.shed_watermark)
        if new_sw is not None and int(new_sw) < 1:
            reject(f"shed_watermark {new_sw} must be >= 1")
        # a present-but-None entry is the wire's "disable" (<= 0)
        new_dl = (changes["default_deadline_ms"]
                  if "default_deadline_ms" in changes
                  else self.default_deadline_ms)
        with self._cond:
            self.max_batch = new_max
            self.batch_timeout_ms = new_to
            self.queue_depth = new_qd
            # the constructor's invariant, re-established: the watermark
            # never exceeds the (possibly new) queue bound
            self.shed_watermark = min(int(new_sw or new_qd), new_qd)
            self.default_deadline_ms = new_dl
            self._cond.notify_all()
        after = self.current_config()
        self.metrics.inc("config_applies_total")
        if _flight._ACTIVE is not None:
            _flight._ACTIVE.record("config_applied",
                                   changed=",".join(sorted(changes)),
                                   before=before, after=after)
        logger.info("serving: config applied (%s)",
                    {k: after[k] for k in changes})
        return {"status": "ok", "before": before, "after": after}

    def begin_drain(self):
        """Close admission; queued and in-flight work still completes.
        The SIGTERM handler calls this (``serving/server.py``)."""
        with self._cond:
            first = not self._draining
            queued = len(self._queue)
            self._draining = True
            self._cond.notify_all()
        if first:
            # log + flight OUTSIDE the engine lock (lock discipline:
            # the obs plane never nests under a subsystem lock)
            logger.info("serving: draining (admission closed, "
                        "%d queued)", queued)
            if _flight._ACTIVE is not None:
                _flight._ACTIVE.record("drain_begin", queued=queued)

    def shutdown(self, drain: bool = True, timeout: float = 30.0):
        """Drain (default) or abort the queue, then stop the worker."""
        with self._cond:
            self._draining = True
            if not drain:
                for r in self._queue:
                    r.error = ShuttingDown(
                        "server shutting down; request not started")
                    r.event.set()
                    self._trace_backlog.append(r)
                    self.metrics.inc("shed_total")
                self._queue.clear()
            self._cond.notify_all()
        self._drain_trace_backlog()
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    # ---------------------------------------------------------- admission
    def _retry_after_ms(self) -> float:
        backlog_batches = max(len(self._queue), 1) / self.max_batch
        return max(self.batch_timeout_ms,
                   self._batch_ewma_ms * backlog_batches)

    def submit(self, sample, *, kind: str = "score",
               deadline_ms: Optional[float] = None,
               beam_size=None, max_length=None) -> _Request:
        """Admit one request; raises typed errors synchronously (shed /
        draining / inadmissible shape). Returns the pending request —
        wait on ``.event`` and read ``.result`` / ``.error``."""
        if self.fatal is not None:
            # the worker is dead (a bug, not load): admitting would
            # enqueue into a queue nothing drains
            raise ServingError(f"serving worker died: {self.fatal!r}")
        if self._draining:
            raise ShuttingDown("server is draining; retry elsewhere",
                               retry_after_ms=self._retry_after_ms())
        if kind == "generate":
            self.predictor.check_gen_opts(beam_size, max_length)
        elif kind != "score":
            raise BadRequest(f"unknown request kind {kind!r}")
        self.predictor.check_sample(sample)
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        deadline = (time.perf_counter() + float(deadline_ms) / 1e3
                    if deadline_ms else None)
        req = _Request(tuple(sample), kind, deadline)
        rec = self.workload_recorder
        try:
            with self._cond:
                if self.fatal is not None:
                    # re-check under the lock: a request racing the
                    # worker's death must not land in a queue nothing
                    # drains
                    raise ServingError(
                        f"serving worker died: {self.fatal!r}")
                if self._draining:
                    raise ShuttingDown(
                        "server is draining; retry elsewhere",
                        retry_after_ms=self._retry_after_ms())
                if len(self._queue) >= self.shed_watermark:
                    self.metrics.inc("shed_total")
                    raise Overloaded(
                        f"queue depth {len(self._queue)} at the shed "
                        f"watermark {self.shed_watermark}",
                        retry_after_ms=self._retry_after_ms())
                self._queue.append(req)
                self.metrics.inc("requests_total")
                self._cond.notify_all()
        except Overloaded as e:  # includes ShuttingDown
            # the shed is part of the offered stream too — a replayed
            # trace must re-offer it (outside the lock, lock-free append)
            if rec is not None:
                rec.observe(req.sample, kind=kind,
                            deadline_ms=deadline_ms, beam_size=beam_size,
                            max_length=max_length, outcome=e.code)
            raise
        if rec is not None:
            rec.observe(req.sample, kind=kind, deadline_ms=deadline_ms,
                        beam_size=beam_size, max_length=max_length,
                        outcome="admitted")
        return req

    def infer(self, sample, *, kind: str = "score",
              deadline_ms: Optional[float] = None, beam_size=None,
              max_length=None, wait_timeout: float = 120.0):
        """Synchronous submit-and-wait; raises the request's typed error
        or returns its result."""
        req = self.submit(sample, kind=kind, deadline_ms=deadline_ms,
                          beam_size=beam_size, max_length=max_length)
        if not req.event.wait(wait_timeout):
            raise DeadlineExceeded(
                f"no answer within wait_timeout={wait_timeout}s")
        if req.error is not None:
            raise req.error
        return req.result

    # ------------------------------------------------------------- worker
    def _expire_locked(self, now: float):
        live = []
        for r in self._queue:
            if r.expired(now):
                r.error = DeadlineExceeded(
                    "deadline passed while queued "
                    f"(queued {1e3 * (now - r.enqueue_t):.1f} ms)")
                r.timings["queue_wait"] = 1e3 * (now - r.enqueue_t)
                r.event.set()
                self._trace_backlog.append(r)
                self.metrics.inc("deadline_exceeded_total")
            else:
                live.append(r)
        self._queue[:] = live

    def _collect(self) -> Optional[List[_Request]]:
        """Block for the next coalesced batch; None when drained dry."""
        with self._cond:
            while True:
                now = time.perf_counter()
                self._expire_locked(now)
                if self._queue:
                    break
                if self._draining:
                    return None
                self._cond.wait(0.1)
            head = self._queue[0]
            window_end = time.perf_counter() + self.batch_timeout_ms / 1e3
            if head.deadline is not None:
                # dispatch before the head's deadline, not after
                window_end = min(window_end, head.deadline)
            while True:
                now = time.perf_counter()
                self._expire_locked(now)
                batch = [r for r in self._queue
                         if r.kind == head.kind][:self.max_batch]
                if len(batch) >= self.max_batch or self._draining:
                    break
                remaining = window_end - now
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            for r in batch:
                self._queue.remove(r)
            # claim the in-flight window BEFORE releasing the queue
            # lock: a remote drain_wait polling /healthz must never
            # observe queue_depth==0 AND inflight==0 while a popped
            # batch has not run yet (it would reap the process under
            # the batch)
            self._inflight = len(batch)
            self._cond.notify_all()
            return batch

    def _work(self):
        while True:
            batch = None
            try:
                batch = self._collect()
                self._drain_trace_backlog()
                if batch is None:
                    logger.info("serving: worker drained and stopped")
                    if _flight._ACTIVE is not None:
                        _flight._ACTIVE.record("drain_end")
                    return
                if batch:
                    try:
                        if _chaos._ACTIVE is not None:
                            # straggler injection point: a FaultPlan
                            # stall here models a slow device step —
                            # deadline and retry_after_ms behavior must
                            # stay honest
                            _chaos._ACTIVE.hit("serve_batch",
                                               kind=batch[0].kind,
                                               size=len(batch))
                        if (self._session is not None
                                and batch[0].kind == "generate"):
                            self._run_generate_continuous(batch)
                        else:
                            self._run_batch(batch)
                    finally:
                        self._inflight = 0
                        self._drain_trace_backlog()
            except BaseException as e:  # noqa: BLE001 — a worker bug
                self.fatal = e
                logger.error("serving worker died: %r", e)
                err = ServingError(f"serving worker died: {e!r}")
                # answer EVERYTHING in flight — the collected batch was
                # already off the queue, so it must be errored explicitly
                # or its callers would block forever
                for r in batch or []:
                    if not r.event.is_set():
                        r.error = r.error or err
                        r.event.set()
                        self._emit_trace(r)
                with self._cond:
                    for r in self._queue:
                        r.error = err
                        r.event.set()
                        self._trace_backlog.append(r)
                    self._queue.clear()
                self._drain_trace_backlog()
                self.metrics.inc("internal_error_total")
                if _flight._ACTIVE is not None:
                    # worker-fatal is EXACTLY what a black box is for:
                    # record and dump now (the process may linger
                    # answering health checks, never reaching atexit
                    # with anything this recent)
                    _flight._ACTIVE.record("worker_fatal", error=repr(e))
                    _flight.dump_now()
                raise

    # ------------------------------------------------- continuous decode
    def _steal_queued(self, kind: str, n: int) -> List[_Request]:
        """Pop up to ``n`` queued requests of ``kind`` (expiring stale
        ones first) — the chunk-boundary admission path. Draining does
        not close this: queued work is answered during drain.

        Fairness: when a request of another kind is waiting, nothing is
        stolen — the continuous loop then drains its live lanes and
        returns to ``_collect``, which serves the queue head in arrival
        order. Without this, sustained generate traffic keeping one lane
        live forever would starve queued scoring requests."""
        if n <= 0:
            return []
        with self._cond:
            self._expire_locked(time.perf_counter())
            if any(r.kind != kind for r in self._queue):
                return []
            take = [r for r in self._queue if r.kind == kind][:n]
            for r in take:
                self._queue.remove(r)
            if take:
                self._cond.notify_all()
            return take

    def _admit_lane(self, sess, lane: int, req: _Request,
                    now: float) -> bool:
        """Encode one request and splice it into ``lane``. Admission is
        inherently per-request, so a malformed request fails ALONE here
        (typed 400) — the continuous path gets lane isolation for free,
        no probe pass needed. Only the feeder/encode conversion is
        client-attributable; a failure in ``sess.admit`` is a server bug
        and propagates to the worker-fatal path, never a 400."""
        t0 = time.perf_counter()
        try:
            outer = self.predictor.encode_rows([req.sample])
        except ServingError as e:
            req.error = e
            req.event.set()
            self.metrics.inc("bad_request_total")
            return False
        except (ValueError, TypeError, KeyError) as e:
            req.error = BadRequest(str(e))
            req.event.set()
            self.metrics.inc("bad_request_total")
            return False
        sess.admit(lane, outer, row=0)
        req.timings["queue_wait"] = 1e3 * (now - req.enqueue_t)
        req.timings["pad_overhead"] = 1e3 * (time.perf_counter() - t0)
        req.timings["compute"] = 0.0
        return True

    def _retire_lane(self, sess, lane: int, req: _Request):
        """Answer a finished lane and free it."""
        td0 = time.perf_counter()
        tokens, scores, lengths, steps = sess.peek(lane)
        sess.release(lane)
        req.result = {"sequences": [
            {"tokens": tokens[k, :int(lengths[k])].tolist(),
             "score": float(scores[k])}
            for k in range(tokens.shape[0])]}
        now = time.perf_counter()
        req.timings["decode"] = 1e3 * (now - td0)
        self.metrics.observe_decode(steps, sess.L - steps)
        if req.expired(now):
            req.error = DeadlineExceeded(
                "computed, but past the deadline "
                f"(total {1e3 * (now - req.enqueue_t):.1f} ms)")
            self.metrics.inc("deadline_exceeded_total")
        else:
            self.metrics.observe_request(req.timings)
        req.event.set()
        self._emit_trace(req)
        # per-request service time (admission -> retire; queue wait
        # excluded, or the drain estimate would double-count backlog
        # when _retry_after_ms multiplies by queued batches) feeds the
        # estimator — there is no whole-batch wall time in continuous
        # mode
        service_ms = max(0.0, 1e3 * (now - req.enqueue_t)
                         - req.timings.get("queue_wait", 0.0))
        self._batch_ewma_ms += 0.25 * (service_ms - self._batch_ewma_ms)

    def _run_generate_continuous(self, reqs: List[_Request]):
        """Drive the decode session until the seed batch AND everything
        admitted from the queue at chunk boundaries is answered. Returns
        to ``_collect`` (scoring requests interleave there) only when no
        generate lane is live."""
        sess = self._session
        pending = deque(reqs)
        lanes: Dict[int, _Request] = {}
        started = False
        try:
            while True:
                # ---- admit into free lanes: seed batch first, then the
                # queue (mid-decode admission, the anti-convoy move)
                free = deque(sess.free_lanes())
                while free:
                    if not pending:
                        pending.extend(
                            self._steal_queued("generate", len(free)))
                        if not pending:
                            break
                    req = pending.popleft()
                    now = time.perf_counter()
                    if req.expired(now):
                        req.error = DeadlineExceeded(
                            "deadline passed while queued "
                            f"(queued {1e3 * (now - req.enqueue_t):.1f} "
                            "ms)")
                        req.event.set()
                        self._emit_trace(req)
                        self.metrics.inc("deadline_exceeded_total")
                        continue
                    lane = free.popleft()
                    if self._admit_lane(sess, lane, req, now):
                        lanes[lane] = req
                        if started:
                            self.metrics.inc(
                                "continuous_admissions_total")
                    else:
                        free.append(lane)  # admission failed; still free
                if not lanes:
                    return
                # ---- one chunk for every live lane
                t0 = time.perf_counter()
                sess.run_chunk()
                chunk_ms = 1e3 * (time.perf_counter() - t0)
                started = True
                self.metrics.observe_lanes(len(lanes), sess.width)
                for req in lanes.values():
                    req.timings["compute"] += chunk_ms
                # one fused flag fetch serves both the deadline sweep
                # and the retire sweep — per-accessor fetches would put
                # several serialized host round-trips on the hot path
                active, fin, t = sess.poll()
                # ---- deadlines are checkable mid-decode: an expired
                # lane is answered and freed NOW, not at search end
                now = time.perf_counter()
                for lane, req in list(lanes.items()):
                    if req.expired(now):
                        req.error = DeadlineExceeded(
                            "deadline passed mid-decode "
                            f"(total {1e3 * (now - req.enqueue_t):.1f} "
                            f"ms, {int(t[lane])} steps in)")
                        req.event.set()
                        self._emit_trace(req)
                        self.metrics.inc("deadline_exceeded_total")
                        sess.release(lane)
                        del lanes[lane]
                # ---- retire finished lanes
                for lane in range(sess.width):
                    if not (active[lane] and (fin[lane]
                                              or t[lane] >= sess.L)):
                        continue
                    req = lanes.pop(lane, None)
                    if req is not None:
                        self._retire_lane(sess, lane, req)
        except BaseException as e:  # noqa: BLE001 — worker bug
            # answer every in-flight lane + the unadmitted tail before
            # _work's handler deals with the shared queue; events set
            # here make _work's batch sweep skip them
            err = ServingError(f"serving worker died: {e!r}")
            for req in list(lanes.values()) + list(pending):
                if not req.event.is_set():
                    req.error = req.error or err
                    req.event.set()
                    self._emit_trace(req)
            raise

    # ------------------------------------------------------------- spans
    def _drain_trace_backlog(self):
        """Record the spans of requests that were answered while the
        engine lock was held (queue expiry, drain=False shed,
        worker-fatal). Called from lock-free contexts only; the deque's
        popleft is GIL-atomic against concurrent appends."""
        while True:
            try:
                req = self._trace_backlog.popleft()
            except IndexError:
                return
            self._emit_trace(req)

    def _emit_trace(self, req: _Request):
        """Turn one answered request's timing split into real spans:
        a ``replica.<kind>`` parent covering enqueue → answer and the
        four phase children, laid end to end from the enqueue wall
        time (they partition the parent by construction). Worker
        thread, after ``event.set()``, no engine lock held — the obs
        plane never nests under a subsystem lock."""
        tracer = _trace._TRACER
        if tracer is None or req.trace is None:
            return
        total = sum(req.timings.get(p, 0.0) for p in _PHASES)
        parent = tracer.record_span(
            f"replica.{req.kind}", trace_id=req.trace.trace_id,
            parent_id=req.trace.span_id, ts=req.wall_t, dur_ms=total,
            status="ok" if req.error is None else "error",
            error=(type(req.error).__name__ if req.error else None))
        t = req.wall_t
        for p in _PHASES:
            ms = req.timings.get(p)
            if ms is None:
                continue
            tracer.record_span(f"phase.{p}",
                               trace_id=req.trace.trace_id,
                               parent_id=parent, ts=t, dur_ms=ms)
            t += ms / 1e3

    # ------------------------------------------------------------ batches
    def _predict(self, kind: str, rows, lane_valid=None):
        if kind == "generate":
            return self.predictor.generate_rows(rows, lane_valid)
        return self.predictor.predict_rows(rows, lane_valid)

    def _run_batch(self, reqs: List[_Request]):
        t_assemble = time.perf_counter()
        kind = reqs[0].kind
        rows = [r.sample for r in reqs]
        lane_valid = [True] * len(reqs)
        t0 = time.perf_counter()
        try:
            outs, info = self._predict(kind, rows)
        except (BadRequest, ValueError, TypeError, KeyError) as batch_err:
            # conversion failed somewhere in the batch: probe per lane,
            # replace bad rows with synthetic padding, zero their mask
            # lanes, and answer neighbors from the cleaned batch
            probe = self.predictor.probe_rows(rows)
            clean_rows = list(rows)
            for i, err in enumerate(probe):
                if err is not None:
                    lane_valid[i] = False
                    clean_rows[i] = self.predictor.padding_row()
                    reqs[i].error = (err if isinstance(err, BadRequest)
                                     else BadRequest(str(err)))
                    self.metrics.inc("bad_request_total")
            if all(lane_valid):
                # conversion failed but no single lane reproduces it —
                # a batch-level problem; every request gets the error
                for r in reqs:
                    r.error = (batch_err
                               if isinstance(batch_err, BadRequest)
                               else BadRequest(str(batch_err)))
                    r.event.set()
                    self._emit_trace(r)
                    self.metrics.inc("bad_request_total")
                return
            outs, info = self._predict(kind, clean_rows, lane_valid)
        except ServingError as e:
            for r in reqs:
                r.error = e
                r.event.set()
                self._emit_trace(r)
            return
        wall_ms = 1e3 * (time.perf_counter() - t0)
        self._batch_ewma_ms += 0.25 * (wall_ms - self._batch_ewma_ms)
        self.metrics.observe_batch(
            info["bucket"], real_rows=sum(lane_valid),
            padded_rows=info["padded_rows"])
        pad_ms, compute_ms = info["pad_ms"], info["compute_ms"]
        for i, r in enumerate(reqs):
            if r.error is not None:  # malformed lane, already typed
                r.event.set()
                self._emit_trace(r)
                continue
            if kind == "generate":
                # convoy accounting: every rider pays the batch's shared
                # early-exit step count (continuous mode records each
                # lane's own)
                self.metrics.observe_decode(info.get("decode_steps"),
                                            info.get("steps_saved"))
            td0 = time.perf_counter()
            r.result = self._decode(kind, outs, i)
            now = time.perf_counter()
            r.timings = {
                "queue_wait": 1e3 * (t_assemble - r.enqueue_t),
                "pad_overhead": pad_ms,
                "compute": compute_ms,
                "decode": 1e3 * (now - td0),
            }
            if r.expired(now):
                r.error = DeadlineExceeded(
                    "computed, but past the deadline "
                    f"(total {1e3 * (now - r.enqueue_t):.1f} ms)")
                self.metrics.inc("deadline_exceeded_total")
            else:
                self.metrics.observe_request(r.timings)
            r.event.set()
            self._emit_trace(r)
        self._maybe_replay(kind, reqs, lane_valid)

    def _maybe_replay(self, kind: str, reqs: List[_Request], lane_valid):
        """Append this batch's successfully-answered score rows to the
        replay sink. Worker thread, AFTER every caller is answered and
        with no engine lock held — replay durability is never on a
        request's latency path. A failed append (full disk, or a chaos
        ``drop`` at ``replay_append``) sheds the rows with a counter;
        ``ChaosKilled`` is a BaseException and still takes the worker
        down, the replica-death drill."""
        if self.replay_sink is None or kind != "score":
            return
        rows = [r.sample for i, r in enumerate(reqs)
                if lane_valid[i] and r.error is None]
        if not rows:
            return
        try:
            for row in rows:
                self.replay_sink.append(row)
        except OSError as e:  # ChaosDropped is a ConnectionError too
            self.metrics.inc("replay_dropped_total", len(rows))
            logger.warning("replay append shed %d row(s): %r",
                           len(rows), e)

    @staticmethod
    def _decode(kind: str, outs, lane: int):
        if kind == "generate":
            tokens, scores, lengths = outs
            return {"sequences": [
                {"tokens": tokens[lane, k, :int(lengths[lane, k])].tolist(),
                 "score": float(scores[lane, k])}
                for k in range(tokens.shape[1])]}
        return {"outputs": {name: v[lane].tolist()
                            for name, v in outs.items()}}
