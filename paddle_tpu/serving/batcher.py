"""Dynamic micro-batching engine: queue, coalesce, deadline, shed, drain.

The chip-side economics: one jitted call over a padded batch of N costs
barely more than a batch of 1 (the MXU is wildly under-filled at small
N), so concurrent single-row requests should ride ONE program launch.
The engine holds a bounded request queue; a single worker thread
coalesces whatever is waiting — same endpoint kind, up to ``max_batch``
— within a ``batch_timeout`` window into the smallest admissible batch
bucket, runs it, and fans results back out. This is the TensorFlow
Serving / "dynamic batcher" shape of the problem, sitting on the deploy
surface the reference ships as merged-model + C API (SURVEY L7b).

Production behaviors, all typed (``serving/errors.py``):

- **Deadlines** — per-request; a request that expires in the queue is
  answered ``DeadlineExceeded`` without wasting compute, and one whose
  batch finishes too late is answered the same (the work is sunk, the
  answer honest).
- **Admission control / load shedding** — the queue is bounded; past
  ``shed_watermark`` new requests get ``Overloaded`` with a
  ``retry_after_ms`` drain estimate (EWMA batch time × queued batches).
- **Drain** — ``begin_drain()`` (the SIGTERM handler) closes admission
  (``ShuttingDown``) while the worker finishes every queued request;
  ``shutdown()`` waits for that and stops the worker.
- **Lane isolation** — a malformed request discovered at batch-assembly
  time (conversion failure, e.g. an id outside the declared range)
  cannot poison the batch it was coalesced into: bad rows are probed
  out per-lane, replaced with synthetic padding rows, and their row-mask
  lanes zeroed; the bad request alone gets ``BadRequest``, its
  neighbors' answers are bit-identical to a clean batch's.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from paddle_tpu.serving.errors import (BadRequest, DeadlineExceeded,
                                       Overloaded, ServingError,
                                       ShuttingDown)
from paddle_tpu.serving.metrics import ServingMetrics
from paddle_tpu.utils.log import get_logger

logger = get_logger("serving")


class _Request:
    __slots__ = ("sample", "kind", "enqueue_t", "deadline", "event",
                 "result", "error", "timings")

    def __init__(self, sample, kind: str, deadline: Optional[float]):
        self.sample = sample
        self.kind = kind
        self.enqueue_t = time.perf_counter()
        self.deadline = deadline  # absolute perf_counter time, or None
        self.event = threading.Event()
        self.result = None
        self.error: Optional[ServingError] = None
        self.timings: Dict[str, float] = {}

    def expired(self, now: Optional[float] = None) -> bool:
        return (self.deadline is not None
                and (now or time.perf_counter()) > self.deadline)


class ServingEngine:
    """One predictor + one worker thread + one bounded queue."""

    def __init__(self, predictor, *, max_batch: Optional[int] = None,
                 batch_timeout_ms: float = 5.0, queue_depth: int = 64,
                 shed_watermark: Optional[int] = None,
                 default_deadline_ms: Optional[float] = None,
                 metrics: Optional[ServingMetrics] = None):
        self.predictor = predictor
        self.max_batch = int(max_batch or predictor.batch_buckets[-1])
        if self.max_batch > predictor.batch_buckets[-1]:
            raise ValueError(
                f"max_batch {self.max_batch} exceeds the largest warmed "
                f"batch bucket {predictor.batch_buckets[-1]}")
        self.batch_timeout_ms = float(batch_timeout_ms)
        self.queue_depth = int(queue_depth)
        # the queue bound is queue_depth, full stop — a watermark above
        # it would silently unbound the "bounded" queue
        self.shed_watermark = min(int(shed_watermark or queue_depth),
                                  self.queue_depth)
        self.default_deadline_ms = default_deadline_ms
        self.metrics = metrics or ServingMetrics()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: List[_Request] = []
        self._draining = False
        self._batch_ewma_ms = 10.0  # drain-time estimator seed
        self._thread: Optional[threading.Thread] = None
        self.fatal: Optional[BaseException] = None

    # ------------------------------------------------------------ control
    def start(self, warmup: bool = True) -> "ServingEngine":
        if warmup and not self.predictor.warmed:
            self.predictor.warmup(log=logger.info)
        self._thread = threading.Thread(target=self._work,
                                        name="serving-batcher", daemon=True)
        self._thread.start()
        return self

    @property
    def draining(self) -> bool:
        return self._draining

    def queue_len(self) -> int:
        with self._lock:
            return len(self._queue)

    def begin_drain(self):
        """Close admission; queued and in-flight work still completes.
        The SIGTERM handler calls this (``serving/server.py``)."""
        with self._cond:
            if not self._draining:
                logger.info("serving: draining (admission closed, "
                            "%d queued)", len(self._queue))
            self._draining = True
            self._cond.notify_all()

    def shutdown(self, drain: bool = True, timeout: float = 30.0):
        """Drain (default) or abort the queue, then stop the worker."""
        with self._cond:
            self._draining = True
            if not drain:
                for r in self._queue:
                    r.error = ShuttingDown(
                        "server shutting down; request not started")
                    r.event.set()
                    self.metrics.inc("shed_total")
                self._queue.clear()
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    # ---------------------------------------------------------- admission
    def _retry_after_ms(self) -> float:
        backlog_batches = max(len(self._queue), 1) / self.max_batch
        return max(self.batch_timeout_ms,
                   self._batch_ewma_ms * backlog_batches)

    def submit(self, sample, *, kind: str = "score",
               deadline_ms: Optional[float] = None,
               beam_size=None, max_length=None) -> _Request:
        """Admit one request; raises typed errors synchronously (shed /
        draining / inadmissible shape). Returns the pending request —
        wait on ``.event`` and read ``.result`` / ``.error``."""
        if self.fatal is not None:
            # the worker is dead (a bug, not load): admitting would
            # enqueue into a queue nothing drains
            raise ServingError(f"serving worker died: {self.fatal!r}")
        if self._draining:
            raise ShuttingDown("server is draining; retry elsewhere",
                               retry_after_ms=self._retry_after_ms())
        if kind == "generate":
            self.predictor.check_gen_opts(beam_size, max_length)
        elif kind != "score":
            raise BadRequest(f"unknown request kind {kind!r}")
        self.predictor.check_sample(sample)
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        deadline = (time.perf_counter() + float(deadline_ms) / 1e3
                    if deadline_ms else None)
        req = _Request(tuple(sample), kind, deadline)
        with self._cond:
            if self.fatal is not None:
                # re-check under the lock: a request racing the worker's
                # death must not land in a queue nothing drains
                raise ServingError(
                    f"serving worker died: {self.fatal!r}")
            if self._draining:
                raise ShuttingDown(
                    "server is draining; retry elsewhere",
                    retry_after_ms=self._retry_after_ms())
            if len(self._queue) >= self.shed_watermark:
                self.metrics.inc("shed_total")
                raise Overloaded(
                    f"queue depth {len(self._queue)} at the shed "
                    f"watermark {self.shed_watermark}",
                    retry_after_ms=self._retry_after_ms())
            self._queue.append(req)
            self.metrics.inc("requests_total")
            self._cond.notify_all()
        return req

    def infer(self, sample, *, kind: str = "score",
              deadline_ms: Optional[float] = None, beam_size=None,
              max_length=None, wait_timeout: float = 120.0):
        """Synchronous submit-and-wait; raises the request's typed error
        or returns its result."""
        req = self.submit(sample, kind=kind, deadline_ms=deadline_ms,
                          beam_size=beam_size, max_length=max_length)
        if not req.event.wait(wait_timeout):
            raise DeadlineExceeded(
                f"no answer within wait_timeout={wait_timeout}s")
        if req.error is not None:
            raise req.error
        return req.result

    # ------------------------------------------------------------- worker
    def _expire_locked(self, now: float):
        live = []
        for r in self._queue:
            if r.expired(now):
                r.error = DeadlineExceeded(
                    "deadline passed while queued "
                    f"(queued {1e3 * (now - r.enqueue_t):.1f} ms)")
                r.timings["queue_wait"] = 1e3 * (now - r.enqueue_t)
                r.event.set()
                self.metrics.inc("deadline_exceeded_total")
            else:
                live.append(r)
        self._queue[:] = live

    def _collect(self) -> Optional[List[_Request]]:
        """Block for the next coalesced batch; None when drained dry."""
        with self._cond:
            while True:
                now = time.perf_counter()
                self._expire_locked(now)
                if self._queue:
                    break
                if self._draining:
                    return None
                self._cond.wait(0.1)
            head = self._queue[0]
            window_end = time.perf_counter() + self.batch_timeout_ms / 1e3
            if head.deadline is not None:
                # dispatch before the head's deadline, not after
                window_end = min(window_end, head.deadline)
            while True:
                now = time.perf_counter()
                self._expire_locked(now)
                batch = [r for r in self._queue
                         if r.kind == head.kind][:self.max_batch]
                if len(batch) >= self.max_batch or self._draining:
                    break
                remaining = window_end - now
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            for r in batch:
                self._queue.remove(r)
            self._cond.notify_all()
            return batch

    def _work(self):
        while True:
            batch = None
            try:
                batch = self._collect()
                if batch is None:
                    logger.info("serving: worker drained and stopped")
                    return
                if batch:
                    self._run_batch(batch)
            except BaseException as e:  # noqa: BLE001 — a worker bug
                self.fatal = e
                logger.error("serving worker died: %r", e)
                err = ServingError(f"serving worker died: {e!r}")
                # answer EVERYTHING in flight — the collected batch was
                # already off the queue, so it must be errored explicitly
                # or its callers would block forever
                for r in batch or []:
                    if not r.event.is_set():
                        r.error = r.error or err
                        r.event.set()
                with self._cond:
                    for r in self._queue:
                        r.error = err
                        r.event.set()
                    self._queue.clear()
                self.metrics.inc("internal_error_total")
                raise

    # ------------------------------------------------------------ batches
    def _predict(self, kind: str, rows, lane_valid=None):
        if kind == "generate":
            return self.predictor.generate_rows(rows, lane_valid)
        return self.predictor.predict_rows(rows, lane_valid)

    def _run_batch(self, reqs: List[_Request]):
        t_assemble = time.perf_counter()
        kind = reqs[0].kind
        rows = [r.sample for r in reqs]
        lane_valid = [True] * len(reqs)
        t0 = time.perf_counter()
        try:
            outs, info = self._predict(kind, rows)
        except (BadRequest, ValueError, TypeError, KeyError) as batch_err:
            # conversion failed somewhere in the batch: probe per lane,
            # replace bad rows with synthetic padding, zero their mask
            # lanes, and answer neighbors from the cleaned batch
            probe = self.predictor.probe_rows(rows)
            clean_rows = list(rows)
            for i, err in enumerate(probe):
                if err is not None:
                    lane_valid[i] = False
                    clean_rows[i] = self.predictor.padding_row()
                    reqs[i].error = (err if isinstance(err, BadRequest)
                                     else BadRequest(str(err)))
                    self.metrics.inc("bad_request_total")
            if all(lane_valid):
                # conversion failed but no single lane reproduces it —
                # a batch-level problem; every request gets the error
                for r in reqs:
                    r.error = (batch_err
                               if isinstance(batch_err, BadRequest)
                               else BadRequest(str(batch_err)))
                    r.event.set()
                    self.metrics.inc("bad_request_total")
                return
            outs, info = self._predict(kind, clean_rows, lane_valid)
        except ServingError as e:
            for r in reqs:
                r.error = e
                r.event.set()
            return
        wall_ms = 1e3 * (time.perf_counter() - t0)
        self._batch_ewma_ms += 0.25 * (wall_ms - self._batch_ewma_ms)
        self.metrics.observe_batch(
            info["bucket"], real_rows=sum(lane_valid),
            padded_rows=info["padded_rows"])
        pad_ms, compute_ms = info["pad_ms"], info["compute_ms"]
        for i, r in enumerate(reqs):
            if r.error is not None:  # malformed lane, already typed
                r.event.set()
                continue
            td0 = time.perf_counter()
            r.result = self._decode(kind, outs, i)
            now = time.perf_counter()
            r.timings = {
                "queue_wait": 1e3 * (t_assemble - r.enqueue_t),
                "pad_overhead": pad_ms,
                "compute": compute_ms,
                "decode": 1e3 * (now - td0),
            }
            if r.expired(now):
                r.error = DeadlineExceeded(
                    "computed, but past the deadline "
                    f"(total {1e3 * (now - r.enqueue_t):.1f} ms)")
                self.metrics.inc("deadline_exceeded_total")
            else:
                self.metrics.observe_request(r.timings)
            r.event.set()

    @staticmethod
    def _decode(kind: str, outs, lane: int):
        if kind == "generate":
            tokens, scores, lengths = outs
            return {"sequences": [
                {"tokens": tokens[lane, k, :int(lengths[lane, k])].tolist(),
                 "score": float(scores[lane, k])}
                for k in range(tokens.shape[1])]}
        return {"outputs": {name: v[lane].tolist()
                            for name, v in outs.items()}}
