"""Deterministic trace replay: record a serving request stream, replay
it against a fleet, score the outcome.

The self-tuning loop (``serving/tuner.py``) needs a way to ask "would
this knob config have served yesterday's traffic better?" without
yesterday's traffic. This module closes that loop in three pieces:

- :class:`WorkloadRecorder` — a lock-free tap on the admission paths
  (``ServingEngine.submit`` records admitted AND shed offers,
  ``ReplicaRouter._dispatch`` records the fleet-level offered stream).
  One ``deque.append`` per request, outside every subsystem lock, off
  the latency path — the same discipline as the r20 online-loop replay
  sink (``batcher.py:_maybe_replay``).
- :class:`Workload` — the recorded stream as a committed
  ``WORKLOAD_*.json`` artifact: relative arrival time, request kind,
  the sample itself (traces are self-contained — replay needs no
  dataset), generate options and deadline per event. Schema checked by
  PT401 (``analysis/bench_schema.py``).
- :func:`replay` / :func:`replay_score` — re-offer every event at its
  recorded offset (one pacer-released thread per event, so concurrent
  arrivals overlap exactly as recorded) against any dispatch callable
  (an engine, an :class:`~paddle_tpu.serving.router.ReplicaRouter`, an
  ``InProcessFleet``), and fold the outcomes into a summary the SLO
  score (``tuner.py:slo_score``) consumes.

Determinism contract: the EVENT stream is exactly reproducible — same
trace in, same offers out, counts (``offered``/``ok``/``shed``/
``deadline_miss``/``failed_non_shed``) and their derived rates are
structural. Absolute latencies are NOT bit-stable on a shared host
(throughput drifts ±50% between runs — CLAUDE.md), so ``replay_score``
takes each latency metric's best over R interleaved rounds (the
``_timed_chain`` min discipline) and callers comparing scores declare
:data:`SCORE_DRIFT_BOUND` as the tolerance; counts and failure totals
are compared exactly (and ``failed_non_shed`` is SUMMED across rounds,
never hidden behind a best-of).
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Callable, List, Optional

from paddle_tpu.serving.errors import (DeadlineExceeded, Overloaded,
                                       ServingError)
from paddle_tpu.utils.log import get_logger

logger = get_logger("serving.workload")

WORKLOAD_VERSION = 1

# declared drift bound for score comparisons between two replays of the
# SAME trace + config on this host: counts are exact, the latency
# factor of the score moves with host load. Tests and the in-bench
# determinism assert both cite this one constant.
SCORE_DRIFT_BOUND = 0.25

# keys every event carries (the PT401 family join checks these): a
# trace is replayable by construction, not by convention.
EVENT_KEYS = ("t", "kind", "sample", "deadline_ms", "beam_size",
              "max_length", "outcome")


class WorkloadRecorder:
    """Admission-stream tap. Install as ``engine.workload_recorder`` /
    ``router.workload_recorder``; every offered request becomes one
    event stamped with its arrival offset from the FIRST event (traces
    start at t=0 regardless of when recording was switched on).

    Lock-free by the replay-sink argument: ``deque.append`` is atomic
    under CPython, the recorder is bounded (``maxlen``), and a dropped
    oldest event under overflow is a truncated trace, not a serving
    failure. Never touched under the engine/router lock.
    """

    def __init__(self, maxlen: int = 100_000):
        self._events: deque = deque(maxlen=maxlen)
        self._t0: Optional[float] = None
        self._t0_lock = threading.Lock()  # only the FIRST event races

    def observe(self, sample, *, kind: str = "score",
                deadline_ms: Optional[float] = None,
                beam_size=None, max_length=None,
                outcome: str = "offered") -> None:
        now = time.perf_counter()
        if self._t0 is None:
            with self._t0_lock:
                if self._t0 is None:
                    self._t0 = now
        self._events.append({
            "t": max(0.0, now - self._t0),
            "kind": kind,
            "sample": _jsonify(sample),
            "deadline_ms": (float(deadline_ms)
                            if deadline_ms is not None else None),
            "beam_size": (int(beam_size) if beam_size is not None
                          else None),
            "max_length": (int(max_length) if max_length is not None
                           else None),
            "outcome": outcome,
        })

    def __len__(self) -> int:
        return len(self._events)

    def snapshot(self, name: str) -> "Workload":
        """The trace so far, time-ordered (concurrent admission threads
        may append a hair out of order; replay pacing needs monotone
        offsets)."""
        events = sorted(self._events, key=lambda e: e["t"])
        return Workload(name, events)

    def clear(self) -> None:
        self._events.clear()
        self._t0 = None


def _jsonify(sample):
    """Samples arrive as tuples of tuples/arrays; the artifact stores
    plain lists so ``load(save(w))`` round-trips identically."""
    if isinstance(sample, (list, tuple)):
        return [_jsonify(v) for v in sample]
    if hasattr(sample, "tolist"):
        return sample.tolist()
    return sample


class Workload:
    """A named, replayable request trace — the ``WORKLOAD_*.json``
    artifact in memory."""

    def __init__(self, name: str, events: List[dict]):
        self.name = name
        self.events = [self._check_event(i, dict(e))
                       for i, e in enumerate(events)]

    @staticmethod
    def _check_event(i: int, e: dict) -> dict:
        for k in ("t", "kind", "sample"):
            if k not in e:
                raise ValueError(f"workload event {i} missing {k!r}")
        if e["kind"] not in ("score", "generate"):
            raise ValueError(
                f"workload event {i}: unknown kind {e['kind']!r}")
        for k in ("deadline_ms", "beam_size", "max_length"):
            e.setdefault(k, None)
        e.setdefault("outcome", "offered")
        return e

    @property
    def duration_s(self) -> float:
        return self.events[-1]["t"] if self.events else 0.0

    def to_dict(self) -> dict:
        return {"workload": self.name,
                "version": WORKLOAD_VERSION,
                "n_events": len(self.events),
                "duration_s": self.duration_s,
                "events": self.events}

    def save(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_dict(), f)
            f.write("\n")
        logger.info("workload %s: %d events over %.2fs -> %s",
                    self.name, len(self.events), self.duration_s, path)
        return path

    @classmethod
    def load(cls, path: str) -> "Workload":
        with open(path) as f:
            d = json.load(f)
        if d.get("version") != WORKLOAD_VERSION:
            raise ValueError(
                f"{path}: workload version {d.get('version')!r}, "
                f"expected {WORKLOAD_VERSION}")
        w = cls(d["workload"], d["events"])
        if d.get("n_events") != len(w.events):
            raise ValueError(
                f"{path}: n_events {d.get('n_events')} != "
                f"{len(w.events)} events present")
        return w


# ------------------------------------------------------------- dispatch

def engine_dispatch(engine) -> Callable[[dict], object]:
    """Dispatch callable over one :class:`ServingEngine` (or anything
    with its ``infer`` signature)."""
    def _call(ev: dict):
        return engine.infer(ev["sample"], kind=ev["kind"],
                            deadline_ms=ev["deadline_ms"],
                            beam_size=ev["beam_size"],
                            max_length=ev["max_length"])
    return _call


def router_dispatch(router) -> Callable[[dict], object]:
    """Dispatch callable over a :class:`ReplicaRouter` (pass
    ``fleet.router`` for an ``InProcessFleet``)."""
    def _call(ev: dict):
        result, _prov = router.dispatch(ev["sample"], kind=ev["kind"],
                                        deadline_ms=ev["deadline_ms"],
                                        beam_size=ev["beam_size"],
                                        max_length=ev["max_length"])
        return result
    return _call


# --------------------------------------------------------------- replay

def replay(workload: Workload, dispatch: Callable[[dict], object], *,
           speed: float = 1.0, wait_timeout_s: float = 120.0) -> dict:
    """Re-offer every event of ``workload`` at its recorded arrival
    offset (divided by ``speed``) against ``dispatch`` and fold the
    outcomes into a summary.

    One thread per event, all released against a shared start
    instant, each sleeping until its own due time — concurrent
    arrivals in the trace are concurrent offers in the replay, which
    is what exercises batching/shedding the way the live stream did.
    Every event is accounted for exactly once:
    ``ok + shed + deadline_miss + failed_non_shed == offered``.

    Outcome classes map from the typed error family:
    :class:`Overloaded` (and subclasses — shed, drain, fleet 429) ⇒
    ``shed``; :class:`DeadlineExceeded` ⇒ ``deadline_miss``; any other
    failure ⇒ ``failed_non_shed`` (a replay with nonzero
    ``failed_non_shed`` found a BUG, not a tuning datum). Latency
    stats are over ``ok`` events only — a shed answers in microseconds
    and would flatter p50 if counted.
    """
    if speed <= 0:
        raise ValueError(f"speed must be > 0, got {speed}")
    events = workload.events
    n = len(events)
    lat_ms: List[float] = [0.0] * n
    outcome: List[str] = ["failed_non_shed"] * n
    errors: List[str] = []
    err_lock = threading.Lock()
    start = time.perf_counter() + 0.05  # lead-in: let all threads park

    def _one(i: int, ev: dict):
        due = start + ev["t"] / speed
        delay = due - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        t_off = time.perf_counter()
        try:
            dispatch(ev)
            outcome[i] = "ok"
        except Overloaded:
            outcome[i] = "shed"
        except DeadlineExceeded:
            outcome[i] = "deadline_miss"
        except ServingError as e:
            with err_lock:
                errors.append(f"event {i}: {e.code}: {e}")
        except Exception as e:  # noqa: BLE001 — a replay must not hang
            with err_lock:
                errors.append(f"event {i}: {e!r}")
        lat_ms[i] = (time.perf_counter() - t_off) * 1e3

    threads = [threading.Thread(target=_one, args=(i, ev), daemon=True,
                                name=f"replay-{i}")
               for i, ev in enumerate(events)]
    wall0 = time.perf_counter()
    for t in threads:
        t.start()
    deadline = time.perf_counter() + wait_timeout_s
    for t in threads:
        t.join(max(0.1, deadline - time.perf_counter()))
        if t.is_alive():
            raise TimeoutError(
                f"replay of {workload.name}: thread {t.name} still "
                f"running after {wait_timeout_s}s")
    wall_s = time.perf_counter() - wall0

    ok_lat = sorted(lat_ms[i] for i in range(n) if outcome[i] == "ok")
    counts = {c: outcome.count(c)
              for c in ("ok", "shed", "deadline_miss",
                        "failed_non_shed")}
    summary = {
        "workload": workload.name,
        "offered": n,
        **counts,
        "shed_rate": counts["shed"] / n if n else 0.0,
        "miss_rate": counts["deadline_miss"] / n if n else 0.0,
        "p50_ms": _pct(ok_lat, 0.50),
        "p99_ms": _pct(ok_lat, 0.99),
        "mean_ms": (sum(ok_lat) / len(ok_lat)) if ok_lat else None,
        "throughput_rps": (counts["ok"] / wall_s) if wall_s > 0 else 0.0,
        "duration_s": workload.duration_s,
        "wall_s": wall_s,
        "errors": errors[:8],  # enough to diagnose, bounded in artifacts
    }
    return summary


def _pct(sorted_vals: List[float], q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def replay_score(workload: Workload, dispatch: Callable[[dict], object],
                 slo, *, rounds: int = 2, speed: float = 1.0,
                 wait_timeout_s: float = 120.0) -> dict:
    """Best-of-R replay: run ``rounds`` replays, take each LATENCY
    metric's best (min — the ``_timed_chain`` discipline against the
    host's ±50% drift) and throughput's best (max), keep the counts of
    the LAST round (they are structural — identical across rounds on a
    correct fleet), and SUM ``failed_non_shed`` across every round so a
    bug in any round survives the best-of. Returns the folded summary
    with ``score`` (``tuner.py:slo_score``) and ``rounds`` attached.
    """
    from paddle_tpu.serving.tuner import slo_score
    if rounds < 1:
        raise ValueError(f"rounds must be >= 1, got {rounds}")
    sums: List[dict] = []
    for _ in range(rounds):
        sums.append(replay(workload, dispatch, speed=speed,
                           wait_timeout_s=wait_timeout_s))
    best = dict(sums[-1])
    for key, pick in (("p50_ms", min), ("p99_ms", min), ("mean_ms", min),
                      ("throughput_rps", max), ("wall_s", min)):
        vals = [s[key] for s in sums if s[key] is not None]
        best[key] = pick(vals) if vals else None
    # never best-of a failure count: a single bad round is a finding
    best["failed_non_shed"] = sum(s["failed_non_shed"] for s in sums)
    best["errors"] = [e for s in sums for e in s["errors"]][:8]
    best["rounds"] = rounds
    best["score"] = slo_score(best, slo)
    best["slo"] = slo.to_dict()
    return best
