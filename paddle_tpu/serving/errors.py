"""Typed serving errors — the wire contract for everything that is NOT
a 500.

The reference's C inference API signals failure through ``paddle_error``
return codes (``paddle/capi/error.h``); an HTTP serving plane needs the
same discipline: every anticipated failure mode has a *typed* error with
a stable ``code`` string and the right status class, so clients can
branch on machine-readable fields instead of parsing tracebacks. Only a
genuine bug (e.g. :class:`~paddle_tpu.data.prefetch.RecompileError`
escaping the hardened guard) surfaces as a 500.
"""

from __future__ import annotations

from typing import Optional


class ServingError(Exception):
    """Base of the typed family. ``status`` is the HTTP status the
    frontend maps it to; ``code`` is the stable machine-readable
    discriminator carried in the JSON body. ``allowed`` (when set) is
    the admissible menu for the rejected field(s) — e.g. the warmed
    ``{"beam_size": [...], "max_length": [...]}`` pairs — carried on the
    wire so clients can self-correct instead of guessing."""

    status = 500
    code = "internal"

    def __init__(self, message: str,
                 retry_after_ms: Optional[float] = None,
                 allowed: Optional[dict] = None):
        super().__init__(message)
        self.retry_after_ms = retry_after_ms
        self.allowed = allowed

    def to_wire(self) -> dict:
        body = {"code": self.code, "message": str(self)}
        if self.retry_after_ms is not None:
            body["retry_after_ms"] = round(float(self.retry_after_ms), 1)
        if self.allowed is not None:
            body["allowed"] = self.allowed
        return {"error": body}


class BadRequest(ServingError):
    """Malformed or inadmissible request: wrong slot count, a sequence
    longer than the largest warmed length bucket, an id outside the
    declared range, an unwarmed (beam_size, max_length) pair. 400.
    Closed-menu rejections carry ``allowed`` — the warmed values the
    client may use."""

    status = 400
    code = "bad_request"


class DeadlineExceeded(ServingError):
    """The request's deadline passed before its result could be
    delivered (in queue, or compute finished too late). 504 — the typed
    counterpart of a gateway timeout, never a bare 500."""

    status = 504
    code = "deadline_exceeded"


class Overloaded(ServingError):
    """Load shed: queue depth crossed the admission watermark. Carries
    ``retry_after_ms`` (the engine's current drain-time estimate) so
    well-behaved clients back off instead of retry-storming. 429."""

    status = 429
    code = "overloaded"


class ShuttingDown(Overloaded):
    """Admission closed because the server is draining (SIGTERM);
    in-flight work still completes. Same 429/backoff contract."""

    code = "shutting_down"


class Unavailable(Overloaded):
    """The replica router has no ready replica to dispatch to (all
    ejected/draining/dead, or every failover attempt burned). 503 with
    the same retry/backoff contract as 429 — ``retry_after_ms`` carries
    the router's FLEET-wide capacity estimate (the earliest any replica
    frees up), not one replica's private EWMA."""

    status = 503
    code = "unavailable"


class QuantGateError(ServingError):
    """A quantized artifact drifted past the warmup accuracy gate: the
    golden-request replay's per-output delta vs the recorded fp32
    references exceeded the per-dtype tolerance. Raised at warmup — the
    replica never reports READY (same discipline as the closed shape
    menu, applied to accuracy). Carries the gate evidence so the
    reload/rollback path can report WHY the artifact was refused."""

    status = 503
    code = "quant_gate"

    def __init__(self, message: str, dtype: Optional[str] = None,
                 deltas: Optional[dict] = None,
                 tol: Optional[float] = None, **kw):
        super().__init__(message, **kw)
        self.dtype = dtype
        self.deltas = deltas
        self.tol = tol

    def to_wire(self) -> dict:
        body = super().to_wire()
        gate = {"dtype": self.dtype, "tol": self.tol,
                "deltas": self.deltas}
        body["error"]["gate"] = gate
        return body


class ReloadRejected(ServingError):
    """A rolling reload's replacement replica failed to build (warmup
    error, quant gate refusal, corrupt artifact); the fleet ROLLED BACK
    to the previous artifact instead of publishing the bad one. 409 —
    the reload is refused, the fleet is still healthy on the old
    version. ``str(self)`` names the underlying refusal."""

    status = 409
    code = "reload_rejected"


class ConfigRejected(ServingError):
    """A runtime knob change was refused at ``apply_config`` time: the
    incumbent config keeps serving (the :class:`ReloadRejected` pattern
    applied to knobs). The canonical case is a ``max_batch`` above the
    warmed bucket menu — admitting it would drive the hardened
    ``RecompileGuard`` into a worker-fatal ``RecompileError`` mid-
    traffic, so the refusal happens here, typed, with the warmed menu
    on ``allowed``. 409."""

    status = 409
    code = "config_rejected"


def from_wire(body: dict, status: int) -> ServingError:
    """Client side: rebuild the typed error from a JSON error body."""
    err = (body or {}).get("error", {})
    code = err.get("code", "internal")
    cls = {
        BadRequest.code: BadRequest,
        DeadlineExceeded.code: DeadlineExceeded,
        Overloaded.code: Overloaded,
        ShuttingDown.code: ShuttingDown,
        Unavailable.code: Unavailable,
        QuantGateError.code: QuantGateError,
        ReloadRejected.code: ReloadRejected,
        ConfigRejected.code: ConfigRejected,
    }.get(code, ServingError)
    e = cls(err.get("message", f"HTTP {status}"),
            retry_after_ms=err.get("retry_after_ms"),
            allowed=err.get("allowed"))
    if isinstance(e, QuantGateError):
        gate = err.get("gate") or {}
        e.dtype = gate.get("dtype")
        e.tol = gate.get("tol")
        e.deltas = gate.get("deltas")
    e.status = status
    return e
