"""Front-tier replica router: health-aware failover, circuit breakers,
hedged score retries, respawn, and rolling hot-swap reload.

One serving process owns one device; a fleet needs N replicas behind a
tier that (a) never routes to a replica that cannot answer, (b) turns a
replica dying mid-request into a retry the client never sees, and (c)
can swap model versions without dropping a single queued request. This
module is that tier, over two replica transports:

- :class:`EngineTransport` — an in-process :class:`~paddle_tpu.serving.
  batcher.ServingEngine` (the ``--job=serve --replicas N`` shape: N
  engines, one process, each with its own predictor warmed from the
  shared AOT cache).
- :class:`HTTPTransport` — a separately-launched single-replica server
  reached over HTTP (the multi-process / multi-host shape; pass a
  ``proc`` handle and drain uses the real SIGTERM machinery).

Dispatch policy (one request through :meth:`ReplicaRouter.dispatch`):

- **pick** — least-inflight READY replica (round-robin tiebreak);
  WARMING / DRAINING / EJECTED / DEAD replicas are never candidates, so
  ``begin_drain()`` stops new traffic at the router, not at the
  replica's refused-request surface.
- **failover** — a *definite* replica failure (connection error,
  worker-died 500, an injected ``route_dispatch`` drop) re-dispatches
  the request to the next replica: serving is stateless, so re-running
  is safe for both kinds. A replica's 429 shed is "busy, not broken":
  the router tries the next replica without charging the breaker, and
  only when EVERY ready replica sheds does the client see a 429 — with
  ``retry_after_ms`` set to the FLEET-wide capacity estimate (the min
  over replica drain hints: a request needs one free slot and queues
  drain in parallel), not one replica's private EWMA.
- **hedging** — idempotent ``score`` requests past ``hedge_ms`` with no
  answer fire a capped second attempt at another replica; first answer
  wins, the loser's compute is sunk (and still scored for breaker
  accounting when it completes). NEVER for ``generate``: a speculative
  duplicate of a long beam search is the one workload where hedging
  costs more capacity than it saves.
- **circuit breaker** — ``eject_after`` consecutive failures opens the
  replica's breaker (EJECTED, no dispatch) for ``breaker_cooldown_ms``;
  the health loop then HALF-OPENs it with a single probe — success
  closes the breaker, failure re-opens it with doubled cooldown
  (capped), so a flapping replica converges to rare probes instead of
  eating live traffic.
- **typed 4xx/504 pass through** — a BadRequest or DeadlineExceeded is
  the CLIENT's outcome from a healthy replica; it is never failed over
  (the retry would fail identically) and never charges the breaker.

The health loop polls every replica's readiness (``/healthz`` payload /
``ServingEngine.health()``) on ``health_poll_ms``; a replica whose
worker died (liveness false) is DEAD and — when a ``spawn`` factory is
configured — respawned in place (chaos site ``replica_spawn``). With the
AOT warmup cache a respawned replica deserializes its whole bucket menu
instead of re-tracing it, which is what makes kill-and-respawn under
load a non-event (``bench.py --fleet``).

Rolling reload (:meth:`ReplicaRouter.rolling_reload`) hot-swaps model
versions replica by replica: mark DRAINING (router dispatch stops
immediately), drain through the existing SIGTERM machinery (every queued
request completes — zero drops by construction), swap in the new
version's transport, wait READY, next. The fleet serves mixed versions
mid-roll by design; ``/healthz`` reports each replica's
``model_version``.

Lock discipline (graftlint pass-3 scope): the router lock guards replica
state bookkeeping ONLY — dispatch, transport calls, chaos hits, and
metrics all happen outside it, so the router adds no lock-order edges
over the engine/metrics graph.
"""

from __future__ import annotations

import threading
import time
from http.server import ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple

from paddle_tpu.obs import flight as _flight
from paddle_tpu.obs import trace as _trace
from paddle_tpu.serving.errors import (BadRequest, ConfigRejected,
                                       DeadlineExceeded, Overloaded,
                                       ServingError, ShuttingDown,
                                       Unavailable)
from paddle_tpu.serving.metrics import RouterMetrics
from paddle_tpu.serving.server import JSONHandler
from paddle_tpu.testing import chaos as _chaos
from paddle_tpu.utils.log import event as log_event
from paddle_tpu.utils.log import get_logger

logger = get_logger("serving.router")

# replica states; only READY receives dispatches
WARMING, READY, DRAINING, EJECTED, HALF_OPEN, DEAD = (
    "warming", "ready", "draining", "ejected", "half_open", "dead")


def _get_json(host: str, port: int, path: str,
              timeout: float) -> Tuple[int, dict]:
    """One bounded GET returning ``(status, parsed body)`` — the body
    is read WHATEVER the status (health/metrics payloads ride 503s
    too). The one wire block behind ``HTTPTransport.healthz`` /
    ``.metrics_snapshot`` and ``RouterHA._poll_peer``; callers apply
    their own payload validation."""
    import http.client
    import json
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


class PendingCall:
    """One in-flight attempt at one replica. ``outcome()`` classifies
    the completed attempt:

    - ``("ok", result)``      — answer for the client
    - ``("client", error)``   — typed 400/429-wire/504 that belongs to
      the CLIENT (never failed over, never charges the breaker)
    - ``("busy", error)``     — the replica shed or is draining; the
      request never ran — try another replica, no breaker charge
    - ``("failed", exc)``     — definite replica failure (connection
      reset, worker died); failover + breaker charge
    """

    def __init__(self):
        self.event = threading.Event()
        self.result = None
        self.error: Optional[ServingError] = None
        self.transport_failure: Optional[BaseException] = None
        self._req = None  # EngineTransport bridges the engine _Request
        self.is_hedge = False  # launched as a hedge (win attribution)
        # span bookkeeping for this attempt (set by dispatch.launch):
        # the attempt's own TraceContext + launch times, recorded as a
        # router.attempt span when the outcome settles — failovers and
        # hedges then read as SIBLING attempts under one dispatch span
        self.trace_ctx = None
        self.t0_wall = 0.0
        self.t0_perf = 0.0

    def outcome(self) -> Tuple[str, object]:
        if self._req is not None:
            self.error, self.result = self._req.error, self._req.result
        if self.transport_failure is not None:
            return "failed", self.transport_failure
        e = self.error
        if e is None:
            return "ok", self.result
        if isinstance(e, (ShuttingDown, Overloaded)):
            return "busy", e
        if isinstance(e, (BadRequest, DeadlineExceeded)):
            return "client", e
        if e.status >= 500:
            return "failed", e  # "serving worker died" and kin
        return "client", e


class EngineTransport:
    """In-process replica: one started :class:`ServingEngine`."""

    def __init__(self, engine):
        self.engine = engine

    def ready_hint(self) -> bool:
        """Lock-free instantaneous readiness — consulted at pick time
        so dispatch stops THE MOMENT ``begin_drain()`` fires (or the
        worker dies), without waiting for the next health sweep. Plain
        attribute reads: no lock, no lock-order edge."""
        e = self.engine
        return (e.fatal is None and not e.draining
                and e.predictor.warmed)

    def start_call(self, kind: str, sample, deadline_ms,
                   gen_opts: Dict) -> PendingCall:
        p = PendingCall()
        try:
            req = self.engine.submit(
                sample, kind=kind, deadline_ms=deadline_ms,
                beam_size=gen_opts.get("beam_size"),
                max_length=gen_opts.get("max_length"))
        except ServingError as e:
            p.error = e
            p.event.set()
            return p
        # share the engine request's completion event — zero polling
        p.event = req.event
        p._req = req
        return p

    def healthz(self) -> dict:
        return self.engine.health()

    def metrics_snapshot(self) -> dict:
        """This replica's serving metrics — the router's ``/metrics``
        federates these so one scrape shows the whole fleet."""
        return self.engine.metrics.snapshot()

    def begin_drain(self):
        self.engine.begin_drain()

    def drain_wait(self, timeout: float = 60.0):
        """Blocks until every queued + in-flight request of this replica
        is answered (the zero-drop half of rolling reload)."""
        self.engine.shutdown(drain=True, timeout=timeout)

    def apply_config(self, cfg) -> dict:
        """Apply an engine-knob delta to this replica (typed refusal
        propagates to the router's fan-out rollback)."""
        return self.engine.apply_config(cfg)


class HTTPTransport:
    """A replica reached over HTTP — a separately-launched single-
    replica server process. Drain is uniform whether or not we hold the
    process handle: ``begin_drain`` POSTs the replica's
    ``/admin/drain`` (admission closes, queued + in-flight work
    completes), so a supervisor-owned and an externally-launched
    replica drain identically; ``proc`` (a ``subprocess.Popen``) lets
    ``drain_wait`` additionally SIGTERM and reap the drained process,
    while a Popen-less transport watches ``/healthz`` until
    ``queue_depth`` and ``inflight`` are dry. The wire layer is
    :class:`ServingClient`'s (retries=0 — retry policy belongs to the
    router's failover, not the transport)."""

    def __init__(self, host: str, port: int, timeout: float = 120.0,
                 proc=None, healthz_timeout: float = 5.0):
        from paddle_tpu.serving.client import ServingClient
        self.host, self.port = host, int(port)
        self.timeout = timeout
        self.proc = proc
        # the supervisor probes with a SHORT deadline (a hung replica
        # must not stall the sweep for the default 5 s)
        self.healthz_timeout = float(healthz_timeout)
        self._client = ServingClient(host, port, timeout=timeout)

    def start_call(self, kind: str, sample, deadline_ms,
                   gen_opts: Dict) -> PendingCall:
        p = PendingCall()
        path = {"score": "/v1/score", "generate": "/v1/generate"}[kind]
        body = {"sample": sample}
        if deadline_ms is not None:
            body["deadline_ms"] = deadline_ms
        for k in ("beam_size", "max_length"):
            if gen_opts.get(k) is not None:
                body[k] = gen_opts[k]

        # contextvars do NOT flow into new threads: capture the
        # dispatcher's ambient attempt context here and re-scope it in
        # the call thread, so the wire hop's X-Trace-Id carries the
        # attempt's span and the remote replica parents under it
        tctx = _trace.current()

        def run():
            try:
                with _trace.use(tctx):
                    p.result = self._client._request_once(
                        "POST", path, body)
                if isinstance(p.result, dict):
                    # the inner client attached ITS provenance (the
                    # replica's X-Trace-Id echo) to the body; forwarded
                    # verbatim it would pre-empt the end client's
                    # setdefault and eat the router's replica/failover
                    # provenance — this hop's details are not the
                    # caller's provenance
                    p.result.pop("provenance", None)
            except ServingError as e:
                p.error = e
            except Exception as e:  # noqa: BLE001 — conn reset/refused
                p.transport_failure = e
            finally:
                p.event.set()

        threading.Thread(target=run, daemon=True,
                         name="router-http-call").start()
        return p

    def healthz(self) -> dict:
        # NOT _request_once: that raises on any >=400 status, but a 503
        # healthz still carries the {live, ready, draining, ...} split
        # the router routes on
        status, data = _get_json(self.host, self.port, "/healthz",
                                 self.healthz_timeout)
        if not isinstance(data, dict) or "live" not in data:
            raise ConnectionError(
                f"healthz from {self.host}:{self.port} is not a "
                f"health payload (HTTP {status})")
        return data

    def metrics_snapshot(self) -> dict:
        """The remote replica's ``/metrics?format=json`` snapshot (the
        federation hook; probe-timeout bounded like healthz)."""
        status, data = _get_json(self.host, self.port,
                                 "/metrics?format=json",
                                 self.healthz_timeout)
        if status >= 400 or not isinstance(data, dict):
            raise ConnectionError(
                f"metrics from {self.host}:{self.port} unavailable "
                f"(HTTP {status})")
        return data

    def begin_drain(self):
        """Close the replica's admission via ``POST /admin/drain`` —
        the ONE drain path for supervisor-owned and externally-launched
        replicas alike. Falls back to SIGTERM when the endpoint is
        unreachable and we hold the process handle (e.g. the listener
        already died but the process lingers)."""
        try:
            self._client._request_once("POST", "/admin/drain")
            return
        except Exception as e:  # noqa: BLE001 — endpoint unreachable
            if self.proc is not None and self.proc.poll() is not None:
                return  # the process already exited (an earlier drain
                # completed, or it died): nothing left to drain
            if self.proc is None:
                logger.warning(
                    "HTTPTransport %s:%d drain endpoint unreachable "
                    "(%r) and no process handle; drain must be driven "
                    "out of band", self.host, self.port, e)
                return
            logger.warning(
                "HTTPTransport %s:%d drain endpoint unreachable (%r); "
                "falling back to SIGTERM", self.host, self.port, e)
            import signal
            try:
                self.proc.send_signal(signal.SIGTERM)
            except (ProcessLookupError, OSError):
                pass  # already gone — drain_wait reaps

    def apply_config(self, cfg) -> dict:
        """Forward an engine-knob delta to the remote replica's
        ``POST /admin/config``. A 409 comes back as the typed
        :class:`~paddle_tpu.serving.errors.ConfigRejected` via
        ``from_wire`` — the router's rollback branches on it exactly
        like the in-process case."""
        body = cfg if isinstance(cfg, dict) else cfg.to_dict()
        return self._client._request_once("POST", "/admin/config", body)

    def drain_wait(self, timeout: float = 60.0):
        """Block until every queued + in-flight request is answered.
        With a process handle the drained replica is then SIGTERMed and
        reaped (the rolling-reload / shutdown contract); without one we
        watch ``/healthz`` until the drain runs dry — an unreachable
        replica counts as drained (it can hold no queued work)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                h = self.healthz()
            except Exception:  # noqa: BLE001 — gone = drained
                break
            if (h.get("draining") and not h.get("queue_depth")
                    and not h.get("inflight")):
                break
            time.sleep(0.02)
        if self.proc is not None:
            import signal
            try:
                self.proc.send_signal(signal.SIGTERM)
            except (ProcessLookupError, OSError):
                pass
            self.proc.wait(timeout=max(1.0,
                                       deadline - time.monotonic()))


class Replica:
    """Router-side state for one replica slot. The transport may be
    swapped (respawn, rolling reload); the slot identity persists."""

    def __init__(self, replica_id: str, transport):
        self.id = str(replica_id)
        self.transport = transport
        self.state = WARMING
        self.inflight = 0
        self.consecutive_failures = 0
        self.poll_failures = 0
        self.breaker_until = 0.0  # monotonic deadline while EJECTED
        self.breaker_cooldown_ms: Optional[float] = None  # doubles
        self.last_health: dict = {}
        self.last_spawn_ms: Optional[float] = None

    def snapshot(self) -> dict:
        t = self.transport
        # HTTP-reachable replicas advertise their address so a warm
        # standby router can rebuild this fleet from /healthz polls
        # alone (router HA: adoption is re-poll + re-arm, no shared db)
        addr = (f"{t.host}:{t.port}"
                if getattr(t, "host", None) is not None
                and getattr(t, "port", None) is not None else None)
        return {"id": self.id, "state": self.state,
                "inflight": self.inflight,
                "consecutive_failures": self.consecutive_failures,
                "model_version": self.last_health.get("model_version"),
                "queue_depth": self.last_health.get("queue_depth"),
                "backlog_ms": self.last_health.get("backlog_ms"),
                "last_spawn_ms": self.last_spawn_ms,
                "addr": addr}


class ReplicaRouter:
    """Owns admission for a fleet of replicas. See the module docstring
    for the dispatch/breaker/hedge/reload policies."""

    def __init__(self, transports, *,
                 spawn: Optional[Callable[[str], object]] = None,
                 health_poll_ms: float = 100.0,
                 eject_after: int = 3,
                 breaker_cooldown_ms: float = 1000.0,
                 breaker_cooldown_max_ms: float = 30000.0,
                 hedge_ms: Optional[float] = None,
                 max_hedges: int = 1,
                 wait_timeout: float = 120.0,
                 fence=None,
                 metrics: Optional[RouterMetrics] = None):
        self.replicas: List[Replica] = [
            t if isinstance(t, Replica) else Replica(f"r{i}", t)
            for i, t in enumerate(transports)]
        if len({r.id for r in self.replicas}) != len(self.replicas):
            raise ValueError("replica ids must be unique")
        self.spawn = spawn
        # optional role fence (a RoleLease, or anything with .valid()):
        # dispatch refuses while the fence is invalid, so a partitioned
        # old ACTIVE router provably stops dispatching within one lease
        # ttl of losing the role (router HA; the r11 epoch-guard idea)
        self.fence = fence
        self.health_poll_ms = float(health_poll_ms)
        self.eject_after = int(eject_after)
        self.breaker_cooldown_ms = float(breaker_cooldown_ms)
        self.breaker_cooldown_max_ms = float(breaker_cooldown_max_ms)
        self.hedge_ms = hedge_ms if hedge_ms is None else float(hedge_ms)
        self.max_hedges = int(max_hedges)
        self.wait_timeout = float(wait_timeout)
        self.metrics = metrics or RouterMetrics()
        self._lock = threading.Lock()
        self._rr = 0  # round-robin tiebreak counter
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._reloading = False
        # set by adopt_replicas: the NEXT successful dispatch records
        # the flight event closing a takeover postmortem (lease expiry
        # -> adoption -> first standby answer); plain attr, read on the
        # dispatch hot path without the lock
        self._first_answer_pending = False
        # monotonic id source for scale-up slots: ids never recycle, so
        # a drained-away "r2" and a later scale-up replica can never be
        # confused in logs/metrics/provenance
        self._next_id = len(self.replicas)
        # optional attachments for the hot-reconfig / tuning plane:
        # an Autoscaler whose watermarks apply_config may retarget, and
        # a WorkloadRecorder tapping the admission stream (both plain
        # attrs — set by the owner, read without the lock)
        self.autoscaler = None
        self.workload_recorder = None

    # ------------------------------------------------------------ control
    def start(self, poll_now: bool = True) -> "ReplicaRouter":
        if poll_now:
            self.poll_once()
        self._thread = threading.Thread(target=self._health_loop,
                                        name="router-health", daemon=True)
        self._thread.start()
        return self

    def shutdown(self, drain: bool = True, timeout: float = 60.0):
        """Stop the health loop and drain every replica (zero queued
        drops, same as single-replica SIGTERM)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        for rep in self.replicas:
            with self._lock:
                rep.state = DRAINING
            try:
                rep.transport.begin_drain()
            except Exception as e:  # noqa: BLE001 — best-effort drain
                logger.warning("drain of %s failed: %r", rep.id, e)
        if drain:
            for rep in self.replicas:
                try:
                    rep.transport.drain_wait(timeout=timeout)
                except Exception as e:  # noqa: BLE001
                    logger.warning("drain wait of %s failed: %r",
                                   rep.id, e)

    # ------------------------------------------------------------- health
    def _health_loop(self):
        while not self._stop.wait(self.health_poll_ms / 1e3):
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001 — the loop must live
                logger.error("router health poll crashed: %r", e)

    def poll_once(self):
        """One health sweep: readiness transitions, breaker half-open
        probes, dead-replica respawn. Also callable inline (tests, and
        ``start(poll_now=True)`` so the first dispatch has states)."""
        now = time.monotonic()
        with self._lock:
            snapshot = list(self.replicas)
        for rep in snapshot:
            if rep.state == DEAD:
                self._maybe_respawn(rep)
                continue
            if rep.state == EJECTED:
                if now < rep.breaker_until:
                    continue
                with self._lock:
                    rep.state = HALF_OPEN
                log_event(logger, "breaker_half_open",
                          "router: %s breaker half-open, probing",
                          rep.id, level=20, replica=rep.id)
            try:
                h = rep.transport.healthz()
            except Exception as e:  # noqa: BLE001 — any probe failure
                self._poll_failed(rep, e)
                continue
            self._apply_health(rep, h)

    def _poll_failed(self, rep: Replica, exc: BaseException):
        with self._lock:
            rep.poll_failures += 1
            half_open = rep.state == HALF_OPEN
            should_eject = (rep.poll_failures >= self.eject_after
                            and rep.state in (READY, WARMING, DRAINING))
        if half_open:
            self._reopen_breaker(rep)
        elif should_eject:
            logger.warning("router: ejecting %s after %d failed health "
                           "probes (%r)", rep.id, rep.poll_failures, exc)
            self._eject(rep)

    def _apply_health(self, rep: Replica, h: dict):
        closed = False
        with self._lock:
            rep.poll_failures = 0
            rep.last_health = dict(h)
            if not h.get("live", True):
                dead = rep.state != DEAD
                rep.state = DEAD
            elif h.get("draining"):
                rep.state = DRAINING
                dead = False
            elif not h.get("ready", False):
                if rep.state != HALF_OPEN:
                    rep.state = WARMING
                dead = False
            else:
                closed = rep.state in (HALF_OPEN, EJECTED)
                rep.state = READY
                rep.consecutive_failures = 0
                if closed:
                    rep.breaker_cooldown_ms = None
                dead = False
        # events (log + flight) outside the router lock
        if closed:
            log_event(logger, "breaker_close",
                      "router: %s breaker closed (probe ok)", rep.id,
                      level=20, replica=rep.id)
        if dead:
            log_event(logger, "replica_dead",
                      "router: replica %s is dead (worker fatal: %s)",
                      rep.id, h.get("fatal"), replica=rep.id,
                      fatal=h.get("fatal"))
            self.metrics.inc("replica_deaths_total")
            self._maybe_respawn(rep)

    def _maybe_respawn(self, rep: Replica):
        """Replace a dead replica's transport via the spawn factory.
        Synchronous on the health thread: the fleet serves on the other
        replicas while the new one warms (ms with the AOT cache)."""
        if self.spawn is None:
            return
        try:
            if _chaos._ACTIVE is not None:
                _chaos._ACTIVE.hit("replica_spawn", replica=rep.id)
            t0 = time.perf_counter()
            new = self.spawn(rep.id)
            spawn_ms = 1e3 * (time.perf_counter() - t0)
        except Exception as e:  # noqa: BLE001 — retry next sweep
            log_event(logger, "respawn_failed",
                      "router: respawn of %s failed (%r); will retry",
                      rep.id, e, replica=rep.id, error=repr(e))
            return
        with self._lock:
            rep.transport = new
            rep.state = WARMING
            rep.consecutive_failures = 0
            rep.poll_failures = 0
            rep.breaker_cooldown_ms = None
            rep.last_spawn_ms = spawn_ms
        self.metrics.inc("respawns_total")
        log_event(logger, "respawn",
                  "router: respawned %s in %.1f ms", rep.id, spawn_ms,
                  level=20, replica=rep.id,
                  spawn_ms=round(spawn_ms, 1))
        try:
            self._apply_health(rep, rep.transport.healthz())
        except Exception:  # noqa: BLE001 — next sweep will see it
            pass

    # ------------------------------------------------------------ breaker
    def _eject(self, rep: Replica):
        with self._lock:
            cooldown = rep.breaker_cooldown_ms or self.breaker_cooldown_ms
            rep.breaker_cooldown_ms = min(2 * cooldown,
                                          self.breaker_cooldown_max_ms)
            rep.state = EJECTED
            rep.breaker_until = time.monotonic() + cooldown / 1e3
        self.metrics.inc("ejections_total")
        self.metrics.inc("breaker_open_total")
        log_event(logger, "breaker_open",
                  "router: %s breaker opened (cooldown %.0f ms)",
                  rep.id, cooldown, replica=rep.id,
                  cooldown_ms=round(cooldown, 1))

    def _reopen_breaker(self, rep: Replica):
        logger.warning("router: %s failed its half-open probe; breaker "
                       "re-opened", rep.id)
        self._eject(rep)

    def _record_failure(self, rep: Replica, exc: BaseException):
        with self._lock:
            rep.consecutive_failures += 1
            eject = (rep.consecutive_failures >= self.eject_after
                     and rep.state == READY)
        log_event(logger, "dispatch_failed",
                  "router: dispatch to %s failed (%r)", rep.id, exc,
                  replica=rep.id, error=repr(exc))
        if eject:
            logger.warning("router: ejecting %s after %d consecutive "
                           "dispatch failures", rep.id,
                           rep.consecutive_failures)
            self._eject(rep)

    def _record_success(self, rep: Replica):
        with self._lock:
            rep.consecutive_failures = 0

    # ----------------------------------------------------------- dispatch
    def _pick(self, exclude) -> Optional[Replica]:
        with self._lock:
            # state is the health loop's view; ready_hint (where the
            # transport offers one — in-process engines) is the LIVE
            # view, so a begin_drain or worker death stops dispatch
            # immediately, not at the next poll
            ready = [r for r in self.replicas
                     if r.state == READY and r.id not in exclude
                     and getattr(r.transport, "ready_hint",
                                 lambda: True)()]
            if not ready:
                return None
            self._rr += 1
            rr = self._rr
            n = len(self.replicas)
            rep = min(ready, key=lambda r: (
                r.inflight, (self.replicas.index(r) + rr) % n))
            rep.inflight += 1
            return rep

    def _end_inflight(self, rep: Replica):
        with self._lock:
            rep.inflight = max(0, rep.inflight - 1)

    def _abandon(self, rep: Replica, pend: PendingCall):
        """A hedge lost the race: its compute is sunk, but its outcome
        still matters to the breaker, so reap it off-thread."""

        def run():
            settled = pend.event.wait(self.wait_timeout)
            self._end_inflight(rep)
            kind, payload = pend.outcome()
            # a reap that timed out never answered: outcome() would
            # read ("ok", None) from the empty call — the span must
            # say "unanswered", and neither breaker counter may move
            # (crediting a hung replica with a success would mask it)
            self._record_attempt(rep.id, pend.trace_ctx, pend.t0_wall,
                                 pend.t0_perf,
                                 kind if settled else "unanswered",
                                 pend.is_hedge, abandoned=True)
            if not settled:
                return
            if kind == "failed":
                self._record_failure(rep, payload)
            elif kind == "ok":
                self._record_success(rep)

        threading.Thread(target=run, daemon=True,
                         name="router-abandoned-hedge").start()

    def fleet_retry_after_ms(self, hints=()) -> float:
        """Earliest-capacity estimate across the fleet: the MIN over
        per-replica drain hints — a request needs ONE free slot and
        replica queues drain in parallel, so the fleet frees up as fast
        as its least-loaded member, not as slow as its average."""
        # None-checks, not truthiness: 0.0 is a legitimate hint (an
        # idle replica IS the fleet's earliest capacity)
        vals = [float(h) for h in hints if h is not None]
        with self._lock:
            for r in self.replicas:
                if r.state in (READY, DRAINING, WARMING):
                    b = r.last_health.get("backlog_ms")
                    if b is not None:
                        vals.append(float(b))
        return min(vals) if vals else 50.0

    def _record_attempt(self, rep_id: str, ctx, t0_wall: float,
                        t0_perf: float, outcome: str, hedge: bool,
                        abandoned: bool = False):
        """One settled attempt -> one ``router.attempt`` span. Sibling
        attempts under one dispatch span ARE the failover/hedge story a
        trace tells; "ok"/"client" are healthy-replica outcomes."""
        tracer = _trace._TRACER
        if tracer is None or ctx is None:
            return
        tracer.record("router.attempt", ctx, ts=t0_wall,
                      dur_ms=1e3 * (time.perf_counter() - t0_perf),
                      status=("ok" if outcome in ("ok", "client")
                              else "error"),
                      replica=rep_id, outcome=outcome,
                      hedge=True if hedge else None,
                      abandoned=True if abandoned else None)

    def dispatch(self, sample, *, kind: str = "score",
                 deadline_ms: Optional[float] = None,
                 beam_size=None, max_length=None,
                 trace_parent=None) -> Tuple[dict, dict]:
        """Route one request; returns ``(result, provenance)`` or raises
        the typed error the client should see. ``provenance`` =
        ``{"replica", "failovers", "hedges"}`` (the HTTP frontend
        surfaces it as ``X-Replica-Id`` / ``X-Failovers`` /
        ``X-Hedged``). ``trace_parent`` roots the routing decision's
        ``router.dispatch`` span (and its per-attempt children) under
        the caller's context — the HTTP frontend passes the parsed
        ``X-Trace-Id``."""
        with _trace.span("router.dispatch", parent=trace_parent,
                         kind=kind):
            return self._dispatch(sample, kind=kind,
                                  deadline_ms=deadline_ms,
                                  beam_size=beam_size,
                                  max_length=max_length)

    def _dispatch(self, sample, *, kind: str, deadline_ms,
                  beam_size, max_length) -> Tuple[dict, dict]:
        if kind not in ("score", "generate"):
            raise BadRequest(f"unknown request kind {kind!r}")
        rec = self.workload_recorder
        if rec is not None:
            # admission-stream tap for the trace-replay harness: one
            # lock-free deque append, off the latency path (the r20
            # replay-sink discipline applied at the front tier)
            rec.observe(sample, kind=kind, deadline_ms=deadline_ms,
                        beam_size=beam_size, max_length=max_length)
        if self.fence is not None and not self.fence.valid():
            # fenced: we lost (or never held) the active-role lease —
            # a zombie active must NOT keep dispatching while a standby
            # serves the same fleet. 503 so clients re-resolve to the
            # other endpoint (ServingClient rotates on Unavailable).
            self.metrics.inc("fenced_total")
            if _flight._ACTIVE is not None:
                _flight._ACTIVE.record("fenced_dispatch", kind=kind)
            raise Unavailable(
                "router fenced: not the active role holder (the lease "
                "lapsed or a standby adopted the fleet); retry against "
                "the other router endpoint", retry_after_ms=50.0)
        gen_opts = {"beam_size": beam_size, "max_length": max_length}
        t0 = time.perf_counter()
        tried: set = set()
        busy: List[ServingError] = []
        prov = {"replica": None, "failovers": 0, "hedges": 0}
        live: List[Tuple[Replica, PendingCall]] = []
        self.metrics.inc("dispatches_total")

        def launch(as_hedge: bool = False) -> str:
            """Start one attempt. Returns "live" (attempt in flight),
            "consumed" (a replica was tried but the dispatch itself
            failed — recorded as a failover, NOT as a fired hedge), or
            "none" (no untried ready replica)."""
            rep = self._pick(tried)
            if rep is None:
                return "none"
            tried.add(rep.id)
            # one attempt = one child span of the dispatch span; the
            # ambient context is scoped around start_call so both
            # transports (engine submit / HTTP hop) parent under it
            actx = _trace.child(_trace.current())
            t0_wall, t0_perf = time.time(), time.perf_counter()
            try:
                if _chaos._ACTIVE is not None:
                    # seeded fault site: a "drop" here is a dispatch
                    # that never reached the replica — the failover
                    # path, deterministic from the plan seed
                    _chaos._ACTIVE.hit("route_dispatch",
                                       replica=rep.id, kind=kind)
                with _trace.use(actx):
                    pend = rep.transport.start_call(
                        kind, sample, deadline_ms, gen_opts)
            except Exception as e:  # noqa: BLE001 — incl. ChaosDropped
                self._end_inflight(rep)
                self._record_failure(rep, e)
                prov["failovers"] += 1
                self.metrics.inc("failovers_total")
                self._record_attempt(rep.id, actx, t0_wall, t0_perf,
                                     "failed", as_hedge)
                return "consumed"
            pend.is_hedge = as_hedge
            pend.trace_ctx = actx
            pend.t0_wall, pend.t0_perf = t0_wall, t0_perf
            if as_hedge:
                prov["hedges"] += 1
                self.metrics.inc("hedges_total")
            live.append((rep, pend))
            return "live"

        launch()
        hedge_at = (t0 + self.hedge_ms / 1e3
                    if (kind == "score" and self.hedge_ms is not None)
                    else None)
        hedges = 0
        while True:
            now = time.perf_counter()
            if now - t0 > self.wait_timeout:
                for rep, pend in live:
                    self._abandon(rep, pend)
                raise DeadlineExceeded(
                    f"router got no replica answer within "
                    f"{self.wait_timeout}s")
            progressed = False
            for rep, pend in list(live):
                if not pend.event.is_set():
                    continue
                progressed = True
                live.remove((rep, pend))
                self._end_inflight(rep)
                okind, payload = pend.outcome()
                self._record_attempt(rep.id, pend.trace_ctx,
                                     pend.t0_wall, pend.t0_perf,
                                     okind, pend.is_hedge)
                if okind == "ok":
                    self._record_success(rep)
                    prov["replica"] = rep.id
                    prov["model_version"] = rep.last_health.get(
                        "model_version")
                    if self._first_answer_pending:
                        # the first answer after a standby takeover is
                        # the postmortem's closing bracket (lease
                        # expiry -> adoption -> THIS); the unlocked
                        # read keeps the hot path cheap, the locked
                        # swap keeps the event singular when two
                        # dispatches race past the read
                        with self._lock:
                            won = self._first_answer_pending
                            self._first_answer_pending = False
                        if won and _flight._ACTIVE is not None:
                            _flight._ACTIVE.record(
                                "first_answer_after_takeover",
                                replica=rep.id)
                    if pend.is_hedge:
                        # only a HEDGE beating its primary is a win; a
                        # primary outrunning its hedge is not
                        self.metrics.inc("hedge_wins_total")
                    for orep, opend in live:
                        self._abandon(orep, opend)
                    self.metrics.observe_dispatch(
                        rep.id, 1e3 * (time.perf_counter() - t0))
                    return payload, prov
                if okind == "client":
                    # a typed 400/504 from a healthy replica IS the
                    # answer; failing over would fail identically
                    self._record_success(rep)
                    prov["replica"] = rep.id
                    prov["model_version"] = rep.last_health.get(
                        "model_version")
                    for orep, opend in live:
                        self._abandon(orep, opend)
                    payload.provenance = prov
                    raise payload
                if okind == "busy":
                    busy.append(payload)
                    launch()
                    continue
                # definite failure -> failover
                self._record_failure(rep, payload)
                prov["failovers"] += 1
                self.metrics.inc("failovers_total")
                launch()
            if not live:
                if launch() != "none":
                    continue
                self.metrics.inc("shed_total")
                retry = self.fleet_retry_after_ms(
                    [getattr(e, "retry_after_ms", None) for e in busy])
                err: ServingError
                if busy:
                    err = Overloaded(
                        "every ready replica is shedding load "
                        f"({len(busy)} tried); fleet at capacity",
                        retry_after_ms=retry)
                else:
                    err = Unavailable(
                        "no ready replica to dispatch to",
                        retry_after_ms=retry)
                err.provenance = prov
                raise err
            if (hedge_at is not None and now >= hedge_at
                    and hedges < self.max_hedges):
                st = launch(as_hedge=True)
                if st == "live":
                    hedges += 1
                    hedge_at = now + self.hedge_ms / 1e3
                    if hedges >= self.max_hedges:
                        hedge_at = None
                elif st == "none":
                    hedge_at = None  # nobody to hedge at; stop trying
                # "consumed": the attempt burned as a failover before
                # any hedge fired — the hedge budget is NOT spent; the
                # next loop iteration may try another replica
                continue
            # wait on the oldest pending attempt's event: up to the
            # hedge deadline when one is armed, a short poll while
            # several attempts race, else the full remaining budget —
            # the common single-attempt case must not spin at 200 Hz
            if hedge_at is not None:
                timeout = max(0.001, hedge_at - now)
            elif len(live) > 1:
                timeout = 0.005
            else:
                timeout = max(0.001, self.wait_timeout - (now - t0))
            live[0][1].event.wait(timeout)

    # ------------------------------------------------------------- reload
    def rolling_reload(self, build: Callable[[str], object],
                       wait_ready_s: float = 300.0,
                       fallback_build: Optional[
                           Callable[[str], object]] = None) -> List[str]:
        """Hot-swap the model one replica at a time, zero queued drops:
        DRAINING (dispatch stops now) -> drain via the SIGTERM machinery
        (queued + in-flight requests all complete) -> swap in
        ``build(replica_id)`` (a started transport for the new version;
        ms-fast when its predictor warms from the AOT cache) -> wait
        READY -> next replica. Returns the per-replica model versions
        after the roll.

        **Rollback**: when ``build`` itself raises — a corrupt artifact,
        or a quantized model refused by the warmup accuracy gate
        (``QuantGateError``) — and ``fallback_build`` is given, the
        drained replica is REBUILT on the previous artifact and the roll
        aborts with a typed :class:`~paddle_tpu.serving.errors.
        ReloadRejected` naming the refusal: the bad version is never
        published and the fleet stays whole on the old one. Without a
        fallback the old behavior stands (the replica is left drained;
        the caller must reload again with a good artifact). Raises if a
        swapped replica never turns ready — earlier replicas stay
        swapped (mixed-version fleet; roll back by reloading again with
        the old artifact)."""
        with self._lock:
            if self._reloading:
                raise RuntimeError("a rolling reload is already running")
            self._reloading = True
        try:
            versions = []
            for rep in list(self.replicas):
                with self._lock:
                    rep.state = DRAINING
                logger.info("rolling reload: draining %s", rep.id)
                rep.transport.begin_drain()
                rep.transport.drain_wait()
                try:
                    new = build(rep.id)
                except Exception as e:  # noqa: BLE001 — typed below
                    if fallback_build is None:
                        raise
                    from paddle_tpu.serving.errors import ReloadRejected
                    logger.warning(
                        "rolling reload: new artifact REFUSED on %s "
                        "(%s); rolling back to the previous artifact",
                        rep.id, e)
                    old = fallback_build(rep.id)
                    with self._lock:
                        rep.transport = old
                        rep.state = WARMING
                        rep.consecutive_failures = 0
                        rep.poll_failures = 0
                        rep.breaker_cooldown_ms = None
                    self.metrics.inc("reload_rollbacks_total")
                    self._wait_replica_ready(rep, wait_ready_s)
                    raise ReloadRejected(
                        f"reload rejected: replica {rep.id} refused the "
                        f"new artifact ({e}); fleet rolled back to the "
                        "previous version (no replica serves the bad "
                        "artifact)") from e
                with self._lock:
                    rep.transport = new
                    rep.state = WARMING
                    rep.consecutive_failures = 0
                    rep.poll_failures = 0
                    rep.breaker_cooldown_ms = None
                self.metrics.inc("reloads_total")
                versions.append(self._wait_replica_ready(rep,
                                                         wait_ready_s))
                logger.info("rolling reload: %s ready on version %s",
                            rep.id, versions[-1])
            return versions
        finally:
            with self._lock:
                self._reloading = False

    def _wait_replica_ready(self, rep, wait_ready_s: float):
        """Poll one replica until READY; returns its reported model
        version. Raises RuntimeError past the deadline."""
        deadline = time.monotonic() + wait_ready_s
        while True:
            try:
                h = rep.transport.healthz()
                self._apply_health(rep, h)
                if rep.state == READY:
                    return h.get("model_version")
            except Exception:  # noqa: BLE001 — keep waiting
                pass
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"rolling reload: replica {rep.id} did not "
                    f"turn ready within {wait_ready_s}s; roll "
                    "halted (earlier replicas are on the new "
                    "version)")
            time.sleep(0.01)

    # ------------------------------------------------------ hot reconfig
    def current_config(self) -> dict:
        """The router's own incumbent knob values (the replicas' live
        via their ``current_config``/``/admin/config`` answers)."""
        return {"hedge_ms": self.hedge_ms,
                "max_hedges": self.max_hedges}

    def apply_config(self, cfg) -> dict:
        """Apply a :class:`~paddle_tpu.serving.tuner.FleetConfig` delta
        fleet-wide: engine knobs fan out to every non-dead replica's
        transport, router knobs (``hedge_ms``, ``max_hedges``) commit
        locally, autoscale watermarks retarget the attached
        ``Autoscaler``.

        All-or-nothing like a rolling reload: local knobs validate
        BEFORE the fan-out, and when replica K refuses the delta (typed
        409 — e.g. an off-menu ``max_batch``), replicas 0..K-1 are
        rolled back to their incumbent values and the call raises
        :class:`~paddle_tpu.serving.errors.ConfigRejected` — no replica
        serves the refused config, the fleet stays on the incumbent."""
        from paddle_tpu.serving.tuner import (FleetConfig,
                                              record_tune_decision,
                                              rollback_delta)
        cfg = FleetConfig.coerce(cfg)
        before = self.current_config()

        def reject(reason: str, allowed=None, cause=None):
            self.metrics.inc("config_rejected_total")
            record_tune_decision(action="apply_rejected", reason=reason,
                                 requested=cfg.to_dict(), before=before)
            raise ConfigRejected(
                f"{reason}; incumbent config keeps serving",
                allowed=allowed) from cause

        # ---- validate the locally-owned knobs before any side effect
        router_changes = cfg.router_items()
        if "max_hedges" in router_changes \
                and router_changes["max_hedges"] < 0:
            reject(f"max_hedges {router_changes['max_hedges']} must "
                   "be >= 0")
        auto = cfg.autoscale_items()
        scaler = self.autoscaler
        if auto:
            if scaler is None:
                reject("autoscale watermarks were sent but this router "
                       "has no autoscaler attached")
            scaler.check_config(auto)  # raises ConfigRejected itself
        # ---- fan the engine knobs out, rollback on refusal
        engine_cfg = cfg.engine_subset()
        applied: List[Tuple[Replica, dict]] = []
        if engine_cfg.set_fields():
            with self._lock:
                targets = [r for r in self.replicas if r.state != DEAD]
            for rep in targets:
                try:
                    res = rep.transport.apply_config(engine_cfg)
                except ServingError as e:
                    for prep, prior in applied:
                        try:
                            prep.transport.apply_config(prior)
                        except Exception as re:  # noqa: BLE001
                            logger.error(
                                "config rollback of %s failed: %r "
                                "(replica may hold the refused delta)",
                                prep.id, re)
                    reject(f"replica {rep.id} refused the config ({e}); "
                           f"{len(applied)} earlier replica(s) rolled "
                           "back", allowed=e.allowed, cause=e)
                applied.append((rep, rollback_delta(
                    res.get("before", {}), engine_cfg.set_fields())))
        # ---- commit the local knobs (plain attrs, read per-dispatch)
        if "hedge_ms" in router_changes:
            self.hedge_ms = router_changes["hedge_ms"]
        if "max_hedges" in router_changes:
            self.max_hedges = int(router_changes["max_hedges"])
        if auto:
            scaler.commit_config(auto)
        after = self.current_config()
        changed = cfg.set_fields()
        self.metrics.inc("config_applies_total")
        if _flight._ACTIVE is not None:
            _flight._ACTIVE.record("config_applied", tier="router",
                                   changed=",".join(changed),
                                   replicas=len(applied),
                                   before=before, after=after)
        log_event(logger, "config_applied",
                  "router: config applied (%s) to %d replica(s)",
                  changed, len(applied), level=20,
                  changed=",".join(changed), replicas=len(applied))
        return {"status": "ok", "before": before, "after": after,
                "replicas": len(applied), "applied": cfg.to_dict()}

    # ------------------------------------------------------ elastic fleet
    def set_transport(self, replica_id: str, transport,
                      state: str = WARMING) -> bool:
        """Swap a replica slot's transport in place (the supervisor's
        respawn push: it killed and relaunched the process, the slot
        identity persists). Resets the slot's failure/breaker state —
        the new process has no history. False when the slot is unknown
        (the caller should ``add_replica`` instead)."""
        with self._lock:
            rep = next((r for r in self.replicas
                        if r.id == str(replica_id)), None)
            if rep is None:
                return False
            rep.transport = transport
            rep.state = state
            rep.consecutive_failures = 0
            rep.poll_failures = 0
            rep.breaker_cooldown_ms = None
        return True

    def add_replica(self, transport, replica_id: Optional[str] = None,
                    state: str = WARMING) -> str:
        """Grow the fleet by one slot (autoscale scale-up, standby
        adoption). The new replica starts WARMING (or the given state)
        and enters dispatch at the next health observation — callers
        that need it routable NOW follow with ``poll_once()``. Returns
        the slot id (monotonic, never recycled)."""
        with self._lock:
            rid = str(replica_id) if replica_id is not None \
                else f"r{self._next_id}"
            if any(r.id == rid for r in self.replicas):
                raise ValueError(f"replica id {rid!r} already exists")
            self._next_id += 1
            rep = Replica(rid, transport)
            rep.state = state
            self.replicas.append(rep)
        logger.info("router: replica %s added (fleet size %d)", rid,
                    len(self.replicas))
        return rid

    def remove_replica(self, replica_id: str, drain: bool = True,
                       timeout: float = 60.0):
        """Shrink the fleet by one slot (autoscale scale-down): the
        replica leaves the dispatch set IMMEDIATELY (state DRAINING
        under the lock), then — outside the lock — drains via the
        uniform ``begin_drain`` path so zero queued requests drop, and
        is popped from the table. Returns the removed transport (the
        caller owns reaping its process)."""
        with self._lock:
            rep = next((r for r in self.replicas if r.id == replica_id),
                       None)
            if rep is None:
                raise KeyError(f"no replica {replica_id!r}")
            rep.state = DRAINING
        if drain:
            try:
                rep.transport.begin_drain()
                rep.transport.drain_wait(timeout=timeout)
            except Exception as e:  # noqa: BLE001 — best-effort drain
                logger.warning("drain of removed replica %s failed: %r",
                               replica_id, e)
        with self._lock:
            self.replicas = [r for r in self.replicas
                             if r.id != replica_id]
        logger.info("router: replica %s removed (fleet size %d)",
                    replica_id, len(self.replicas))
        return rep.transport

    def adopt_replicas(self, pairs) -> List[str]:
        """Replace the whole replica set — the standby's takeover path
        (``RouterHA``). ``pairs`` = ``[(replica_id, transport), ...]``
        mirrored from the dead active's last ``/healthz`` snapshot.
        State is tiny by design: breakers and inflight counts
        reconstruct from the ``poll_once()`` the caller issues next —
        adoption is re-poll + re-arm, not state transfer."""
        with self._lock:
            self.replicas = []
            self._rr = 0
            for rid, t in pairs:
                rep = Replica(str(rid), t)
                self.replicas.append(rep)
            if len({r.id for r in self.replicas}) != len(self.replicas):
                raise ValueError("adopted replica ids must be unique")
            self._next_id = max(self._next_id, len(self.replicas))
            self._first_answer_pending = True
        logger.info("router: adopted %d replica(s): %s",
                    len(self.replicas),
                    [r.id for r in self.replicas])
        return [r.id for r in self.replicas]

    def load_backlog_ms(self) -> Optional[float]:
        """Fleet pressure signal for the autoscaler: the MEAN backlog
        over routable replicas (capacity needs the average — the
        fleet-min is the 429 retry hint's business, not sizing's).
        None when no replica has reported health yet."""
        with self._lock:
            vals = [float(r.last_health["backlog_ms"])
                    for r in self.replicas
                    if r.state in (READY, WARMING)
                    and r.last_health.get("backlog_ms") is not None]
        return sum(vals) / len(vals) if vals else None

    def replica_metrics(self) -> Dict[str, dict]:
        """Per-replica serving-metrics snapshots — ONE router scrape
        then shows the whole fleet (metrics federation). Transports
        without the hook (duck-typed fakes) and unreachable replicas
        report an ``error`` entry instead of failing the scrape;
        transport calls run outside the router lock, and CONCURRENTLY —
        a wedged replica costs the scrape one probe timeout, not one
        per sick replica in series."""
        with self._lock:
            pairs = [(r.id, r.transport) for r in self.replicas]
        out: Dict[str, dict] = {}

        def one(rid, transport):
            try:
                out[rid] = transport.metrics_snapshot()
            except Exception as e:  # noqa: BLE001 — one sick replica
                # must not take down the fleet scrape
                out[rid] = {"error": repr(e)}

        threads = []
        for rid, transport in pairs:
            if not callable(getattr(transport, "metrics_snapshot",
                                    None)):
                continue
            th = threading.Thread(target=one, args=(rid, transport),
                                  daemon=True,
                                  name=f"metrics-scrape-{rid}")
            th.start()
            threads.append((rid, th))
        deadline = time.monotonic() + 5.0
        for rid, th in threads:
            th.join(max(0.0, deadline - time.monotonic()))
            if th.is_alive():
                # the transport outlived its own probe timeout; the
                # scrape moves on (the thread dies with its socket)
                out.setdefault(rid, {"error": "metrics scrape timed "
                                              "out"})
        return out

    # ------------------------------------------------------------- health
    def fleet_health(self) -> dict:
        with self._lock:
            reps = [r.snapshot() for r in self.replicas]
        ready = sum(1 for r in reps if r["state"] == READY)
        fenced = self.fence is not None and not self.fence.valid()
        return {
            "status": ("fenced" if fenced
                       else "ok" if ready else "unavailable"),
            "ready": ready > 0 and not fenced,
            "live": True,
            "ready_replicas": ready,
            "replicas": reps,
            "reloading": self._reloading,
            "role_held": (None if self.fence is None else not fenced),
            "role_epoch": getattr(self.fence, "epoch", None),
        }


class RouterHA:
    """Active/standby controller for one :class:`ReplicaRouter` — the
    warm-standby half of router HA.

    Two router processes front one fleet; a :class:`~paddle_tpu.dist.
    master.RoleLease` over a shared Store elects the ACTIVE. Each side
    runs a ``RouterHA`` over its (fenced) router:

    - **holding the role** — renew the lease every ``interval_ms``
      (chaos site ``lease_renew``: a drop is a lost renewal — enough of
      them and the lease lapses, the router's fence trips, and dispatch
      stops within one ttl: the partitioned-zombie-active guarantee).
    - **standing by** — poll the peer router's ``/healthz`` every
      ``interval_ms``, mirroring its replica snapshot (ids + addrs).
      The standby is WARM: its HTTP frontend is bound and answering
      (503 ``Unavailable`` while fenced, which ``ServingClient``
      rotates away from), so takeover needs no process start.
    - **takeover** — after ``adopt_after`` consecutive failed peer
      polls, ``try_acquire`` the role; the lease gates it (a live
      active's renewals make acquisition impossible, so a standby that
      merely cannot REACH the active cannot split-brain the fleet).
      On winning: chaos site ``router_failover`` fires, the mirrored
      replica set is adopted (default: one :class:`HTTPTransport` per
      advertised addr; in-process fleets inject ``adopt``), and one
      inline ``poll_once`` re-arms states/breakers — adoption is
      re-poll + re-arm because router state is tiny by design.

    ``step()`` runs one iteration inline (deterministic tests);
    ``start()`` runs it on a daemon thread at ``interval_ms``.
    """

    def __init__(self, router: ReplicaRouter, lease, *,
                 peer: Optional[Tuple[str, int]] = None,
                 peer_healthz: Optional[Callable[[], dict]] = None,
                 adopt: Optional[Callable[[List[dict]], List[Tuple[str, object]]]] = None,
                 interval_ms: float = 100.0,
                 adopt_after: int = 2):
        if router.fence is None:
            router.fence = lease
        self.router = router
        self.lease = lease
        self.peer = peer
        self._peer_healthz = peer_healthz
        self._adopt_builder = adopt
        self.interval_ms = float(interval_ms)
        self.adopt_after = int(adopt_after)
        self.peer_failures = 0
        self.last_peer_snapshot: List[dict] = []
        self.adoptions = 0
        self.adopted_at: Optional[float] = None  # monotonic
        # True while the last step held a valid active role: the
        # active→lapsed transition must be DATED even when the lease
        # dies silently (renewals dropped by a partition never reach
        # the store, so no refusal ever fires) — the postmortem's
        # "lease expiry" bracket comes from exactly this edge
        self._was_active = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ----------------------------------------------------------- control
    def start(self, take_role: bool = False) -> "RouterHA":
        if take_role:
            self.lease.try_acquire()
        self._thread = threading.Thread(target=self._loop,
                                        name="router-ha", daemon=True)
        self._thread.start()
        return self

    def shutdown(self, release: bool = True):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        if release and self.lease.valid():
            try:
                self.lease.release()
            except Exception as e:  # noqa: BLE001 — best-effort
                logger.warning("role release failed: %r", e)

    def _loop(self):
        while not self._stop.wait(self.interval_ms / 1e3):
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 — the loop must live
                logger.error("router HA step crashed: %r", e)

    # -------------------------------------------------------------- duty
    def step(self):
        """One HA iteration: renew while active, watch + maybe adopt
        while standing by."""
        if self.lease.valid():
            try:
                renewed = self.lease.renew()
            except ConnectionError as e:
                # an injected lease_renew drop or a store hiccup: the
                # renewal is LOST (validity keeps ticking down; enough
                # losses and the fence trips) — never fatal here
                logger.warning("active-role renewal lost: %r", e)
                renewed = False
            if not renewed and not self.lease.valid():
                self._was_active = False
                log_event(
                    logger, "role_fenced",
                    "router %s FENCED: lost the active role (epoch "
                    "moved or lease lapsed); dispatch now refuses",
                    self.lease.holder_id, holder=self.lease.holder_id,
                    epoch=self.lease.epoch)
            else:
                self._was_active = True
            self.peer_failures = 0
            return
        if self._was_active:
            # the lease lapsed BETWEEN steps (e.g. every renewal was
            # partitioned away and never refused): this edge is the
            # only place the silent expiry can be dated
            self._was_active = False
            log_event(
                logger, "role_fenced",
                "router %s FENCED: active-role lease lapsed (renewals "
                "lost); dispatch now refuses",
                self.lease.holder_id, holder=self.lease.holder_id,
                epoch=self.lease.epoch)
        # ------------------------------------------------ standby watch
        try:
            h = self._poll_peer()
        except Exception as e:  # noqa: BLE001 — peer unreachable
            self.peer_failures += 1
            logger.debug("peer poll failed (%d/%d): %r",
                         self.peer_failures, self.adopt_after, e)
        else:
            reps = h.get("replicas") or []
            if reps:
                self.last_peer_snapshot = reps
            # a peer that answers but cannot serve (fenced, no ready
            # replica, dead) counts as failed — but the LEASE decides:
            # a healthy active's renewals make try_acquire impossible
            self.peer_failures = (0 if h.get("ready")
                                  else self.peer_failures + 1)
        if self.peer_failures >= self.adopt_after \
                and self.lease.try_acquire():
            self._take_over()

    def _poll_peer(self) -> dict:
        if self._peer_healthz is not None:
            return self._peer_healthz()
        if self.peer is None:
            raise RuntimeError("standby has no peer to watch (pass "
                               "peer=(host, port) or peer_healthz=)")
        host, port = self.peer
        # a 503 body still carries the fleet snapshot — _get_json reads
        # it whatever the status (same contract as replica healthz)
        status, data = _get_json(host, port, "/healthz", 2.0)
        if not isinstance(data, dict) or "live" not in data:
            raise ConnectionError(
                f"peer {host}:{port} healthz is not a health "
                f"payload (HTTP {status})")
        return data

    def _take_over(self):
        """Adopt the fleet: rebuild the replica set from the last peer
        snapshot, re-arm via one poll, start answering."""
        if _chaos._ACTIVE is not None:
            _chaos._ACTIVE.hit("router_failover",
                               holder=self.lease.holder_id,
                               epoch=self.lease.epoch)
        snaps = self.last_peer_snapshot
        if self._adopt_builder is not None:
            pairs = self._adopt_builder(snaps)
        else:
            pairs = []
            for s in snaps:
                addr = s.get("addr")
                if not addr:
                    logger.warning(
                        "adoption: replica %s advertises no addr "
                        "(in-process transport?); skipped",
                        s.get("id"))
                    continue
                host, _, port = addr.rpartition(":")
                pairs.append((s["id"], HTTPTransport(host, int(port))))
        if pairs:
            self.router.adopt_replicas(pairs)
        self.router.poll_once()
        self.adoptions += 1
        self.adopted_at = time.monotonic()
        self.peer_failures = 0
        self.router.metrics.inc("adoptions_total")
        log_event(
            logger, "ha_takeover",
            "router %s ADOPTED the fleet (epoch %d): %d replica(s), "
            "%d ready", self.lease.holder_id, self.lease.epoch,
            len(self.router.replicas),
            self.router.fleet_health()["ready_replicas"],
            holder=self.lease.holder_id, epoch=self.lease.epoch,
            replicas=len(self.router.replicas))


# ------------------------------------------------------------- HTTP tier

class RouterHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, addr, router: ReplicaRouter, reload_builder=None,
                 registry=None, model_path=None):
        super().__init__(addr, _RouterHandler)
        self.router = router
        self.reload_builder = reload_builder
        # the artifact path the fleet currently serves — the rollback
        # anchor for /admin/reload (a refused artifact rolls the fleet
        # back to this path instead of leaving a replica down)
        self.current_model_path = model_path
        # optional obs.MetricsRegistry: extra federated providers (the
        # serve_fleet supervisor + autoscaler) riding this frontend's
        # /metrics so one scrape covers the whole process
        self.registry = registry


class _RouterHandler(JSONHandler):
    """The router's HTTP frontend: same endpoint contract as the single-
    replica server (a client cannot tell them apart), plus routing
    provenance headers (``X-Replica-Id``, ``X-Failovers``, ``X-Hedged``)
    and the fleet admin surface (``POST /admin/reload``)."""

    # -------------------------------------------------------------- GET
    def do_GET(self):
        self._tctx = _trace.ctx_from_headers(self.headers)
        router: ReplicaRouter = self.server.router
        path = self.path.split("?", 1)[0]
        if path == "/healthz":
            h = router.fleet_health()
            self._send(200 if h["ready"] else 503, h)
        elif path == "/livez":
            self._send(200, {"status": "ok", "live": True})
        elif path == "/metrics":
            registry = getattr(self.server, "registry", None)
            if "format=json" in self.path:
                snap = router.metrics.snapshot()
                snap["fleet"] = router.fleet_health()
                # federation: per-replica serving snapshots + any extra
                # registered providers — one scrape, the whole fleet
                snap["replicas_metrics"] = router.replica_metrics()
                if registry is not None:
                    snap["federation"] = registry.snapshot()
                self._send(200, snap)
            else:
                from paddle_tpu.obs.registry import prom_from_dict
                chunks = [router.metrics.to_prometheus().rstrip("\n")]
                for rid, rsnap in sorted(
                        router.replica_metrics().items()):
                    chunks.extend(prom_from_dict(
                        "paddle_tpu_replica", rsnap,
                        labels={"replica": rid}))
                if registry is not None:
                    chunks.append(registry.to_prometheus().rstrip("\n"))
                self._send(200, ("\n".join(chunks) + "\n").encode(),
                           content_type="text/plain; version=0.0.4")
        else:
            self._send(404, {"error": {"code": "not_found",
                                       "message": self.path}})

    # ------------------------------------------------------------- POST
    def do_POST(self):
        self._tctx = _trace.ctx_from_headers(self.headers)
        router: ReplicaRouter = self.server.router
        path = self.path.split("?", 1)[0]
        if path == "/admin/reload":
            self._admin_reload()
            return
        if path == "/admin/config":
            self._admin_config()
            return
        kind = {"/v1/score": "score", "/v1/generate": "generate"}.get(path)
        if kind is None:
            self._send(404, {"error": {"code": "not_found",
                                       "message": self.path}})
            return
        prov: Dict = {}
        try:
            body = self._body()
            deadline_ms = body.get("deadline_ms")
            gen = ({"beam_size": body.get("beam_size"),
                    "max_length": body.get("max_length")}
                   if kind == "generate" else {})
            if "rows" in body:
                self._rows(router, kind, body, deadline_ms, gen)
                return
            if "sample" not in body:
                raise BadRequest("need \"sample\" (one request) or "
                                 "\"rows\" (a list)")
            result, prov = router.dispatch(
                body["sample"], kind=kind, deadline_ms=deadline_ms,
                trace_parent=self._tctx, **gen)
            self._send(200, result, headers=self._prov_headers(prov))
        except ServingError as e:
            prov = getattr(e, "provenance", prov)
            self._send_error(e, headers=self._prov_headers(prov))
        except Exception as e:  # noqa: BLE001 — the only 500 source
            logger.error("unhandled router error: %r", e)
            self._send_error(ServingError(repr(e)))

    @staticmethod
    def _prov_headers(prov: Dict) -> Dict:
        if not prov:
            return {}
        return {"X-Replica-Id": prov.get("replica"),
                "X-Model-Version": prov.get("model_version"),
                "X-Failovers": prov.get("failovers"),
                "X-Hedged": prov.get("hedges")}

    def _rows(self, router, kind, body, deadline_ms, gen):
        if not isinstance(body["rows"], list) or not body["rows"]:
            raise BadRequest("\"rows\" must be a non-empty list")
        # rows dispatch CONCURRENTLY: the replicas' batchers coalesce
        # same-kind rows landing together, so a rows call keeps the
        # batching win it has on the single-replica server (sequential
        # dispatch would serialize one device launch per row)
        rows = body["rows"]
        results = [None] * len(rows)
        any_err = [False]

        tctx = self._tctx  # worker threads get no ambient contextvars

        def one(i, row):
            try:
                result, prov = router.dispatch(
                    row, kind=kind, deadline_ms=deadline_ms,
                    trace_parent=tctx, **gen)
                result = dict(result)
                result["replica"] = prov.get("replica")
                results[i] = result
            except ServingError as e:
                results[i] = e.to_wire()
                any_err[0] = True

        workers = [threading.Thread(target=one, args=(i, row),
                                    daemon=True)
                   for i, row in enumerate(rows)]
        for w in workers:
            w.start()
        for w in workers:
            w.join(120.0)
        for i, r in enumerate(results):
            if r is None:  # a worker thread hung past the join bound
                results[i] = DeadlineExceeded(
                    "no answer within the server wait bound").to_wire()
                any_err[0] = True
        self._send(200 if not any_err[0] else 207, {"results": results})

    def _admin_config(self):
        """Fleet-wide hot reconfig: the body is a
        :class:`~paddle_tpu.serving.tuner.FleetConfig` knob delta.
        Synchronous; 200 carries before/after, a refusal answers the
        typed 409 ``config_rejected`` with the incumbent still serving
        on every replica (``ReplicaRouter.apply_config`` rolled back
        any partially-applied fan-out)."""
        try:
            self._send(200, self.server.router.apply_config(
                self._body()))
        except ServingError as e:
            self._send_error(e)
        except Exception as e:  # noqa: BLE001
            logger.error("config apply failed: %r", e)
            self._send(500, {"error": {"code": "config_failed",
                                       "message": repr(e)}})

    def _admin_reload(self):
        """Rolling hot-swap to a new merged model: ``{"model_path":
        "/path/new.ptmodel"}``. Synchronous — the response carries the
        per-replica versions after the roll (long request by design; the
        fleet keeps serving throughout). When the new artifact refuses a
        replica (warmup failure — notably a quantized artifact drifting
        past the accuracy gate), the fleet ROLLS BACK to the previously
        served path and the call answers a typed 409 ``reload_rejected``
        carrying the refusal; the bad artifact is never published."""
        builder = self.server.reload_builder
        try:
            if builder is None:
                raise BadRequest(
                    "this router was started without a reload builder "
                    "(--job=serve --replicas N wires one); rolling "
                    "reload over HTTP is unavailable")
            body = self._body()
            path = body.get("model_path")
            if not path:
                raise BadRequest("need \"model_path\" (a merged PTM1 "
                                 "artifact)")
            prev = self.server.current_model_path
            fallback = ((lambda rid: builder(prev, rid))
                        if prev else None)
            versions = self.server.router.rolling_reload(
                lambda rid: builder(path, rid), fallback_build=fallback)
            self.server.current_model_path = path
            self._send(200, {"status": "ok", "versions": versions})
        except ServingError as e:
            self._send_error(e)
        except Exception as e:  # noqa: BLE001
            logger.error("rolling reload failed: %r", e)
            self._send(500, {"error": {"code": "reload_failed",
                                       "message": repr(e)}})


def make_router_server(router: ReplicaRouter, host: str = "127.0.0.1",
                       port: int = 0, reload_builder=None,
                       registry=None, model_path=None):
    """Bind the router frontend (port=0 = ephemeral, for tests); the
    bound port is ``server.server_address[1]``. ``registry`` federates
    extra metric providers (supervisor, autoscaler) into ``/metrics``;
    ``model_path`` seeds the rollback anchor for ``/admin/reload``."""
    return RouterHTTPServer((host, port), router,
                            reload_builder=reload_builder,
                            registry=registry, model_path=model_path)


def install_router_signal_handlers(router: ReplicaRouter,
                                   server=None):
    """SIGTERM/SIGINT -> drain EVERY replica (zero queued drops), then
    stop the router listener. Returns the previous handlers (tests and
    embedders restore them) — the fleet twin of ``server.py:
    install_signal_handlers``."""
    import signal

    def _drain(signum, frame):
        logger.info("signal %d: draining the fleet", signum)

        def _finish():
            router.shutdown(drain=True)
            if server is not None:
                server.shutdown()

        threading.Thread(target=_finish, daemon=True,
                         name="router-drain").start()

    prev = {}
    for sig in (signal.SIGTERM, signal.SIGINT):
        prev[sig] = signal.signal(sig, _drain)
    return prev


def serve_router_forever(router: ReplicaRouter, host: str = "127.0.0.1",
                         port: int = 8000, reload_builder=None,
                         ready_line: bool = True, registry=None,
                         model_path=None):
    """CLI entry for ``--job=serve --replicas N``: start the health
    loop, bind, install SIGTERM handlers that drain EVERY replica (zero
    queued drops), serve until drained."""
    router.start()
    server = make_router_server(router, host, port,
                                reload_builder=reload_builder,
                                registry=registry, model_path=model_path)
    install_router_signal_handlers(router, server)
    if ready_line:
        h = router.fleet_health()
        print(f"router serving on http://{host}:"
              f"{server.server_address[1]} "
              f"({h['ready_replicas']}/{len(router.replicas)} replicas "
              "ready)", flush=True)
    try:
        server.serve_forever(poll_interval=0.2)
    finally:
        server.server_close()
        router.shutdown(drain=True)
    return 0
