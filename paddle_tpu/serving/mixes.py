"""Canonical workload mixes for the self-tuning loop.

The committed ``WORKLOAD_r21_*.json`` traces pin the REQUEST stream;
this module pins the fleet they were recorded against. ``bench.py
--autotune`` (which records the traces and runs the defaults-vs-tuned
A/B) and ``tests/test_workload_replay.py`` (which replays the committed
traces and asserts the determinism contract) both build their engines
HERE, so a drifted model or knob default shows up as a test failure,
not as a silently unreplayable artifact.

Two mixes, chosen to stress different knobs:

- ``short_burst`` — the DIM-8 classifier behind score traffic arriving
  in synchronized bursts: the burst width vs ``queue_depth`` /
  ``batch_timeout_ms`` trade is what the tuner must discover.
- ``convoy`` — a shrunk r10 length-controlled decode model (EOS logit =
  3 * sum(memory), memory boots tanh(2*src): positive src finishes in
  <= 2 steps, a 20% ``-1`` tail never emits EOS and runs the full
  max_length) behind generate traffic — the mostly-short-plus-long-tail
  stream where batch coalescing convoys the short requests.

Both models are deterministic by construction (fixed seeds, fixed
surgery), small enough for the 1-core CPU host, and sized so the
structural outcomes (shed counts, batch occupancy) — not absolute
latencies — carry the comparison.
"""

from __future__ import annotations

from typing import List, Optional

from paddle_tpu.serving.workload import Workload

# shrunk r10 decode-convoy geometry (bench.py:bench_decode is the
# full-size original); small enough that warmup compiles fit tier-1
CONVOY_V, CONVOY_E, CONVOY_H = 64, 8, 16
CONVOY_K, CONVOY_L, CONVOY_CHUNK = 2, 16, 4

CLASSIFIER_DIM, CLASSIFIER_CLASSES = 8, 4


# ----------------------------------------------------------- classifier

def classifier_model(seed: int = 0):
    """Tiny dense classifier (the serving-test workhorse shape);
    returns ``(graph, params, feeding)``."""
    import jax
    from paddle_tpu.config import dsl
    from paddle_tpu.core.network import Network
    from paddle_tpu.data import dense_vector, integer_value

    dsl.reset()
    x = dsl.data(name="x", size=CLASSIFIER_DIM)
    lab = dsl.data(name="label", size=CLASSIFIER_CLASSES)
    hid = dsl.fc(input=x, size=12, act="relu", name="hid")
    out = dsl.fc(input=hid, size=CLASSIFIER_CLASSES, act="softmax",
                 name="out")
    dsl.classification_cost(input=out, label=lab, name="cost")
    graph = dsl.current_graph()
    params = Network(graph, outputs=["out"]).init_params(
        jax.random.PRNGKey(seed))
    feeding = {"x": dense_vector(CLASSIFIER_DIM),
               "label": integer_value(CLASSIFIER_CLASSES)}
    return graph, params, feeding


def build_classifier_engine(*, max_batch: int = 2,
                            batch_timeout_ms: float = 4.0,
                            queue_depth: int = 6,
                            warmup: bool = True):
    """The ``short_burst`` serving engine. The DEFAULT knobs are the
    deliberately hand-set ones the bench's A/B measures against: a
    queue narrower than the burst (structural sheds) and a long
    coalescing wait — exactly what ``--autotune``'s grid search is
    expected to fix (queue >= burst, shorter timeout). Menu is
    ``batch_buckets=[1, 2, 4]``, so ``max_batch=8`` is the canonical
    off-menu refusal."""
    from paddle_tpu.serving import ServingEngine, ServingPredictor

    graph, params, feeding = classifier_model()
    pred = ServingPredictor(graph, params, ["out"], feeding,
                            batch_buckets=[1, 2, 4])
    return ServingEngine(pred, max_batch=max_batch,
                         batch_timeout_ms=batch_timeout_ms,
                         queue_depth=queue_depth).start(warmup=warmup)


def short_burst_schedule(n_bursts: int = 4, burst: int = 12,
                         gap_s: float = 0.08) -> List[dict]:
    """Synthetic pacer events: ``n_bursts`` synchronized bursts of
    ``burst`` score requests each. Samples are deterministic (seeded)
    and in-distribution for :func:`classifier_model`."""
    import numpy as np
    rng = np.random.RandomState(0)
    events = []
    for b in range(n_bursts):
        for _ in range(burst):
            vec = (rng.rand(CLASSIFIER_DIM) / CLASSIFIER_DIM).tolist()
            events.append({"t": round(b * gap_s, 6), "kind": "score",
                           "sample": (vec, 1)})
    return events


def short_burst_workload() -> Workload:
    return Workload("short_burst", short_burst_schedule())


# --------------------------------------------------------------- convoy

def convoy_model():
    """The r10 length-controlled decode model, shrunk: boot = 2*eye so
    memory starts at tanh(2*src); ``_prob.w0[:, 1] = 3`` makes the EOS
    logit 3 * sum(memory). ``[1]*H`` sources finish in <= 2 steps,
    ``[-1]*H`` sources never emit EOS and run the full ``CONVOY_L`` —
    margins too fat for cross-batch-width drift to flip a token.
    Returns ``(graph, params, feeding)``."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from paddle_tpu.config import dsl
    from paddle_tpu.core.network import Network
    from paddle_tpu.core.registry import get_layer_impl
    from paddle_tpu.data import dense_vector

    V, E, H = CONVOY_V, CONVOY_E, CONVOY_H
    dsl.reset()
    src = dsl.data("src", size=H)
    boot = dsl.fc(src, size=H, act="tanh", name="boot", bias_attr=False)

    def step(prev_emb):
        m = dsl.memory(name="h", size=H, boot_layer=boot)
        h = dsl.fc([prev_emb, m], size=H, act="tanh", name="h",
                   bias_attr=False)
        return dsl.fc(h, size=V, act="softmax", name="prob",
                      bias_attr=False)

    dsl.beam_search(
        step, [dsl.GeneratedInput(size=V, embedding_name="gen_emb",
                                  embedding_size=E)],
        bos_id=0, eos_id=1, beam_size=CONVOY_K, max_length=CONVOY_L,
        name="gen")
    graph = dsl.current_graph()
    net = Network(graph, outputs=["boot"])
    params = dict(net.init_params(jax.random.PRNGKey(0)))
    boot_key = next(k for k in params if "boot" in k)
    params[boot_key] = jnp.asarray(2.0 * np.eye(H, dtype=np.float32))
    for _, spec in get_layer_impl("beam_search_group").params(
            graph.layers["gen"], []).items():
        params[spec.absolute_name] = jnp.zeros(spec.shape, jnp.float32)
    params["_h.w1"] = jnp.asarray(np.eye(H, dtype=np.float32))
    u = np.zeros((H, V), np.float32)
    u[:, 1] = 3.0
    params["_prob.w0"] = jnp.asarray(u)
    params["gen_emb"] = jnp.zeros((V, E), jnp.float32)
    return graph, params, {"src": dense_vector(H)}


def build_convoy_engine(*, max_batch: int = 4,
                        batch_timeout_ms: float = 8.0,
                        queue_depth: int = 4,
                        continuous_batching: bool = True,
                        warmup: bool = True):
    """The ``convoy`` serving engine. Defaults again hand-set on the
    slow side (wide coalescing window, queue narrower than the offered
    burst) so the bench's tuned config has structural headroom. Menu is
    ``batch_buckets=[1, 2, 4]``."""
    from paddle_tpu.serving import ServingEngine, ServingPredictor

    graph, params, feeding = convoy_model()
    pred = ServingPredictor(graph, params, ["gen"], feeding,
                            batch_buckets=[1, 2, 4],
                            gen_decode_chunk=CONVOY_CHUNK)
    return ServingEngine(pred, max_batch=max_batch,
                         batch_timeout_ms=batch_timeout_ms,
                         queue_depth=queue_depth,
                         continuous_batching=continuous_batching,
                         ).start(warmup=warmup)


def convoy_schedule(n: int = 20, long_frac: float = 0.2,
                    spacing_s: float = 0.02,
                    burst: int = 10) -> List[dict]:
    """Synthetic pacer events: generate requests in bursts of ``burst``
    with a deterministic ~``long_frac`` tail of full-length ``[-1]*H``
    convoys interleaved among ``[1]*H`` shorts (seeded, so the SAME
    positions are long on every build)."""
    import numpy as np
    H = CONVOY_H
    rng = np.random.RandomState(7)
    events = []
    for i in range(n):
        is_long = bool(rng.rand() < long_frac)
        sample = ([-1.0] * H,) if is_long else ([1.0] * H,)
        t = (i // burst) * (burst * spacing_s)
        events.append({"t": round(t, 6), "kind": "generate",
                       "sample": sample})
    return events


def convoy_workload() -> Workload:
    return Workload("convoy", convoy_schedule())


# ----------------------------------------------------------------- menu

MIXES = {
    "short_burst": (build_classifier_engine, short_burst_workload),
    "convoy": (build_convoy_engine, convoy_workload),
}


def committed_trace_path(mix: str, root: Optional[str] = None) -> str:
    """Repo-root path of the committed ``WORKLOAD_r21_<mix>.json``."""
    import os
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    return os.path.join(root, f"WORKLOAD_r21_{mix}.json")
