"""ServingClient: typed stdlib client for the serving HTTP plane.

Raises the same typed error family the server answers with
(``serving/errors.py`` rebuilt from the wire), so caller code branches
on ``Overloaded.retry_after_ms`` / ``DeadlineExceeded`` instead of
status-code string matching. Closed-menu 400s carry
``BadRequest.allowed`` — the warmed values (e.g. the pinned
``beam_size`` / ``max_length`` / length-bucket menu) the client can
retry with.
"""

from __future__ import annotations

import http.client
import json
from typing import List, Optional

from paddle_tpu.serving.errors import ServingError, from_wire


class ServingClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000,
                 timeout: float = 120.0):
        self.host = host
        self.port = int(port)
        self.timeout = timeout

    # ------------------------------------------------------------- wire
    def _request(self, method: str, path: str, body=None) -> dict:
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout)
        try:
            payload = json.dumps(body).encode() if body is not None else None
            headers = {"Content-Type": "application/json"} if payload else {}
            conn.request(method, path, body=payload, headers=headers)
            resp = conn.getresponse()
            raw = resp.read()
            try:
                data = json.loads(raw) if raw else {}
            except json.JSONDecodeError:
                data = {"raw": raw.decode(errors="replace")}
            if resp.status >= 400:
                raise from_wire(data, resp.status)
            return data
        finally:
            conn.close()

    # ---------------------------------------------------------- methods
    def score(self, sample, deadline_ms: Optional[float] = None) -> dict:
        """One sample -> ``{"outputs": {layer: values}}``."""
        body = {"sample": sample}
        if deadline_ms is not None:
            body["deadline_ms"] = deadline_ms
        return self._request("POST", "/v1/score", body)

    def score_rows(self, rows: List,
                   deadline_ms: Optional[float] = None) -> List[dict]:
        """Many samples in one HTTP call; per-row results in order (a
        failed row carries its typed error body instead of outputs)."""
        body = {"rows": rows}
        if deadline_ms is not None:
            body["deadline_ms"] = deadline_ms
        return self._request("POST", "/v1/score", body)["results"]

    def generate(self, sample, beam_size: Optional[int] = None,
                 max_length: Optional[int] = None,
                 deadline_ms: Optional[float] = None) -> dict:
        """One encoder input -> ``{"sequences": [{tokens, score}, ...]}``
        (beams best-first)."""
        body = {"sample": sample}
        if beam_size is not None:
            body["beam_size"] = beam_size
        if max_length is not None:
            body["max_length"] = max_length
        if deadline_ms is not None:
            body["deadline_ms"] = deadline_ms
        return self._request("POST", "/v1/generate", body)

    def healthz(self) -> dict:
        return self._request("GET", "/healthz")

    def metrics(self) -> dict:
        """The structured snapshot (``/metrics?format=json``)."""
        return self._request("GET", "/metrics?format=json")

    def metrics_text(self) -> str:
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout)
        try:
            conn.request("GET", "/metrics")
            resp = conn.getresponse()
            raw = resp.read().decode()
            if resp.status >= 400:
                raise ServingError(raw[:300])
            return raw
        finally:
            conn.close()
