"""ServingClient: typed stdlib client for the serving HTTP plane.

Raises the same typed error family the server answers with
(``serving/errors.py`` rebuilt from the wire), so caller code branches
on ``Overloaded.retry_after_ms`` / ``DeadlineExceeded`` instead of
status-code string matching. Closed-menu 400s carry
``BadRequest.allowed`` — the warmed values (e.g. the pinned
``beam_size`` / ``max_length`` / length-bucket menu) the client can
retry with.

Against the replica router (``serving/router.py``) the client also
surfaces routing provenance: every response carries the router's
``X-Replica-Id`` / ``X-Failovers`` / ``X-Hedged`` headers as
``last_provenance`` (and as a ``"provenance"`` key on successful result
dicts; typed errors carry ``.provenance``). Router 429s put the
FLEET-wide backlog estimate in ``retry_after_ms`` — the min over
replica drain hints, since queues drain in parallel — so the existing
backoff honors fleet capacity, not one replica's private EWMA.

Router HA (``endpoints=["host:port", ...]``): the client may be given
the active router AND its warm standby(s). A connection reset (the
active died) or a 503 ``Unavailable`` (a fenced old active / a standby
that has not adopted yet) rotates to the next endpoint inside the same
retry budget, and ``last_provenance["endpoint"]`` records which one
finally answered.

Opt-in retries (``retries=N``): every serving request is idempotent
(stateless inference), so the client may safely re-send on a connection
reset (a worker restart, a drained-and-relaunched server) and on 429
load-shed — honoring the server's ``Overloaded.retry_after_ms`` drain
estimate when present, else capped jittered exponential backoff. Other
typed errors (400 bad request, 504 deadline) are NOT retried: the same
request would fail the same way, and a deadline has, by definition,
already passed.
"""

from __future__ import annotations

import http.client
import random
import time
from typing import List, Optional

import json

from paddle_tpu.obs import trace as _trace
from paddle_tpu.serving.errors import (Overloaded, ServingError,
                                       Unavailable, from_wire)
from paddle_tpu.utils.backoff import backoff_delay, jittered_up


class ServingClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000,
                 timeout: float = 120.0, *, retries: int = 0,
                 backoff_base_ms: float = 50.0,
                 backoff_cap_ms: float = 2000.0,
                 backoff_seed: Optional[int] = None,
                 endpoints: Optional[List] = None):
        # ``endpoints`` = HA address list ["host:port", ...] (or
        # (host, port) tuples): the ACTIVE router and its warm
        # standby(s). On a connection reset — the active died — or a
        # 503 Unavailable — a fenced/un-adopted router answered — the
        # client rotates to the next endpoint inside the SAME retry
        # budget/backoff it already has, and ``last_provenance``
        # carries which endpoint finally answered. Default: the single
        # (host, port), with rotation a no-op.
        self._endpoints: List[tuple] = []
        for ep in (endpoints if endpoints else [(host, port)]):
            if isinstance(ep, str):
                h, _, p = ep.rpartition(":")
                self._endpoints.append((h or "127.0.0.1", int(p)))
            else:
                self._endpoints.append((ep[0], int(ep[1])))
        self._ep_idx = 0
        self.host, self.port = self._endpoints[0]
        # an HA list with the DEFAULT retries=0 would be silently
        # inert (rotation only happens on a retried attempt): floor
        # the budget at one attempt per extra endpoint. An explicit
        # retries>0 is honored as given.
        if len(self._endpoints) > 1 and retries == 0:
            retries = len(self._endpoints) - 1
        self.timeout = timeout
        self.retries = int(retries)
        self.backoff_base_ms = backoff_base_ms
        self.backoff_cap_ms = backoff_cap_ms
        self._jitter = random.Random(backoff_seed)
        # routing provenance of the LAST response (None for a single-
        # replica server): {"replica", "failovers", "hedges"} — also
        # attached to successful router responses under "provenance"
        # and to raised typed errors as .provenance
        self.last_provenance: Optional[dict] = None

    # ------------------------------------------------------------- wire
    def _rotate_endpoint(self):
        """Advance to the next endpoint of the HA list (no-op with
        one): the connection-reset / 503 re-resolution path."""
        if len(self._endpoints) > 1:
            self._ep_idx = (self._ep_idx + 1) % len(self._endpoints)
            self.host, self.port = self._endpoints[self._ep_idx]

    def _sleep_ms(self, ms: float):
        time.sleep(max(0.0, ms) / 1e3)

    def _backoff_ms(self, attempt: int,
                    retry_after_ms: Optional[float] = None) -> float:
        """Capped jittered exponential backoff; a server-provided
        ``retry_after_ms`` (the 429 drain estimate) takes precedence,
        jittered UP (``uniform(1.0, 1.5)`` of itself) so a fleet of
        clients does not return in lockstep at exactly the drain
        horizon — never below it, since re-sending into a still-full
        queue burns the retry budget on fresh 429s. For the same
        reason the client-side cap applies only to its OWN
        exponential schedule, never to the server's estimate."""
        if retry_after_ms is not None:
            return jittered_up(float(retry_after_ms), self._jitter)
        return backoff_delay(attempt, base=self.backoff_base_ms,
                             cap=self.backoff_cap_ms, rng=self._jitter)

    def _provenance_from(self, resp) -> Optional[dict]:
        """Routing provenance the replica router attaches as headers —
        which replica answered, how many failovers/hedges the request
        survived — plus the ``X-Trace-Id`` echo every serving response
        (errors and fenced 503s included) carries, so a caller can
        always NAME the trace that answered or refused it. None only
        when no provenance header came back at all. ANY header marks a
        provenance-bearing response: an error that never landed on a
        replica has no X-Replica-Id but its failover count and trace id
        are still provenance worth surfacing."""
        prov = {}
        rid = resp.getheader("X-Replica-Id")
        if rid is not None:
            prov["replica"] = rid
        for header, key in (("X-Failovers", "failovers"),
                            ("X-Hedged", "hedges")):
            v = resp.getheader(header)
            if v is not None:
                try:
                    prov[key] = int(v)
                except ValueError:
                    prov[key] = v
        tid = resp.getheader(_trace.HEADER)
        if tid is not None:
            # the echo is a bare trace id (the request's); keep only
            # the trace part if a full trace-span pair ever shows up
            prov["trace_id"] = tid.partition("-")[0]
        return prov or None

    def _request_once(self, method: str, path: str, body=None) -> dict:
        # cleared up front: a connection-level failure below must not
        # leave the PREVIOUS response's replica attributed to this one
        self.last_provenance = None
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout)
        # one client-side span per HTTP attempt — the ROOT span of a
        # serving trace when this client originates it (its wall time
        # IS the client-observed latency the replica-side children
        # must reconstruct), a child hop when a router transport calls
        # through with an ambient attempt context. The context (and
        # the X-Trace-Id header) flows whether or not a tracer is
        # installed; only the span record is gated.
        with _trace.span("client.request", method=method,
                         path=path) as tctx:
            return self._exchange(conn, method, path, body, tctx)

    def _exchange(self, conn, method: str, path: str, body,
                  tctx) -> dict:
        try:
            payload = json.dumps(body).encode() if body is not None else None
            headers = {"Content-Type": "application/json"} if payload else {}
            headers[_trace.HEADER] = tctx.to_header()
            conn.request(method, path, body=payload, headers=headers)
            resp = conn.getresponse()
            raw = resp.read()
            try:
                data = json.loads(raw) if raw else {}
            except json.JSONDecodeError:
                data = {"raw": raw.decode(errors="replace")}
            # retry provenance rides every router response, errors
            # included (last_provenance survives a raise below)
            self.last_provenance = self._provenance_from(resp)
            if len(self._endpoints) > 1:
                # HA list: surface WHICH endpoint answered (the active
                # vs a standby that adopted) alongside the router's
                # replica provenance
                prov = self.last_provenance or {}
                prov["endpoint"] = f"{self.host}:{self.port}"
                self.last_provenance = prov
            if resp.status >= 400:
                err = from_wire(data, resp.status)
                err.provenance = self.last_provenance
                raise err
            if self.last_provenance is not None and isinstance(data, dict):
                data.setdefault("provenance", self.last_provenance)
            return data
        finally:
            conn.close()

    def _request(self, method: str, path: str, body=None) -> dict:
        last: Optional[BaseException] = None
        for attempt in range(self.retries + 1):
            try:
                return self._request_once(method, path, body)
            except Overloaded as e:
                # 429 (load shed / draining): back off for the server's
                # drain estimate when it gave one
                last = e
                if attempt >= self.retries:
                    raise
                if isinstance(e, Unavailable):
                    # 503: THIS endpoint has no capacity to offer (a
                    # fenced old active, an un-adopted standby, a fleet
                    # with no ready replica) — re-resolve to the next
                    # endpoint of the HA list before retrying
                    self._rotate_endpoint()
                self._sleep_ms(self._backoff_ms(attempt, e.retry_after_ms))
            except (ConnectionError, http.client.HTTPException,
                    TimeoutError, OSError) as e:
                # connection reset / refused mid-restart: idempotent
                # requests may re-send — against the NEXT endpoint of
                # the HA list (a dead active's standby) when one exists
                last = e
                if attempt >= self.retries:
                    raise
                self._rotate_endpoint()
                self._sleep_ms(self._backoff_ms(attempt))
        raise ServingError(f"unreachable: {last!r}")  # not reached

    # ---------------------------------------------------------- methods
    def score(self, sample, deadline_ms: Optional[float] = None) -> dict:
        """One sample -> ``{"outputs": {layer: values}}``."""
        body = {"sample": sample}
        if deadline_ms is not None:
            body["deadline_ms"] = deadline_ms
        return self._request("POST", "/v1/score", body)

    def score_rows(self, rows: List,
                   deadline_ms: Optional[float] = None) -> List[dict]:
        """Many samples in one HTTP call; per-row results in order (a
        failed row carries its typed error body instead of outputs)."""
        body = {"rows": rows}
        if deadline_ms is not None:
            body["deadline_ms"] = deadline_ms
        return self._request("POST", "/v1/score", body)["results"]

    def generate(self, sample, beam_size: Optional[int] = None,
                 max_length: Optional[int] = None,
                 deadline_ms: Optional[float] = None) -> dict:
        """One encoder input -> ``{"sequences": [{tokens, score}, ...]}``
        (beams best-first)."""
        body = {"sample": sample}
        if beam_size is not None:
            body["beam_size"] = beam_size
        if max_length is not None:
            body["max_length"] = max_length
        if deadline_ms is not None:
            body["deadline_ms"] = deadline_ms
        return self._request("POST", "/v1/generate", body)

    def apply_config(self, config: dict) -> dict:
        """Hot-apply a knob delta (``POST /admin/config``). The body is
        a :class:`~paddle_tpu.serving.tuner.FleetConfig` dict; a 409
        refusal raises the typed
        :class:`~paddle_tpu.serving.errors.ConfigRejected` (NOT retried
        — neither an overload nor a connection error: the incumbent
        config is still serving and a re-send would refuse
        identically); 200 returns the before/after knob values."""
        body = config if isinstance(config, dict) else config.to_dict()
        return self._request("POST", "/admin/config", body)

    def healthz(self) -> dict:
        return self._request("GET", "/healthz")

    def metrics(self) -> dict:
        """The structured snapshot (``/metrics?format=json``)."""
        return self._request("GET", "/metrics?format=json")

    def metrics_text(self) -> str:
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout)
        try:
            conn.request("GET", "/metrics")
            resp = conn.getresponse()
            raw = resp.read().decode()
            if resp.status >= 400:
                raise ServingError(raw[:300])
            return raw
        finally:
            conn.close()
