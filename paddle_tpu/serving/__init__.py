"""paddle_tpu.serving — TPU-native model serving.

The inference half of the north star: a merged deploy model
(``trainer/merge_model.py``, the artifact ``--job=merge`` writes and the
C API loads) served over HTTP with

- a bucketed, AOT-warmed, donation-friendly predictor whose shape menu
  is CLOSED (``RecompileGuard.harden()`` — a stray shape is a typed 400,
  never a hot-path XLA compile),
- a dynamic micro-batching engine with per-request deadlines, admission
  control / load shedding, drain-on-SIGTERM, and per-lane isolation of
  malformed requests — plus continuous batching for the generate path
  (``continuous_batching=True`` / ``--serving_continuous_batching``):
  finished lanes retire and queued requests are admitted at every
  ``decode_chunk`` boundary of the early-exit beam search, so one slow
  request no longer convoys its batch and deadlines are enforced
  mid-decode,
- a metrics plane splitting request latency into
  {queue_wait, pad_overhead, compute, decode} with batch occupancy and
  per-bucket hit counts, on ``/metrics`` + ``/healthz``.

Entry points: ``python -m paddle_tpu.trainer.cli --job=serve`` (flags
``--port --batch_timeout_ms --max_batch --queue_depth``), or
programmatically::

    pred = ServingPredictor.from_merged("model.ptmodel", feeding,
                                        batch_buckets=[1, 2, 4, 8],
                                        length_buckets=[32, 64])
    engine = ServingEngine(pred, batch_timeout_ms=5).start()
    serve_forever(engine, port=8000)      # or engine.infer(sample)

Design record: ``docs/serving.md``.
"""

from paddle_tpu.serving.batcher import ServingEngine  # noqa: F401
from paddle_tpu.serving.client import ServingClient  # noqa: F401
from paddle_tpu.serving.errors import (BadRequest,  # noqa: F401
                                       DeadlineExceeded, Overloaded,
                                       ServingError, ShuttingDown)
from paddle_tpu.serving.metrics import ServingMetrics  # noqa: F401
from paddle_tpu.serving.predictor import ServingPredictor  # noqa: F401
from paddle_tpu.serving.server import (install_signal_handlers,  # noqa: F401
                                       make_server, serve_forever)
