"""paddle_tpu.serving — TPU-native model serving.

The inference half of the north star: a merged deploy model
(``trainer/merge_model.py``, the artifact ``--job=merge`` writes and the
C API loads) served over HTTP with

- a bucketed, AOT-warmed, donation-friendly predictor whose shape menu
  is CLOSED (``RecompileGuard.harden()`` — a stray shape is a typed 400,
  never a hot-path XLA compile),
- a dynamic micro-batching engine with per-request deadlines, admission
  control / load shedding, drain-on-SIGTERM, and per-lane isolation of
  malformed requests — plus continuous batching for the generate path
  (``continuous_batching=True`` / ``--serving_continuous_batching``):
  finished lanes retire and queued requests are admitted at every
  ``decode_chunk`` boundary of the early-exit beam search, so one slow
  request no longer convoys its batch and deadlines are enforced
  mid-decode,
- a metrics plane splitting request latency into
  {queue_wait, pad_overhead, compute, decode} with batch occupancy and
  per-bucket hit counts, on ``/metrics`` + ``/healthz`` (readiness) /
  ``/livez`` (liveness),
- a fleet tier (``--replicas N``, ``serving/router.py``): N replica
  engines behind a health-aware router — failover of definite replica
  failures, per-replica circuit breakers with half-open probing, capped
  hedged retries for idempotent score requests (never generate),
  auto-respawn of dead replicas, rolling hot-swap reload with zero
  queued drops, fleet-wide 429 backpressure — with an AOT warmup cache
  (``--aot_cache_dir``, ``serving/aot_cache.py``) that persists the
  warmed bucket menu as serialized compiled executables so a respawned
  replica cold-starts in milliseconds instead of re-tracing the shape
  cross-product,
- a self-operating tier (``--job=serve_fleet``,
  ``serving/supervisor.py``): a replica supervisor that spawns, leases
  (``dist/master.py:LeaseTable``), kills and respawns real
  single-replica server processes (reap-gated — no double spawn),
  router HA via a warm standby adopting the fleet over an epoch-fenced
  ``RoleLease`` (a partitioned old active provably stops dispatching),
  and load-driven autoscaling with hysteresis inside
  ``[--min_replicas, --max_replicas]``,
- a self-tuning tier (``serving/tuner.py`` + ``serving/workload.py``):
  one typed hot-reconfig path (``FleetConfig`` deltas through
  ``apply_config`` / ``POST /admin/config`` — validate-then-commit,
  off-menu values refused with a typed 409 ``ConfigRejected`` while
  the incumbent keeps serving), a deterministic trace-replay harness
  (record the admission stream as a ``WORKLOAD_*.json`` artifact,
  replay it against an in-process fleet, score p50/p99/throughput/
  shed/deadline-miss against a declared ``SLOTarget``), an offline
  coordinate-descent ``GridTuner`` over the replay score, and an
  online ``SLOController`` applying bounded nudges with
  Autoscaler-style hysteresis — every decision a ``tune_decision``
  flight event.

Entry points: ``python -m paddle_tpu.trainer.cli --job=serve`` (flags
``--port --batch_timeout_ms --max_batch --queue_depth --replicas
--aot_cache_dir``), or programmatically::

    pred = ServingPredictor.from_merged("model.ptmodel", feeding,
                                        batch_buckets=[1, 2, 4, 8],
                                        length_buckets=[32, 64])
    engine = ServingEngine(pred, batch_timeout_ms=5).start()
    serve_forever(engine, port=8000)      # or engine.infer(sample)

Design record: ``docs/serving.md``.
"""

from paddle_tpu.serving.aot_cache import AOTCache  # noqa: F401
from paddle_tpu.serving.batcher import ServingEngine  # noqa: F401
from paddle_tpu.serving.client import ServingClient  # noqa: F401
from paddle_tpu.serving.errors import (BadRequest,  # noqa: F401
                                       ConfigRejected, DeadlineExceeded,
                                       Overloaded, QuantGateError,
                                       ReloadRejected, ServingError,
                                       ShuttingDown, Unavailable)
from paddle_tpu.serving.metrics import (RouterMetrics,  # noqa: F401
                                        ServingMetrics)
from paddle_tpu.serving.predictor import ServingPredictor  # noqa: F401
from paddle_tpu.serving.server import (install_signal_handlers,  # noqa: F401
                                       make_server, serve_forever)
from paddle_tpu.serving.router import (EngineTransport,  # noqa: F401
                                       HTTPTransport, ReplicaRouter,
                                       RouterHA, make_router_server,
                                       serve_router_forever)
from paddle_tpu.serving.supervisor import (Autoscaler,  # noqa: F401
                                           InProcessFleet,
                                           ReplicaSupervisor)
from paddle_tpu.serving.tuner import (FleetConfig,  # noqa: F401
                                      GridTuner, SLOController,
                                      SLOTarget, slo_score)
from paddle_tpu.serving.workload import (Workload,  # noqa: F401
                                         WorkloadRecorder, replay,
                                         replay_score)
