"""Replica supervisor + load-driven autoscaling: the operator, built in.

The r13 router fully manages IN-PROCESS replicas but treats remote ones
as externally scheduled — spawn and SIGTERM were the operator's job.
This module is that operator as tested framework behavior, over the
primitives the repo already chaos-proved:

- **a replica is a task with a lease** — the supervisor monitors each
  spawned single-replica server process through the SAME
  :class:`~paddle_tpu.dist.master.LeaseTable` the master leases
  trainers with. A successful ``/healthz`` probe renews the replica's
  lease (chaos site ``lease_renew``: a dropped renewal ages the lease
  exactly like a hung replica would). Lease expiry ⇒ SIGTERM, a grace
  window, SIGKILL, and an UNCONDITIONAL reap before any respawn — two
  live processes serving one replica id are impossible by construction
  (the no-double-spawn invariant, asserted at the spawn site).
- **kill-discrimination matrix** — a CRASHED replica (process exited)
  is reaped and respawned immediately; a HUNG replica (process alive,
  health probes failing) dies by lease expiry; a SLOW-BUT-HEARTBEATING
  straggler keeps renewing and is NEVER killed — slowness is the
  breaker/hedge plane's business (router), not the lifecycle plane's.
- **warm respawns** — the spawn factory threads ``--aot_cache_dir``
  through to every child, so a respawned replica deserializes its
  bucket menu (ms) instead of re-tracing it (BENCH_r13: 58 ms vs
  476 ms). Spawns fire the chaos site ``supervisor_spawn`` (a drop =
  failed spawn, retried next sweep).
- **uniform drain** — scale-down and shutdown drain through
  ``POST /admin/drain`` (:meth:`HTTPTransport.begin_drain`), identical
  for supervisor-owned and externally-launched replicas, then reap.

:class:`Autoscaler` closes the loop on capacity: an EWMA of the
fleet's backlog estimate (the same ``backlog_ms`` the 429
``retry_after_ms`` hint is built from) crossing ``up_backlog_ms`` for a
sustained window scales up; sustained idle below ``down_backlog_ms``
scales down; a cooldown after every action plus the two separate
sustain windows give the hysteresis that keeps flapping load from
thrashing spawn/drain. Replica count stays inside
``[min_replicas, max_replicas]`` unconditionally.

Lock discipline (graftlint pass-3 scope): the supervisor lock guards
replica-table / lease / event bookkeeping ONLY — process signals,
transport probes, chaos hits, and metrics all happen outside it, so the
supervisor adds no lock-order edges over the router/metrics graph. The
autoscaler is single-writer (its own loop thread or a test driving
:meth:`Autoscaler.observe`) and holds no lock at all.
"""

from __future__ import annotations

import socket
import subprocess
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from paddle_tpu.dist.master import LeaseTable
from paddle_tpu.obs import flight as _flight
from paddle_tpu.serving.metrics import RouterMetrics
from paddle_tpu.serving.router import HTTPTransport
from paddle_tpu.testing import chaos as _chaos
from paddle_tpu.utils.log import event as log_event
from paddle_tpu.utils.log import get_logger

logger = get_logger("serving.supervisor")


def free_port(host: str = "127.0.0.1") -> int:
    """An OS-assigned free TCP port (bind-0 probe). Racy by nature —
    fine for spawn factories on one host; real deployments pass fixed
    ports."""
    s = socket.socket()
    try:
        s.bind((host, 0))
        return s.getsockname()[1]
    finally:
        s.close()


class SupervisedReplica:
    """Supervisor-side state for one replica process slot."""

    def __init__(self, replica_id: str):
        self.id = str(replica_id)
        self.proc: Optional[subprocess.Popen] = None
        self.transport: Optional[HTTPTransport] = None
        self.respawns = 0
        self.last_spawn_ms: Optional[float] = None
        self.last_health: dict = {}
        # lifecycle claim: exactly ONE thread (monitor sweep, scaler,
        # shutdown) may run this slot's kill/spawn transition at a time
        # — claimed under the supervisor lock, held across the (slow,
        # unlocked) process work. THIS is what makes no-double-spawn
        # hold between threads, not just within one.
        self.busy = False
        # boot tracking: a freshly (re)spawned process gets boot grace
        # (it cannot renew until its server listens); `booted` flips at
        # the first successful probe and normal lease aging takes over
        self.booted = False
        self.spawned_t: Optional[float] = None

    def snapshot(self) -> dict:
        return {"id": self.id,
                "pid": (self.proc.pid if self.proc is not None
                        and self.proc.poll() is None else None),
                "respawns": self.respawns,
                "last_spawn_ms": self.last_spawn_ms,
                "addr": (f"{self.transport.host}:{self.transport.port}"
                         if self.transport is not None else None)}


class ReplicaSupervisor:
    """Spawns, leases, kills, reaps, and respawns real single-replica
    server processes behind :class:`HTTPTransport`. See the module
    docstring for the lifecycle contract.

    ``spawn(replica_id) -> (proc, host, port)`` is the process factory
    (the CLI's builds ``python -m paddle_tpu.trainer.cli --job=serve``
    children with the AOT cache dir threaded through; tests use stub
    servers). ``attach_router`` connects a :class:`ReplicaRouter` so
    respawns swap the fresh transport into the router's slot — the
    router's OWN ``spawn`` factory must stay ``None`` in that wiring
    (two spawners racing one replica id is exactly the double-spawn
    this module exists to prevent).
    """

    def __init__(self, spawn: Callable[[str], Tuple[subprocess.Popen,
                                                    str, int]], *,
                 replicas: int = 1,
                 lease_timeout_s: float = 3.0,
                 poll_ms: float = 200.0,
                 grace_s: float = 2.0,
                 boot_grace_s: float = 600.0,
                 healthz_timeout_s: Optional[float] = None,
                 metrics: Optional[RouterMetrics] = None):
        self.spawn = spawn
        self.lease_timeout_s = float(lease_timeout_s)
        self.poll_ms = float(poll_ms)
        self.grace_s = float(grace_s)
        # how long a (re)spawned process may take to answer its FIRST
        # probe before it counts as hung — a child booting jax + the
        # model cannot renew a lease yet, and killing it mid-boot would
        # crash-loop forever (the lease ttl only governs replicas that
        # have answered at least once since their spawn)
        self.boot_grace_s = float(boot_grace_s)
        self.healthz_timeout_s = (float(healthz_timeout_s)
                                  if healthz_timeout_s is not None
                                  else max(0.5, self.lease_timeout_s / 3))
        self.metrics = metrics or RouterMetrics()
        self.router = None
        self._lock = threading.Lock()
        self._replicas: Dict[str, SupervisedReplica] = {
            f"r{i}": SupervisedReplica(f"r{i}")
            for i in range(int(replicas))}
        self._next_id = int(replicas)
        self._leases = LeaseTable(self.lease_timeout_s)
        # audit trail for tests/ops: (monotonic, kind, replica_id, info)
        self.events: List[tuple] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---------------------------------------------------------- plumbing
    def _event(self, kind: str, rid: str, **info):
        with self._lock:
            self.events.append((time.monotonic(), kind, rid, info))
        # the audit trail doubles as the flight-recorder feed: the
        # SAME lifecycle transitions (crashed / lease_expired / killed
        # / spawned / spawn_failed / scale_up / scale_down /
        # lease_renew_lost) land in the merged postmortem timeline —
        # recorded OUTSIDE the supervisor lock (edge-free discipline);
        # the child's pid travels as replica_pid — the record's own
        # ``pid`` is the supervisor's (blackbox merges/attributes on it)
        if _flight._ACTIVE is not None:
            _flight._ACTIVE.record(
                f"replica_{kind}", replica=rid,
                **{("replica_pid" if k == "pid" else k): v
                   for k, v in info.items()})

    def _claim(self, rep: SupervisedReplica) -> bool:
        """Claim a slot's lifecycle (kill/spawn) transition. False when
        another thread holds it OR the slot left the table (a scaled-
        away replica must never be respawned by a stale sweep
        snapshot)."""
        with self._lock:
            if rep.busy or self._replicas.get(rep.id) is not rep:
                return False
            rep.busy = True
            return True

    def _release(self, rep: SupervisedReplica):
        with self._lock:
            rep.busy = False

    def attach_router(self, router) -> "ReplicaSupervisor":
        if router.spawn is not None:
            raise ValueError(
                "the router's own spawn factory must be None under a "
                "supervisor: two independent spawners for one replica "
                "id is the double-spawn hazard")
        self.router = router
        return self

    # ----------------------------------------------------------- control
    def start(self, wait_ready_s: Optional[float] = None
              ) -> List[HTTPTransport]:
        """Spawn every configured replica (failures retry on the
        monitor sweep) and return the transports, in slot order, for
        router construction. ``wait_ready_s`` blocks until each spawned
        replica's ``/healthz`` turns ready (or the bound passes)."""
        with self._lock:
            reps = list(self._replicas.values())
        for rep in reps:
            if not self._claim(rep):
                continue  # a (prematurely started) monitor got here
            try:
                self._respawn(rep, why="start")
            finally:
                self._release(rep)
        if wait_ready_s:
            self.wait_ready(wait_ready_s)
        with self._lock:
            return [r.transport for r in self._replicas.values()
                    if r.transport is not None]

    def wait_ready(self, timeout_s: float = 60.0) -> bool:
        deadline = time.monotonic() + float(timeout_s)
        while time.monotonic() < deadline:
            with self._lock:
                reps = list(self._replicas.values())
            states = []
            for rep in reps:
                if rep.transport is None:
                    states.append(False)
                    continue
                try:
                    h = rep.transport.healthz()
                except Exception:  # noqa: BLE001 — still booting
                    states.append(False)
                else:
                    # ANY successful probe ends boot grace — from here
                    # the lease ttl governs (a later hang must expire,
                    # not ride the boot budget)
                    rep.booted = True
                    states.append(bool(h.get("ready")))
            if states and all(states):
                return True
            time.sleep(0.05)
        return False

    def start_monitor(self) -> "ReplicaSupervisor":
        self._thread = threading.Thread(target=self._monitor,
                                        name="replica-supervisor",
                                        daemon=True)
        self._thread.start()
        return self

    def shutdown(self, drain: bool = True, timeout: float = 60.0):
        """Stop the monitor, drain every replica through the uniform
        ``/admin/drain`` path, then reap the processes."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        with self._lock:
            reps = list(self._replicas.values())
        for rep in reps:
            if rep.transport is None:
                continue
            if drain:
                try:
                    rep.transport.begin_drain()
                    rep.transport.drain_wait(timeout=timeout)
                except Exception as e:  # noqa: BLE001 — best effort
                    logger.warning("drain of %s failed: %r", rep.id, e)
            self._kill(rep, escalate_only=not drain)

    # ------------------------------------------------------------ monitor
    def _monitor(self):
        while not self._stop.wait(self.poll_ms / 1e3):
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001 — the loop must live
                logger.error("supervisor sweep crashed: %r", e)

    def poll_once(self):
        """One supervision sweep: probe each replica (a live answer
        renews its lease), respawn crashed/down slots, escalate-kill
        and respawn expired leases. Callable inline for deterministic
        tests. Every kill/spawn transition runs under the slot's
        lifecycle CLAIM, so a concurrent scaler (scale_up's spawn in
        flight, scale_down's retire) and this sweep can never both
        transition one slot — the cross-thread half of the
        no-double-spawn invariant."""
        with self._lock:
            reps = list(self._replicas.values())
        for rep in reps:
            if self._stop.is_set():
                return
            if rep.proc is not None and rep.proc.poll() is not None:
                # CRASHED: the process exited on its own; poll() reaped
                # it, so the no-double-spawn precondition already holds
                if not self._claim(rep):
                    continue  # scaled away / mid-transition elsewhere
                try:
                    self._event("crashed", rep.id,
                                rc=rep.proc.returncode)
                    with self._lock:
                        self._leases.drop(rep.id)
                    self._respawn(rep, why="crashed")
                finally:
                    self._release(rep)
                continue
            if rep.proc is None:
                # a failed spawn left the slot down; retry
                if not self._claim(rep):
                    continue
                try:
                    self._respawn(rep, why="down")
                finally:
                    self._release(rep)
                continue
            try:
                h = rep.transport.healthz()
            except Exception:  # noqa: BLE001 — hung, or still booting
                if not rep.booted and rep.spawned_t is not None \
                        and (time.monotonic() - rep.spawned_t
                             <= self.boot_grace_s):
                    # boot grace: a child that has never answered yet
                    # (jax import, model build) cannot renew — extend
                    # its lease directly (no lease_renew chaos site:
                    # this is not a heartbeat) until the first answer
                    # or the boot budget runs out, else respawns
                    # crash-loop on any boot longer than the ttl
                    with self._lock:
                        self._leases.renew(rep.id)
                continue  # booted & silent: the lease ages to expiry
            rep.last_health = h
            if h.get("live", False):
                # a SLOW answer still lands here: a straggler that
                # heartbeats within the probe timeout renews and is
                # never killed — slowness is the router's business
                rep.booted = True
                self._renew(rep)
        with self._lock:
            expired = self._leases.expired()
        for rid in expired:
            with self._lock:
                rep = self._replicas.get(rid)
            if rep is None:
                continue  # scaled away while its lease aged
            if not self._claim(rep):
                continue
            try:
                self._event("lease_expired", rid)
                logger.warning("supervisor: replica %s lease expired "
                               "(hung or partitioned); escalating", rid)
                self._kill(rep)
                self._respawn(rep, why="lease expired")
            finally:
                self._release(rep)

    def _renew(self, rep: SupervisedReplica):
        if _chaos._ACTIVE is not None:
            try:
                _chaos._ACTIVE.hit("lease_renew", replica=rep.id,
                                   role="replica")
            except ConnectionError:  # an injected drop: renewal LOST
                self.metrics.inc("lease_renew_lost_total")
                self._event("lease_renew_lost", rep.id)
                return
        with self._lock:
            self._leases.renew(rep.id)

    # ---------------------------------------------------------- lifecycle
    def _kill(self, rep: SupervisedReplica, escalate_only: bool = False):
        """SIGTERM → ``grace_s`` → SIGKILL → reap. Returns only once
        the process is REAPED (``poll()`` non-None): every respawn is
        gated on this, which is what makes two live processes per
        replica id impossible."""
        proc = rep.proc
        if proc is None or proc.poll() is not None:
            return  # nothing running (never spawned, or already
            # reaped): a "kill" of a dead process is not an event
        escalated = False
        if proc.poll() is None:
            try:
                proc.terminate()
            except (ProcessLookupError, OSError):
                pass
            try:
                proc.wait(timeout=self.grace_s)
            except subprocess.TimeoutExpired:
                escalated = True
                try:
                    proc.kill()
                except (ProcessLookupError, OSError):
                    pass
                proc.wait()
        if proc.poll() is None:  # pragma: no cover — SIGKILL is final
            raise RuntimeError(
                f"replica {rep.id} survived SIGKILL (pid {proc.pid})")
        self._event("killed", rep.id, pid=proc.pid,
                    escalated=escalated)
        self.metrics.inc("replica_kills_total")
        if not escalate_only:
            logger.warning("supervisor: replica %s pid %d killed "
                           "(%s)", rep.id, proc.pid,
                           "SIGKILL after grace" if escalated
                           else "SIGTERM")

    def _respawn(self, rep: SupervisedReplica, why: str):
        """Spawn (or re-spawn) a replica slot's process. A spawn
        failure (including an injected ``supervisor_spawn`` drop)
        leaves the slot down; the next sweep retries."""
        if rep.proc is not None and rep.proc.poll() is None:
            # the no-double-spawn invariant, enforced at the spawn
            # site itself: whatever path got here with a live process
            # must kill+reap first
            self._kill(rep)
        try:
            if _chaos._ACTIVE is not None:
                _chaos._ACTIVE.hit("supervisor_spawn", replica=rep.id,
                                   why=why)
            t0 = time.perf_counter()
            proc, host, port = self.spawn(rep.id)
            spawn_ms = 1e3 * (time.perf_counter() - t0)
        except Exception as e:  # noqa: BLE001 — retry next sweep
            with self._lock:
                rep.proc = None
            self._event("spawn_failed", rep.id, error=repr(e))
            logger.warning("supervisor: spawn of %s failed (%r); will "
                           "retry", rep.id, e)
            return
        transport = HTTPTransport(
            host, port, proc=proc,
            healthz_timeout=self.healthz_timeout_s)
        with self._lock:
            rep.proc = proc
            rep.transport = transport
            rep.respawns += 1
            rep.last_spawn_ms = spawn_ms
            rep.spawned_t = time.monotonic()
            rep.booted = False  # boot grace until the first answer
            # a fresh process gets a fresh lease grace period
            self._leases.renew(rep.id)
        self._event("spawned", rep.id, pid=proc.pid, why=why,
                    spawn_ms=round(spawn_ms, 1))
        self.metrics.inc("respawns_total")
        if self.router is not None:
            if not self.router.set_transport(rep.id, transport):
                self.router.add_replica(transport, rep.id)
        logger.info("supervisor: replica %s spawned (pid %d, %s, "
                    "%.1f ms)", rep.id, proc.pid, why, spawn_ms)

    # ------------------------------------------------------ scale target
    def replica_count(self) -> int:
        with self._lock:
            return len(self._replicas)

    def scale_up(self) -> bool:
        """Grow the fleet by one supervised replica (warm via the AOT
        cache the spawn factory threads through). The new slot is BORN
        CLAIMED, so the monitor sweep cannot see its momentary
        proc-is-None state and race a second spawn into it."""
        with self._lock:
            rid = f"r{self._next_id}"
            self._next_id += 1
            rep = SupervisedReplica(rid)
            rep.busy = True  # born claimed: released after the spawn
            self._replicas[rid] = rep
        try:
            self._respawn(rep, why="scale-up")
        finally:
            self._release(rep)
        if rep.proc is None:  # spawn failed; drop the slot
            with self._lock:
                self._replicas.pop(rid, None)
                self._leases.drop(rid)
            return False
        self.metrics.inc("scale_up_total")
        self._event("scale_up", rid)
        return True

    def scale_down(self) -> bool:
        """Retire the newest replica: claim its lifecycle (waiting out
        a sweep mid-transition on it), pop it from the table — from
        here no stale sweep snapshot can respawn it (`_claim` checks
        membership) — then out of dispatch immediately, drained
        through ``/admin/drain`` (zero queued drops), and reaped."""
        with self._lock:
            if not self._replicas:
                return False
            rid, rep = next(reversed(self._replicas.items()))
        deadline = time.monotonic() + 30.0
        while not self._claim(rep):
            with self._lock:
                if self._replicas.get(rid) is not rep:
                    return False  # someone else retired it meanwhile
            if time.monotonic() > deadline:
                logger.warning("scale-down of %s timed out waiting for "
                               "its lifecycle claim", rid)
                return False
            time.sleep(0.02)
        try:
            with self._lock:
                self._replicas.pop(rid, None)
                self._leases.drop(rid)
            if self.router is not None:
                try:
                    self.router.remove_replica(rid, drain=True)
                except KeyError:
                    pass
            elif rep.transport is not None:
                try:
                    rep.transport.begin_drain()
                    rep.transport.drain_wait()
                except Exception as e:  # noqa: BLE001 — best effort
                    logger.warning("scale-down drain of %s failed: %r",
                                   rid, e)
            self._kill(rep, escalate_only=True)
        finally:
            self._release(rep)
        self.metrics.inc("scale_down_total")
        self._event("scale_down", rid)
        return True

    def load_backlog_ms(self) -> Optional[float]:
        if self.router is not None:
            return self.router.load_backlog_ms()
        with self._lock:
            vals = [float(r.last_health["backlog_ms"])
                    for r in self._replicas.values()
                    if r.last_health.get("backlog_ms") is not None]
        return sum(vals) / len(vals) if vals else None

    def snapshot(self) -> dict:
        with self._lock:
            return {"replicas": [r.snapshot()
                                 for r in self._replicas.values()],
                    "leased": self._leases.holders()}


class InProcessFleet:
    """Autoscaler target over a router of in-process
    :class:`EngineTransport` replicas (bench + tests) — the
    process-backed twin is :class:`ReplicaSupervisor`. ``build``
    returns a started transport (an EngineTransport over an engine
    warmed from the shared AOT cache, so scale-up is warm here too)."""

    def __init__(self, router, build: Callable[[], object]):
        self.router = router
        self.build = build

    def replica_count(self) -> int:
        # lock-free snapshot read (CPython list ops are atomic; a
        # momentarily stale count only delays one policy tick)
        return len(self.router.replicas)

    def scale_up(self) -> bool:
        rid = self.router.add_replica(self.build())
        self.router.poll_once()  # routable NOW, not at the next sweep
        self.router.metrics.inc("scale_up_total")
        logger.info("in-process fleet: scaled up (+%s)", rid)
        return True

    def scale_down(self) -> bool:
        reps = list(self.router.replicas)
        if not reps:
            return False
        rid = reps[-1].id
        self.router.remove_replica(rid, drain=True)
        self.router.metrics.inc("scale_down_total")
        logger.info("in-process fleet: scaled down (-%s)", rid)
        return True

    def load_backlog_ms(self) -> Optional[float]:
        return self.router.load_backlog_ms()

    def apply_config(self, cfg) -> dict:
        """Fleet-wide hot reconfig — delegates to the router's fan-out
        (engine knobs to every replica with rollback-on-refusal, router
        knobs local, autoscale watermarks to the attached scaler)."""
        return self.router.apply_config(cfg)


class Autoscaler:
    """Metrics-driven elastic capacity with hysteresis.

    Policy (see the module docstring): EWMA of the fleet backlog
    estimate above ``up_backlog_ms`` for ``sustain_up_s`` ⇒ scale up;
    below ``down_backlog_ms`` for ``sustain_down_s`` ⇒ scale down;
    ``cooldown_s`` of quiet after every action; count clamped to
    ``[min_replicas, max_replicas]`` (bound repair runs even when the
    load signal is absent). The up/down thresholds are deliberately far
    apart and the sustain windows separate — THAT is the hysteresis
    that keeps flapping load from thrashing spawn/drain.

    Single-writer: state is touched only by the loop thread (or a test
    driving :meth:`observe` inline with an explicit clock), so there is
    no lock to order against the router/supervisor graph.
    """

    def __init__(self, target, *, min_replicas: int = 1,
                 max_replicas: int = 4,
                 up_backlog_ms: float = 50.0,
                 down_backlog_ms: float = 5.0,
                 sustain_up_s: float = 0.5,
                 sustain_down_s: float = 2.0,
                 cooldown_s: float = 1.0,
                 poll_ms: float = 100.0,
                 ewma_alpha: float = 0.3):
        if not (0 < min_replicas <= max_replicas):
            raise ValueError("need 0 < min_replicas <= max_replicas")
        if down_backlog_ms >= up_backlog_ms:
            raise ValueError("down_backlog_ms must sit BELOW "
                             "up_backlog_ms (the hysteresis band)")
        self.target = target
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.up_backlog_ms = float(up_backlog_ms)
        self.down_backlog_ms = float(down_backlog_ms)
        self.sustain_up_s = float(sustain_up_s)
        self.sustain_down_s = float(sustain_down_s)
        self.cooldown_s = float(cooldown_s)
        self.poll_ms = float(poll_ms)
        self.ewma_alpha = float(ewma_alpha)
        self.ewma: Optional[float] = None
        self._above_since: Optional[float] = None
        self._below_since: Optional[float] = None
        self._last_action_t: Optional[float] = None
        self._t0: Optional[float] = None
        # [(seconds-since-start, replica_count)] — recorded at start
        # and after every change: the bench's replica-count trajectory
        self.trajectory: List[Tuple[float, int]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ----------------------------------------------------------- control
    def start(self) -> "Autoscaler":
        self._thread = threading.Thread(target=self._loop,
                                        name="autoscaler", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _loop(self):
        while not self._stop.wait(self.poll_ms / 1e3):
            try:
                self.observe()
            except Exception as e:  # noqa: BLE001 — the loop must live
                logger.error("autoscaler tick crashed: %r", e)

    # ------------------------------------------------------- hot reconfig
    def check_config(self, auto: dict):
        """Validate an autoscale-watermark delta WITHOUT committing it
        (the router's all-or-nothing apply validates local knobs before
        fanning engine knobs out). The constructor's band invariant must
        survive a partial delta, so the unchanged half participates."""
        from paddle_tpu.serving.errors import ConfigRejected
        up = float(auto.get("autoscale_up_backlog_ms",
                            self.up_backlog_ms))
        down = float(auto.get("autoscale_down_backlog_ms",
                              self.down_backlog_ms))
        if not (0 <= down < up):
            raise ConfigRejected(
                f"autoscale watermarks must satisfy 0 <= down < up, got "
                f"down={down} up={up} (the hysteresis band would "
                "collapse); incumbent config keeps serving")

    def commit_config(self, auto: dict):
        """Commit a delta :meth:`check_config` already admitted. Plain
        attribute writes the policy loop reads per tick; ordered so
        ``down < up`` holds at every instant (raise the ceiling before
        the floor, lower the floor before the ceiling) — the loop can
        never observe a collapsed band mid-commit."""
        up = float(auto.get("autoscale_up_backlog_ms",
                            self.up_backlog_ms))
        down = float(auto.get("autoscale_down_backlog_ms",
                              self.down_backlog_ms))
        if up >= self.up_backlog_ms:
            self.up_backlog_ms = up
            self.down_backlog_ms = down
        else:
            self.down_backlog_ms = down
            self.up_backlog_ms = up
        logger.info("autoscaler: watermarks retargeted (down %.1f ms, "
                    "up %.1f ms)", self.down_backlog_ms,
                    self.up_backlog_ms)

    # ------------------------------------------------------------ policy
    def _record(self, now: float, n: int):
        if self._t0 is None:
            self._t0 = now
        self.trajectory.append((round(now - self._t0, 3), n))

    def _cooling(self, now: float) -> bool:
        return (self._last_action_t is not None
                and now - self._last_action_t < self.cooldown_s)

    def observe(self, backlog_ms: Optional[float] = None,
                now: Optional[float] = None):
        """One policy tick. ``backlog_ms``/``now`` injectable so tests
        drive the hysteresis deterministically."""
        now = time.monotonic() if now is None else now
        n = self.target.replica_count()
        if not self.trajectory:
            self._record(now, n)
        # bound repair first: min/max hold even with no load signal
        if n < self.min_replicas:
            if self.target.scale_up():
                self._last_action_t = now
                self._record(now, self.target.replica_count())
            return
        if n > self.max_replicas:
            if self.target.scale_down():
                self._last_action_t = now
                self._record(now, self.target.replica_count())
            return
        if backlog_ms is None:
            backlog_ms = self.target.load_backlog_ms()
        if backlog_ms is None:
            return  # no health observation yet — no policy, no clocks
        self.ewma = (float(backlog_ms) if self.ewma is None
                     else self.ewma_alpha * float(backlog_ms)
                     + (1 - self.ewma_alpha) * self.ewma)
        if self.ewma > self.up_backlog_ms:
            self._below_since = None
            if self._above_since is None:
                self._above_since = now
            if (now - self._above_since >= self.sustain_up_s
                    and not self._cooling(now) and n < self.max_replicas):
                if self.target.scale_up():
                    self._last_action_t = now
                    self._above_since = None
                    self._record(now, self.target.replica_count())
                    log_event(
                        logger, "autoscale_up",
                        "autoscaler: scale UP (ewma backlog %.1f ms > "
                        "%.1f ms sustained)", self.ewma,
                        self.up_backlog_ms, level=20,
                        ewma_backlog_ms=round(self.ewma, 1),
                        replicas=self.target.replica_count())
        elif self.ewma < self.down_backlog_ms:
            self._above_since = None
            if self._below_since is None:
                self._below_since = now
            if (now - self._below_since >= self.sustain_down_s
                    and not self._cooling(now) and n > self.min_replicas):
                if self.target.scale_down():
                    self._last_action_t = now
                    self._below_since = None
                    self._record(now, self.target.replica_count())
                    log_event(
                        logger, "autoscale_down",
                        "autoscaler: scale DOWN (ewma backlog %.1f ms "
                        "< %.1f ms sustained)", self.ewma,
                        self.down_backlog_ms, level=20,
                        ewma_backlog_ms=round(self.ewma, 1),
                        replicas=self.target.replica_count())
        else:
            # inside the hysteresis band: both sustain clocks reset —
            # a flap back into the band forfeits its progress
            self._above_since = None
            self._below_since = None
