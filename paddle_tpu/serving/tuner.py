"""Self-tuning serving fleet: the typed hot-reconfig contract and the
SLO controller over it.

The fleet's performance knobs — ``batch_timeout_ms``, ``max_batch``,
``hedge_ms``, ``shed_watermark``, the autoscale watermarks — were
constructor-frozen: re-tuning for a shifted workload mix meant a
restart. This module makes serving configuration part of the SYSTEM
rather than of the operator (the TensorFlow-paper production stance,
recast at the fleet layer):

- :class:`FleetConfig` — the one typed knob-change payload. Every field
  is optional; ``None`` means "leave unchanged", so a config is a DELTA
  against the incumbent. Parsed with a closed key set (an unknown knob
  is a typed 400, never silently dropped). Applied via
  ``ServingEngine.apply_config`` / ``ReplicaRouter.apply_config`` /
  ``POST /admin/config`` — all three validate-then-commit: an
  inadmissible value (the canonical case: ``max_batch`` above the
  warmed bucket menu, which would drive the hardened ``RecompileGuard``
  into a worker-fatal ``RecompileError`` mid-traffic) is refused with a
  typed 409 :class:`~paddle_tpu.serving.errors.ConfigRejected` and the
  INCUMBENT config keeps serving (the rolling-reload rollback pattern
  applied to knobs).
- :class:`GridTuner` — offline mode: coordinate descent over a bounded
  knob grid, each candidate scored by deterministically replaying a
  recorded workload trace (``serving/workload.py``) against a live
  fleet. Determinism is what makes the comparison meaningful; the
  scorer carries best-of-R semantics so the 1-core host's ±50% drift
  cannot invert a structural ordering.
- :class:`SLOController` — online mode: bounded nudges with hysteresis
  EXACTLY like the r14 ``Autoscaler`` (EWMA signal, sustain clocks that
  reset inside the band, a cooldown after every action, hard clamps),
  fed by the same metrics plane and targeting a declared
  :class:`SLOTarget`. A nudge the fleet refuses (typed 409) clamps the
  controller's own bound — the controller LEARNS the menu edge instead
  of hammering it.

Every decision — applied, refused, or clamped — emits a
``tune_decision`` flight event with before/after knob values and the
triggering signal, so a bad tune is postmortem-able from
``tools/blackbox.py`` alone.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, fields
from typing import Callable, Dict, List, Optional, Tuple

from paddle_tpu.obs import flight as _flight
from paddle_tpu.serving.errors import (BadRequest, ConfigRejected,
                                       ServingError)
from paddle_tpu.utils.log import event as log_event
from paddle_tpu.utils.log import get_logger

logger = get_logger("serving.tuner")

# knob ownership: which component applies each field (docs/serving.md
# carries the operator-facing table; this is the programmatic split)
ENGINE_KNOBS = ("max_batch", "batch_timeout_ms", "queue_depth",
                "shed_watermark", "default_deadline_ms", "decode_chunk")
ROUTER_KNOBS = ("hedge_ms", "max_hedges")
AUTOSCALE_KNOBS = ("autoscale_up_backlog_ms", "autoscale_down_backlog_ms")

_INT_KNOBS = ("max_batch", "queue_depth", "shed_watermark", "max_hedges",
              "decode_chunk")
# knobs where the incumbent value may legitimately be None ("off"): a
# delta cannot say None (that means "unchanged"), so <= 0 encodes "off"
_NULLABLE_KNOBS = ("default_deadline_ms", "hedge_ms", "decode_chunk")


@dataclass
class FleetConfig:
    """One typed knob delta. ``None`` = leave unchanged. For the
    nullable knobs (``default_deadline_ms``, ``hedge_ms``,
    ``decode_chunk``) a value ``<= 0`` means "disable" (the incumbent
    may be None, and a delta needs a way to say so on the wire)."""

    max_batch: Optional[int] = None
    batch_timeout_ms: Optional[float] = None
    queue_depth: Optional[int] = None
    shed_watermark: Optional[int] = None
    default_deadline_ms: Optional[float] = None
    decode_chunk: Optional[int] = None
    hedge_ms: Optional[float] = None
    max_hedges: Optional[int] = None
    autoscale_up_backlog_ms: Optional[float] = None
    autoscale_down_backlog_ms: Optional[float] = None

    # ------------------------------------------------------------ parse
    @classmethod
    def from_dict(cls, d: dict) -> "FleetConfig":
        """Closed-key parse: an unknown knob or a non-numeric value is
        a typed 400 (``BadRequest``) — a config typo must never be
        silently dropped (the operator would believe it applied)."""
        if not isinstance(d, dict):
            raise BadRequest("config must be a JSON object of knobs")
        known = {f.name for f in fields(cls)}
        unknown = sorted(set(d) - known)
        if unknown:
            raise BadRequest(
                f"unknown config knob(s) {unknown}; "
                f"the knob menu is {sorted(known)}",
                allowed={"knobs": sorted(known)})
        kw = {}
        for k, v in d.items():
            if v is None:
                continue  # wire None == omitted == unchanged
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise BadRequest(
                    f"config knob {k!r} must be a number, got {v!r}")
            kw[k] = int(v) if k in _INT_KNOBS else float(v)
        return cls(**kw)

    @classmethod
    def coerce(cls, obj) -> "FleetConfig":
        if isinstance(obj, cls):
            return obj
        return cls.from_dict(obj)

    # ------------------------------------------------------------ views
    def to_dict(self) -> dict:
        """Only the SET fields — the wire payload stays a delta."""
        return {f.name: getattr(self, f.name) for f in fields(self)
                if getattr(self, f.name) is not None}

    def set_fields(self) -> List[str]:
        return sorted(self.to_dict())

    def _items(self, names) -> Dict[str, object]:
        out = {}
        for k in names:
            v = getattr(self, k)
            if v is None:
                continue
            if k in _NULLABLE_KNOBS and v <= 0:
                v = None  # "disable" on the wire -> stored None
            out[k] = v
        return out

    def engine_items(self) -> Dict[str, object]:
        return self._items(ENGINE_KNOBS)

    def router_items(self) -> Dict[str, object]:
        return self._items(ROUTER_KNOBS)

    def autoscale_items(self) -> Dict[str, object]:
        return self._items(AUTOSCALE_KNOBS)

    def engine_subset(self) -> "FleetConfig":
        return FleetConfig(**{k: getattr(self, k) for k in ENGINE_KNOBS
                              if getattr(self, k) is not None})


def rollback_delta(before: dict, changed_keys) -> dict:
    """Build the delta that restores ``changed_keys`` to their
    ``before`` values — the router's fan-out rollback payload. A
    nullable knob whose incumbent was None maps to the wire's
    "disable" spelling (``0``)."""
    out = {}
    for k in changed_keys:
        v = before.get(k)
        if v is None and k in _NULLABLE_KNOBS:
            v = 0
        if v is not None:
            out[k] = v
    return out


def record_tune_decision(**fields_):
    """One ``tune_decision`` flight event (applied / refused / clamped
    nudges all land here — the blackbox postmortem trail). Callers hold
    no locks (the obs plane never nests under a subsystem lock)."""
    if _flight._ACTIVE is not None:
        _flight._ACTIVE.record("tune_decision", **fields_)


# --------------------------------------------------------------- scoring

@dataclass
class SLOTarget:
    """The declared SLO a config is scored against: p99 e2e latency at
    most ``p99_ms`` with at most ``max_shed_rate`` of offered requests
    shed (and deadline misses counted against goodput)."""

    p99_ms: float
    max_shed_rate: float = 0.0

    def to_dict(self) -> dict:
        return {"p99_ms": self.p99_ms,
                "max_shed_rate": self.max_shed_rate}


def slo_score(summary: dict, slo: SLOTarget) -> float:
    """Score one replay summary against the SLO. Bounded [0, 1] and
    structurally dominated: goodput (answered in time / offered) times
    a latency factor that only discounts when p99 exceeds the SLO, plus
    a shed penalty past the allowance. Drift in absolute latencies
    moves the score smoothly; shed/miss counts — the structural part —
    move it in steps."""
    n = max(1, int(summary.get("offered", 0)))
    ok = int(summary.get("ok", 0))
    shed = int(summary.get("shed", 0))
    goodput = ok / n
    p99 = summary.get("p99_ms")
    lat = 1.0
    if p99 is not None and p99 > 0:
        lat = min(1.0, float(slo.p99_ms) / float(p99))
    shed_rate = shed / n
    over_shed = max(0.0, shed_rate - float(slo.max_shed_rate))
    return max(0.0, goodput * lat - over_shed)


# ------------------------------------------------------------ offline

class GridTuner:
    """Coordinate descent over a bounded knob grid, scored by a
    deterministic replay. ``score_fn(config_dict) -> float`` (higher is
    better; the caller owns applying the config to its fleet and
    replaying the trace). Ties keep the incumbent — determinism of the
    search itself, not just of each score. Every score is cached by
    config, so revisited points cost nothing and the search terminates
    after a sweep that improves nothing."""

    def __init__(self, grid: Dict[str, List], score_fn: Callable[[dict], float],
                 *, base: Optional[dict] = None, sweeps: int = 2):
        if not grid:
            raise ValueError("grid must name at least one knob")
        for k, vals in grid.items():
            if not vals:
                raise ValueError(f"grid knob {k!r} has no candidates")
        self.grid = {k: list(v) for k, v in grid.items()}
        self.score_fn = score_fn
        self.base = dict(base or {})
        self.sweeps = int(sweeps)
        self.history: List[dict] = []
        self._cache: Dict[tuple, float] = {}

    def _key(self, cfg: dict) -> tuple:
        return tuple(sorted(cfg.items()))

    def _score(self, cfg: dict) -> float:
        key = self._key(cfg)
        if key not in self._cache:
            self._cache[key] = float(self.score_fn(dict(cfg)))
        return self._cache[key]

    def tune(self) -> Tuple[dict, float]:
        """Run the descent; returns ``(best_config, best_score)``."""
        best = dict(self.base)
        for k, vals in self.grid.items():
            best.setdefault(k, vals[0])
        best_score = self._score(best)
        for sweep in range(self.sweeps):
            improved = False
            for knob in sorted(self.grid):
                for cand in self.grid[knob]:
                    if cand == best[knob]:
                        continue
                    trial = dict(best)
                    trial[knob] = cand
                    s = self._score(trial)
                    decision = {"sweep": sweep, "knob": knob,
                                "candidate": cand, "score": round(s, 4),
                                "incumbent": best[knob],
                                "incumbent_score": round(best_score, 4),
                                "accepted": s > best_score}
                    self.history.append(decision)
                    record_tune_decision(
                        action="grid_accept" if s > best_score
                        else "grid_reject", knob=knob,
                        before=best[knob], after=cand,
                        score=round(s, 4),
                        incumbent_score=round(best_score, 4))
                    if s > best_score:
                        best[knob] = cand
                        best_score = s
                        improved = True
            if not improved:
                break
        return best, best_score


# ------------------------------------------------------------- online

class SLOController:
    """Online closed-loop nudging with hysteresis — the ``Autoscaler``
    policy shape pointed at latency knobs instead of replica count.

    Signal: ``signal()`` (or an injected dict) yields ``p99_ms`` and
    ``shed_rate``. The p99 is EWMA-smoothed; the band is
    ``[band_lo * slo.p99_ms, slo.p99_ms]``:

    - **above the SLO** (or shedding past the allowance) sustained for
      ``sustain_high_s`` and not cooling: halve ``batch_timeout_ms``
      (less coalescing wait, lower latency), clamped at
      ``timeout_lo_ms``. Already at the clamp and still shedding:
      escalate ``max_batch`` one doubling (more rows per launch) — the
      fleet refuses an off-menu value with a typed 409, which the
      controller records and converts into its own learned upper bound.
    - **far below the SLO** sustained for ``sustain_low_s``: double
      ``batch_timeout_ms`` back toward ``timeout_hi_ms`` (recover batch
      occupancy when latency headroom is abundant).
    - **inside the band**: both sustain clocks reset — a flap into the
      band forfeits its progress (the Autoscaler's anti-thrash rule).

    Single-writer like the Autoscaler: state is touched only by the
    loop thread or a test driving :meth:`observe` with an explicit
    clock, so the controller adds no lock-order edges.
    """

    def __init__(self, target, slo: SLOTarget, *,
                 signal: Optional[Callable[[], Optional[dict]]] = None,
                 timeout_ms: float = 5.0,
                 timeout_lo_ms: float = 0.5,
                 timeout_hi_ms: float = 50.0,
                 max_batch: Optional[int] = None,
                 max_batch_cap: Optional[int] = None,
                 step: float = 2.0,
                 band_lo: float = 0.4,
                 sustain_high_s: float = 0.5,
                 sustain_low_s: float = 2.0,
                 cooldown_s: float = 1.0,
                 poll_ms: float = 200.0,
                 ewma_alpha: float = 0.3):
        if not (0 < timeout_lo_ms <= timeout_ms <= timeout_hi_ms):
            raise ValueError("need timeout_lo_ms <= timeout_ms <= "
                             "timeout_hi_ms (all > 0)")
        if step <= 1.0:
            raise ValueError("step must be > 1 (a multiplicative nudge)")
        if not (0.0 < band_lo < 1.0):
            raise ValueError("band_lo must sit in (0, 1) — it is the "
                             "hysteresis band's lower edge")
        self.target = target
        self.slo = slo
        self.signal = signal
        self.timeout_ms = float(timeout_ms)
        self.timeout_lo_ms = float(timeout_lo_ms)
        self.timeout_hi_ms = float(timeout_hi_ms)
        self.max_batch = max_batch if max_batch is None else int(max_batch)
        # learned menu edge: a refused max_batch nudge pins this
        self.max_batch_cap = (None if max_batch_cap is None
                              else int(max_batch_cap))
        self.step = float(step)
        self.band_lo = float(band_lo)
        self.sustain_high_s = float(sustain_high_s)
        self.sustain_low_s = float(sustain_low_s)
        self.cooldown_s = float(cooldown_s)
        self.poll_ms = float(poll_ms)
        self.ewma_alpha = float(ewma_alpha)
        self.ewma: Optional[float] = None
        self._high_since: Optional[float] = None
        self._low_since: Optional[float] = None
        self._last_action_t: Optional[float] = None
        self._t0: Optional[float] = None
        self.decisions = 0
        self.rejections = 0
        # [(seconds-since-start, {knob: value})] — the tune trajectory
        self.trajectory: List[Tuple[float, dict]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ----------------------------------------------------------- control
    def start(self) -> "SLOController":
        self._thread = threading.Thread(target=self._loop,
                                        name="slo-controller", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _loop(self):
        while not self._stop.wait(self.poll_ms / 1e3):
            try:
                self.observe()
            except Exception as e:  # noqa: BLE001 — the loop must live
                logger.error("SLO controller tick crashed: %r", e)

    # ------------------------------------------------------------ policy
    def _knobs(self) -> dict:
        k = {"batch_timeout_ms": round(self.timeout_ms, 3)}
        if self.max_batch is not None:
            k["max_batch"] = self.max_batch
        return k

    def _record(self, now: float):
        if self._t0 is None:
            self._t0 = now
        self.trajectory.append((round(now - self._t0, 3), self._knobs()))

    def _cooling(self, now: float) -> bool:
        return (self._last_action_t is not None
                and now - self._last_action_t < self.cooldown_s)

    def _inc_metric(self, name: str):
        m = getattr(self.target, "metrics", None)
        if m is not None and name in getattr(m, "counters", {}):
            m.inc(name)

    def _apply(self, action: str, knob: str, before, after,
               sig: dict, now: float) -> bool:
        """One bounded nudge through the typed hot-reconfig path. A
        refusal (409) is recorded, counted, and — for max_batch — pins
        the controller's learned cap. Returns True when applied."""
        self.decisions += 1
        self._inc_metric("tune_decisions_total")
        try:
            self.target.apply_config(FleetConfig(**{knob: after}))
        except ConfigRejected as e:
            self.rejections += 1
            if knob == "max_batch":
                self.max_batch_cap = before
            record_tune_decision(
                action="apply_rejected", knob=knob, before=before,
                after=after, reason=str(e)[:200],
                signal_p99_ms=sig.get("p99_ms"),
                signal_shed_rate=sig.get("shed_rate"),
                ewma_p99_ms=(round(self.ewma, 2)
                             if self.ewma is not None else None))
            log_event(logger, "tune_rejected",
                      "SLO controller: %s nudge %s -> %s REFUSED (%s); "
                      "bound learned", knob, before, after, e,
                      knob=knob, before=before, after=after)
            return False
        record_tune_decision(
            action=action, knob=knob, before=before, after=after,
            signal_p99_ms=sig.get("p99_ms"),
            signal_shed_rate=sig.get("shed_rate"),
            ewma_p99_ms=(round(self.ewma, 2)
                         if self.ewma is not None else None))
        log_event(logger, "tune_nudge",
                  "SLO controller: %s %s %s -> %s (ewma p99 %.1f ms, "
                  "SLO %.1f ms)", action, knob, before, after,
                  self.ewma if self.ewma is not None else -1.0,
                  self.slo.p99_ms, level=20, knob=knob,
                  before=before, after=after)
        self._last_action_t = now
        self._record(now)
        return True

    def observe(self, signal: Optional[dict] = None,
                now: Optional[float] = None):
        """One policy tick. ``signal``/``now`` injectable so tests
        drive the hysteresis deterministically (the Autoscaler test
        pattern)."""
        now = time.monotonic() if now is None else now
        if not self.trajectory:
            self._record(now)
        if signal is None:
            signal = self.signal() if self.signal is not None else None
        if not signal or signal.get("p99_ms") is None:
            return  # no load observation yet — no policy, no clocks
        p99 = float(signal["p99_ms"])
        shed_rate = float(signal.get("shed_rate") or 0.0)
        self.ewma = (p99 if self.ewma is None
                     else self.ewma_alpha * p99
                     + (1 - self.ewma_alpha) * self.ewma)
        shedding = shed_rate > self.slo.max_shed_rate
        if self.ewma > self.slo.p99_ms or shedding:
            self._low_since = None
            if self._high_since is None:
                self._high_since = now
            if (now - self._high_since >= self.sustain_high_s
                    and not self._cooling(now)):
                if self.timeout_ms > self.timeout_lo_ms:
                    new = max(self.timeout_lo_ms,
                              self.timeout_ms / self.step)
                    if self._apply("nudge_timeout_down",
                                   "batch_timeout_ms", self.timeout_ms,
                                   new, signal, now):
                        self.timeout_ms = new
                        self._high_since = None
                elif shedding and self.max_batch is not None:
                    # timeout already floored and still shedding: widen
                    # the batch (more rows per launch). The fleet — not
                    # this controller — owns the menu edge: a 409 pins
                    # max_batch_cap so the bound is learned, not guessed
                    new = self.max_batch * 2
                    if (self.max_batch_cap is not None
                            and new > self.max_batch_cap):
                        self._high_since = None  # clamped: nothing to do
                        return
                    if self._apply("widen_max_batch", "max_batch",
                                   self.max_batch, new, signal, now):
                        self.max_batch = new
                    self._high_since = None
        elif self.ewma < self.band_lo * self.slo.p99_ms and not shedding:
            self._high_since = None
            if self._low_since is None:
                self._low_since = now
            if (now - self._low_since >= self.sustain_low_s
                    and not self._cooling(now)
                    and self.timeout_ms < self.timeout_hi_ms):
                new = min(self.timeout_hi_ms, self.timeout_ms * self.step)
                if self._apply("nudge_timeout_up", "batch_timeout_ms",
                               self.timeout_ms, new, signal, now):
                    self.timeout_ms = new
                    self._low_since = None
        else:
            # inside the hysteresis band: both sustain clocks reset —
            # a flap back into the band forfeits its progress
            self._high_since = None
            self._low_since = None


def engine_signal(engine) -> Callable[[], Optional[dict]]:
    """Metrics-plane signal for :class:`SLOController` over a live
    :class:`~paddle_tpu.serving.engine.ServingEngine`: p99 comes from
    the rolling latency window, shed_rate from counter DELTAS between
    ticks (snapshot counters are process-lifetime totals — the
    controller must react to the current window, not the whole run).
    Returns ``None`` until traffic has been observed and on quiet ticks
    (no new offers since the last tick), so the hysteresis clocks only
    run under load."""
    last = {"shed": 0, "offered": 0, "primed": False}

    def _signal() -> Optional[dict]:
        snap = engine.metrics.snapshot()
        shed = int(snap.get("shed_total") or 0)
        offered = int(snap.get("requests_total") or 0) + shed
        d_shed = shed - last["shed"]
        d_offered = offered - last["offered"]
        primed = last["primed"]
        last.update(shed=shed, offered=offered, primed=True)
        total = snap.get("latency_ms", {}).get("total") or {}
        p99 = total.get("p99_ms")
        if not primed or p99 is None or d_offered <= 0:
            return None
        return {"p99_ms": float(p99),
                "shed_rate": d_shed / float(d_offered)}

    return _signal
