"""Bucketed, AOT-warmed, donation-friendly predictor over a deploy model.

The deploy artifact is the merged model (``trainer/merge_model.py`` —
the same PTM1 file the C API's ``ptc_load`` consumes), or any live
(graph, params) pair. On top of it this module enforces the serving
shape discipline:

- **Closed shape menu.** Batch sizes come from ``batch_buckets`` and
  padded sequence lengths from ``length_buckets`` — the feeder's own
  bucketing machinery (``data/feeder.py``), reused verbatim so serving
  and training pad identically. Unlike training there is NO overflow
  rule: a sequence longer than the largest edge is *inadmissible*
  (typed ``BadRequest``), never a new compile.
- **AOT warmup.** ``warmup()`` drives every (batch, length) bucket pair
  through the jitted forward — and, for generating configs, the jitted
  beam search — before the first request, so startup pays all XLA
  compile time.
- **Hardened recompile guard.** After warmup every guard is
  ``harden()``-ed (``data/prefetch.py:RecompileGuard``): jit-cache
  growth on the hot path raises ``RecompileError`` instead of silently
  serving at compile speed.
- **Donation.** Request feeds are fresh arrays, dead after the call, so
  the jitted forward donates them (TPU/GPU; XLA ignores donation on
  CPU, where it is skipped to avoid warning spam).
- **Collective-free.** The warm path is a single-device program and
  must stay one: graftlint pass 4 compiles ``_infer`` and pins its
  collective manifest EMPTY (``analysis/comm_budget.toml`` — any
  collective the serving step grows is PT501 drift at lint time).
- **Quantized tier.** A ``--quantize`` PTM1 artifact loads with its
  weights in STORAGE dtype (int8 stays int8 in HBM, bf16 stays bf16)
  plus traced per-tensor scale leaves; ``paddle_tpu/quant.py:
  materialize`` rebuilds the f32 view inside each jitted program so
  XLA fuses the dequant converts at point of use — no resident f32
  twin (graftlint pass 5 pins the ``serving_quant`` footprint). At
  warmup the embedded golden-request set replays through the real
  bucketed path and the per-output delta vs the recorded fp32
  references must stay within the artifact's per-dtype tolerance — a
  drifted quantized model raises ``QuantGateError`` and never goes
  READY (the closed-shape-menu discipline applied to accuracy); the
  gate verdict rides ``/healthz`` and the rolling-reload report.
  Masks are feed-side and stay f32 through the quantized funnel
  (``assert_feed_masks_f32`` in ``_convert``, unchanged).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.serving.errors import BadRequest
from paddle_tpu.utils.log import get_logger
from paddle_tpu.utils.masks import assert_feed_masks_f32

logger = get_logger("serving")


def _is_seq(itype) -> bool:
    from paddle_tpu.data import types as T
    return itype.seq_type != T.NO_SEQUENCE


def _synth_sample(itype, length: int):
    """An all-zeros warmup sample for one input slot at padded length
    ``length`` (sequence slots) — shaped exactly like real traffic so
    the warmed jit variants are the ones requests hit."""
    from paddle_tpu.data import types as T
    if itype.seq_type == T.NO_SEQUENCE:
        if itype.type == T.INDEX:
            return 0
        if itype.type in (T.SPARSE_BINARY, T.SPARSE_FLOAT):
            return []
        return np.zeros(itype.dim, dtype=np.float32)
    # SUB_SEQUENCE never reaches here — the predictor refuses nested
    # inputs at construction (unbucketed outer axis)
    if itype.type == T.INDEX:
        return [0] * length
    if itype.type in (T.SPARSE_BINARY, T.SPARSE_FLOAT):
        return [[] for _ in range(length)]
    return [np.zeros(itype.dim, dtype=np.float32) for _ in range(length)]


class ServingPredictor:
    """Loads a model and serves bucketed batches with zero hot-path
    compiles. ``predict_rows`` scores; ``generate_rows`` runs the beam
    search of a generating config (``beam_search_group`` present),
    honoring any beam-control hooks pinned in the config."""

    def __init__(self, graph, params: Dict[str, Any],
                 output_names: Sequence[str],
                 feeding: Dict[str, Any], *,
                 batch_buckets: Sequence[int],
                 length_buckets: Optional[Sequence[int]] = None,
                 gen_beam_size: Optional[int] = None,
                 gen_max_length: Optional[int] = None,
                 gen_decode_chunk: Optional[int] = None,
                 gen_full_scan: Optional[bool] = None,
                 donate: Optional[bool] = None,
                 recompile_warn: int = 64,
                 aot_cache=None, model_hash: Optional[str] = None,
                 quant: Optional[Dict[str, Any]] = None,
                 golden: Optional[Dict[str, Any]] = None):
        import jax
        import jax.numpy as jnp

        from paddle_tpu.core.network import Network
        from paddle_tpu.data.feeder import DataFeeder
        from paddle_tpu.data.prefetch import RecompileGuard

        self.graph = graph
        self.params = {k: jnp.asarray(v) for k, v in params.items()}
        # model identity: the PTM1 digest for merged artifacts (passed by
        # from_merged), else a structural fingerprint — keys the AOT
        # warmup cache and names the version /healthz + rolling reload
        # report
        if model_hash is None:
            from paddle_tpu.serving.aot_cache import model_fingerprint
            model_hash = model_fingerprint(graph, self.params)
        self.model_hash = str(model_hash)
        self.model_version = self.model_hash[:12]
        # quantized artifacts: weights stay in storage dtype, traced
        # scale leaves join the params pytree, and every jitted program
        # sees the f32 view through _materialize (dequant fused at
        # point of use). The dtype suffix makes precision part of the
        # published version so canaries/provenance can tell tiers apart
        # even before reading /healthz's quant block.
        self.quant = dict(quant) if quant else None
        self.golden = golden
        self.quant_gate: Optional[Dict[str, Any]] = None
        self._materialize = None
        if self.quant:
            from paddle_tpu import quant as quant_lib
            self.params.update(
                {k: jnp.asarray(v) for k, v in
                 quant_lib.scale_leaves(self.quant).items()})
            meta = self.quant
            self._materialize = (
                lambda p: quant_lib.materialize(p, meta))
            self.model_version += "+" + str(self.quant["dtype"])
        if isinstance(aot_cache, str):
            from paddle_tpu.serving.aot_cache import AOTCache
            aot_cache = AOTCache(aot_cache, self.model_hash)
        self.aot_cache = aot_cache
        # (name, bucket key) -> jax.stages.Compiled: the warmed menu as
        # ready-to-call executables (populated only when a cache is
        # configured; without one the plain jit path serves as before)
        self._aot: Dict[Tuple[str, str], Any] = {}
        self.feeding = dict(feeding)
        self.names = list(self.feeding)
        self.batch_buckets = sorted(int(b) for b in batch_buckets)
        if not self.batch_buckets or self.batch_buckets[0] < 1:
            raise ValueError(f"bad batch_buckets: {batch_buckets}")
        from paddle_tpu.data import types as T
        nested = [n for n, t in self.feeding.items()
                  if t.seq_type == T.SUB_SEQUENCE]
        if nested:
            # the outer subsequence count is an unbounded shape axis the
            # bucket menu does not close: one well-formed 2-subsequence
            # request would compile on the hot path and (hardened guard)
            # kill the worker. Refuse at build time instead.
            raise ValueError(
                f"serving does not support nested-sequence (SUB_SEQUENCE)"
                f" inputs yet: {nested} — the outer subsequence count is"
                " an unbucketed shape axis")
        self.has_sequences = any(_is_seq(t) for t in self.feeding.values())
        self.length_buckets = (sorted(int(e) for e in length_buckets)
                               if length_buckets and self.has_sequences
                               else None)
        if self.has_sequences and not self.length_buckets:
            # silently unbucketed lengths = every batch pads to its own
            # max = post-warmup compile = worker death on the first real
            # request. A sequence model MUST close the length menu.
            raise ValueError(
                "this model has sequence inputs; serving needs non-empty "
                "length_buckets (--serving_length_buckets) so the shape "
                "menu is closed")
        self.max_seq_len = (self.length_buckets[-1]
                            if self.length_buckets else None)
        # id validation ON: an out-of-range id must be a loud per-lane
        # BadRequest, not a silent zero-row lookup (feeder validate_ids).
        # shared_length_bucket ON: every sequence slot of a batch pads to
        # ONE bucket, so the warmed menu is the bucket list — per-slot
        # independent bucketing would make legal multi-sequence requests
        # hit unwarmed cross-product shapes (hot-path compile)
        self.feeder = DataFeeder(
            self.feeding, batch_buckets=self.batch_buckets,
            length_buckets=self.length_buckets, validate_ids=True,
            shared_length_bucket=True)

        self.output_names = [o.name if hasattr(o, "name") else o
                             for o in output_names]
        # the generation group (if any) is served by the beam-search
        # engine, not the plain forward — score outputs exclude it
        self._gen_name = next(
            (n for n, l in graph.layers.items()
             if l.type == "beam_search_group"), None)
        score_outputs = [n for n in self.output_names
                         if n != self._gen_name]
        self.network = (Network(graph, outputs=score_outputs)
                        if score_outputs else None)

        if donate is None:
            donate = jax.default_backend() in ("tpu", "gpu")
        donate_args = (1,) if donate else ()

        self.guards: List[RecompileGuard] = []
        if self.network is not None:
            def _fwd(p, feed):
                # quantized models: dequant INSIDE the trace, so XLA
                # fuses the converts into each weight's consumer; the
                # fp32 path is structurally untouched (identical jaxpr)
                pp = (self._materialize(p) if self._materialize
                      else p)
                outs = self.network.apply(pp, feed, train=False)
                return {n: outs[n].value for n in score_outputs}

            self._infer = jax.jit(_fwd, donate_argnums=donate_args)
            self.guards.append(RecompileGuard(
                self._infer, warn_after=recompile_warn,
                name="serving_infer"))

        self.engine = None
        self._encode = None
        if self._gen_name is not None:
            from paddle_tpu.core.generation import (
                SequenceGenerator as EngineGenerator)
            self.engine = EngineGenerator(graph, self._gen_name)
            if self._materialize is not None:
                # the generation engine consumes params at exactly one
                # interior site (SequenceGenerator.step); the view hook
                # dequantizes there, inside the jitted search
                self.engine._param_view = self._materialize
            self.gen_beam_size = int(
                gen_beam_size or self.engine.cfg.attrs.get("beam_size", 1))
            self.gen_max_length = int(
                gen_max_length
                or self.engine.cfg.attrs.get("max_length", 100))
            # decode-cost policy: chunked early-exit by default (cost
            # proportional to actual output length), full_scan as the
            # escape hatch / A-B baseline. None everywhere = inherit the
            # config's pinned decode policy (dsl.beam_search attrs) —
            # the same precedence beam-control hooks get. The resolved
            # values are part of the warmed closed menu, like
            # (beam, length).
            if gen_decode_chunk is not None and int(gen_decode_chunk) <= 0:
                gen_full_scan, gen_decode_chunk = True, None
            self.gen_full_scan = gen_full_scan
            self.gen_decode_chunk = (int(gen_decode_chunk)
                                     if gen_decode_chunk else None)
            enc_outputs = self.engine.static_input_layers()
            encoder = Network(graph, outputs=enc_outputs)

            def _enc(p, feed):
                pp = (self._materialize(p) if self._materialize
                      else p)
                outs = encoder.apply(pp, feed, train=False)
                return {n: outs[n] for n in enc_outputs}

            self._encode = jax.jit(_enc, donate_argnums=donate_args)
            self.guards.append(RecompileGuard(
                self._encode, warn_after=recompile_warn,
                name="serving_encode"))

        self.warmed = False

    # ------------------------------------------------------------ loaders
    @classmethod
    def from_merged(cls, path: str, feeding: Dict[str, Any],
                    **kwargs) -> "ServingPredictor":
        """Build from a ``--job=merge`` artifact (PTM1 file). ``feeding``
        still comes from the config — the merged payload carries graph +
        params + output names, not input type declarations. The PTM1
        payload digest becomes the model hash (AOT-cache key + reported
        version), unless the caller pins its own. A ``--quantize``
        artifact's optional sections thread through automatically:
        ``quant`` activates the storage-dtype load + dequant view,
        ``golden`` arms the warmup accuracy gate. The quantized payload
        digest differs from the fp32 merge of the same model, so the
        AOT cache and the published version can never collide across
        precision tiers."""
        from paddle_tpu.trainer.merge_model import load_merged_ex, \
            merged_digest
        graph, params, outputs, extras = load_merged_ex(path)
        kwargs.setdefault("model_hash", merged_digest(path))
        kwargs.setdefault("quant", extras.get("quant"))
        kwargs.setdefault("golden", extras.get("golden"))
        return cls(graph, params, outputs, feeding, **kwargs)

    # ------------------------------------------------------------- warmup
    def warmup(self, log=None) -> int:
        """Compile (or deserialize from the AOT cache) every bucket
        variant ahead of traffic; returns the number of warmup
        executions. Hardens all recompile guards."""
        lengths = self.length_buckets or [None]
        t0 = time.perf_counter()
        runs = 0
        for b in self.batch_buckets:
            for ln in lengths:
                rows = [tuple(_synth_sample(self.feeding[n], ln or 1)
                              for n in self.names)] * b
                if self.network is not None:
                    self._warm_score(rows)
                    runs += 1
                if self.engine is not None:
                    self._warm_generate(rows)
                    runs += 1
        if self.engine is not None:
            # the engine jits lazily per (beam, length, hooks) key; the
            # warmup loop above populated it — bring those under guard
            self._ensure_engine_guard()
        for g in self.guards:
            g.harden()
        # quantized artifacts must PASS the accuracy gate before this
        # predictor may report warmed/READY — a drifted model raises
        # here, exactly like a shape outside the closed menu would
        self._run_quant_gate(log)
        self.warmed = True
        if log:
            cache = ""
            if self.aot_cache is not None:
                s = self.aot_cache.stats
                cache = (f"; aot_cache hits={s['hits']} "
                         f"misses={s['misses'] + s['stale']} "
                         f"quarantined={s['quarantined']}")
            log(f"serving warmup: {runs} bucket variants ready in "
                f"{time.perf_counter() - t0:.1f}s "
                f"(batch={self.batch_buckets}, "
                f"length={self.length_buckets}{cache})")
        return runs

    # ------------------------------------------------------- quant gate
    def quant_health(self) -> Dict[str, Any]:
        """The precision tier + gate verdict ``/healthz`` publishes (a
        canary reads this to know which precision answered)."""
        return {"dtype": (self.quant["dtype"] if self.quant else "fp32"),
                "gate": self.quant_gate}

    def _run_quant_gate(self, log=None):
        """Replay the artifact's golden-request set through the REAL
        bucketed scoring path and compare per-output deltas against the
        recorded fp32 references. Raises ``QuantGateError`` past the
        per-dtype tolerance; records the verdict either way. A
        quantized artifact without a usable golden set (generation-only
        config) stands down with a NAMED warning — never silently."""
        if not self.quant:
            return
        from paddle_tpu import quant as quant_lib
        from paddle_tpu.serving.errors import QuantGateError
        dtype = str(self.quant["dtype"])
        tol = float(self.quant.get("tol",
                                   quant_lib.GATE_TOLERANCES[dtype]))
        golden = self.golden
        if (self.network is None or not golden
                or not golden.get("rows")):
            reason = ("no scoring outputs (generation-only config)"
                      if self.network is None
                      else "artifact carries no golden section")

            self.quant_gate = {"checked": False, "dtype": dtype,
                               "tol": tol, "reason": reason}
            logger.warning(
                "quantized model %s: warmup accuracy gate STOOD DOWN "
                "(%s) — serving %s weights unverified",
                self.model_version, reason, dtype)
            return
        rows = [tuple(r) for r in golden["rows"]]
        refs = golden["outputs"]
        bmax = self.batch_buckets[-1]
        deltas: Dict[str, float] = {n: 0.0 for n in refs}
        try:
            for i in range(0, len(rows), bmax):
                chunk = rows[i:i + bmax]
                outs, _info = self.predict_rows(chunk)
                for name, ref in refs.items():
                    got = outs[name][:len(chunk)]
                    d = quant_lib.gate_delta(got,
                                             ref[i:i + len(chunk)])
                    deltas[name] = max(deltas[name], d)
        except BadRequest as e:
            raise QuantGateError(
                f"warmup accuracy gate could not replay the golden "
                f"set through the serving menu: {e}", dtype=dtype,
                deltas={}, tol=tol) from e
        worst = max(deltas.values())
        passed = worst <= tol
        self.quant_gate = {"checked": True, "dtype": dtype, "tol": tol,
                           "max_delta": worst, "passed": passed,
                           "outputs": dict(deltas)}
        if not passed:
            raise QuantGateError(
                f"quantized model {self.model_version} drifted past "
                f"the warmup accuracy gate: max output delta "
                f"{worst:.4g} > tolerance {tol:g} for {dtype} "
                f"(per-output: {deltas}) — refusing to go READY",
                dtype=dtype, deltas=deltas, tol=tol)
        if log:
            log(f"quant gate PASSED ({dtype}): max output delta "
                f"{worst:.4g} <= tol {tol:g} over "
                f"{len(rows)} golden rows")

    def _aot_executable(self, name: str, sig: str, args, build):
        """One warmed executable: deserialize from the cache when it has
        a valid entry (verified by executing against the warmup
        ``args``), else ``build()`` the live compile and persist it."""
        comp = self.aot_cache.load(name, sig, verify_args=args)
        if comp is not None:
            return comp
        comp = build()
        comp(*args)  # first-call buffer touch, symmetric with the
        # loaded path's verification run
        self.aot_cache.save(name, sig, comp)
        return comp

    def _warm_score(self, rows):
        if self.aot_cache is None:
            self.predict_rows(rows)
            return
        feed = self._convert(rows)
        key, _ = self._bucket_key(feed)
        args = (self.params, feed)
        self._aot[("infer", key)] = self._aot_executable(
            "infer", key, args,
            lambda: self._infer.lower(*args).compile())

    def _warm_generate(self, rows):
        if self.aot_cache is None:
            self.generate_rows(rows)
            return
        feed = self._convert(rows)
        key, _ = self._bucket_key(feed)
        eargs = (self.params, feed)
        enc = self._aot_executable(
            "encode", key, eargs,
            lambda: self._encode.lower(*eargs).compile())
        self._aot[("encode", key)] = enc
        outer = enc(self.params, feed)
        static_feed = self.engine.static_feed_from_outer(outer)
        K, L = self.gen_beam_size, self.gen_max_length
        hooks = self.engine._resolve_hooks(None, None, None, None)
        chunk = self.engine._resolve_chunk(L, self.gen_decode_chunk,
                                           self.gen_full_scan)
        gargs = (self.params, static_feed)
        gsig = f"{key}_k{K}_l{L}" + ("" if chunk is None else f"_c{chunk}")
        self._aot[("generate", key)] = self._aot_executable(
            "generate", gsig, gargs,
            lambda: self.engine._jit_for(
                (K, L, chunk) + hooks, K, L, hooks,
                chunk).lower(*gargs).compile())

    def check_guards(self):
        """Hot-path assertion: raises RecompileError on jit-cache growth
        after warmup (see module docstring)."""
        for g in self.guards:
            g.check()

    # --------------------------------------------------------- admission
    def check_sample(self, sample):
        """Cheap host-side admissibility check, run at enqueue time so a
        doomed request is rejected before it occupies queue space. Raises
        ``BadRequest``; does NOT validate value types (that is conversion
        work, isolated per-lane at batch time)."""
        if not isinstance(sample, (list, tuple)):
            raise BadRequest(
                f"sample must be a list of {len(self.names)} input "
                f"slots ({self.names}), got {type(sample).__name__}")
        if len(sample) != len(self.names):
            raise BadRequest(
                f"sample has {len(sample)} slots, the model needs "
                f"{len(self.names)} ({self.names})")
        for name, slot in zip(self.names, sample):
            itype = self.feeding[name]
            if not _is_seq(itype):
                continue
            if not isinstance(slot, (list, tuple, np.ndarray)):
                raise BadRequest(
                    f"input {name!r} is a sequence slot; got "
                    f"{type(slot).__name__}")
            n = len(slot)
            if self.max_seq_len is not None and n > self.max_seq_len:
                raise BadRequest(
                    f"input {name!r} has length {n}, beyond the largest "
                    f"warmed length bucket {self.max_seq_len}; serving "
                    "shapes are a closed menu (no hot-path compiles)")

    def padding_row(self) -> tuple:
        """A synthetic all-padding row (what batch-bucket padding uses);
        the batcher swaps it in for a malformed lane."""
        return tuple(_synth_sample(self.feeding[n], 1) for n in self.names)

    def probe_rows(self, rows) -> List[Optional[Exception]]:
        """Per-lane conversion probe for the malformed-batch error path:
        converts each row alone (padded to the smallest batch bucket with
        synthetic rows) and returns its exception, or None when clean.
        Only runs after a full-batch conversion already failed, so the
        per-row cost is off the happy path."""
        pad = [self.padding_row()] * (self.batch_buckets[0] - 1)
        out: List[Optional[Exception]] = []
        for row in rows:
            try:
                self.feeder([tuple(row)] + pad)
                out.append(None)
            except Exception as e:  # noqa: BLE001 — typed by the caller
                out.append(e)
        return out

    # ------------------------------------------------------------ scoring
    def _convert(self, rows, lane_valid=None):
        """rows -> feed dict through the bucketing feeder. ``lane_valid``
        (bool per row) zeroes the row mask of known-bad lanes so they are
        exact padding."""
        import jax.numpy as jnp

        from paddle_tpu.data.feeder import ROW_MASK_KEY
        feed = self.feeder(list(rows))
        # runtime twin of graftlint PT102: every mask the feeder built
        # must be f32 before it reaches the warmed executables
        assert_feed_masks_f32(feed, "serving feed")
        if lane_valid is not None and ROW_MASK_KEY in feed:
            mask = feed[ROW_MASK_KEY]
            lv = np.ones(mask.value.shape[0], dtype=np.float32)
            lv[:len(lane_valid)] = np.asarray(lane_valid, np.float32)
            feed[ROW_MASK_KEY] = mask.replace(
                value=mask.value * jnp.asarray(lv))
        return feed

    def _bucket_key(self, feed) -> Tuple[str, int]:
        """(metrics bucket label, padded row count) for a converted
        feed."""
        first = feed[self.names[0]].value
        padded = int(first.shape[0])
        key = f"b{padded}"
        for n in self.names:
            if _is_seq(self.feeding[n]):
                key += f"_t{int(feed[n].value.shape[1])}"
                break
        return key, padded

    def predict_rows(self, rows: List[tuple], lane_valid=None):
        """Score a bucketed batch. Returns ``(outs, info)`` where
        ``outs`` maps output layer name -> np array over the PADDED
        batch (caller slices real lanes) and ``info`` carries
        ``{bucket, padded_rows, pad_ms, compute_ms}``."""
        if self.network is None:
            raise BadRequest("this model has no scoring outputs "
                             "(generation-only config)")
        t0 = time.perf_counter()
        feed = self._convert(rows, lane_valid)
        key, padded = self._bucket_key(feed)
        t1 = time.perf_counter()
        # warmed AOT executable when the cache populated one for this
        # bucket; the plain jit path otherwise (and as the fall-through
        # a hardened guard turns into a loud RecompileError)
        comp = self._aot.get(("infer", key))
        out = (comp if comp is not None else self._infer)(
            self.params, feed)
        out = {n: np.asarray(v) for n, v in out.items()}  # host fetch
        t2 = time.perf_counter()
        if self.warmed:
            self.check_guards()
        return out, {"bucket": key, "padded_rows": padded,
                     "pad_ms": (t1 - t0) * 1e3,
                     "compute_ms": (t2 - t1) * 1e3}

    # --------------------------------------------------------- generation
    def gen_effective_full_scan(self) -> bool:
        """The decode policy actually in force: the constructor/CLI
        override when given (an explicit positive chunk requests chunked
        decode), else the config's pinned ``full_scan`` — mirroring
        ``SequenceGenerator._resolve_chunk``'s precedence."""
        if self.gen_full_scan is not None:
            return bool(self.gen_full_scan)
        if self.gen_decode_chunk:
            return False
        return bool(self.engine.cfg.attrs.get("full_scan", False))

    def gen_allowed_menu(self) -> dict:
        """The warmed generation option menu, carried in closed-menu 400s
        (``serving/errors.py`` wire contract) so clients self-correct."""
        return {"beam_size": [self.gen_beam_size],
                "max_length": [self.gen_max_length]}

    def check_gen_opts(self, beam_size=None, max_length=None):
        """Serving pins ONE (beam_size, max_length) pair at warmup — any
        other pair would be a hot-path compile, so it is inadmissible.
        The 400 names the rejected value AND carries the warmed menu
        (``allowed``) so the client can retry without guessing."""
        if self.engine is None:
            raise BadRequest("this model has no generation group")
        if beam_size is not None and int(beam_size) != self.gen_beam_size:
            raise BadRequest(
                f"beam_size={beam_size} is not the warmed value "
                f"{self.gen_beam_size} (closed shape menu)",
                allowed=self.gen_allowed_menu())
        if (max_length is not None
                and int(max_length) != self.gen_max_length):
            raise BadRequest(
                f"max_length={max_length} is not the warmed value "
                f"{self.gen_max_length} (closed shape menu)",
                allowed=self.gen_allowed_menu())

    def encode_rows(self, rows: List[tuple], lane_valid=None):
        """Run just the encoder over a bucketed batch: rows -> outer
        layer name -> Argument (padded batch). The continuous batcher
        encodes each request ONCE here at admission, then splices the
        result into the live decode state."""
        if self.engine is None:
            raise BadRequest("this model has no generation group")
        feed = self._convert(rows, lane_valid)
        comp = (self._aot.get(("encode", self._bucket_key(feed)[0]))
                if self._aot else None)
        outer = (comp if comp is not None else self._encode)(
            self.params, feed)
        if self.warmed:
            self.check_guards()
        return outer

    def generate_rows(self, rows: List[tuple], lane_valid=None):
        """Beam-search a bucketed batch of encoder inputs. Returns
        ``((tokens, scores, lengths), info)`` — each np, [B, K, ...] over
        the padded batch. Config-pinned beam-control hooks apply (the
        engine reads them from the group attrs). ``info`` carries the
        early-exit accounting: ``decode_steps`` actually executed and
        ``steps_saved`` (= max_length - decode_steps)."""
        if self.engine is None:
            raise BadRequest("this model has no generation group")
        t0 = time.perf_counter()
        feed = self._convert(rows, lane_valid)
        key, padded = self._bucket_key(feed)
        t1 = time.perf_counter()
        enc = self._aot.get(("encode", key))
        outer = (enc if enc is not None else self._encode)(
            self.params, feed)
        comp = self._aot.get(("generate", key))
        if comp is not None:
            # warmed AOT search executable: same program the engine
            # would jit for the pinned (beam, length, chunk, hooks) key
            static_feed = self.engine.static_feed_from_outer(outer)
            tokens, scores, lengths, steps = comp(self.params,
                                                  static_feed)
            steps = int(steps)
            gen_info = {"decode_steps": steps,
                        "steps_saved": self.gen_max_length - steps}
        else:
            tokens, scores, lengths = self.engine.generate(
                self.params, outer, beam_size=self.gen_beam_size,
                max_length=self.gen_max_length,
                decode_chunk=self.gen_decode_chunk,
                full_scan=self.gen_full_scan)
            gen_info = self.engine.last_info
        tokens, scores, lengths = (np.asarray(tokens), np.asarray(scores),
                                   np.asarray(lengths))
        t2 = time.perf_counter()
        if self.warmed:
            # the serving key set is pinned and fully populated at
            # warmup (warmup() ran _ensure_engine_guard) — only the
            # cheap cache-size check belongs on the hot path
            self.check_guards()
        return (tokens, scores, lengths), {
            "bucket": key + f"_k{self.gen_beam_size}",
            "padded_rows": padded,
            "pad_ms": (t1 - t0) * 1e3,
            "compute_ms": (t2 - t1) * 1e3,
            "decode_steps": gen_info.get("decode_steps"),
            "steps_saved": gen_info.get("steps_saved")}

    def build_session(self, width: int):
        """A warmed continuous-batching :class:`DecodeSession` of
        ``width`` lanes (``core/generation.py``): admits one synthetic
        request, runs one chunk, releases it — so the session's three
        device programs (admit / chunk / release) are compiled — then
        brings them under hardened recompile guards. The engine calls
        this from ``start()`` when ``continuous_batching`` is on.

        Returns ``None`` (warn + stand down to convoy batching) when the
        model's static/boot inputs change shape across length buckets —
        a sequence-valued ``StaticInput`` (e.g. seq2seq's encoded
        source) pads to its request's bucket, but a session's lane
        buffers have ONE fixed shape; admitting a larger-bucket request
        would be a trace error surfacing as a spurious per-request 400.
        Fail loudly at startup instead (the closed-menu discipline)."""
        from paddle_tpu.data.prefetch import RecompileGuard
        if self.engine is None:
            raise BadRequest("this model has no generation group")
        outers, shapes = [], set()
        for warm_len in (self.length_buckets or [1]):
            row = tuple(_synth_sample(self.feeding[n], warm_len)
                        for n in self.names)
            outer = self.encode_rows([row])
            feed = self.engine.static_feed_from_outer(outer, row=0)
            shapes.add(tuple(sorted(
                (b, a.value.shape[1:],
                 None if a.mask is None else a.mask.shape[1:])
                for b, a in feed.items())))
            outers.append(outer)
        if len(shapes) > 1:
            logger.warning(
                "continuous batching stood down: this model's "
                "static/boot generation inputs change shape across the "
                "%d warmed length buckets (a sequence-valued "
                "StaticInput pads per bucket), but a decode session's "
                "lane buffers have one fixed shape. Serving falls back "
                "to convoy batching; use a single "
                "--serving_length_buckets entry to enable continuous "
                "batching for this model.", len(self.length_buckets))
            return None
        if self.gen_effective_full_scan():
            # full-scan decode has no chunk boundaries to admit/retire
            # at — continuous batching would silently override the
            # requested policy; refuse loudly instead
            logger.warning(
                "continuous batching stood down: the decode policy is "
                "full_scan (--decode_chunk 0, or pinned in the config) "
                "and a full-length scan has no chunk boundaries to "
                "admit/retire at. Serving falls back to convoy "
                "batching; drop the full-scan override to enable "
                "continuous batching.")
            return None
        sess = self.engine.session(
            self.params, width, beam_size=self.gen_beam_size,
            max_length=self.gen_max_length,
            decode_chunk=self.gen_decode_chunk)
        sess.admit(0, outers[0], row=0)
        sess.run_chunk()
        # the lane-flag reductions and result fetch compile on first
        # use too — pay them here, not inside the first request's decode
        sess.free_lanes()
        sess.finished_lanes()
        sess.peek(0)
        sess.release(0)
        for fn in sess.jitted_fns():
            g = RecompileGuard(fn, name="serving_decode_session")
            g.harden()
            self.guards.append(g)
        return sess

    def _ensure_engine_guard(self):
        from paddle_tpu.data.prefetch import RecompileGuard
        watched = {id(g.fn) for g in self.guards}
        for fn in self.engine._jitted.values():
            if id(fn) not in watched:
                g = RecompileGuard(fn, name="serving_generate")
                g.harden()
                self.guards.append(g)
